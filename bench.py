"""Benchmark: pods scheduled/sec for the device solve.

Reference baseline: the Go scheduler enforces a floor of 100 pods/sec for
batches > 100 pods (reference scheduling_benchmark_test.go:50,180-184) and
publishes no absolute numbers; vs_baseline is therefore measured against that
floor. The timed region is the jitted device program — feasibility +
packing — which is the analog of Scheduler.Solve() (snapshot encoding is the
control plane's job and is reported separately on stderr).

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": "pods/sec", "vs_baseline": N/100}

Hardened (round 2): the bench NEVER exits without printing that JSON line.
Backend init is probed in a subprocess with retries (round 1 died at
"Unable to initialize backend 'axon': UNAVAILABLE" and recorded nothing);
if the accelerator stays unavailable the bench falls back to CPU and says so
in the metric name, because a CPU number beats no number.
"""
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

N_PODS = int(os.environ.get("BENCH_PODS", "2000"))
N_TYPES = int(os.environ.get("BENCH_TYPES", "100"))
N_RUNS = int(os.environ.get("BENCH_RUNS", "5"))
MIX = os.environ.get("BENCH_MIX", "reference")  # reference | plain
CONFIG = os.environ.get("BENCH_CONFIG", "solve")  # solve | consolidation
N_EXISTING = int(os.environ.get("BENCH_EXISTING", "1000"))
# node-slot budget: hostname-spread pods (1/7 of the mix) need a slot each
MAX_NODES = int(os.environ.get("BENCH_NODES", str(max(1024, N_PODS // 4))))
PROBE_RETRIES = int(os.environ.get("BENCH_PROBE_RETRIES", "2"))
PROBE_TIMEOUT = int(os.environ.get("BENCH_PROBE_TIMEOUT", "240"))

BACKEND_NOTE = ""


def ensure_backend():
    """Probe jax backend init in a SUBPROCESS (so a wedged/unavailable TPU
    can't poison this process — the axon tunnel is observed to HANG
    indefinitely, not just error), retrying with backoff; on exhaustion
    force the CPU backend so the bench still records a number.

    NOTE: the image's sitecustomize pins JAX_PLATFORMS=axon before any user
    code, so the env var cannot override the platform — only
    jax.config.update("jax_platforms", "cpu") after import works. This
    function therefore does the config.update in-process on fallback.
    Round-1 failure mode: rc=1 at 'Unable to initialize backend axon'."""
    global BACKEND_NOTE
    force_cpu = os.environ.get("BENCH_CPU", "") == "1"
    last_err = "forced by BENCH_CPU=1"
    if not force_cpu:
        for attempt in range(PROBE_RETRIES):
            proc = None
            try:
                proc = subprocess.run(
                    [sys.executable, "-c",
                     "import jax; d=jax.devices(); print(d[0].platform, d[0].device_kind)"],
                    capture_output=True, text=True, timeout=PROBE_TIMEOUT,
                    env=dict(os.environ),
                )
            except subprocess.TimeoutExpired:
                last_err = f"probe timeout after {PROBE_TIMEOUT}s"
            if proc is not None and proc.returncode == 0:
                BACKEND_NOTE = proc.stdout.strip()
                print(f"[bench] backend ok: {BACKEND_NOTE} (attempt {attempt + 1})",
                      file=sys.stderr)
                return
            if proc is not None:
                err = (proc.stderr or "").strip()
                last_err = err.splitlines()[-1] if err else "rc!=0"
            print(f"[bench] backend probe attempt {attempt + 1} failed: {last_err}",
                  file=sys.stderr)
            if attempt < PROBE_RETRIES - 1:
                time.sleep(min(30, 5 * (attempt + 1)))
    import jax

    jax.config.update("jax_platforms", "cpu")
    BACKEND_NOTE = f"cpu-fallback ({last_err})"
    print(f"[bench] accelerator unavailable; running on CPU: {last_err}",
          file=sys.stderr)


def _reference_mix(n_pods: int, n_types: int):
    """The reference benchmark's diverse pod mix
    (scheduling_benchmark_test.go:187-199): 1/7 zonal topology spread,
    1/7 hostname spread, 2/7 pod affinity, 3/7 generic."""
    from karpenter_core_tpu.cloudprovider import fake
    from karpenter_core_tpu.kube.objects import (
        LABEL_HOSTNAME,
        LABEL_TOPOLOGY_ZONE,
        LabelSelector,
        PodAffinityTerm,
        TopologySpreadConstraint,
    )
    from karpenter_core_tpu.testing import make_pod, make_provisioner

    zonal = TopologySpreadConstraint(
        max_skew=1,
        topology_key=LABEL_TOPOLOGY_ZONE,
        when_unsatisfiable="DoNotSchedule",
        label_selector=LabelSelector(match_labels={"app": "spread"}),
    )
    hostname = TopologySpreadConstraint(
        max_skew=1,
        topology_key=LABEL_HOSTNAME,
        when_unsatisfiable="DoNotSchedule",
        label_selector=LabelSelector(match_labels={"app": "hspread"}),
    )
    affinity = PodAffinityTerm(
        topology_key=LABEL_TOPOLOGY_ZONE,
        label_selector=LabelSelector(match_labels={"app": "aff"}),
    )
    pods = []
    for i in range(n_pods):
        kind = i % 7
        if kind == 0:
            pods.append(
                make_pod(labels={"app": "spread"}, requests={"cpu": "1"}, topology_spread=[zonal])
            )
        elif kind == 1:
            pods.append(
                make_pod(
                    labels={"app": "hspread"}, requests={"cpu": "1"}, topology_spread=[hostname]
                )
            )
        elif kind in (2, 3):
            pods.append(
                make_pod(
                    labels={"app": "aff"},
                    requests={"cpu": "1"},
                    pod_affinity_required=[affinity],
                )
            )
        else:
            pods.append(make_pod(requests={"cpu": "1", "memory": "1Gi"}))
    provisioners = [make_provisioner(name="default")]
    return pods, provisioners, {"default": fake.instance_types(n_types)}


def consolidation_bench():
    """Config 4 analog: N_EXISTING under-utilized nodes, N_PODS running
    pods, full multi-node replan (the parallel prefix ladder over
    simulate_scheduling, replacing multinodeconsolidation.go:87-113's
    sequential binary search). Timed region: the whole ComputeCommand
    ladder, steady-state (compiled programs cached)."""
    import time as _time

    from karpenter_core_tpu.api.labels import (
        LABEL_CAPACITY_TYPE,
        LABEL_NODE_INITIALIZED,
        PROVISIONER_NAME_LABEL_KEY,
    )
    from karpenter_core_tpu.api.settings import Settings
    from karpenter_core_tpu.cloudprovider import fake
    from karpenter_core_tpu.controllers.deprovisioning.core import candidate_nodes
    from karpenter_core_tpu.kube.objects import LABEL_INSTANCE_TYPE_STABLE, LABEL_TOPOLOGY_ZONE
    from karpenter_core_tpu.operator import new_operator
    from karpenter_core_tpu.solver.tpu_solver import TPUSolver
    from karpenter_core_tpu.testing import FakeClock, make_node, make_pod, make_provisioner

    clock = FakeClock()
    universe = fake.instance_types(N_TYPES)
    cp = fake.FakeCloudProvider(universe)
    solver = TPUSolver(max_nodes=max(1024, N_PODS // 4))
    op = new_operator(cp, settings=Settings(), solver=solver, clock=clock)
    op.kube_client.create(make_provisioner(name="default", consolidation_enabled=True))

    pods_per_node = max(1, N_PODS // N_EXISTING)
    t0 = time.perf_counter()
    for n in range(N_EXISTING):
        it = universe[n % len(universe)]
        name = f"node-{n}"
        node = make_node(
            name=name,
            labels={
                PROVISIONER_NAME_LABEL_KEY: "default",
                LABEL_NODE_INITIALIZED: "true",
                LABEL_INSTANCE_TYPE_STABLE: it.name,
                LABEL_CAPACITY_TYPE: "on-demand",
                LABEL_TOPOLOGY_ZONE: f"test-zone-{1 + n % 3}",
            },
            capacity={k: str(v) for k, v in it.capacity.items()},
        )
        op.kube_client.create(node)
        for _ in range(pods_per_node):
            pod = make_pod(requests={"cpu": "0.1"}, node_name=name, unschedulable=False)
            pod.status.phase = "Running"
            op.kube_client.create(pod)
    op.sync_state()
    setup_s = time.perf_counter() - t0

    multi = next(
        d for d in op.deprovisioning.deprovisioners
        if type(d).__name__ == "MultiNodeConsolidation"
    )
    multi.validation_ttl = 0.0

    def replan():
        candidates = multi.sort_and_filter_candidates(
            candidate_nodes(
                op.cluster, op.kube_client, cp, multi.should_deprovision, clock
            )
        )
        return candidates, multi.first_n_consolidation_ladder(candidates)

    t0 = time.perf_counter()
    candidates, cmd = replan()
    warm_s = time.perf_counter() - t0
    times = []
    for _ in range(max(1, N_RUNS - 1)):
        t0 = time.perf_counter()
        candidates, cmd = replan()
        times.append(time.perf_counter() - t0)
    replan_s = float(np.median(times)) if times else warm_s

    total_pods = N_EXISTING * pods_per_node
    pods_per_sec = total_pods / replan_s
    print(
        f"[bench] consolidation nodes={N_EXISTING} pods={total_pods} "
        f"types={N_TYPES} candidates={len(candidates)} action={cmd.action} "
        f"removed={len(cmd.nodes_to_remove)} setup={setup_s:.1f}s "
        f"warm={warm_s:.1f}s replan_med={replan_s * 1e3:.1f}ms",
        file=sys.stderr,
    )
    suffix = "_cpu_fallback" if BACKEND_NOTE.startswith("cpu-fallback") else ""
    print(
        json.dumps(
            {
                "metric": (
                    f"consolidation_replan_pods_per_sec_{N_EXISTING}nodes_"
                    f"{total_pods}pods{suffix}"
                ),
                "value": round(pods_per_sec, 1),
                "unit": "pods/sec",
                "vs_baseline": round(pods_per_sec / 100.0, 2),
            }
        )
    )


def main():
    import jax

    from __graft_entry__ import _scenario
    from karpenter_core_tpu.solver.encode import encode_snapshot
    from karpenter_core_tpu.solver.tpu_solver import build_device_solve, device_args

    t0 = time.perf_counter()
    if MIX == "reference":
        pods, provisioners, instance_types = _reference_mix(N_PODS, N_TYPES)
    else:
        pods, provisioners, instance_types = _scenario(N_PODS, N_TYPES)
    snap = encode_snapshot(pods, provisioners, instance_types, max_nodes=MAX_NODES)
    encode_s = time.perf_counter() - t0

    _, run = build_device_solve(snap, max_nodes=MAX_NODES)
    args = device_args(snap, provisioners)
    fn = jax.jit(run)

    t0 = time.perf_counter()
    out = fn(*args)
    jax.block_until_ready(out)
    compile_s = time.perf_counter() - t0

    times = []
    for _ in range(N_RUNS):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        times.append(time.perf_counter() - t0)

    from karpenter_core_tpu.solver.tpu_solver import expand_log

    log, ptr, state = out
    log = {k: np.asarray(v) for k, v in log.items()}
    assigned = expand_log(snap, log, int(ptr))
    scheduled = int((assigned >= 0).sum())
    solve_s = float(np.median(times))
    pods_per_sec = scheduled / solve_s

    print(
        f"[bench] device={jax.devices()[0].device_kind} pods={N_PODS} types={N_TYPES} "
        f"scheduled={scheduled} encode={encode_s:.2f}s compile={compile_s:.1f}s "
        f"solve_med={solve_s * 1e3:.1f}ms p_best={min(times) * 1e3:.1f}ms",
        file=sys.stderr,
    )
    suffix = "_cpu_fallback" if BACKEND_NOTE.startswith("cpu-fallback") else ""
    print(
        json.dumps(
            {
                "metric": (
                    f"pods_scheduled_per_sec_device_solve_{N_PODS}pods_{N_TYPES}types{suffix}"
                ),
                "value": round(pods_per_sec, 1),
                "unit": "pods/sec",
                "vs_baseline": round(pods_per_sec / 100.0, 2),
            }
        )
    )


if __name__ == "__main__":
    try:
        ensure_backend()
        if CONFIG == "consolidation":
            consolidation_bench()
        else:
            main()
    except BaseException as exc:  # never exit without the JSON line
        import traceback

        traceback.print_exc()
        print(
            json.dumps(
                {
                    "metric": f"bench_failed_{CONFIG}_{N_PODS}pods_{N_TYPES}types",
                    "value": 0.0,
                    "unit": "pods/sec",
                    "vs_baseline": 0.0,
                    "error": f"{type(exc).__name__}: {exc}"[:400],
                }
            )
        )
        sys.exit(0)
