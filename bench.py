"""Benchmark: the NORTH-STAR config — 50k pods x 500 instance types, >=1000
distinct pod specs, 1000 existing nodes — end-to-end Solve() p99 over
varied batch sizes on real TPU hardware.

Reference baseline: the Go scheduler enforces a floor of 100 pods/sec for
batches > 100 pods (reference scheduling_benchmark_test.go:50,180-184) and
publishes no absolute numbers; vs_baseline is measured against that floor.
The chartered target (BASELINE.json north_star): < 1s p99 Solve() at
50k x 500 on a v5e-4 (this bench runs on ONE v5e chip).

The timed region is the FULL Solve() — encode + device program + decode —
because that is what the reference's Solve() does; the device-only time is
reported in "extra". p99 is taken across >= BENCH_RUNS solves whose pod /
existing-node counts vary inside one bucket geometry (so steady-state
production solves hit the compiled cache; the compile is reported
separately). The workload is the reference benchmark's diverse mix
(scheduling_benchmark_test.go:187-199) with BENCH_DISTINCT distinct generic
specs — round 2's mix collapsed to 4 equivalence classes, which measured
the bulk-replica fast path instead of the per-item scan.

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": "pods/sec", "vs_baseline": N/100,
   "extra": {...p50/p99, device ms, consolidation replan number...}}

Hardened (round 2): the bench NEVER exits without printing that JSON line.
Backend init is probed in a subprocess with retries; on exhaustion it falls
back to CPU and says so in the metric name. Each failed probe attempt is
printed to stderr (preserved in the driver's recorded tail).

Hardened again (round 4, VERDICT #1): the top-level process is now an
ORCHESTRATOR that never imports jax itself. It probes the backend with a
progressive schedule (60s -> 240s -> 600s), runs the actual bench in a
WORKER subprocess under a watchdog timeout (so a mid-run tunnel wedge
cannot hang the driver with no JSON emitted), retries the worker once
after a re-probe if it wedges, and — if it had to settle for a CPU
fallback — makes one FINAL long TPU probe before emitting, re-running the
TPU workload if the tunnel came back. A transient wedge at any single
point in time can no longer cost the round its TPU number.

Rebuilt (round 5, ISSUE 11): the default config is now a RESUMABLE STAGE
GRAPH on the shared wedge-proof supervisor
(karpenter_core_tpu/utils/supervise.py — docs/bench-rounds.md). Each stage
(headline, pipelined, config5, grid, multichip, consolidation,
consolidation_xl, warm_restart) runs in its OWN supervised worker process
with a heartbeat file (staleness = wedge, killed early; distinct from slow)
and writes its own atomic artifact into the round directory as it
finishes. Backend health comes from an OUT-OF-BAND sidecar probe daemon
publishing a TTL'd verdict file, so no stage ever pays a probe timeout
in-line: a wedged tunnel degrades exactly the stage it wedged (its column
carries the killed worker's env-redacted stderr tail as
`extra.<stage>.wedge_log`), every other column still lands, and
`bench.py --resume <round-dir>` re-runs ONLY missing/degraded stages (and
involuntary-CPU fallback stages, once the verdict says the TPU is back)
before merging into the unchanged BENCH_r{N}.json schema. The legacy
single-worker orchestration is kept for BENCH_CONFIG=consolidation/sweep.
"""
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

from karpenter_core_tpu.obs import envflags
from karpenter_core_tpu.utils import supervise

N_PODS = int(os.environ.get("BENCH_PODS", "50000"))
N_TYPES = int(os.environ.get("BENCH_TYPES", "500"))
N_RUNS = int(os.environ.get("BENCH_RUNS", "20"))
N_DISTINCT = int(os.environ.get("BENCH_DISTINCT", "1000"))
CONFIG = os.environ.get("BENCH_CONFIG", "solve")  # solve | consolidation | sweep
# sweep mode: distinct-spec counts to measure the per-item scan cost curve
SWEEP_DISTINCT = [
    int(x) for x in os.environ.get("BENCH_SWEEP", "10,100,1000,5000").split(",")
]
N_EXISTING = int(os.environ.get("BENCH_EXISTING", "1000"))
# consolidation sub-bench scale (ref multinodeconsolidation.go:87-113)
CONS_NODES = int(os.environ.get("BENCH_CONS_NODES", "1000"))
CONS_PODS = int(os.environ.get("BENCH_CONS_PODS", "10000"))
CONS_TYPES = int(os.environ.get("BENCH_CONS_TYPES", "100"))
# ROADMAP item 4 exit-criterion geometry (ISSUE 10): 10k nodes / 100k pods
# consolidation pass, target replan_med < 1s. Shed by worker budget like
# the grid stages — but the column/geometry always appear in the JSON so a
# TPU round can prove (or disprove) consolidation_under_1s.
CONS_XL_NODES = int(os.environ.get("BENCH_CONS_XL_NODES", "10000"))
CONS_XL_PODS = int(os.environ.get("BENCH_CONS_XL_PODS", "100000"))
# host-side budget the XL stage needs before the watchdog (setup of 100k
# pod objects + state sync dominates on CPU fallback)
CONS_XL_MIN_BUDGET = int(os.environ.get("BENCH_CONS_XL_MIN_BUDGET", "900"))
# node-slot budget: hostname-spread pods (1/7 of the mix) need a slot each,
# plus headroom for the machine opens of the other kinds — oversizing the
# budget taxes every [N]-wide op in the scan
MAX_NODES = int(os.environ.get("BENCH_NODES", str(max(1024, N_PODS // 5 + 1536))))
PROBE_RETRIES = int(os.environ.get("BENCH_PROBE_RETRIES", "2"))
PROBE_TIMEOUT = int(os.environ.get("BENCH_PROBE_TIMEOUT", "240"))
# orchestrator knobs (round 4): progressive probe schedule, worker watchdog,
# and the last-chance probe made after a CPU fallback before emitting
PROBE_SCHEDULE = [
    int(x) for x in os.environ.get("BENCH_PROBE_SCHEDULE", "60,240,600").split(",")
]
# sized for: probe + cold compiles (headline, pipelined, config-5 2-template
# geometry, 3 grid geometries) + 20 varied runs + pipelined + config5 +
# consolidation + configs 1-3 grid; the worker additionally sheds the
# optional late stages (grid, then consolidation-after-grid) when it nears
# its own watchdog, so the JSON line with the already-measured headline
# numbers is emitted even if the budget runs short. The orchestrator still
# fits a shrunk retry inside TOTAL_BUDGET.
WORKER_TIMEOUT = int(os.environ.get("BENCH_WORKER_TIMEOUT", "3300"))
WORKER_START = time.monotonic()


def _worker_time_left():
    """Seconds until ~the worker watchdog fires (15% safety margin)."""
    return WORKER_TIMEOUT * 0.85 - (time.monotonic() - WORKER_START)
CPU_WORKER_TIMEOUT = int(os.environ.get("BENCH_CPU_WORKER_TIMEOUT", "1500"))
FINAL_PROBE_TIMEOUT = int(os.environ.get("BENCH_FINAL_PROBE_TIMEOUT", "300"))
# hard wall-clock budget for the WHOLE orchestration: later stages get
# min(stage_timeout, remaining) and the rescue stages are skipped once the
# budget is spent, so the JSON line is guaranteed to appear before a
# driver-side patience limit of this size kills the process silently
TOTAL_BUDGET = int(os.environ.get("BENCH_TOTAL_BUDGET", "5400"))

# ---------------------------------------------------------------------------
# stage graph (round 5, ISSUE 11): per-stage supervised workers + resumable
# artifacts + an out-of-band health daemon. docs/bench-rounds.md is the spec.

# heartbeat staleness threshold for a stage worker: longer than any legit
# silent stretch (a cold XLA compile at the headline geometry), far shorter
# than a stage budget — a wedge is detected in minutes, not at the watchdog
STAGE_STALE = int(os.environ.get("BENCH_STAGE_STALE", "600"))
# the sidecar health daemon's re-probe cadence; verdict TTL covers two
# cycles plus a probe timeout so a dead daemon reads as "no verdict"
HEALTH_INTERVAL = int(os.environ.get("BENCH_HEALTH_INTERVAL", "120"))
# probe-forensics caps (ISSUE 18): karpenter-namespaced knobs route through
# the audited envflags funnel (the BENCH_* spellings above predate it)
PROBE_FORENSIC_TAIL = int(envflags.raw("KARPENTER_PROBE_FORENSIC_TAIL", "2048"))

# (name, default worker budget seconds, ordered-after stages). The `needs`
# edges order the graph (a later stage reuses the round's shared compile
# cache its dependency populated); they are scheduling edges, not hard
# gates — a degraded dependency still lets the stage run and report
# honestly (warm_restart's cache_files count, multichip's mesh check).
STAGE_GRAPH = (
    ("headline", 2400, ()),
    ("pipelined", 900, ("headline",)),
    ("config5", 1200, ("headline",)),
    ("grid", 900, ()),
    ("multichip", 900, ("headline",)),
    ("consolidation", 600, ()),
    ("consolidation_xl", 1500, ("consolidation",)),
    ("warm_restart", 900, ("headline",)),
)
STAGE_NAMES = tuple(name for name, _, _ in STAGE_GRAPH)
# legacy skip-env spellings, honored by the planner (a skipped stage gets a
# completed {"skipped": ...} artifact so the merged schema stays full)
STAGE_SKIP_ENVS = {
    "pipelined": ("BENCH_SKIP_PIPELINED",),
    "config5": ("BENCH_SKIP_CONFIG5",),
    "grid": ("BENCH_SKIP_GRID",),
    "multichip": ("BENCH_SKIP_MULTICHIP",),
    "consolidation": ("BENCH_SKIP_CONSOLIDATION",),
    "consolidation_xl": ("BENCH_SKIP_CONS_XL", "BENCH_SKIP_CONSOLIDATION"),
    "warm_restart": ("BENCH_SKIP_WARM_RESTART",),
}


def _stage_timeout(name: str, default: int) -> int:
    return int(os.environ.get(f"BENCH_STAGE_TIMEOUT_{name.upper()}",
                              str(default)))


def _stage_chaos(name: str) -> str:
    """BENCH_STAGE_CHAOS grammar: `stage=<KARPENTER_CHAOS spec>` clauses
    joined by '|' — a chaos spec armed in exactly ONE stage's worker (the
    bench-smoke wedge drill arms solver.device.hang in one stage and
    proves the round survives it). Returns the spec for `name` or ''."""
    raw = os.environ.get("BENCH_STAGE_CHAOS", "")
    for clause in raw.split("|"):
        clause = clause.strip()
        if not clause:
            continue
        stage, _, spec = clause.partition("=")
        if stage.strip() == name:
            return spec.strip()
    return ""


# the worker-side heartbeat (set by stage_worker from BENCH_HEARTBEAT_FILE):
# touched at every progress point a stage makes — per measured run, per
# phase boundary via the solver's own supervise.touch_heartbeat hook — so
# the supervisor can tell a slow stage (alive, still touching) from a
# wedged one (silent)
_HB = None


def _touch(label=None):
    if _HB is not None:
        _HB.touch(label)
    supervise.touch_heartbeat(label)
    # heartbeat tick on the stage's own trace fragment (ISSUE 15): the
    # round timeline shows the worker's progress pulse between phase
    # spans, so a wedge's silent stretch is visible as a gap
    from karpenter_core_tpu.obs import TRACER

    TRACER.instant("bench.heartbeat", **({"label": label} if label else {}))


# cap on the chrome-trace fragment a stage worker ships in its artifact:
# newest events win (the tail names the work closest to the outcome/kill)
TIMELINE_STAGE_EVENTS = int(os.environ.get("BENCH_TIMELINE_EVENTS", "1500"))


def _trace_fragment():
    """The stage worker's bounded, WALL-ANCHORED chrome-trace fragment:
    events ride with a (wall_anchor_s, anchor_ts_us) pair so the round
    merge can rebase each worker's perf_counter timebase onto the shared
    wall clock — the only clock the stages and the orchestrator share."""
    from karpenter_core_tpu.obs import TRACER

    if not TRACER.enabled:
        return None
    trace = TRACER.chrome_trace()
    events = [e for e in trace["traceEvents"] if e.get("ph") != "M"]
    dropped = max(0, len(events) - TIMELINE_STAGE_EVENTS)
    return {
        "wall_anchor_s": time.time(),
        "anchor_ts_us": (time.perf_counter_ns() - TRACER._t0_ns) / 1e3,
        "pid": os.getpid(),
        "events": events[-TIMELINE_STAGE_EVENTS:],
        "dropped": dropped + int(trace["otherData"].get("dropped_spans", 0)),
    }

BACKEND_NOTE = ""
# each probe attempt's outcome, recorded into the final JSON's "extra" so a
# failed-then-rescued run leaves durable evidence in BENCH_r{N}.json itself
# (round-2 advisor finding: the rescue story was unverifiable after the fact)
PROBE_LOG = []


def ensure_backend():
    """Probe jax backend init in a SUBPROCESS (so a wedged/unavailable TPU
    can't poison this process — the axon tunnel is observed to HANG
    indefinitely, not just error), retrying with backoff; on exhaustion
    force the CPU backend so the bench still records a number.

    NOTE: the image's sitecustomize pins JAX_PLATFORMS=axon before any user
    code, so the env var cannot override the platform — only
    jax.config.update("jax_platforms", "cpu") after import works. This
    function therefore does the config.update in-process on fallback.
    Round-1 failure mode: rc=1 at 'Unable to initialize backend axon'."""
    global BACKEND_NOTE
    force_cpu = os.environ.get("BENCH_CPU", "") == "1"
    last_err = "forced by BENCH_CPU=1"
    if not force_cpu and os.environ.get("BENCH_SKIP_PROBE", "") == "1":
        # orchestrator already proved the backend is alive; just use it
        import jax

        BACKEND_NOTE = f"{jax.devices()[0].platform} {jax.devices()[0].device_kind}"
        print(f"[bench] backend (pre-probed by orchestrator): {BACKEND_NOTE}",
              file=sys.stderr)
        return
    if not force_cpu:
        for attempt in range(PROBE_RETRIES):
            ok, note = _probe_once(PROBE_TIMEOUT)
            if ok:
                BACKEND_NOTE = note
                PROBE_LOG.append(f"attempt {attempt + 1}: ok ({BACKEND_NOTE})"[:200])
                print(f"[bench] backend ok: {BACKEND_NOTE} (attempt {attempt + 1})",
                      file=sys.stderr)
                return
            last_err = note
            PROBE_LOG.append(f"attempt {attempt + 1}: FAILED ({last_err})"[:200])
            print(f"[bench] backend probe attempt {attempt + 1} failed: {last_err}",
                  file=sys.stderr)
            if attempt < PROBE_RETRIES - 1:
                time.sleep(min(30, 5 * (attempt + 1)))
    import jax

    jax.config.update("jax_platforms", "cpu")
    BACKEND_NOTE = f"cpu-fallback ({last_err})"
    PROBE_LOG.append(f"fallback: cpu ({last_err})"[:200])
    print(f"[bench] accelerator unavailable; running on CPU: {last_err}",
          file=sys.stderr)
    # shrink on involuntary fallback — including when the ORCHESTRATOR made
    # the fallback decision and signals it via BENCH_CPU_SHRINK (plain
    # BENCH_CPU=1 alone means a deliberate full-config CPU run)
    if not force_cpu or os.environ.get("BENCH_CPU_SHRINK", "") == "1":
        # shrink the involuntary-CPU workload so a wedged accelerator still
        # yields a recorded (clearly suffixed) number in minutes, not hours:
        # the 50k x 500 config is sized for the TPU, and the 2026-07-30
        # tunnel wedge showed the full config grinding past the driver's
        # patience on CPU
        global N_PODS, N_TYPES, N_RUNS, N_EXISTING, MAX_NODES
        global CONS_NODES, CONS_PODS
        N_PODS = min(N_PODS, 5000)
        N_TYPES = min(N_TYPES, 100)
        N_RUNS = min(N_RUNS, 6)
        N_EXISTING = min(N_EXISTING, 200)
        MAX_NODES = max(1024, N_PODS // 5 + 512)
        CONS_NODES = min(CONS_NODES, 100)
        CONS_PODS = min(CONS_PODS, 1000)
        print(
            f"[bench] cpu-fallback workload shrunk to {N_PODS}x{N_TYPES}, "
            f"{N_RUNS} runs",
            file=sys.stderr,
        )


def _existing_nodes(n: int, universe):
    """n initialized provisioned nodes over the type universe, 3 zones."""
    from karpenter_core_tpu.api.labels import (
        LABEL_CAPACITY_TYPE,
        LABEL_NODE_INITIALIZED,
        PROVISIONER_NAME_LABEL_KEY,
    )
    from karpenter_core_tpu.kube.objects import (
        LABEL_INSTANCE_TYPE_STABLE,
        LABEL_TOPOLOGY_ZONE,
    )
    from karpenter_core_tpu.state.node import StateNode
    from karpenter_core_tpu.testing import make_node

    nodes = []
    for i in range(n):
        it = universe[i % len(universe)]
        node = make_node(
            name=f"node-{i}",
            labels={
                PROVISIONER_NAME_LABEL_KEY: "default",
                LABEL_NODE_INITIALIZED: "true",
                LABEL_INSTANCE_TYPE_STABLE: it.name,
                LABEL_CAPACITY_TYPE: "on-demand",
                LABEL_TOPOLOGY_ZONE: f"test-zone-{1 + i % 3}",
            },
            capacity={k: str(v) for k, v in it.capacity.items()},
        )
        nodes.append(StateNode(node=node))
    return nodes


def _reference_mix(n_pods: int, n_types: int, distinct: int = 1, seed: int = 0,
                   universe=None):
    """The reference benchmark's diverse pod mix
    (scheduling_benchmark_test.go:187-199): 1/7 zonal topology spread,
    1/7 hostname spread, 2/7 pod affinity, 3/7 generic — the generic share
    split over `distinct` spec-equivalence classes so the per-item scan
    (not just the bulk-replica fast path) is what gets measured. `seed`
    varies the class labels so repeat runs are distinct workloads;
    `universe` reuses an instance-type list instead of building one."""
    from karpenter_core_tpu.cloudprovider import fake
    from karpenter_core_tpu.kube.objects import (
        LABEL_HOSTNAME,
        LABEL_TOPOLOGY_ZONE,
        LabelSelector,
        PodAffinityTerm,
        TopologySpreadConstraint,
    )
    from karpenter_core_tpu.testing import make_pod, make_provisioner

    zonal = TopologySpreadConstraint(
        max_skew=1,
        topology_key=LABEL_TOPOLOGY_ZONE,
        when_unsatisfiable="DoNotSchedule",
        label_selector=LabelSelector(match_labels={"app": "spread"}),
    )
    hostname = TopologySpreadConstraint(
        max_skew=1,
        topology_key=LABEL_HOSTNAME,
        when_unsatisfiable="DoNotSchedule",
        label_selector=LabelSelector(match_labels={"app": "hspread"}),
    )
    affinity = PodAffinityTerm(
        topology_key=LABEL_TOPOLOGY_ZONE,
        label_selector=LabelSelector(match_labels={"app": "aff"}),
    )
    pods = []
    for i in range(n_pods):
        kind = i % 7
        if kind == 0:
            pods.append(
                make_pod(labels={"app": "spread"}, requests={"cpu": "1"}, topology_spread=[zonal])
            )
        elif kind == 1:
            pods.append(
                make_pod(
                    labels={"app": "hspread"}, requests={"cpu": "1"}, topology_spread=[hostname]
                )
            )
        elif kind in (2, 3):
            pods.append(
                make_pod(
                    labels={"app": "aff"},
                    requests={"cpu": "1"},
                    pod_affinity_required=[affinity],
                )
            )
        else:
            pods.append(
                make_pod(
                    labels={"app": f"gen-{seed}-{i % max(distinct, 1)}"},
                    requests={"cpu": "1", "memory": "1Gi"},
                )
            )
    provisioners = [make_provisioner(name="default")]
    return pods, provisioners, {
        "default": universe if universe is not None else fake.instance_types(n_types)
    }


def _segmented_probe_workload(n_pods: int, distinct: int, pools: int,
                              seed: int, universe):
    """The PARTITIONABLE generic mix for the segmented-scan A/B (ISSUE 14):
    the _reference_mix generic share split across `pools` selector-scoped
    provisioners (per-team pools — the realistic multi-tenant shape). No
    topology families: those are structurally ineligible for segmentation
    and are measured by the headline mix itself."""
    from karpenter_core_tpu.testing import make_pod, make_pool_provisioners

    provisioners, its = make_pool_provisioners(pools, universe)
    pods = []
    for i in range(n_pods):
        p = i % pools
        pods.append(make_pod(
            labels={"app": f"seg-{seed}-{i % max(distinct, 1)}"},
            requests={"cpu": "1", "memory": "1Gi"},
            node_selector={"team": f"pool-{p}"},
        ))
    return pods, provisioners, its


def _segmented_ab(universe, n_pods: int, distinct: int, pairs: int = 3):
    """Same-host interleaved A/B: sequential vs segmented pack scan on the
    partitionable generic mix at the current (possibly CPU-shrunk)
    geometry. Returns the headline columns — segment_count,
    fixup_fraction, segmented_speedup — plus the per-mode device medians,
    measured PR 8-style (honest: the segmented window includes the
    partition + merge cost, and a 1-segment collapse reports speedup ~1.0
    with fixup 1.0 rather than hiding behind the fallback)."""
    from karpenter_core_tpu.obs.flightrec import (
        canonical_placements,
        placements_json,
    )
    from karpenter_core_tpu.solver.tpu_solver import TPUSolver

    pools = int(os.environ.get("BENCH_SEGMENT_POOLS", "8"))
    solver = TPUSolver(max_nodes=max(512, n_pods // 4 + 256))
    pods, provisioners, its = _segmented_probe_workload(
        n_pods, distinct, pools, 0, universe
    )
    import copy as _copy

    def run(mode, batch):
        solver.pack_scan = mode
        t0 = time.perf_counter()
        res = solver.solve(_copy.deepcopy(batch), provisioners, its)
        dt = (time.perf_counter() - t0) * 1e3
        ph = dict(solver.last_phase_ms)
        # the per-mode window is partition + lane dispatch + fetch + host
        # merge for segmented vs dispatch + fetch for sequential — the
        # merge and the partition are real per-solve costs sequential mode
        # never pays, so they stay inside the compared window
        dev = sum(
            ph.get(k, 0.0) for k in ("segment", "device", "fetch", "merge")
        )
        return res, dev, dt

    # warm both modes (compiles excluded from the timed pairs)
    res_seq, _, _ = run("sequential", pods)
    res_seg, _, _ = run("segmented", pods)
    stats = solver.last_segment_stats or {}
    identical = placements_json(canonical_placements(res_seq)) == (
        placements_json(canonical_placements(res_seg))
    )
    seq_dev, seg_dev = [], []
    for _r in range(pairs):
        _, d1, _ = run("sequential", pods)
        _, d2, _ = run("segmented", pods)
        seq_dev.append(d1)
        seg_dev.append(d2)
    seq_med = float(np.median(seq_dev))
    seg_med = float(np.median(seg_dev))
    return {
        "segment_count": int(stats.get("segments", 0)),
        "fixup_fraction": float(stats.get("fixup_fraction", 1.0)),
        "segmented_speedup": round(seq_med / seg_med, 3) if seg_med else None,
        "segmented_device_med_ms": round(seg_med, 1),
        "sequential_device_med_ms": round(seq_med, 1),
        "segmented_mode": stats.get("mode"),
        "segmented_identical": bool(identical),
        "segmented_pools": pools,
    }


def _config5_provisioners():
    """BASELINE config 5's control-plane shape: multiple weighted
    provisioners over spot+on-demand priced offerings — a high-weight
    spot-only pool tried first (weight ordering, provisioner.go:132-136)
    with the unrestricted on-demand-capable pool beneath it."""
    from karpenter_core_tpu.api.labels import LABEL_CAPACITY_TYPE
    from karpenter_core_tpu.kube.objects import NodeSelectorRequirement
    from karpenter_core_tpu.testing import make_provisioner

    spot_first = make_provisioner(
        name="spot-first",
        weight=100,
        requirements=[
            NodeSelectorRequirement(
                key=LABEL_CAPACITY_TYPE, operator="In", values=["spot"]
            )
        ],
    )
    default = make_provisioner(name="default", weight=10)
    return [spot_first, default]


def _config_grid_stage(kind: str):
    """Workload builders for BASELINE configs 1-3.

    1: 100 pods, CPU+mem requests only, 10 types (the reference bench's
       smallest cell, scheduling_benchmark_test.go:56-76)
    2: 5k pods with nodeSelector + taints/tolerations, 100 types, one
       provisioner (tainted pool + zone selectors)
    3: 20k pods with pod anti-affinity + topology-spread over 3 zones,
       200 types
    Returns (pods, provisioners, its, max_nodes). BENCH_GRID_SCALE shrinks
    pod counts (CPU smokes); type counts are kept."""
    scale = float(os.environ.get("BENCH_GRID_SCALE", "1"))

    def _gs(n):
        return max(64, int(n * scale))

    from karpenter_core_tpu.cloudprovider import fake
    from karpenter_core_tpu.kube.objects import (
        LABEL_TOPOLOGY_ZONE,
        LabelSelector,
        PodAffinityTerm,
        Taint,
        Toleration,
        TopologySpreadConstraint,
    )
    from karpenter_core_tpu.testing import make_pod, make_provisioner

    if kind == "config1":
        n_pods, n_types = _gs(100), 10
        pods = [
            make_pod(requests={"cpu": "1", "memory": "1Gi"})
            if i % 2
            else make_pod(requests={"cpu": "0.5", "memory": "2Gi"})
            for i in range(n_pods)
        ]
        provisioners = [make_provisioner(name="default")]
    elif kind == "config2":
        n_pods, n_types = _gs(5000), 100
        taint = Taint(key="dedicated", value="batch", effect="NoSchedule")
        tol = Toleration(key="dedicated", operator="Equal", value="batch")
        pods = []
        for i in range(n_pods):
            if i % 2:
                pods.append(
                    make_pod(
                        requests={"cpu": "1"},
                        node_selector={
                            LABEL_TOPOLOGY_ZONE: f"test-zone-{1 + i % 3}"
                        },
                        tolerations=[tol],
                    )
                )
            else:
                pods.append(
                    make_pod(requests={"cpu": "1", "memory": "1Gi"},
                             tolerations=[tol])
                )
        provisioners = [make_provisioner(name="default", taints=[taint])]
    elif kind == "config3":
        # 16 services whose replicas repel over hostname (the one-replica-
        # per-node pattern) + a zonal DoNotSchedule spread cohort + generic
        # filler. Group count is deliberately small: real clusters have a
        # handful of anti-affinity deployments, not thousands, and each
        # distinct selector is its own TopologyGroup/equivalence class.
        n_pods, n_types = _gs(20000), 200
        from karpenter_core_tpu.kube.objects import LABEL_HOSTNAME

        zonal = TopologySpreadConstraint(
            max_skew=1,
            topology_key=LABEL_TOPOLOGY_ZONE,
            when_unsatisfiable="DoNotSchedule",
            label_selector=LabelSelector(match_labels={"app": "spread"}),
        )
        n_groups = 16
        pods = []
        for i in range(n_pods):
            kind_i = i % 4
            if kind_i == 0:
                group = f"anti-{i % (4 * n_groups) // 4}"
                pods.append(
                    make_pod(
                        labels={"app": group},
                        requests={"cpu": "1"},
                        pod_anti_affinity_required=[
                            PodAffinityTerm(
                                topology_key=LABEL_HOSTNAME,
                                label_selector=LabelSelector(
                                    match_labels={"app": group}
                                ),
                            )
                        ],
                    )
                )
            elif kind_i == 1:
                pods.append(
                    make_pod(labels={"app": "spread"}, requests={"cpu": "1"},
                             topology_spread=[zonal])
                )
            else:
                pods.append(
                    make_pod(requests={"cpu": "1", "memory": "1Gi"})
                )
        provisioners = [make_provisioner(name="default")]
    else:
        raise ValueError(kind)
    its = {p.name: fake.instance_types(n_types) for p in provisioners}
    # node budget sized to the cell, not the 50k headline: an oversized node
    # axis taxes every [N]-wide op and would dominate the smallest cell
    return pods, provisioners, its, max(128, n_pods // 3 + 64)


def consolidation_bench(emit: bool = True, n_nodes: int = None,
                        n_pods: int = None, n_types: int = None):
    """Config 4 analog: n_nodes under-utilized nodes, n_pods running pods,
    full multi-node replan — the batched candidate-subset evaluator
    (solver/replan.py: one union encode + one vmapped device dispatch
    screening every ladder rung, ranked by the savings objective),
    replacing multinodeconsolidation.go:87-113's sequential binary search.
    Timed region: the whole first_n_consolidation_ladder, steady-state
    (compiled programs cached). Returns a result dict with the ISSUE 10
    first-class columns (replan_med_ms, candidates_per_sec,
    consolidation_under_1s, replan per-phase spans); emit=True also prints
    the standalone JSON line."""
    from karpenter_core_tpu.api.labels import (
        LABEL_CAPACITY_TYPE,
        LABEL_NODE_INITIALIZED,
        PROVISIONER_NAME_LABEL_KEY,
    )
    from karpenter_core_tpu.api.settings import Settings
    from karpenter_core_tpu.cloudprovider import fake
    from karpenter_core_tpu.controllers.deprovisioning.core import candidate_nodes
    from karpenter_core_tpu.kube.objects import LABEL_INSTANCE_TYPE_STABLE, LABEL_TOPOLOGY_ZONE
    from karpenter_core_tpu.operator import new_operator
    from karpenter_core_tpu.solver.tpu_solver import TPUSolver
    from karpenter_core_tpu.testing import FakeClock, make_node, make_pod, make_provisioner

    n_nodes = n_nodes or CONS_NODES
    n_pods = n_pods or CONS_PODS
    n_types = n_types or CONS_TYPES

    clock = FakeClock()
    universe = fake.instance_types(n_types)
    cp = fake.FakeCloudProvider(universe)
    # slot budget: existing nodes get their own slots on top; the machine
    # region only needs headroom for the handful of replacement opens a
    # replan can produce — oversizing it taxes every [N]-wide op at the
    # 10k-node geometry
    solver = TPUSolver(max_nodes=min(max(1024, n_pods // 4), 4096))
    op = new_operator(cp, settings=Settings(), solver=solver, clock=clock)
    op.kube_client.create(make_provisioner(name="default", consolidation_enabled=True))

    pods_per_node = max(1, n_pods // n_nodes)
    t0 = time.perf_counter()
    for n in range(n_nodes):
        it = universe[n % len(universe)]
        name = f"node-{n}"
        node = make_node(
            name=name,
            labels={
                PROVISIONER_NAME_LABEL_KEY: "default",
                LABEL_NODE_INITIALIZED: "true",
                LABEL_INSTANCE_TYPE_STABLE: it.name,
                LABEL_CAPACITY_TYPE: "on-demand",
                LABEL_TOPOLOGY_ZONE: f"test-zone-{1 + n % 3}",
            },
            capacity={k: str(v) for k, v in it.capacity.items()},
        )
        op.kube_client.create(node)
        for _ in range(pods_per_node):
            pod = make_pod(requests={"cpu": "0.1"}, node_name=name, unschedulable=False)
            pod.status.phase = "Running"
            op.kube_client.create(pod)
    op.sync_state()
    _touch()  # state sync done: the stage is alive, not wedged
    setup_s = time.perf_counter() - t0

    multi = next(
        d for d in op.deprovisioning.deprovisioners
        if type(d).__name__ == "MultiNodeConsolidation"
    )
    multi.validation_ttl = 0.0

    def replan():
        candidates = multi.sort_and_filter_candidates(
            candidate_nodes(
                op.cluster, op.kube_client, cp, multi.should_deprovision, clock
            )
        )
        return candidates, multi.first_n_consolidation_ladder(candidates)

    t0 = time.perf_counter()
    candidates, cmd = replan()
    warm_s = time.perf_counter() - t0
    times = []
    for _ in range(4):
        _touch()
        t0 = time.perf_counter()
        candidates, cmd = replan()
        times.append(time.perf_counter() - t0)
    replan_s = float(np.median(times)) if times else warm_s

    total_pods = n_nodes * pods_per_node
    pods_per_sec = total_pods / replan_s
    candidates_per_sec = len(candidates) / replan_s if replan_s else 0.0
    under_1s = bool(replan_s < 1.0)
    phases = dict(getattr(solver, "last_replan_phase_ms", {}) or {})
    print(
        f"[bench] consolidation nodes={n_nodes} pods={total_pods} "
        f"types={n_types} candidates={len(candidates)} action={cmd.action} "
        f"removed={len(cmd.nodes_to_remove)} setup={setup_s:.1f}s "
        f"warm={warm_s:.1f}s replan_med={replan_s * 1e3:.1f}ms "
        f"candidates_per_sec={candidates_per_sec:.1f} under_1s={under_1s} "
        f"phases={phases}",
        file=sys.stderr,
    )
    result = {
        "nodes": n_nodes,
        "pods": total_pods,
        "types": n_types,
        "candidates": len(candidates),
        "action": str(cmd.action),
        "removed": len(cmd.nodes_to_remove),
        "replan_med_ms": round(replan_s * 1e3, 1),
        "warm_s": round(warm_s, 1),
        "pods_per_sec": round(pods_per_sec, 1),
        "candidates_per_sec": round(candidates_per_sec, 1),
        "consolidation_under_1s": under_1s,
        "replan_phases_ms": phases,
    }
    if emit:
        suffix = "_cpu_fallback" if BACKEND_NOTE.startswith("cpu-fallback") else ""
        print(
            json.dumps(
                {
                    "metric": (
                        f"consolidation_replan_pods_per_sec_{n_nodes}nodes_"
                        f"{total_pods}pods{suffix}"
                    ),
                    "value": round(pods_per_sec, 1),
                    "unit": "pods/sec",
                    "vs_baseline": round(pods_per_sec / 100.0, 2),
                    "extra": {
                        "backend_probe": PROBE_LOG,
                        "replan_med_ms": result["replan_med_ms"],
                        "candidates_per_sec": result["candidates_per_sec"],
                        "consolidation_under_1s": under_1s,
                        "replan_phases_ms": phases,
                    },
                }
            )
        )
    return result


def consolidation_xl_stage(budget_fn=_worker_time_left):
    """The exit-criterion geometry (CONS_XL_NODES x CONS_XL_PODS), shed by
    worker budget like the grid stages — but ALWAYS returns a dict with
    the geometry + consolidation_under_1s column so the bench artifact
    records the stage even when the host couldn't afford the run."""
    stub = {
        "nodes": CONS_XL_NODES,
        "pods": CONS_XL_PODS,
        "consolidation_under_1s": False,
    }
    if os.environ.get("BENCH_SKIP_CONS_XL", "") == "1":
        return dict(stub, skipped="BENCH_SKIP_CONS_XL=1")
    if budget_fn() < CONS_XL_MIN_BUDGET:
        print(
            "[bench] consolidation XL skipped: worker budget low",
            file=sys.stderr,
        )
        return dict(stub, skipped="worker budget low")
    try:
        return consolidation_bench(
            emit=False, n_nodes=CONS_XL_NODES, n_pods=CONS_XL_PODS,
        )
    except BaseException as exc:  # noqa: BLE001 — still record the stage
        import traceback

        traceback.print_exc()
        return dict(stub, error=f"{type(exc).__name__}: {exc}"[:200])


def sweep():
    """Per-item scan cost curve (round-2 verdict: 'measure 2-3 points on
    the item axis to establish the actual scaling'): device-solve median
    at N_PODS x N_TYPES for each distinct-spec count in BENCH_SWEEP, one
    JSON line with the full curve. Items scale with distinct specs, so
    this isolates the scan's sequential-axis cost from the bulk-replica
    fast path."""
    import jax

    from karpenter_core_tpu.cloudprovider import fake
    from karpenter_core_tpu.solver.encode import encode_snapshot
    from karpenter_core_tpu.solver.tpu_solver import build_device_solve, device_args

    universe = fake.instance_types(N_TYPES)
    points = []
    for distinct in SWEEP_DISTINCT:
        pods, provisioners, its = _reference_mix(
            N_PODS, N_TYPES, distinct, seed=0, universe=universe
        )
        nodes = _existing_nodes(N_EXISTING, universe)
        snap = encode_snapshot(
            pods, provisioners, its, None, nodes, max_nodes=MAX_NODES
        )
        args = jax.device_put(device_args(snap, provisioners))
        _, run = build_device_solve(snap, max_nodes=MAX_NODES)
        fn = jax.jit(run)
        out = fn(*args)
        jax.block_until_ready(out)
        dts = []
        for _ in range(3):
            t0 = time.perf_counter()
            out = fn(*args)
            jax.block_until_ready(out)
            dts.append(time.perf_counter() - t0)
        items = len(snap.item_counts)
        ms = float(np.median(dts)) * 1e3
        points.append({"distinct": distinct, "items": items,
                       "device_ms": round(ms, 1)})
        print(f"[bench] sweep distinct={distinct} items={items} "
              f"device={ms:.0f}ms", file=sys.stderr)
        del out, args
    # marginal per-item cost from the curve's endpoints
    d_items = points[-1]["items"] - points[0]["items"]
    per_item_us = (
        (points[-1]["device_ms"] - points[0]["device_ms"]) / d_items * 1e3
        if d_items
        else 0.0
    )
    suffix = "_cpu_fallback" if BACKEND_NOTE.startswith("cpu-fallback") else ""
    print(
        json.dumps(
            {
                "metric": f"item_axis_sweep_device_ms_{N_PODS}pods_{N_TYPES}types{suffix}",
                "value": points[-1]["device_ms"],
                "unit": "ms",
                "vs_baseline": round(
                    (N_PODS / (points[-1]["device_ms"] / 1e3)) / 100.0, 2
                ),
                "extra": {
                    "points": points,
                    "marginal_us_per_item": round(per_item_us, 1),
                    "backend_probe": PROBE_LOG,
                },
            }
        )
    )


def _enable_stage_cache() -> str:
    """Tracing + the round-shared persistent compile cache: every stage
    worker of one round (and a --resume of it) reloads the same compiled
    programs from disk instead of re-paying the cold compile per process.
    Returns the cache dir in use."""
    import tempfile

    from karpenter_core_tpu.obs import TRACER
    from karpenter_core_tpu.utils.compilecache import enable_persistent_cache

    # solve-path tracing ON: the phase breakdown reads from the SAME tracer
    # spans production exports (ISSUE 1 — bench and production report
    # identical numbers instead of bench-private timers)
    TRACER.enable()
    cache_dir = os.environ.get("BENCH_COMPILE_CACHE_DIR") or tempfile.mkdtemp(
        prefix="kct-xla-cache-"
    )
    enable_persistent_cache(cache_dir)
    return cache_dir


def _worker_ctx():
    """Shared stage-worker setup: cache + tracer + the PRODUCTION solver
    factory (one chip -> TPUSolver, a multi-chip process -> ShardedSolver
    over the dp×tp mesh) + the headline workload builder."""
    from types import SimpleNamespace

    import jax

    from karpenter_core_tpu.cloudprovider import fake
    from karpenter_core_tpu.solver.factory import build_solver, describe

    cache_dir = _enable_stage_cache()
    universe = fake.instance_types(N_TYPES)
    solver = build_solver(max_nodes=MAX_NODES)
    solver_desc = describe(solver)
    print(f"[bench] solver: {solver_desc}", file=sys.stderr)

    def workload(n_pods, n_existing, seed):
        pods, provisioners, its = _reference_mix(
            n_pods, N_TYPES, N_DISTINCT, seed=seed, universe=universe
        )
        return pods, provisioners, its, _existing_nodes(n_existing, universe)

    return SimpleNamespace(
        jax=jax, solver=solver, solver_desc=solver_desc, universe=universe,
        workload=workload, cache_dir=cache_dir,
    )


def _warm_buckets(ctx, seed_base: int = 0):
    """Warm the two pod-axis buckets the varied sizes land in (untimed):
    resumed/satellite stages reload the headline stage's compiled programs
    from the round's shared disk cache here."""
    pods, provisioners, its, nodes = ctx.workload(N_PODS, N_EXISTING, seed_base)
    ctx.solver.solve(pods, provisioners, its, state_nodes=nodes)
    _touch()
    pods, provisioners, its, nodes = ctx.workload(
        int(N_PODS * 0.8), N_EXISTING, seed_base + 1
    )
    ctx.solver.solve(pods, provisioners, its, state_nodes=nodes)
    _touch()


def stage_headline():
    """The chartered single-call measurement: cold compile, device-only
    median, and the varied-batch e2e p50/p99 loop at the north-star
    geometry. Produces the columns the merged artifact's top-level metric
    derives from."""
    from karpenter_core_tpu.obs import TRACER
    from karpenter_core_tpu.solver.encode import encode_snapshot
    from karpenter_core_tpu.solver.tpu_solver import build_device_solve, device_args

    ctx = _worker_ctx()
    jax, solver, workload = ctx.jax, ctx.solver, ctx.workload
    solver_desc = ctx.solver_desc

    # -- warm the compiled program for the bucket geometry ----------------
    t0 = time.perf_counter()
    pods, provisioners, its, nodes = workload(N_PODS, N_EXISTING, 0)
    gen_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    res = solver.solve(pods, provisioners, its, state_nodes=nodes)
    cold_s = time.perf_counter() - t0
    _touch()  # cold compile survived: the longest legit heartbeat gap
    scheduled = res.pod_count_new() + res.pod_count_existing()
    print(
        f"[bench] device={jax.devices()[0].device_kind} cold={cold_s:.1f}s "
        f"gen={gen_s:.1f}s scheduled={scheduled}/{N_PODS} "
        f"existing_used={res.pod_count_existing()} failed={len(res.failed_pods)}",
        file=sys.stderr,
    )

    # second warm at a smaller size: the post-solve fetch slices bucket by
    # outcome (ptr/nopen), and the first solve at a new bucket combo pays
    # small one-time compiles — warm them out of the timed region
    pods2, provisioners2, its2, nodes2 = workload(int(N_PODS * 0.8), N_EXISTING, 1)
    solver.solve(pods2, provisioners2, its2, state_nodes=nodes2)

    # the production processes' long-lived-server GC tuning (the operator
    # applies the same call at startup — utils/gctuning.py), here applied
    # after warmup so the frozen baseline covers the compiled programs
    from karpenter_core_tpu.utils.gctuning import apply_server_gc_tuning

    apply_server_gc_tuning()

    # device-only time at the headline config (r01/r02-comparable region)
    snap = encode_snapshot(pods, provisioners, its, None, nodes, max_nodes=MAX_NODES)
    args = jax.device_put(device_args(snap, provisioners))
    _, run = build_device_solve(snap, max_nodes=MAX_NODES)
    fn = jax.jit(run)
    out = fn(*args)
    jax.block_until_ready(out)
    dts = []
    for _ in range(3):
        _touch()
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        dts.append(time.perf_counter() - t0)
    device_ms = float(np.median(dts)) * 1e3
    del out, args

    # -- p99 across varied batch sizes (same bucket => compiled-cache hits,
    # the production steady state; each solve is a FRESH workload) --------
    # bucket_hit_ratio: executable-cache hits over lookups across the timed
    # varied-batch loop — under the geometry bucket ladder this must be
    # ~1.0 (every varied size lands on an already-compiled tier); a sag is
    # the cold-start/bucketing regression this column exists to catch
    from karpenter_core_tpu.utils.compilecache import CACHE_HITS, CACHE_MISSES

    def _lookup_totals():
        sites = ("tpu_solver", "service", "service_sharded")
        return (
            sum(CACHE_HITS.get({"site": s}) or 0.0 for s in sites),
            sum(CACHE_MISSES.get({"site": s}) or 0.0 for s in sites),
        )

    hits0, misses0 = _lookup_totals()
    rng = np.random.default_rng(7)
    times = []
    device_times = []
    sched_counts = []
    run_phases = []  # per-run phase breakdown: attributes the p50->p99 tail
    for r in range(N_RUNS):
        n_pods = int(N_PODS * (0.8 + 0.25 * rng.random()))  # 40k..52.5k
        n_exist = int(N_EXISTING * (0.88 + 0.12 * rng.random()))  # same E bucket
        pods, provisioners, its, nodes = workload(n_pods, n_exist, r)
        # collect the WORKLOAD GENERATOR's garbage outside the timed window:
        # a major GC scanning the 50k fresh pod objects lands inside random
        # solves otherwise, turning p99 into a GC artifact (observed +1.3s
        # spikes with normal device time). Solve-generated garbage still
        # lands in the timed region.
        import gc

        gc.collect()
        _touch()  # one heartbeat per measured run
        seq = TRACER.mark()
        t0 = time.perf_counter()
        res = solver.solve(pods, provisioners, its, state_nodes=nodes)
        dt = time.perf_counter() - t0
        times.append(dt)
        device_times.append(getattr(solver, "last_device_ms", 0.0))
        # phase breakdown from the TRACER's solver.phase.* spans — the same
        # spans production exports to /debug/trace. Keys match the
        # historical artifact (args/pack/upload/device/fetch/other_host);
        # the tracer's extra encode/bind spans fold into other_host, and
        # last_only reproduces the old timers' last-relax-round-wins
        # semantics, so BENCH_r* comparisons stay apples-to-apples.
        tr_phases = TRACER.phase_ms_since(seq, last_only=True)
        phases = {
            k: tr_phases.get(k, 0.0)
            for k in ("args", "pack", "upload", "prescreen", "device",
                      "fetch", "encode", "bind")
        }
        # everything solve() spent outside the instrumented phases —
        # relaxation bookkeeping and result accounting only, now that
        # encode/bind (and the prescreen dispatch) carry their own columns
        phases["other_host"] = round(dt * 1e3 - sum(phases.values()), 1)
        run_phases.append(phases)
        sched_counts.append(res.pod_count_new() + res.pod_count_existing())
        print(
            f"[bench] run {r + 1}/{N_RUNS}: pods={n_pods} nodes={n_exist} "
            f"solve={dt * 1e3:.0f}ms device={device_times[-1]:.0f}ms "
            f"scheduled={sched_counts[-1]} phases={phases}",
            file=sys.stderr,
        )
    ts = np.sort(np.array(times))
    p50 = float(np.percentile(ts, 50))
    p99 = float(np.percentile(ts, 99))
    # same-run histogram + the slowest run's phase attribution: the tail
    # must be explainable from the artifact itself (PERF.md section)
    worst = int(np.argmax(times))
    median_run = int(np.argsort(times)[len(times) // 2])
    tail_attrib = {
        "e2e_sorted_ms": [round(t * 1e3, 1) for t in ts.tolist()],
        "p99_run_phases": run_phases[worst],
        "p50_run_phases": run_phases[median_run],
    }
    dev_p50 = float(np.percentile(device_times, 50))
    dev_p99 = float(np.percentile(device_times, 99))
    compiled = len(solver._compiled)
    hits1, misses1 = _lookup_totals()
    lookups = (hits1 - hits0) + (misses1 - misses0)
    bucket_hit_ratio = round((hits1 - hits0) / lookups, 3) if lookups else None
    pods_per_sec = N_PODS / p99  # pods/sec at the p99 latency, headline size

    # segmented-scan A/B (ISSUE 14): first-class headline columns, measured
    # on the partitionable generic mix at this round's geometry so a
    # resumed TPU round backfills them in the same artifact. Budget-shed
    # like the optional stages — the columns always appear (null on shed).
    seg_cols = {
        "segment_count": None, "fixup_fraction": None,
        "segmented_speedup": None,
    }
    if _worker_time_left() > 180 and os.environ.get(
        "BENCH_SKIP_SEGMENTED", ""
    ) != "1":
        try:
            _touch()
            seg_cols = _segmented_ab(universe=ctx.universe,
                                     n_pods=N_PODS, distinct=N_DISTINCT)
            print(f"[bench] segmented A/B: {seg_cols}", file=sys.stderr)
        except Exception as exc:  # noqa: BLE001 — a probe failure costs
            # only these columns, never the headline numbers
            seg_cols["segmented_error"] = f"{type(exc).__name__}: {exc}"
            print(f"[bench] segmented A/B failed: {exc}", file=sys.stderr)
    print(
        f"[bench] e2e p50={p50 * 1e3:.0f}ms p99={p99 * 1e3:.0f}ms "
        f"device_med={device_ms:.0f}ms compiled_programs={compiled}",
        file=sys.stderr,
    )
    return {
        "pods": N_PODS,
        "types": N_TYPES,
        "distinct": N_DISTINCT,
        "existing": N_EXISTING,
        "pods_per_sec": round(pods_per_sec, 1),
        "e2e_p50_ms": round(p50 * 1e3, 1),
        "e2e_p99_ms": round(p99 * 1e3, 1),
        "device_solve_med_ms": round(device_ms, 1),
        "device_p50_ms_varied": round(dev_p50, 1),
        "device_p99_ms_varied": round(dev_p99, 1),
        "runs": N_RUNS,
        "tail": tail_attrib,
        "scheduled_min": int(min(sched_counts)),
        "compile_cold_s": round(cold_s, 1),
        "bucket_hit_ratio": bucket_hit_ratio,
        "compiled_programs_after_varied_batches": compiled,
        "solver": solver_desc,
        "chips": len(jax.devices()),
        "cpu_fallback": BACKEND_NOTE.startswith("cpu-fallback"),
        **seg_cols,
    }


def stage_pipelined():
    """PIPELINED steady state: the production loop overlaps the NEXT
    batch's encode with the current solve's device window (the host is
    idle in device_get), so steady-state Solve latency drops by ~the
    encode slice. Its own stage so a wedge here costs only the pipelined
    column, never the headline single-call number.

    Only ENCODE runs on the worker thread: in production the pods already
    exist (watch cache) — generating 50k Python pod objects is a bench
    artifact, and doing it on the worker during the timed solve starved
    the main thread's host-side fetch/decode of the GIL (first measured
    TPU run: pipelined p50 1.97s vs plain 1.44s). Generation happens on
    the MAIN thread between timed windows; encode (numpy-heavy,
    GIL-releasing) is what overlaps the device window, which is the
    production overlap being measured."""
    from karpenter_core_tpu.utils.gctuning import apply_server_gc_tuning

    ctx = _worker_ctx()
    solver, workload = ctx.solver, ctx.workload
    _warm_buckets(ctx)
    apply_server_gc_tuning()
    rng = np.random.default_rng(7)

    def pipe_gen(r):
        n_pods = int(N_PODS * (0.8 + 0.25 * rng.random()))
        n_exist = int(N_EXISTING * (0.88 + 0.12 * rng.random()))
        return workload(n_pods, n_exist, 1000 + r)

    pipe_times = _pipelined_loop(
        N_RUNS,
        pipe_gen,
        lambda b: solver.encode(b[0], b[1], b[2], state_nodes=b[3]),
        lambda b, snap: solver.solve(
            b[0], b[1], b[2], state_nodes=b[3], encoded=snap
        ),
        "pipelined",
    )
    pipe_p50 = float(np.percentile(pipe_times, 50)) if pipe_times else 0.0
    pipe_p99 = float(np.percentile(pipe_times, 99)) if pipe_times else 0.0
    return {
        "pipelined_p50_ms": round(pipe_p50 * 1e3, 1),
        "pipelined_p99_ms": round(pipe_p99 * 1e3, 1),
        "pipelined_runs": len(pipe_times),
        "cpu_fallback": BACKEND_NOTE.startswith("cpu-fallback"),
    }


def stage_config5():
    """Config 5 (BASELINE.json): 50k pods, spot+on-demand price-weighted,
    multi-Provisioner — same pod mix solved against TWO weighted pools
    (spot-only weight 100 over the default pool). New template geometry
    => its own compile, warmed out of the timed region."""
    import gc as _gc

    from karpenter_core_tpu.utils.gctuning import apply_server_gc_tuning

    ctx = _worker_ctx()
    solver, workload = ctx.solver, ctx.workload
    apply_server_gc_tuning()
    rng = np.random.default_rng(9)
    c5_provs = _config5_provisioners()
    # full headline sample size (verdict r4 weak #4: 5 runs was too
    # thin next to 20 for the headline)
    c5_runs = N_RUNS
    c5_times = []
    c5_sched = []
    # warm BOTH pod-axis buckets the varied sizes can land in (the
    # headline loop does the same): the 2-template geometry compiles
    # its own programs
    for frac in (1.0, 0.8):
        pods, _, its, nodes = workload(
            int(N_PODS * frac), N_EXISTING, 2999
        )
        its = {p.name: its["default"] for p in c5_provs}
        solver.solve(pods, c5_provs, its, state_nodes=nodes)
        _touch()

    def c5_gen(r):
        n_pods = int(N_PODS * (0.8 + 0.25 * rng.random()))
        n_exist = int(N_EXISTING * (0.88 + 0.12 * rng.random()))
        pods, _, its, nodes = workload(n_pods, n_exist, 3000 + r)
        its = {p.name: its["default"] for p in c5_provs}
        return pods, its, nodes

    for r in range(c5_runs):
        pods, its, nodes = c5_gen(r)
        _gc.collect()
        _touch()
        t0 = time.perf_counter()
        res = solver.solve(pods, c5_provs, its, state_nodes=nodes)
        dt = time.perf_counter() - t0
        c5_times.append(dt)
        c5_sched.append(res.pod_count_new() + res.pod_count_existing())
        print(
            f"[bench] config5 {r + 1}/{c5_runs}: pods={len(pods)} "
            f"solve={dt * 1e3:.0f}ms scheduled={c5_sched[-1]}",
            file=sys.stderr,
        )
    # the same encode-overlap treatment as the headline: the NEXT
    # batch's encode rides the current solve's device window
    c5_pipe = _pipelined_loop(
        c5_runs,
        lambda r: c5_gen(500 + r),
        lambda b: solver.encode(b[0], c5_provs, b[1], state_nodes=b[2]),
        lambda b, snap: solver.solve(
            b[0], c5_provs, b[1], state_nodes=b[2], encoded=snap
        ),
        "config5 pipelined",
    )
    return {
        "provisioners": len(c5_provs),
        "e2e_p50_ms": round(float(np.percentile(c5_times, 50)) * 1e3, 1),
        "e2e_p99_ms": round(float(np.percentile(c5_times, 99)) * 1e3, 1),
        "pipelined_p50_ms": round(
            float(np.percentile(c5_pipe, 50)) * 1e3, 1
        ),
        "pipelined_p99_ms": round(
            float(np.percentile(c5_pipe, 99)) * 1e3, 1
        ),
        "runs": len(c5_times),
        "scheduled_min": int(min(c5_sched)),
    }


def stage_consolidation():
    """Config 4 analog as its own stage (chartered; r03 lacked a TPU
    artifact for it): the batched replan at the default geometry."""
    _enable_stage_cache()
    return consolidation_bench(emit=False)


def stage_consolidation_xl():
    """The exit-criterion geometry (10k nodes / 100k pods): shed by the
    stage's own worker budget, but the column + geometry always land."""
    _enable_stage_cache()
    return consolidation_xl_stage()


def stage_grid():
    """BASELINE configs 1-3: the chartered scaling grid's remaining rungs,
    each its own geometry (own compile, warmed out of the timed region)
    and its own right-sized solver instance."""
    import gc as _gc

    from karpenter_core_tpu.solver.tpu_solver import TPUSolver

    if N_PODS < 20000 and os.environ.get("BENCH_FORCE_GRID", "") != "1":
        # shrunk (wedge-fallback) runs skip the grid; FORCE for smokes
        return {"skipped": f"shrunk workload ({N_PODS} pods)"}
    _enable_stage_cache()
    grid = {}
    for kind in ("config1", "config2", "config3"):
        if _worker_time_left() < 120:
            grid[kind] = {"skipped": "worker budget low"}
            print(f"[bench] {kind} skipped: worker budget low",
                  file=sys.stderr)
            continue
        try:
            g_times = []
            g_sched = []
            # deterministic workload (no rng input): build once, reuse
            # across rounds — solve never mutates caller objects
            pods, provs, its, g_nodes = _config_grid_stage(kind)
            # the PRODUCTION Solve() path: ResilientSolver routes
            # small batches (pods x types work product) to the serial
            # FFD, where the device path's fixed encode/transfer cost
            # would dominate — config 1 measures the routed path, the
            # larger rungs pass straight through to the device solver
            from karpenter_core_tpu.solver.fallback import ResilientSolver
            from karpenter_core_tpu.solver.tpu_solver import GreedySolver

            stage_solver = ResilientSolver(
                TPUSolver(max_nodes=g_nodes), GreedySolver(),
                prober=lambda: None,
            )
            g_pods = len(pods)
            for r in range(5):
                _gc.collect()
                _touch()
                t0 = time.perf_counter()
                res = stage_solver.solve(pods, provs, its)
                dt = time.perf_counter() - t0
                if r == 0:
                    continue  # geometry compile warmup
                g_times.append(dt)
                g_sched.append(
                    res.pod_count_new() + res.pod_count_existing()
                )
            g_p99 = float(np.percentile(g_times, 99))
            # record WHICH path served the rung: under BENCH_GRID_SCALE
            # shrinks, rungs above config 1 can fall below the routing
            # work product too — the artifact must say what it measured
            # (the solver's own predicate, so the label cannot drift)
            routed = stage_solver._small_batch(pods, its)
            grid[kind] = {
                "pods": g_pods,
                "e2e_p50_ms": round(
                    float(np.percentile(g_times, 50)) * 1e3, 1
                ),
                "e2e_p99_ms": round(g_p99 * 1e3, 1),
                # p99-based, comparable with the headline metric and the
                # reference's 100 pods/sec floor
                "pods_per_sec": round(g_pods / g_p99, 1),
                "scheduled_min": int(min(g_sched)),
                "path": "host_ffd_routed" if routed else "device",
            }
            print(
                f"[bench] {kind}: pods={g_pods} "
                f"p50={grid[kind]['e2e_p50_ms']}ms "
                f"p99={grid[kind]['e2e_p99_ms']}ms "
                f"scheduled_min={grid[kind]['scheduled_min']}",
                file=sys.stderr,
            )
        except BaseException as exc:  # noqa: BLE001 — record and move on
            import traceback

            traceback.print_exc()
            grid[kind] = {"error": f"{type(exc).__name__}: {exc}"[:200]}
    return grid


def stage_warm_restart():
    """Warm restart from the round's persistent compile cache: a stage
    worker is ALREADY a fresh process, so this stage simply solves the
    headline geometry against the disk cache the headline stage populated
    and times the first Solve() — the restart stall a redeployed solver
    actually pays (verdict r4 weak #3: 125s cold with no mitigation). The
    merge step validates platform + pods against the headline artifact so
    a CPU-fallback or shrunk worker cannot masquerade as the TPU restart
    stall."""
    t_boot = time.perf_counter()
    from karpenter_core_tpu.cloudprovider import fake
    from karpenter_core_tpu.solver.factory import build_solver

    cache_dir = _enable_stage_cache()
    # cache verification for the restart claim: count the persistent-cache
    # entries the headline stage populated — zero files means this worker
    # measures a COLD compile, not the warm-restart stall, and the merge
    # labels it so
    try:
        cache_files = len([
            f for f in os.listdir(cache_dir) if not f.startswith(".")
        ])
    except OSError:
        cache_files = 0
    universe = fake.instance_types(N_TYPES)
    pods, provisioners, its = _reference_mix(
        N_PODS, N_TYPES, N_DISTINCT, seed=0, universe=universe
    )
    nodes = _existing_nodes(N_EXISTING, universe)
    solver = build_solver(max_nodes=MAX_NODES)
    gen_s = time.perf_counter() - t_boot
    _touch()
    t0 = time.perf_counter()
    res = solver.solve(pods, provisioners, its, state_nodes=nodes)
    first_solve_s = time.perf_counter() - t0
    import jax

    return {
        "first_solve_s": round(first_solve_s, 1),
        "total_restart_s": round(time.perf_counter() - t_boot, 1),
        "workload_gen_s": round(gen_s, 1),
        "cache_files": cache_files,
        "scheduled": res.pod_count_new() + res.pod_count_existing(),
        # the merge validates these against the headline artifact: a
        # CPU-fallback or shrunk worker must not masquerade as the TPU
        # restart stall
        "platform": jax.devices()[0].platform,
        "pods": N_PODS,
    }


def stage_multichip():
    """Multichip same-host A/B (ISSUE 8): when the factory serves the
    GSPMD mesh path, measure `sharded_speedup` = warm single-device wall
    over warm mesh wall on the SAME headline batch, assert the placements
    are byte-identical, and record the mesh shape + the mesh path's
    per-phase timings. On a single-device worker (incl. every CPU-fallback
    worker) the stage completes as skipped — the column always lands."""
    from karpenter_core_tpu.solver.tpu_solver import TPUSolver

    ctx = _worker_ctx()
    solver, workload = ctx.solver, ctx.workload
    if getattr(solver, "mesh", None) is None:
        return {"skipped": "single-device worker (no mesh)"}
    from karpenter_core_tpu.obs.flightrec import (
        canonical_placements,
        placements_json,
    )

    mc_single = TPUSolver(max_nodes=MAX_NODES)
    pods, provisioners, its, nodes = workload(N_PODS, N_EXISTING, 4242)

    def _mc_run(s):
        return s.solve(
            pods, provisioners, its,
            state_nodes=[n.deep_copy() for n in nodes],
        )

    res_m = _mc_run(solver)  # mesh compile (or round-cache reload)
    _touch()
    res_s = _mc_run(mc_single)  # pays the single-path compile
    _touch()
    identical = placements_json(
        canonical_placements(res_m)
    ) == placements_json(canonical_placements(res_s))
    m_ts, s_ts = [], []
    for _ in range(3):  # interleaved warm A/B
        _touch()
        t0 = time.perf_counter()
        _mc_run(solver)
        m_ts.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        _mc_run(mc_single)
        s_ts.append(time.perf_counter() - t0)
    mesh = solver.mesh
    multichip = {
        "mesh_dp": int(mesh.shape["dp"]),
        "mesh_tp": int(mesh.shape["tp"]),
        "path": solver.last_path,
        "sharded_ms": round(min(m_ts) * 1e3, 1),
        "single_ms": round(min(s_ts) * 1e3, 1),
        "sharded_speedup": round(min(s_ts) / max(min(m_ts), 1e-9), 3),
        "byte_identical": bool(identical),
        "sharded_phases_ms": dict(solver.last_phase_ms),
    }
    print(f"[bench] multichip A/B: {multichip}", file=sys.stderr)
    return multichip


STAGE_FNS = {
    "headline": stage_headline,
    "pipelined": stage_pipelined,
    "config5": stage_config5,
    "grid": stage_grid,
    "multichip": stage_multichip,
    "consolidation": stage_consolidation,
    "consolidation_xl": stage_consolidation_xl,
    "warm_restart": stage_warm_restart,
}


def _programs_digest() -> str:
    """Short identity digest of this worker's compiled-program inventory
    (family + key of every live record) — '' when the ledger is disabled
    or empty. Stable across re-runs of the same workload on the same
    build; a changed digest between rounds says the program population
    itself moved, not just the timings."""
    try:
        import hashlib

        from karpenter_core_tpu.obs import proghealth

        snap = proghealth.LEDGER.snapshot()
        ident = sorted(
            (str(p.get("family", "")), str(p.get("key", "")))
            for p in snap.get("programs", [])
        )
        if not ident:
            return ""
        blob = json.dumps(ident, sort_keys=True).encode()
        return hashlib.blake2s(blob, digest_size=6).hexdigest()
    except Exception:  # noqa: BLE001 — forensics must never fail a stage
        return ""


def stage_worker(name: str) -> int:
    """BENCH_STAGE=<name> subprocess entry: resolve the backend the
    orchestrator decided (BENCH_SKIP_PROBE / BENCH_CPU — never an in-line
    probe), run the one stage, print ONE JSON line. The heartbeat file
    (BENCH_HEARTBEAT_FILE) is touched at every progress point; the
    supervisor kills this process group on staleness."""
    global _HB
    hb_path = os.environ.get("BENCH_HEARTBEAT_FILE", "")
    if hb_path:
        _HB = supervise.Heartbeat(hb_path)
        _HB.touch()
    # stage workers trace by default (ISSUE 15): the solver's phase spans
    # + bench heartbeat ticks become this stage's timeline fragment,
    # shipped in the artifact and stitched round-wide by build_timeline.
    # KARPENTER_TPU_TRACE=0 opts out (the fragment is then omitted).
    from karpenter_core_tpu.obs import enable_tracing_from_env

    enable_tracing_from_env(default_on=True)
    try:
        ensure_backend()
        _touch()
        fn = STAGE_FNS[name]
        data = fn()
        import jax

        if isinstance(data, dict):
            # ISSUE 18: tie this stage's numbers to the exact compiled-
            # program population that produced them (the ledger row's
            # programs_digest column)
            data.setdefault("programs_digest", _programs_digest())
        print(json.dumps({
            "stage": name,
            "backend": BACKEND_NOTE,
            "platform": jax.devices()[0].platform,
            "backend_probe": PROBE_LOG,
            "trace": _trace_fragment(),
            "data": data,
        }))
        return 0
    except BaseException as exc:  # noqa: BLE001 — the artifact records it
        import traceback

        traceback.print_exc()
        print(json.dumps({
            "stage": name,
            "error": f"{type(exc).__name__}: {exc}"[:400],
            "backend": BACKEND_NOTE,
        }))
        return 1


# ---------------------------------------------------------------------------
# the out-of-band device-health daemon (sidecar subprocess)


def health_daemon() -> None:
    """BENCH_HEALTH_DAEMON=1 sidecar: probe the backend in a subprocess
    (wedge-proof — _run_subprocess hard-kills a hung probe's process
    group) and publish a TTL'd verdict file the orchestrator reads before
    every stage launch. The stages themselves never pay a probe timeout:
    a wedged tunnel costs THIS process a timeout, out of band, while the
    stage graph keeps running on the CPU fallback — and a verdict that
    flips back to ok mid-round lets later stages (and --resume) reclaim
    the TPU."""
    path = os.environ["BENCH_HEALTH_VERDICT_FILE"]
    parent = os.getppid()
    first = True
    while True:
        timeout = PROBE_SCHEDULE[0] if first else PROBE_TIMEOUT
        first = False
        ok, note, forensics = _probe_forensic(timeout)
        supervise.write_verdict(
            path, ok, note, ttl_s=HEALTH_INTERVAL * 2 + timeout,
            # ISSUE 18: the forensic record rides the verdict file so a
            # wedged round's merged artifact names the failing init phase
            extra={"probe_forensics": forensics},
        )
        print(f"[bench-health] verdict ok={ok} ({note})", file=sys.stderr)
        if os.getppid() != parent:
            return  # orchestrator is gone; don't linger
        time.sleep(HEALTH_INTERVAL if ok else min(HEALTH_INTERVAL, 60))


# ---------------------------------------------------------------------------
# stage-graph planning + merge (pure over the artifact store — what
# tests/test_bench_resume.py drives without subprocesses)


def stage_config(name: str) -> dict:
    """The config digest inputs for one stage: everything that changes
    WHAT the stage measures (workload geometry + stage knobs), nothing
    about HOW it ran (backend, budgets) — so a resume after a wedge
    re-runs the same work, and a changed knob invalidates the artifact."""
    base = {
        "stage": name,
        "pods": N_PODS, "types": N_TYPES, "distinct": N_DISTINCT,
        "existing": N_EXISTING, "nodes": MAX_NODES, "runs": N_RUNS,
    }
    if name in ("consolidation",):
        base.update(cons_nodes=CONS_NODES, cons_pods=CONS_PODS,
                    cons_types=CONS_TYPES)
    if name == "consolidation_xl":
        base.update(xl_nodes=CONS_XL_NODES, xl_pods=CONS_XL_PODS,
                    cons_types=CONS_TYPES)
    if name == "grid":
        base["grid_scale"] = os.environ.get("BENCH_GRID_SCALE", "1")
    return base


def _stage_skipped(name: str) -> str:
    """Non-empty reason when env config skips this stage outright."""
    stages_env = os.environ.get("BENCH_STAGES", "").strip()
    if stages_env:
        wanted = {s.strip() for s in stages_env.split(",") if s.strip()}
        if name not in wanted:
            return f"not in BENCH_STAGES={stages_env}"
    for env in STAGE_SKIP_ENVS.get(name, ()):
        if os.environ.get(env, "") == "1":
            return f"{env}=1"
    return ""


def plan_stages(store: supervise.ArtifactStore, tpu_available: bool):
    """The stages a (re)run must execute, in graph order: anything with no
    artifact, a degraded artifact, or a config-digest mismatch; plus
    involuntary-CPU `fallback` artifacts when the verdict says the TPU is
    back (the whole point of --resume after a wedged round). Env-skipped
    stages get a completed {"skipped": ...} artifact written up front so
    the merged schema stays full."""
    todo = []
    for name, _, _ in STAGE_GRAPH:
        cfg = stage_config(name)
        skip = _stage_skipped(name)
        if skip:
            if store.fresh(name, cfg) is None:
                store.save(name, cfg, {"skipped": skip},
                           meta={"backend": "skipped"})
            continue
        rec = store.fresh(name, cfg)
        if rec is None:
            todo.append(name)
        elif rec.get("fallback") and tpu_available:
            todo.append(name)
    return todo


def _stage_col(rec):
    """One stage's sub-dict column for the merged artifact: its data when
    completed (wedge salvage + fallback markers preserved), a degraded
    marker with the wedge log otherwise."""
    if rec is None:
        return {"degraded": True, "error": "stage never ran"}
    if rec.get("degraded"):
        return {
            "degraded": True,
            "error": rec.get("error"),
            "wedge_log": rec.get("wedge_log"),
        }
    col = dict(rec.get("data") or {})
    if rec.get("wedge_log"):
        col["wedge_log"] = rec["wedge_log"]
    if rec.get("fallback"):
        col["cpu_fallback_column"] = True
    return col


def merge_round(store: supervise.ArtifactStore, round_dir: str = "") -> dict:
    """Assemble the one BENCH_r{N}.json line from the per-stage artifacts.
    Same schema as the single-worker rounds (r01-r05): headline drives the
    top-level metric, every stage contributes its columns, and a degraded
    stage contributes a degraded marker + wedge_log instead of silence —
    all columns ALWAYS present. Pure over the store: merging the same
    round dir twice is byte-identical."""
    recs = {name: store.load(name) for name in STAGE_NAMES}

    def data(name):
        rec = recs.get(name)
        if rec is None or rec.get("degraded"):
            return None
        return rec.get("data")

    head = data("headline")
    complete_head = isinstance(head, dict) and "pods_per_sec" in head
    if complete_head:
        suffix = "_cpu_fallback" if (
            head.get("cpu_fallback") or recs["headline"].get("fallback")
        ) else ""
        metric = (
            f"pods_per_sec_e2e_p99_{head['pods']}pods_{head['types']}types_"
            f"{head['distinct']}distinct_{head['existing']}nodes{suffix}"
        )
        value = head["pods_per_sec"]
    else:
        head = {}
        metric = f"bench_failed_{CONFIG}_{N_PODS}pods_{N_TYPES}types"
        value = 0.0
    pipe = data("pipelined") or {}
    wr = data("warm_restart")
    # restart-claim validity: same platform + same geometry as the headline
    # (the r05 failure mode: a shrunk CPU child masquerading as the TPU
    # restart stall — the stage meta records the platform each worker ran)
    head_platform = ((recs.get("headline") or {}).get("meta") or {}).get(
        "platform"
    )
    wr_valid = (
        isinstance(wr, dict) and "error" not in wr and complete_head
        and wr.get("pods") == head.get("pods")
        and wr.get("platform") == head_platform
    )
    mc = data("multichip") or {}
    xl = _stage_col(recs.get("consolidation_xl"))
    stages_summary = {}
    probe_notes = []
    for name in STAGE_NAMES:
        rec = recs.get(name)
        if rec is None:
            stages_summary[name] = {"status": "missing"}
            continue
        meta = rec.get("meta") or {}
        status = (
            "degraded" if rec.get("degraded")
            else "fallback" if rec.get("fallback")
            else "skipped" if isinstance(rec.get("data"), dict)
            and "skipped" in rec["data"]
            else "ok"
        )
        stages_summary[name] = {
            "status": status,
            "backend": meta.get("backend", ""),
            "attempts": meta.get("attempts", []),
        }
        if meta.get("backend"):
            probe_notes.append(f"{name}: {meta['backend']}"[:200])
    extra = {
        "e2e_p50_ms": head.get("e2e_p50_ms"),
        "e2e_p99_ms": head.get("e2e_p99_ms"),
        "device_solve_med_ms": head.get("device_solve_med_ms"),
        "device_p50_ms_varied": head.get("device_p50_ms_varied"),
        "device_p99_ms_varied": head.get("device_p99_ms_varied"),
        "pipelined_p50_ms": pipe.get("pipelined_p50_ms"),
        "pipelined_p99_ms": pipe.get("pipelined_p99_ms"),
        "pipelined_runs": pipe.get("pipelined_runs", 0),
        "north_star_target_ms": 1000.0,
        # the charter is about Solve(), not the kernel slice (r4 verdict
        # weak #1): judge against the e2e numbers
        "single_call_under_target": bool(
            head.get("e2e_p99_ms") is not None
            and head["e2e_p99_ms"] < 1000.0
        ),
        "pipelined_under_target": bool(
            pipe.get("pipelined_p99_ms") and pipe["pipelined_p99_ms"] < 1000.0
        ),
        "device_under_target": bool(
            head.get("device_p99_ms_varied") is not None
            and head["device_p99_ms_varied"] < 1000.0
        ),
        "runs": head.get("runs"),
        "tail": head.get("tail"),
        "scheduled_min": head.get("scheduled_min"),
        "compile_cold_s": head.get("compile_cold_s"),
        # the warm-restart stage's headline numbers, folded into the main
        # row so the cold-start trajectory is tracked per-release (ISSUE 7)
        "first_solve_warm_s": (
            wr.get("first_solve_s") if isinstance(wr, dict) else None
        ),
        "warm_restart_cache_verified": bool(
            wr_valid and wr.get("cache_files", 0) > 0
        ),
        "warm_restart_under_2s": bool(
            wr_valid and wr.get("cache_files", 0) > 0
            and wr.get("first_solve_s") is not None
            and wr["first_solve_s"] < 2.0
        ),
        "bucket_hit_ratio": head.get("bucket_hit_ratio"),
        "warm_restart": _stage_col(recs.get("warm_restart")),
        "compiled_programs_after_varied_batches": head.get(
            "compiled_programs_after_varied_batches"
        ),
        "solver": head.get("solver"),
        # first-class MULTICHIP columns (ISSUE 8); null on single-device
        "sharded_speedup": mc.get("sharded_speedup"),
        "mesh": (
            f"dp={mc['mesh_dp']},tp={mc['mesh_tp']}"
            if "mesh_dp" in mc else None
        ),
        "multichip": _stage_col(recs.get("multichip")),
        "chips": head.get("chips"),
        "backend_probe": probe_notes,
        "consolidation": _stage_col(recs.get("consolidation")),
        "consolidation_xl": xl,
        "consolidation_under_1s": (
            xl.get("consolidation_under_1s")
            if isinstance(xl, dict) else None
        ),
        "config5_multiprov_spot_od": _stage_col(recs.get("config5")),
        "config_grid_1_2_3": _stage_col(recs.get("grid")),
        "stages": stages_summary,
        "round_dir": round_dir,
    }
    return {
        "metric": metric,
        "value": value,
        "unit": "pods/sec",
        "vs_baseline": round((value or 0.0) / 100.0, 2),
        "extra": extra,
    }


# ---------------------------------------------------------------------------
# cross-round perf ledger (ISSUE 18): cumulative PERF_LEDGER.json + the
# regression tripwire — pure over the store/ledger dicts like merge_round,
# so tests/test_bench_resume.py drives both without subprocesses.

LEDGER_VERSION = 1
# regression threshold, percent worse than best-known on the same platform
LEDGER_REGRESSION_PCT = float(envflags.raw("KARPENTER_PERF_REGRESSION_PCT", "25"))
# column-name direction heuristics: timings regress UP, rates regress DOWN;
# a column matching neither is ledgered but never tripwired (no direction,
# no verdict — counts and geometry knobs are identity, not performance)
_LEDGER_LOWER_BETTER = ("_ms", "_s", "_sec", "_seconds")
_LEDGER_HIGHER_BETTER = ("per_sec", "speedup", "ratio")


def _ledger_direction(column: str) -> str:
    """'lower' / 'higher' when the column's better-direction is known from
    its name, '' otherwise. Rate tokens win first: 'pods_per_sec' ends
    with the '_sec' timing suffix but is a throughput."""
    if any(tok in column for tok in _LEDGER_HIGHER_BETTER):
        return "higher"
    if any(column.endswith(sfx) for sfx in _LEDGER_LOWER_BETTER):
        return "lower"
    return ""


def append_ledger(store: supervise.ArtifactStore, ledger, round_name: str) -> dict:
    """Fold one round's COMPLETED stage artifacts into the cumulative
    ledger — pure (prior ledger dict in, new ledger dict out; the
    orchestrator owns the PERF_LEDGER.json file I/O). One row per
    (round, stage, column) where a column is any numeric scalar in the
    stage's data; re-folding the same round REPLACES its rows, so a
    --resume backfill updates in place instead of duplicating, and the
    sorted rows make the same store fold byte-identically."""
    rows = [
        r for r in (ledger or {}).get("rows", [])
        if isinstance(r, dict) and r.get("round") != round_name
    ]
    for name in STAGE_NAMES:
        rec = store.load(name)
        if rec is None or rec.get("degraded"):
            continue
        data = rec.get("data")
        if not isinstance(data, dict) or "skipped" in data:
            continue
        meta = rec.get("meta") or {}
        platform = str(meta.get("platform") or "")
        digest = str(data.get("programs_digest") or "")
        fallback = bool(rec.get("fallback"))
        for column in sorted(data):
            value = data[column]
            if isinstance(value, bool) or not isinstance(value, (int, float)):
                continue
            rows.append({
                "round": round_name,
                "stage": name,
                "column": column,
                "value": float(value),
                "platform": platform,
                "degraded": False,
                "fallback": fallback,
                "programs_digest": digest,
            })
    rows.sort(key=lambda r: (r["round"], r["stage"], r["column"]))
    return {"version": LEDGER_VERSION, "rows": rows}


def ledger_verdict(ledger, round_name: str, pct=None) -> dict:
    """The regression tripwire: this round's direction-known columns vs
    the best-known value for the same (stage, column, PLATFORM) from
    earlier rounds — cross-platform comparison is exactly the r03-r05
    trap (CPU-fallback numbers vs TPU numbers) this plane exists to end.
    Worse than best-known by more than `pct` percent ⇒ a named regression
    entry. WARN-ONLY by contract: the orchestrator folds the verdict into
    the merged artifact and never fails the round on it. Shrunk fallback
    rows (different workload) are excluded from both sides."""
    pct = LEDGER_REGRESSION_PCT if pct is None else float(pct)
    rows = [
        r for r in (ledger or {}).get("rows", [])
        if isinstance(r, dict) and not r.get("fallback")
    ]
    best: dict = {}
    for r in rows:
        if r.get("round") == round_name:
            continue
        direction = _ledger_direction(str(r.get("column", "")))
        if not direction:
            continue
        key = (r.get("stage"), r.get("column"), r.get("platform"))
        try:
            value = float(r.get("value"))
        except (TypeError, ValueError):
            continue
        cur = best.get(key)
        if cur is None or (value < cur if direction == "lower" else value > cur):
            best[key] = value
    regressions = []
    for r in rows:
        if r.get("round") != round_name:
            continue
        column = str(r.get("column", ""))
        direction = _ledger_direction(column)
        if not direction:
            continue
        ref = best.get((r.get("stage"), column, r.get("platform")))
        if not ref:  # no same-platform history (or a zero best): no verdict
            continue
        value = float(r.get("value", 0.0))
        worse = (
            (value - ref) / abs(ref) if direction == "lower"
            else (ref - value) / abs(ref)
        )
        if worse * 100.0 > pct:
            regressions.append({
                "stage": r.get("stage"),
                "column": column,
                "platform": r.get("platform"),
                "value": value,
                "best_known": ref,
                "worse_pct": round(worse * 100.0, 1),
            })
    regressions.sort(key=lambda g: (-g["worse_pct"], g["stage"], g["column"]))
    return {"ok": not regressions, "threshold_pct": pct,
            "round": round_name, "regressions": regressions}


def _ledger_file_for(round_dir: str) -> str:
    """PERF_LEDGER.json lives BESIDE the round dirs (one ledger spanning
    rounds), overridable for smokes/tests via BENCH_LEDGER_FILE."""
    explicit = os.environ.get("BENCH_LEDGER_FILE", "")
    if explicit:
        return explicit
    rd = os.path.abspath(round_dir)
    return os.path.join(os.path.dirname(rd) or ".", "PERF_LEDGER.json")


def _load_ledger(path: str):
    """The prior cumulative ledger, or None on a cold start (missing or
    unreadable file folds as empty — never raises)."""
    try:
        with open(path) as f:
            ledger = json.load(f)
    except (OSError, ValueError):
        return None
    return ledger if isinstance(ledger, dict) else None


def build_timeline(store: supervise.ArtifactStore) -> dict:
    """Stitch the round-wide Perfetto timeline (BENCH_timeline.json) from
    the per-stage artifacts — PURE over the store, like merge_round, so
    re-merging the same round dir is byte-identical (ISSUE 15).

    Rows: pid 0 is the orchestrator (one 'bench.stage.<name>' slice per
    stage from the meta's wall-clock bounds, wedge/timeout SIGKILLs and
    resume backfills as instant markers); each stage worker's chrome-trace
    fragment renders under its own pid, rebased from the worker's
    perf-counter timebase onto the shared wall clock via the fragment's
    (wall_anchor_s, anchor_ts_us) pair. Timestamps are µs since the
    earliest stage start."""
    recs = {name: store.load(name) for name in STAGE_NAMES}
    starts = [
        m["started_ts"]
        for rec in recs.values() if rec
        for m in (rec.get("meta") or {},) if m.get("started_ts") is not None
    ]
    base = min(starts) if starts else 0.0

    def us(wall_s):
        return round((float(wall_s) - base) * 1e6, 1)

    events = []
    dropped = 0
    statuses = {}
    for idx, name in enumerate(STAGE_NAMES):
        rec = recs.get(name)
        if rec is None:
            statuses[name] = "missing"
            continue
        meta = rec.get("meta") or {}
        status = (
            "degraded" if rec.get("degraded")
            else "fallback" if rec.get("fallback")
            else "ok"
        )
        statuses[name] = status
        t0, t1 = meta.get("started_ts"), meta.get("ended_ts")
        if t0 is not None and t1 is not None:
            events.append({
                "name": f"bench.stage.{name}", "cat": "bench", "ph": "X",
                "ts": us(t0),
                "dur": round(max(float(t1) - float(t0), 0.0) * 1e6, 1),
                "pid": 0, "tid": idx + 1,
                "args": {"status": status,
                         "backend": meta.get("backend", "")},
            })
        wl = rec.get("wedge_log") or {}
        if wl.get("wedged") or wl.get("timed_out"):
            kind = "wedge" if wl.get("wedged") else "timeout"
            events.append({
                "name": f"bench.{kind}.SIGKILL", "cat": "bench",
                "ph": "i", "s": "g",
                "ts": us(t1) if t1 is not None else 0.0,
                "pid": 0, "tid": idx + 1,
                "args": {"stage": name, "phase": wl.get("phase", ""),
                         "note": str(wl.get("note", ""))[:200]},
            })
        if meta.get("resumed"):
            events.append({
                "name": "bench.resume.backfill", "cat": "bench",
                "ph": "i", "s": "g",
                "ts": us(t0) if t0 is not None else 0.0,
                "pid": 0, "tid": idx + 1,
                "args": {"stage": name, "status": status},
            })
        frag = meta.get("trace") or {}
        if frag.get("events") and frag.get("wall_anchor_s") is not None:
            offset_us = us(frag["wall_anchor_s"]) - float(
                frag.get("anchor_ts_us", 0.0)
            )
            pid = int(frag.get("pid", idx + 1) or idx + 1)
            for e in frag["events"]:
                e2 = dict(e)
                e2["ts"] = round(float(e.get("ts", 0.0)) + offset_us, 1)
                e2["pid"] = pid
                events.append(e2)
            events.append({
                "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                "args": {"name": f"stage {name} worker pid {pid}"},
            })
            dropped += int(frag.get("dropped", 0) or 0)
    events.append({
        "name": "process_name", "ph": "M", "pid": 0, "tid": 0,
        "args": {"name": "bench orchestrator"},
    })
    events.sort(
        key=lambda e: (e.get("ts", 0.0), e.get("pid", 0),
                       e.get("tid", 0), e.get("name", ""))
    )
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "base_wall_s": base,
            "stages": statuses,
            "dropped_events": dropped,
        },
    }


# ---------------------------------------------------------------------------
# stage-graph orchestrator (CONFIG=solve): supervised per-stage workers,
# verdict-file backend gating, resumable round dirs


def _echo_stderr(chunk: str) -> None:
    sys.stderr.write(chunk)
    sys.stderr.flush()


def _launch_stage(name: str, env_extra: dict, budget: int, hb_dir: str,
                  cache_dir: str):
    """Run one stage worker under the supervisor. Returns
    (SuperviseResult, parsed_json_or_None)."""
    env = dict(os.environ)
    # the orchestrator decides the backend; scrub any inherited decision
    for key in ("BENCH_CPU", "BENCH_CPU_SHRINK", "BENCH_SKIP_PROBE",
                "KARPENTER_CHAOS", "BENCH_STAGE_CHAOS", "BENCH_STAGES"):
        env.pop(key, None)
    hb_path = os.path.join(hb_dir, f"{name}.hb")
    env.update({
        "BENCH_STAGE": name,
        "BENCH_COMPILE_CACHE_DIR": cache_dir,
        "BENCH_HEARTBEAT_FILE": hb_path,
        # the worker's in-stage budget shedding (_worker_time_left)
        # measures against the timeout actually enforced here
        "BENCH_WORKER_TIMEOUT": str(int(budget)),
    })
    chaos_spec = _stage_chaos(name)
    if chaos_spec:
        env["KARPENTER_CHAOS"] = chaos_spec
    env.update(env_extra)
    res = supervise.run_supervised(
        [sys.executable, os.path.abspath(__file__)],
        env=env,
        timeout_s=budget,
        heartbeat_path=hb_path,
        stale_after_s=STAGE_STALE,
        on_output=_echo_stderr,
    )
    parsed = _parse_json_line(res.stdout)
    if parsed is not None and parsed.get("stage") != name:
        parsed = None  # stray line from some other layer: not this stage's
    return res, parsed


def orchestrate_stage_graph(resume_dir: str = "") -> None:
    """The round driver: plan over the artifact store, gate each stage's
    backend on the sidecar daemon's TTL'd verdict (no in-line probes),
    run each stage in its own supervised worker, degrade exactly the
    stages that wedge (CPU retry when budget allows, else a degraded
    artifact with the wedge log), and merge. Re-entrant by construction:
    `--resume <round-dir>` is the same call with an existing dir."""
    probe_log = []

    def _log(msg):
        probe_log.append(msg[:200])
        print(f"[bench] {probe_log[-1]}", file=sys.stderr)

    import tempfile

    round_dir = (
        resume_dir or os.environ.get("BENCH_ROUND_DIR", "")
        or tempfile.mkdtemp(prefix="kct-bench-round-")
    )
    os.makedirs(round_dir, exist_ok=True)
    hb_dir = os.path.join(round_dir, "hb")
    os.makedirs(hb_dir, exist_ok=True)
    store = supervise.ArtifactStore(os.path.join(round_dir, "stages"))
    # ONE compile cache for the whole round (and its resumes): satellite
    # stages and wedge retries reload the headline's compiled programs
    # from disk instead of re-paying the cold compile per worker
    cache_dir = os.environ.get("BENCH_COMPILE_CACHE_DIR") or os.path.join(
        round_dir, "xla-cache"
    )
    os.makedirs(cache_dir, exist_ok=True)
    verdict_path = os.path.join(round_dir, "health.json")
    force_cpu = os.environ.get("BENCH_CPU", "") == "1"
    deadline = time.monotonic() + TOTAL_BUDGET
    _log(f"round dir: {round_dir} (resume={'yes' if resume_dir else 'no'})")

    def _left() -> int:
        return max(0, int(deadline - time.monotonic()))

    daemon = None
    if not force_cpu:
        denv = dict(os.environ)
        denv["BENCH_HEALTH_DAEMON"] = "1"
        denv["BENCH_HEALTH_VERDICT_FILE"] = verdict_path
        daemon = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__)],
            env=denv, start_new_session=True,
            stdout=subprocess.DEVNULL, stderr=None,
        )
        # wait for the FIRST verdict (one short probe's worth): after this
        # the daemon re-probes out of band and no stage ever blocks on it
        wait_until = time.monotonic() + PROBE_SCHEDULE[0] + 45
        while time.monotonic() < wait_until:
            if supervise.read_verdict(verdict_path) is not None:
                break
            time.sleep(2)
        v = supervise.read_verdict(verdict_path)
        _log(
            "initial health verdict: "
            + (f"ok={v['ok']} ({v.get('note', '')})" if v else "none (daemon slow)")
        )

    # after a TPU-stage wedge, only a verdict published AFTER the wedge
    # re-admits the TPU for later stages
    distrust_after = 0.0

    def _decide_backend():
        """(env for the stage worker, expecting_tpu). An ok verdict always
        skips the in-line probe; `expecting_tpu` is True only when the
        probed platform is an accelerator — a CPU-only host's ok verdict
        runs the full config on CPU *deliberately* (no fallback marking,
        nothing for --resume to reclaim), matching the legacy probe-ok
        semantics. The verdict note's first token is the probed platform."""
        if force_cpu:
            return {"BENCH_CPU": "1"}, False
        v = supervise.read_verdict(verdict_path)
        if v and v.get("ok") and float(v.get("ts", 0)) > distrust_after:
            probed_platform = str(v.get("note", "")).split(" ")[0]
            return {"BENCH_SKIP_PROBE": "1"}, probed_platform not in ("cpu", "")
        return {"BENCH_CPU": "1", "BENCH_CPU_SHRINK": "1"}, False

    try:
        todo = plan_stages(store, tpu_available=_decide_backend()[1])
        _log("stages to run: " + (",".join(todo) if todo else "none (all fresh)"))
        timeouts = {name: _stage_timeout(name, t) for name, t, _ in STAGE_GRAPH}
        for name in todo:
            cfg = stage_config(name)
            if _left() < 90:
                # mark the stage degraded unless a FRESH artifact for THIS
                # config already answers it: a stale-digest leftover from a
                # previous config must not merge as an ok column
                if store.fresh(name, cfg) is None:
                    store.save(name, cfg, None, degraded=True,
                               error="round budget exhausted before stage ran")
                    _log(f"{name}: budget exhausted, left degraded for --resume")
                else:
                    _log(f"{name}: budget exhausted, keeping the existing "
                         "fresh artifact")
                continue
            budget = min(timeouts[name], _left())
            env_extra, on_tpu = _decide_backend()
            _log(f"{name}: starting ({'tpu' if on_tpu else 'cpu'}, "
                 f"budget {budget}s)")
            started_wall = time.time()
            res, parsed = _launch_stage(name, env_extra, budget, hb_dir,
                                        cache_dir)
            # wall-clock stage bounds + the worker's trace fragment ride
            # the artifact meta: build_timeline() stitches the round-wide
            # BENCH_timeline.json purely from the store (ISSUE 15)
            span_meta = {
                "started_ts": round(started_wall, 3),
                "ended_ts": round(started_wall + res.duration_s, 3),
                "resumed": bool(resume_dir),
            }
            if parsed is not None and "data" in parsed:
                # completed (possibly salvaged from a worker that hung at
                # exit after printing its line — keep the log either way)
                meta = {
                    "backend": parsed.get("backend", ""),
                    "platform": parsed.get("platform", ""),
                    "attempts": res.attempts,
                    "duration_s": round(res.duration_s, 1),
                    "trace": parsed.get("trace"),
                    **span_meta,
                }
                # fallback-marked (so --resume reclaims it) only when this
                # column SHOULD have been an accelerator one: the shrunk
                # no-verdict path, or a TPU-expected worker landing on cpu.
                # An ok-but-cpu verdict (CPU-only host) is deliberate.
                involuntary_cpu = (
                    "BENCH_CPU_SHRINK" in env_extra
                    or (on_tpu and parsed.get("platform") == "cpu")
                )
                store.save(
                    name, cfg, parsed["data"],
                    fallback=involuntary_cpu,
                    wedge_log=(
                        res.wedge_log()
                        if (res.wedged or res.timed_out) else None
                    ),
                    meta=meta,
                )
                _log(f"{name}: ok ({res.note}, {res.duration_s:.0f}s"
                     + (", involuntary cpu" if involuntary_cpu else "") + ")")
                continue
            first_log = res.wedge_log()
            err = (parsed or {}).get("error") or res.note
            _log(f"{name}: FAILED ({err})")
            if on_tpu:
                # one wedge costs exactly this stage's TPU attempt: distrust
                # the current verdict (the daemon must re-prove the tunnel)
                # and finish the column on the shrunk CPU fallback if the
                # budget allows
                distrust_after = time.time()
                if res.wedged:
                    _log(f"{name}: tpu attempt wedged; verdict distrusted "
                         "until the health daemon re-proves the tunnel")
                if _left() > 120:
                    budget2 = min(timeouts[name], CPU_WORKER_TIMEOUT, _left())
                    res2, parsed2 = _launch_stage(
                        name, {"BENCH_CPU": "1", "BENCH_CPU_SHRINK": "1"},
                        budget2, hb_dir, cache_dir,
                    )
                    if parsed2 is not None and "data" in parsed2:
                        store.save(
                            name, cfg, parsed2["data"], fallback=True,
                            wedge_log=first_log,
                            meta={
                                "backend": parsed2.get("backend", ""),
                                "platform": parsed2.get("platform", ""),
                                "attempts": res.attempts + res2.attempts,
                                "duration_s": round(
                                    res.duration_s + res2.duration_s, 1
                                ),
                                "trace": parsed2.get("trace"),
                                **span_meta,
                                "ended_ts": round(
                                    started_wall + res.duration_s
                                    + res2.duration_s, 3
                                ),
                            },
                        )
                        _log(f"{name}: cpu fallback ok (column marked "
                             "fallback; --resume reclaims it when the TPU "
                             "is back)")
                        continue
                    err = (parsed2 or {}).get("error") or res2.note
                    _log(f"{name}: cpu fallback FAILED too ({err})")
            store.save(
                name, cfg, None, degraded=True, error=str(err)[:400],
                wedge_log=first_log,
                meta={"backend": (parsed or {}).get("backend", ""),
                      "attempts": res.attempts, **span_meta},
            )
    finally:
        if daemon is not None:
            try:
                os.killpg(daemon.pid, 9)
            except (ProcessLookupError, PermissionError):
                pass
    merged = merge_round(store, round_dir=round_dir)
    merged["extra"]["orchestrator_probe"] = probe_log
    # ISSUE 18: surface the daemon's LAST forensic record in the merged
    # artifact. Read the raw file, not read_verdict — a stale verdict is
    # no verdict for backend gating, but its forensics are still the best
    # evidence of where the device init died.
    forensics = _read_verdict_forensics(verdict_path)
    if forensics:
        merged["extra"]["probe_forensics"] = forensics
    # ISSUE 18: fold this round (fresh run OR --resume backfill — same
    # path) into the cumulative cross-round ledger, then tripwire it.
    # Warn-only by contract: a flagged regression names itself in the
    # merged artifact and stderr but never fails the round.
    ledger_file = _ledger_file_for(round_dir)
    round_name = os.path.basename(os.path.abspath(round_dir))
    ledger = append_ledger(store, _load_ledger(ledger_file), round_name)
    verdict = ledger_verdict(ledger, round_name)
    supervise.atomic_write_json(ledger_file, ledger)
    merged["extra"]["perf_ledger"] = {
        "file": ledger_file,
        "rows": len(ledger["rows"]),
        "verdict": verdict,
    }
    if not verdict["ok"]:
        _log(
            "PERF REGRESSION (warn-only): "
            + "; ".join(
                f"{g['stage']}.{g['column']} {g['worse_pct']}% worse than "
                f"best-known on {g['platform'] or '?'}"
                for g in verdict["regressions"][:5]
            )
        )
    _fold_churn_report(merged)
    supervise.atomic_write_json(
        os.path.join(round_dir, "BENCH_merged.json"), merged
    )
    # the round-wide Perfetto timeline (ISSUE 15): stage slices + worker
    # trace fragments + wedge SIGKILL / resume-backfill markers, stitched
    # purely from the artifacts (byte-stable across re-merges)
    supervise.atomic_write_json(
        os.path.join(round_dir, "BENCH_timeline.json"), build_timeline(store)
    )
    _log(f"timeline: {os.path.join(round_dir, 'BENCH_timeline.json')}")
    print(json.dumps(merged, sort_keys=True))


def _pipelined_loop(n_runs, gen, encode, solve_encoded, label):
    """The production encode-overlap protocol, shared by the headline and
    config-5 measurements: batch N+1's encode rides a worker thread while
    solve N runs (the host is idle in the device window). gen(r) -> batch
    on the MAIN thread (untimed; generating 50k pod objects on the worker
    starved the timed solve's GIL — see the headline loop's history);
    encode(batch) -> snapshot on the worker; solve_encoded(batch, snap) is
    the timed region. Returns per-run seconds."""
    import concurrent.futures
    import gc as _gc

    times = []
    if n_runs < 2:
        return times
    pool = concurrent.futures.ThreadPoolExecutor(max_workers=1)
    cur = gen(0)
    nxt_batch = None
    nxt = pool.submit(encode, cur)
    for r in range(n_runs):
        if r + 1 < n_runs:
            nxt_batch = gen(r + 1)
        snap = nxt.result()
        if r + 1 < n_runs:
            nxt = pool.submit(encode, nxt_batch)
        _gc.collect()
        t0 = time.perf_counter()
        solve_encoded(cur, snap)
        times.append(time.perf_counter() - t0)
        print(
            f"[bench] {label} {r + 1}/{n_runs}: "
            f"solve={times[-1] * 1e3:.0f}ms",
            file=sys.stderr,
        )
        cur, nxt_batch = nxt_batch, None
    pool.shutdown(wait=False)
    return times


def _run_subprocess(cmd, env, timeout_s: int, capture_stderr=False) -> tuple:
    """Popen in its own process group with a HARD watchdog: on timeout the
    whole group is SIGKILLed and pipes are drained on bounded threads, so
    a child stuck in an uninterruptible tunnel syscall (or a grandchild
    holding a pipe) cannot wedge this process. Returns
    (rc_or_None, stdout_text, stderr_text, timed_out). With
    capture_stderr=False, stderr is inherited (streams live into the
    driver's recorded tail)."""
    import signal
    import threading

    proc = subprocess.Popen(
        cmd, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE if capture_stderr else None,
        text=True, env=env, start_new_session=True,
    )
    out_chunks, err_chunks = [], []

    def _drain(stream, chunks):
        try:
            chunks.append(stream.read())
        except Exception:
            pass

    drainers = [threading.Thread(target=_drain, args=(proc.stdout, out_chunks),
                                 daemon=True)]
    if capture_stderr:
        drainers.append(threading.Thread(
            target=_drain, args=(proc.stderr, err_chunks), daemon=True))
    deadline = time.monotonic() + timeout_s
    for d in drainers:
        d.start()
    for d in drainers:
        d.join(max(0.0, deadline - time.monotonic()))
    if any(d.is_alive() for d in drainers):
        timed_out = True
    else:
        # pipes hit EOF; reap the child (poll() right after EOF can race)
        try:
            proc.wait(timeout=30)
            timed_out = False
        except subprocess.TimeoutExpired:
            timed_out = True
    if timed_out:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
        for d in drainers:
            d.join(10)  # bounded: give the pipes a moment to close
    rc = proc.poll()
    return rc, "".join(out_chunks), "".join(err_chunks), timed_out


# ISSUE 18: the probe child marks each device-init phase on a labeled
# heartbeat file — the same one-line contract supervise.Heartbeat reads —
# so a wedged probe names the phase it died in instead of just "timeout".
# Deliberately no package import inside the child: the daemon may run from
# any cwd, and a probe that can't even reach the interpreter should still
# leave the phases it DID reach behind (no mark at all reads as "spawn").
_PROBE_SCRIPT = """\
import os, sys, time
def mark(label):
    with open(os.environ["BENCH_PROBE_HEARTBEAT"], "w") as f:
        f.write(label)
mark("import")
t0 = time.perf_counter()
import jax
mark("device-init")
t1 = time.perf_counter()
devs = jax.devices()
t2 = time.perf_counter()
mark("done")
d = devs[0]
print(d.platform, d.device_kind)
print("PROBE_TIMINGS %.1f %.1f %d" % ((t1 - t0) * 1e3, (t2 - t1) * 1e3, len(devs)))
"""

# the env vars that steer platform resolution — recorded verbatim in the
# forensic record (they name backends, never secrets; everything else in
# the stderr tail goes through supervise.redact_env_text)
_PROBE_PLATFORM_ENVS = (
    "JAX_PLATFORMS", "JAX_PLATFORM_NAME", "PJRT_DEVICE", "TPU_SKIP_MDS_QUERY",
)


def _probe_forensic(timeout_s: int) -> tuple:
    """One subprocess backend probe with a device-init forensic record
    (ISSUE 18). Returns (ok, note, forensics): the note keeps its legacy
    shape (first token of an ok note is the platform — _decide_backend's
    contract); the forensic dict is bounded and env-redacted, and names
    the init phase the probe died in via the labeled-heartbeat file."""
    import tempfile

    hb_fd, hb_path = tempfile.mkstemp(prefix="bench-probe-hb-")
    os.close(hb_fd)
    env = dict(os.environ)
    env["BENCH_PROBE_HEARTBEAT"] = hb_path
    t0 = time.monotonic()
    try:
        rc, out, err, timed_out = _run_subprocess(
            [sys.executable, "-c", _PROBE_SCRIPT], env, timeout_s,
            capture_stderr=True,
        )
        phase = supervise.Heartbeat(hb_path).read_label() or "spawn"
    finally:
        try:
            os.unlink(hb_path)
        except OSError:
            pass
    forensics = {
        "ts": round(time.time(), 3),
        "timeout_s": timeout_s,
        "elapsed_s": round(time.monotonic() - t0, 2),
        "rc": rc,
        "timed_out": bool(timed_out),
        "phase": phase,
        "platform_resolution": {
            k: os.environ[k] for k in _PROBE_PLATFORM_ENVS if k in os.environ
        },
        "stderr_tail": supervise.redact_env_text(
            err[-PROBE_FORENSIC_TAIL:] if err else ""
        ),
    }
    for line in out.splitlines():
        if line.startswith("PROBE_TIMINGS "):
            parts = line.split()
            try:
                forensics["import_ms"] = float(parts[1])
                forensics["device_init_ms"] = float(parts[2])
                forensics["device_count"] = int(parts[3])
            except (IndexError, ValueError):
                pass
    if timed_out:
        return False, f"probe timeout after {timeout_s}s (in {phase})", forensics
    if rc == 0:
        first = out.strip().splitlines()
        note = first[0].strip() if first else ""
        forensics["platform"] = note.split(" ")[0] if note else ""
        return True, note, forensics
    lines = [ln for ln in err.strip().splitlines() if ln.strip()]
    return False, (lines[-1] if lines else f"probe rc={rc}"), forensics


def _probe_once(timeout_s: int) -> tuple:
    """One subprocess backend probe. Returns (ok, note); on failure the
    note carries the backend's own last stderr line (e.g. 'Unable to
    initialize backend axon') so BENCH_r{N}.json distinguishes a tunnel
    wedge from an import error. The forensic record is captured on every
    attempt; callers that publish it use _probe_forensic directly."""
    ok, note, _ = _probe_forensic(timeout_s)
    return ok, note


def _read_verdict_forensics(verdict_path: str):
    """The probe_forensics dict from a verdict file, TTL-ignored (a stale
    verdict is no verdict for gating, but its forensic record is still the
    last word on where device init died). None when absent/unreadable."""
    try:
        with open(verdict_path) as f:
            verdict = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(verdict, dict):
        return None
    forensics = verdict.get("probe_forensics")
    return forensics if isinstance(forensics, dict) else None


def _parse_json_line(text: str):
    result = None
    for line in text.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                result = json.loads(line)
            except ValueError:
                continue
    return result


def _run_worker(extra_env: dict, timeout_s: int) -> tuple:
    """Run this script as a worker subprocess under a watchdog. stderr is
    inherited (streams live into the driver's recorded tail); stdout is
    captured and the last JSON-parseable line is the result. Returns
    (result_dict_or_None, note)."""
    env = dict(os.environ)
    env["BENCH_WORKER"] = "1"
    # export the EFFECTIVE watchdog so the worker's stage-shedding guard
    # (_worker_time_left) measures against the timeout actually enforced
    # here — a TOTAL_BUDGET-clamped retry or the CPU fallback watchdog is
    # far shorter than the 3300s default the worker would otherwise assume
    env["BENCH_WORKER_TIMEOUT"] = str(timeout_s)
    env.update(extra_env)
    rc, out, _, timed_out = _run_subprocess(
        [sys.executable, os.path.abspath(__file__)], env, timeout_s)
    # parse even a timed-out worker's captured stdout: a worker that printed
    # its JSON but hung at interpreter shutdown still produced a result
    result = _parse_json_line(out)
    if result is not None:
        return result, ("ok (worker hung at exit, result salvaged)"
                        if timed_out else "ok")
    if timed_out:
        return None, f"worker wedged: no result within {timeout_s}s (killed)"
    return None, f"worker rc={rc}, no JSON line on stdout"


def _failure_record(note: str, extra: dict) -> dict:
    return {
        "metric": f"bench_failed_{CONFIG}_{N_PODS}pods_{N_TYPES}types",
        "value": 0.0,
        "unit": "pods/sec",
        "vs_baseline": 0.0,
        "error": note[:400],
        "extra": extra,
    }


def _fold_churn_report(result: dict) -> None:
    """BENCH_CHURN_REPORT names a soak-report JSON (`hack/soak.py --out`):
    its churn_* columns (admission->bind SLOs, queue depth, incremental
    re-solve ratio, refresh-vs-full prescreen medians — docs/PERF.md
    "churn columns") fold into the bench artifact's extra, so the one-shot
    Solve() numbers and the steady-state churn numbers travel in the same
    BENCH_r{N}.json. The soak runs on its own wall clock (make soak), not
    inside the bench budget."""
    path = os.environ.get("BENCH_CHURN_REPORT", "")
    if not path:
        return
    try:
        with open(path) as f:
            churn = json.load(f)
        result.setdefault("extra", {}).update(
            {k: v for k, v in churn.items() if k.startswith("churn_")}
        )
    except Exception as exc:  # noqa: BLE001 — a bad report must not kill the bench line
        result.setdefault("extra", {})["churn_report_error"] = (
            f"{type(exc).__name__}: {exc}"[:200]
        )


def orchestrate_legacy():
    """Single-worker orchestration, kept for the one-stage configs
    (BENCH_CONFIG=consolidation/sweep): probe schedule, worker watchdog,
    CPU fallback, final rescue probe. The default (solve) config runs the
    stage graph instead (orchestrate_stage_graph). Never imports jax in
    this process, so no wedge can stop the final JSON line."""
    probe_log = []
    deadline = time.monotonic() + TOTAL_BUDGET
    # one compile-cache dir for ALL worker attempts this orchestration: a
    # retry/rescue worker after a mid-run wedge reloads the first attempt's
    # compiled programs from disk instead of re-paying the ~2-minute cold
    # compile out of its (already shrunk) budget
    if not os.environ.get("BENCH_COMPILE_CACHE_DIR"):
        import tempfile

        os.environ["BENCH_COMPILE_CACHE_DIR"] = tempfile.mkdtemp(
            prefix="kct-xla-cache-"
        )

    def _left() -> int:
        return max(0, int(deadline - time.monotonic()))

    def _budget(stage_timeout: int) -> int:
        return min(stage_timeout, _left())

    def _log(msg):
        probe_log.append(msg[:200])
        print(f"[bench] {probe_log[-1]}", file=sys.stderr)

    if os.environ.get("BENCH_CPU", "") == "1":
        # deliberate CPU run: skip all TPU probing, honor the full config
        result, note = _run_worker({}, _budget(TOTAL_BUDGET))
        if result is None:
            result = _failure_record(note, {})
        result.setdefault("extra", {})["orchestrator_probe"] = ["forced cpu"]
        _fold_churn_report(result)
        print(json.dumps(result))
        return

    tpu_ok = False
    probe_dead = False
    for i, t in enumerate(PROBE_SCHEDULE):
        ok, note = _probe_once(_budget(t))
        _log(f"probe {i + 1} ({t}s): {'ok ' if ok else 'FAILED '}({note})")
        if ok:
            tpu_ok = True
            break
        if note.startswith("probe timeout"):
            # a HUNG backend init doesn't heal with a longer timeout — the
            # r05 run burned 60+240+600+300s of probes on one wedged
            # tunnel. Record the timeout and go straight to the CPU
            # fallback; a fast *error* (rc!=0) still gets the escalating
            # retries, since transient init races do recover.
            probe_dead = True
            _log("probe hang: short-circuiting remaining probes to the "
                 "cpu fallback")
            break
        if i < len(PROBE_SCHEDULE) - 1 and _left() > 60:
            time.sleep(min(30, 5 * (i + 1)))

    result = None
    got_tpu = False
    if tpu_ok:
        result, note = _run_worker({"BENCH_SKIP_PROBE": "1"},
                                   _budget(WORKER_TIMEOUT))
        if result is None and _left() > 300:
            # the tunnel can wedge mid-run: re-probe, then one retry with a
            # reduced run count so the retry fits the remaining patience
            _log(f"worker attempt 1: {note}")
            ok, pnote = _probe_once(_budget(240))
            _log(f"re-probe (240s): {'ok ' if ok else 'FAILED '}({pnote})")
            if ok:
                result, note = _run_worker(
                    {"BENCH_SKIP_PROBE": "1",
                     "BENCH_RUNS": str(max(6, N_RUNS // 2))},
                    _budget(WORKER_TIMEOUT),
                )
                if result is None:
                    _log(f"worker attempt 2: {note}")
        got_tpu = result is not None

    if result is None:
        # CPU fallback: always produces a (shrunk, clearly suffixed) number.
        # Reserve ~60s of budget headroom so the record is always emitted.
        print("[bench] falling back to CPU worker", file=sys.stderr)
        result, note = _run_worker(
            {"BENCH_CPU": "1", "BENCH_CPU_SHRINK": "1"},
            _budget(CPU_WORKER_TIMEOUT),
        )
    if (not got_tpu and not probe_dead and result is not None
            and _left() > FINAL_PROBE_TIMEOUT + 120):
        # last chance before settling for the CPU number: the wedge may have
        # been transient (applies whether the probes FAILED fast up front or
        # the worker wedged mid-run — but not when a probe HUNG: a wedged
        # tunnel doesn't heal within one run, and r05 burned ~20 min of
        # probe budget proving it four times; probe_dead caps the whole
        # orchestration at one probe timeout)
        ok, pnote = _probe_once(FINAL_PROBE_TIMEOUT)
        _log(f"final probe ({FINAL_PROBE_TIMEOUT}s): "
             f"{'ok ' if ok else 'FAILED '}({pnote})")
        if ok:
            tpu_result, tnote = _run_worker(
                {"BENCH_SKIP_PROBE": "1"}, _budget(WORKER_TIMEOUT))
            if tpu_result is not None:
                _log("rescued: TPU came back on final probe")
                result = tpu_result
            else:
                _log(f"final TPU attempt: {tnote}")
    if result is None:
        result = _failure_record(note, {})

    result.setdefault("extra", {})["orchestrator_probe"] = probe_log
    _fold_churn_report(result)
    print(json.dumps(result))


if __name__ == "__main__":
    if os.environ.get("BENCH_HEALTH_DAEMON", "") == "1":
        # the out-of-band sidecar prober: publishes the TTL'd verdict file
        # until the orchestrator kills it (or the orchestrator dies)
        try:
            health_daemon()
        except KeyboardInterrupt:
            pass
        sys.exit(0)
    _stage = os.environ.get("BENCH_STAGE", "")
    if _stage:
        if _stage not in STAGE_FNS:
            print(json.dumps({"stage": _stage,
                              "error": f"unknown stage {_stage!r}"}))
            sys.exit(2)
        sys.exit(stage_worker(_stage))
    if os.environ.get("BENCH_WORKER", "") != "1":
        # top-level entry: --resume <round-dir> re-enters an existing round
        resume_dir = ""
        argv = sys.argv[1:]
        if "--resume" in argv:
            idx = argv.index("--resume")
            if idx + 1 >= len(argv):
                print("usage: bench.py [--resume <round-dir>]",
                      file=sys.stderr)
                sys.exit(2)
            resume_dir = argv[idx + 1]
            if not os.path.isdir(resume_dir):
                print(f"[bench] --resume: no such round dir {resume_dir}",
                      file=sys.stderr)
                sys.exit(2)
        try:
            if CONFIG in ("consolidation", "sweep"):
                orchestrate_legacy()
            else:
                orchestrate_stage_graph(resume_dir)
        except BaseException as exc:  # never exit without the JSON line
            import traceback

            traceback.print_exc()
            print(json.dumps(_failure_record(f"{type(exc).__name__}: {exc}", {})))
        sys.exit(0)
    try:
        ensure_backend()
        if CONFIG == "consolidation":
            base = consolidation_bench(emit=False)
            xl = consolidation_xl_stage()
            suffix = (
                "_cpu_fallback"
                if BACKEND_NOTE.startswith("cpu-fallback") else ""
            )
            print(
                json.dumps(
                    {
                        "metric": (
                            "consolidation_replan_pods_per_sec_"
                            f"{base.get('nodes')}nodes_"
                            f"{base.get('pods')}pods{suffix}"
                        ),
                        "value": base.get("pods_per_sec", 0.0),
                        "unit": "pods/sec",
                        "vs_baseline": round(
                            (base.get("pods_per_sec") or 0.0) / 100.0, 2
                        ),
                        "extra": {
                            "backend_probe": PROBE_LOG,
                            "consolidation": base,
                            "consolidation_xl": xl,
                            "consolidation_under_1s": (
                                xl.get("consolidation_under_1s")
                                if isinstance(xl, dict) else None
                            ),
                        },
                    }
                )
            )
        elif CONFIG == "sweep":
            sweep()
        else:
            # the solve config has no legacy single-worker path anymore:
            # the stage graph (BENCH_STAGE workers) replaced it
            raise RuntimeError(
                "BENCH_WORKER=1 is only valid for "
                "BENCH_CONFIG=consolidation/sweep; the solve config runs "
                "as a stage graph (see docs/bench-rounds.md)"
            )
    except BaseException as exc:  # never exit without the JSON line
        import traceback

        traceback.print_exc()
        print(
            json.dumps(
                _failure_record(
                    f"{type(exc).__name__}: {exc}",
                    {"backend_probe": PROBE_LOG},
                )
            )
        )
        sys.exit(0)
