#!/usr/bin/env python
"""Multichip smoke: virtual 8-device GSPMD parity + speedup sanity (ISSUE 8).

Gates, in order:

  1. BYTE-IDENTITY (fatal): the GSPMD mesh program's placements must be
     flightrec-canonical byte-identical to the single-device program on
     the same batch — on the full detected mesh AND on the cores-matched
     tp-major mesh the speedup A/B uses.
  2. SMALL-BATCH ROUTING (fatal): a tiny batch must dispatch the plain
     single-device program (ShardedSolver.last_path == "single").
  3. SPEEDUP SANITY (fatal): warm mesh wall on the cores-matched mesh
     must stay within KCT_SMOKE_MAX_SLOWDOWN (default 2.5x — the guarded
     failure mode is the 35x MULTICHIP_r05 wall, and a shared CI box
     adds real scheduling noise to sub-second walls) of the warm
     single-device wall.
     The measured `sharded_speedup` is printed either way; >1.0 is the
     ROADMAP exit bar on real multi-chip hardware, where every mesh
     device is its own chip (virtual CPU devices share host cores, so
     the CPU number is a lower bound).

Hermetic: forces the CPU backend with 8 virtual devices in-process, like
tests/conftest.py — a wedged TPU tunnel cannot hang the smoke.

Wired non-fatally into `make verify` (multichip-smoke target) and fatally
into hack/presubmit.sh.
"""
import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from jax.sharding import Mesh  # noqa: E402

from karpenter_core_tpu.cloudprovider import fake  # noqa: E402
from karpenter_core_tpu.obs.flightrec import (  # noqa: E402
    canonical_placements,
    placements_json,
)
from karpenter_core_tpu.parallel.sharded import ShardedSolver  # noqa: E402
from karpenter_core_tpu.solver.tpu_solver import TPUSolver  # noqa: E402
from karpenter_core_tpu.state.node import StateNode  # noqa: E402
from karpenter_core_tpu.testing import (  # noqa: E402
    make_node,
    make_pod,
    make_provisioner,
)
from karpenter_core_tpu.utils.compilecache import (  # noqa: E402
    enable_persistent_cache,
)

MAX_SLOWDOWN = float(os.environ.get("KCT_SMOKE_MAX_SLOWDOWN", "2.5"))
N_PODS = int(os.environ.get("KCT_SMOKE_PODS", "4000"))
N_DISTINCT = int(os.environ.get("KCT_SMOKE_DISTINCT", "100"))
N_TYPES = int(os.environ.get("KCT_SMOKE_TYPES", "50"))
N_EXIST = int(os.environ.get("KCT_SMOKE_EXISTING", "100"))
AB_RUNS = int(os.environ.get("KCT_SMOKE_AB_RUNS", "3"))


def workload():
    pods = [
        make_pod(
            labels={"app": f"g{i % N_DISTINCT}"},
            requests={"cpu": str(1 + i % 3), "memory": f"{1 + i % 4}Gi"},
        )
        for i in range(N_PODS)
    ]
    nodes = [
        StateNode(node=make_node(
            labels={
                "karpenter.sh/provisioner-name": "default",
                "karpenter.sh/initialized": "true",
            },
            capacity={"cpu": "16", "memory": "32Gi", "pods": "64"},
        )).deep_copy()
        for _ in range(N_EXIST)
    ]
    return pods, [make_provisioner(name="default")], {
        "default": fake.instance_types(N_TYPES)
    }, nodes


def main() -> int:
    enable_persistent_cache()
    pods, provisioners, its, nodes = workload()

    def solve(solver):
        return solver.solve(
            pods, provisioners, its,
            state_nodes=[n.deep_copy() for n in nodes],
        )

    single = TPUSolver(max_nodes=1024)
    t0 = time.perf_counter()
    res_single = solve(single)
    print(f"[smoke] single cold {time.perf_counter() - t0:.1f}s",
          file=sys.stderr)
    ref = placements_json(canonical_placements(res_single))
    assert not res_single.failed_pods

    # full detected-shape mesh: parity on the production mesh shape
    devices = np.array(jax.devices()[:8])
    full = ShardedSolver(Mesh(devices.reshape(4, 2), ("dp", "tp")),
                         max_nodes=1024)
    t0 = time.perf_counter()
    res_full = solve(full)
    print(f"[smoke] mesh(4,2) cold {time.perf_counter() - t0:.1f}s",
          file=sys.stderr)
    assert full.last_path == "mesh"
    assert placements_json(canonical_placements(res_full)) == ref, (
        "FATAL: mesh(4,2) placements diverged from single-device"
    )

    # cores-matched tp-major mesh: the honest same-host speedup A/B on a
    # shared-core box (see __graft_entry__._dryrun_generic_mix)
    n_cores = min(os.cpu_count() or 1, 8)
    if n_cores < 2:
        n_cores = 2
    matched = ShardedSolver(
        Mesh(devices[:n_cores].reshape(1, n_cores), ("dp", "tp")),
        max_nodes=1024,
    )
    t0 = time.perf_counter()
    res_matched = solve(matched)
    print(f"[smoke] mesh(1,{n_cores}) cold {time.perf_counter() - t0:.1f}s",
          file=sys.stderr)
    assert placements_json(canonical_placements(res_matched)) == ref, (
        "FATAL: cores-matched mesh placements diverged from single-device"
    )

    # small-batch routing
    tiny = ShardedSolver(Mesh(devices.reshape(4, 2), ("dp", "tp")),
                         max_nodes=32)
    tiny.solve([make_pod(requests={"cpu": "1"}) for _ in range(4)],
               provisioners, its)
    assert tiny.last_path == "single", (
        "FATAL: tiny batch entered the mesh program"
    )

    # warm interleaved A/B
    m_ts, s_ts = [], []
    for _ in range(AB_RUNS):
        t0 = time.perf_counter()
        solve(matched)
        m_ts.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        solve(single)
        s_ts.append(time.perf_counter() - t0)
    mesh_ms = min(m_ts) * 1e3
    single_ms = min(s_ts) * 1e3
    speedup = single_ms / max(mesh_ms, 1e-9)
    print(
        f"[smoke] sharded_speedup={speedup:.2f} "
        f"(mesh(1,{n_cores}) {mesh_ms:.0f}ms vs single {single_ms:.0f}ms "
        f"warm, {N_PODS} pods x {N_DISTINCT} distinct x {N_TYPES} types "
        f"+ {N_EXIST} existing; byte-identical on both meshes; "
        f"small-batch routes single)",
    )
    if mesh_ms > single_ms * MAX_SLOWDOWN:
        print(
            f"FATAL: mesh wall {mesh_ms:.0f}ms exceeds "
            f"{MAX_SLOWDOWN}x single {single_ms:.0f}ms — the multi-chip "
            f"path regressed toward the MULTICHIP_r05 failure mode",
            file=sys.stderr,
        )
        return 1
    print("[smoke] OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
