"""Segment-smoke: the segmented pack scan end to end against a LIVE
operator at a shrunk geometry (ISSUE 14).

Drives the operator's provisioning loop with segmented mode forced on over
a partitionable workload (selector-scoped per-team pools), and gates on:

  * every pod binds (the loop converges through the segmented dispatch);
  * the segmented dispatch actually engaged (>1 segment, fixup fraction
    0.0) and its placements are BYTE-IDENTICAL (flightrec-canonical) to a
    sequential solve of the same batch — the tentpole's correctness bar,
    proven on the live path, not just the unit suites;
  * the fixup fraction is REPORTED (the honest-perf contract: the bench
    artifact and this smoke both carry it);
  * one chaos-armed solver.segment injection degrades segmented ->
    sequential cleanly: the solve succeeds, placements stay identical,
    stats record the degradation.

Non-fatal in `make verify`, FATAL in hack/presubmit.sh — the same
promotion pattern as prewarm/multichip/consolidation smoke. Hermetic:
forces the CPU backend in-process (the image's sitecustomize pins the
axon tunnel; env vars can't override it).
"""
import copy
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

N_PODS = int(os.environ.get("KCT_SEGMENT_SMOKE_PODS", "48"))
POOLS = int(os.environ.get("KCT_SEGMENT_SMOKE_POOLS", "4"))


def main() -> int:
    from karpenter_core_tpu import chaos
    from karpenter_core_tpu.api.settings import Settings
    from karpenter_core_tpu.cloudprovider import fake
    from karpenter_core_tpu.obs.flightrec import (
        canonical_placements,
        placements_json,
    )
    from karpenter_core_tpu.operator import new_operator
    from karpenter_core_tpu.solver.tpu_solver import TPUSolver
    from karpenter_core_tpu.testing import (
        make_pod,
        make_pool_provisioners,
        solve_scan_parity,
    )

    problems = []
    universe = fake.instance_types(6)
    cp = fake.FakeCloudProvider(universe)
    solver = TPUSolver(max_nodes=64, pack_scan="segmented")
    op = new_operator(cp, settings=Settings(), solver=solver)

    provisioners, its = make_pool_provisioners(POOLS, universe)
    for prov in provisioners:
        op.kube_client.create(prov)
    pods = []
    for i in range(N_PODS):
        p = i % POOLS
        pod = make_pod(
            name=f"seg-smoke-{i}",
            labels={"app": f"dep-{p}-{i % 3}"},
            requests={"cpu": str(0.25 * (1 + i % 3))},
            node_selector={"team": f"pool-{p}"},
        )
        pods.append(pod)
        op.kube_client.create(pod)

    for _ in range(8):
        op.step()

    # the operator must have launched capacity for every pool through the
    # segmented solver (in-flight absorption of selector pods is a known
    # operator-layer gap independent of the scan mode — the convergence
    # bar here is per-pool capacity + the solver-level identity below)
    machines = op.kube_client.list("Machine")
    if not machines:
        problems.append("operator launched no machines")
    pools_launched = {
        m.metadata.labels.get("karpenter.sh/provisioner-name")
        for m in machines
    }
    missing = {f"pool-{p}" for p in range(POOLS)} - pools_launched
    if missing:
        problems.append(f"no capacity launched for pools: {sorted(missing)}")
    stats = solver.last_segment_stats or {}
    if stats.get("mode") != "segmented":
        problems.append(f"segmented mode never engaged: stats={stats}")
    if stats.get("segments", 0) < 2:
        problems.append(f"expected >1 segment, got {stats.get('segments')}")
    print(
        f"segment-smoke: segments={stats.get('segments')} "
        f"lanes={stats.get('lanes')} max_segment={stats.get('max_segment')} "
        f"fixup_fraction={stats.get('fixup_fraction')}"
    )

    # byte-identity on the live batch: segmented vs sequential, through
    # the SAME parity bar the unit/fuzz suites assert (incl. rounds and
    # failed-pod equality, with a flightrec diff on divergence)
    scan_solvers = {}
    try:
        r_seq, _r_seg = solve_scan_parity(
            scan_solvers, pods, provisioners, its, max_nodes=64
        )
    except AssertionError as err:
        problems.append(str(err))
        r_seq = scan_solvers["sequential"].solve(
            copy.deepcopy(pods), provisioners, its
        )
    seg2 = scan_solvers["segmented"]

    # chaos drill: a device fault inside the segmented attempt must
    # degrade to the sequential kernel, not fail the solve
    chaos.arm(chaos.SOLVER_SEGMENT, error="runtime", times=1)
    try:
        r_chaos = seg2.solve(copy.deepcopy(pods), provisioners, its)
    finally:
        chaos.disarm(chaos.SOLVER_SEGMENT)
    cstats = seg2.last_segment_stats or {}
    if cstats.get("mode") != "sequential-fallback" or not str(
        cstats.get("reason", "")
    ).startswith("error:"):
        problems.append(
            f"chaos injection did not degrade cleanly: stats={cstats}"
        )
    if placements_json(canonical_placements(r_chaos)) != placements_json(
        canonical_placements(r_seq)
    ):
        problems.append("degraded solve diverged from sequential")

    if problems:
        for p in problems:
            print(f"segment-smoke FAIL: {p}", file=sys.stderr)
        return 1
    print(
        f"segment-smoke ok: {N_PODS} pods over {POOLS} pools launched, "
        f"segments={stats.get('segments')} fixup={stats.get('fixup_fraction')}"
        f", byte-identical to sequential, chaos degraded cleanly"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
