"""Prewarm smoke (ISSUE 7): prove a restarted process solves fast from the
warm persistent compile cache.

Two child processes share one fresh cache directory:

  1. populate: AOT-prewarm the ladder's S tier (solver/prewarm.py) plus one
     live solve — exactly what an operator boot does — writing the
     persistent XLA cache to disk.
  2. restart: a FRESH process (cold jit caches, warm disk) solves the same
     tier-S geometry; its first Solve() must land under the budget —
     KCT_PREWARM_SMOKE_BUDGET seconds when set, else 60% of the measured
     populate (cold-compile) time, so the gate is robust to machine speed.
     This is the CPU-tier analog of the ROADMAP "first Solve() after
     operator restart < 2s on TPU at the bench geometry" exit criterion,
     which bench.py's warm-restart probe measures for real.

Exit code 0 on success; non-zero on a slow or cache-missing restart.
Wired as `make prewarm-smoke`: non-fatal in `make verify`, fatal in
hack/presubmit.sh.
"""
import json
import os
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

BUDGET_ENV = os.environ.get("KCT_PREWARM_SMOKE_BUDGET", "")
N_PODS = 40


def _workload():
    """One-tier ladder + matching synthetic workload, installed as the
    process-wide Settings so BOTH the prewarm and the later live solve's
    encode snap to the same geometry — the restart child must hit the
    prewarmed key, not merely the disk cache."""
    import karpenter_core_tpu.api.settings as api_settings
    from karpenter_core_tpu.api.settings import GeometryTier, Settings
    from karpenter_core_tpu.cloudprovider import fake
    from karpenter_core_tpu.solver.prewarm import synthetic_workload
    from karpenter_core_tpu.testing import make_provisioner

    tier = GeometryTier("S", pods=128, items=32, instance_types=8,
                        existing_nodes=8)
    settings = Settings(bucket_ladder=(tier,))
    api_settings.set_current(settings)
    provisioners = [make_provisioner(name="default")]
    its = {"default": fake.instance_types(5)}
    pods, nodes = synthetic_workload(tier, provisioners, its)
    return tier, settings, provisioners, its, pods, nodes


def child_populate() -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")
    from karpenter_core_tpu.solver.prewarm import prewarm
    from karpenter_core_tpu.solver.tpu_solver import TPUSolver
    from karpenter_core_tpu.utils.compilecache import enable_persistent_cache

    enable_persistent_cache(os.environ["KCT_PREWARM_SMOKE_CACHE"])
    tier, settings, provisioners, its, pods, nodes = _workload()
    solver = TPUSolver(max_nodes=48)
    t0 = time.perf_counter()
    outcomes = prewarm(solver, provisioners, its, settings=settings)
    # one live solve warms the fetch-slice mini-programs into the disk
    # cache too (they compile lazily per outcome bucket)
    solver.solve(pods[:N_PODS], provisioners, its, state_nodes=nodes)
    print(json.dumps({
        "prewarm_s": round(time.perf_counter() - t0, 1),
        "outcomes": outcomes,
    }))


def child_restart() -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")
    from karpenter_core_tpu.solver.tpu_solver import TPUSolver
    from karpenter_core_tpu.utils.compilecache import enable_persistent_cache

    cache_dir = os.environ["KCT_PREWARM_SMOKE_CACHE"]
    enable_persistent_cache(cache_dir)
    cache_files = len([f for f in os.listdir(cache_dir) if not f.startswith(".")])
    _tier, _settings, provisioners, its, pods, nodes = _workload()
    solver = TPUSolver(max_nodes=48)
    t0 = time.perf_counter()
    res = solver.solve(pods[:N_PODS], provisioners, its, state_nodes=nodes)
    first_solve_s = time.perf_counter() - t0
    print(json.dumps({
        "first_solve_s": round(first_solve_s, 2),
        "cache_files": cache_files,
        "scheduled": res.pod_count_new() + res.pod_count_existing(),
    }))


def _run_child(stage: str, cache_dir: str) -> dict:
    env = dict(os.environ)
    env["KCT_PREWARM_SMOKE_CHILD"] = stage
    env["KCT_PREWARM_SMOKE_CACHE"] = cache_dir
    env.setdefault("JAX_PLATFORMS", "cpu")
    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__)],
        env=env, stdout=subprocess.PIPE, text=True, timeout=600,
    )
    for line in reversed(out.stdout.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            return json.loads(line)
    raise RuntimeError(f"{stage} child produced no JSON (rc={out.returncode})")


def main() -> int:
    cache_dir = tempfile.mkdtemp(prefix="kct-prewarm-smoke-")
    print(f"[prewarm-smoke] cache dir {cache_dir}", file=sys.stderr)
    populate = _run_child("populate", cache_dir)
    print(f"[prewarm-smoke] populate: {populate}", file=sys.stderr)
    restart = _run_child("restart", cache_dir)
    print(f"[prewarm-smoke] restart: {restart}", file=sys.stderr)
    budget_s = (
        float(BUDGET_ENV)
        if BUDGET_ENV
        else 0.6 * float(populate.get("prewarm_s", 0.0) or 20.0)
    )
    ok = True
    if restart.get("cache_files", 0) <= 0:
        print("[prewarm-smoke] FAIL: persistent cache dir is empty",
              file=sys.stderr)
        ok = False
    if restart.get("scheduled") != N_PODS:
        print(f"[prewarm-smoke] FAIL: scheduled {restart.get('scheduled')} "
              f"!= {N_PODS}", file=sys.stderr)
        ok = False
    first = restart.get("first_solve_s", 1e9)
    if first >= budget_s:
        print(f"[prewarm-smoke] FAIL: first solve after restart {first}s >= "
              f"budget {budget_s:.1f}s", file=sys.stderr)
        ok = False
    if ok:
        print(f"[prewarm-smoke] OK: first solve after restart {first}s "
              f"(budget {budget_s:.1f}s, {restart['cache_files']} cache files)",
              file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    stage = os.environ.get("KCT_PREWARM_SMOKE_CHILD", "")
    if stage == "populate":
        child_populate()
    elif stage == "restart":
        child_restart()
    else:
        sys.exit(main())
