#!/bin/sh
# No-print guard (make verify): fail on bare print() in karpenter_core_tpu/
# outside hack//tests. AST-based — see hack/check_no_print.py.
exec python "$(dirname "$0")/check_no_print.py" "$@"
