#!/usr/bin/env bash
# Presubmit lane — the reference gates every PR on `make presubmit`
# (.github/workflows/presubmit.yaml:11-12 runs it across a k8s version
# matrix); this chains the same gates for this repo: full test suite,
# enforced perf floor, a short deflake pass over the concurrency-sensitive
# suites, and the driver verify hooks (single-chip compile + 8-way mesh
# dryrun at reduced scale).
#
# Usage: ./hack/presubmit.sh [quick]
#   quick  skips the deflake loop (for fast local iteration)
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== presubmit: make lint (static analysis, fatal)"
make lint

echo "== presubmit: make irlint (IR contract sweep over the staged program family, fatal)"
make irlint

echo "== presubmit: make race-smoke (lock-heavy suites, racewatch exhaustive, fatal)"
make race-smoke

echo "== presubmit: make test"
make test

echo "== presubmit: make perf (>=100 pods/sec floor)"
make perf

echo "== presubmit: make soak-smoke (host-mode churn: SLOs + crash drill + overload shed)"
make soak-smoke

echo "== presubmit: make soak-smoke-inproc (in-process wedge drill posture)"
make soak-smoke-inproc

echo "== presubmit: make prewarm-smoke (warm-cache restart under budget)"
make prewarm-smoke

echo "== presubmit: make multichip-smoke (GSPMD parity + speedup sanity)"
make multichip-smoke

echo "== presubmit: make consolidation-smoke (batched evaluator vs sequential simulator)"
make consolidation-smoke

echo "== presubmit: make bench-smoke (wedged stage degrades, --resume backfills)"
make bench-smoke

echo "== presubmit: make host-smoke (host killed mid-solve: respawn + parity + no zombies)"
make host-smoke

echo "== presubmit: make obs-smoke (cross-process graft + merged metrics + phase-named wedge)"
make obs-smoke

echo "== presubmit: make prof-smoke (program inventory + probe forensics + perf-ledger tripwire)"
make prof-smoke

echo "== presubmit: make segment-smoke (segmented scan: byte-identity + chaos degradation)"
make segment-smoke

if [[ "${1:-}" != "quick" ]]; then
  echo "== presubmit: short deflake (3 iterations)"
  MAX_ITERS=3 ./hack/deflake.sh
fi

echo "== presubmit: verify (entry compile + mesh dryrun, reduced scale)"
KCT_DRYRUN_PODS=600 KCT_DRYRUN_GENERIC_PODS=8000 \
KCT_DRYRUN_GENERIC_DISTINCT=200 KCT_DRYRUN_GENERIC_TYPES=50 \
KCT_DRYRUN_GENERIC_EXISTING=100 make verify

echo "== presubmit: OK"
