#!/usr/bin/env python
"""bench-smoke: the wedge drill for the resumable stage-graph bench
(ISSUE 11 acceptance gate — `make bench-smoke`; non-fatal in `make verify`,
FATAL in hack/presubmit.sh).

A tiny CPU-only round with TWO stages (headline + consolidation), the
`solver.device.hang` chaos point armed in the consolidation stage's worker
(sleep-past-watchdog, the observed tunnel-wedge shape). Asserts the whole
ISSUE-11 story end to end:

  1. the round COMPLETES (rc 0, one merged JSON line) even though one
     stage's worker wedged and was hard-killed by the supervisor;
  2. the wedged stage degrades to a MARKED column — `degraded: true` plus
     a `wedge_log` carrying the killed worker's env-redacted stderr tail —
     while every other column (and the full BENCH_r{N} schema) still lands;
  3. `bench.py --resume <round-dir>` re-runs ONLY the degraded stage (the
     headline artifact is untouched, byte-for-byte) and backfills the
     column.

Keeps a persistent compile cache under the system temp dir so repeat smoke
runs skip the geometry compiles.
"""
import json
import os
import shutil
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# tiny geometry: the point is the orchestration, not the numbers
SMOKE_ENV = {
    "BENCH_CPU": "1",
    "BENCH_STAGES": "headline,consolidation",
    "BENCH_PODS": "200",
    "BENCH_TYPES": "10",
    "BENCH_RUNS": "2",
    "BENCH_DISTINCT": "8",
    "BENCH_EXISTING": "8",
    "BENCH_NODES": "256",
    "BENCH_CONS_NODES": "8",
    "BENCH_CONS_PODS": "40",
    "BENCH_CONS_TYPES": "4",
    # wedge detection must fire in seconds, not the production 600s
    "BENCH_STAGE_STALE": "30",
    "BENCH_TOTAL_BUDGET": "900",
    # repeat smokes share compiled programs (same fixed geometry)
    "BENCH_COMPILE_CACHE_DIR": os.path.join(
        tempfile.gettempdir(), "kct-bench-smoke-cache"
    ),
}
# the hang: armed ONLY in the consolidation stage's worker; latency far
# past the staleness threshold so the supervisor must hard-kill the group
HANG = "consolidation=solver.device.hang=error:none,latency:600,times:1"

# the merged line must stay schema-complete even with a wedged column
EXPECTED_EXTRA_KEYS = {
    "e2e_p50_ms", "e2e_p99_ms", "device_solve_med_ms", "pipelined_p50_ms",
    "pipelined_p99_ms", "single_call_under_target", "pipelined_under_target",
    "device_under_target", "runs", "tail", "scheduled_min", "compile_cold_s",
    "first_solve_warm_s", "warm_restart_cache_verified",
    "warm_restart_under_2s", "bucket_hit_ratio", "warm_restart",
    "compiled_programs_after_varied_batches", "solver", "sharded_speedup",
    "mesh", "multichip", "chips", "backend_probe", "consolidation",
    "consolidation_xl", "consolidation_under_1s", "config5_multiprov_spot_od",
    "config_grid_1_2_3", "stages", "round_dir", "orchestrator_probe",
}


def run_bench(round_dir, resume=False, chaos=""):
    env = dict(os.environ)
    env.update(SMOKE_ENV)
    env["BENCH_ROUND_DIR"] = round_dir
    env.pop("BENCH_STAGE_CHAOS", None)
    if chaos:
        env["BENCH_STAGE_CHAOS"] = chaos
    cmd = [sys.executable, os.path.join(REPO, "bench.py")]
    if resume:
        cmd += ["--resume", round_dir]
    proc = subprocess.run(
        cmd, env=env, cwd=REPO, capture_output=True, text=True, timeout=900,
    )
    sys.stderr.write(proc.stderr[-4000:])
    line = None
    for ln in proc.stdout.splitlines():
        ln = ln.strip()
        if ln.startswith("{"):
            try:
                line = json.loads(ln)
            except ValueError:
                continue
    return proc.returncode, line


def main() -> int:
    failures = []

    def check(cond, what):
        print(("ok   " if cond else "FAIL ") + what, file=sys.stderr)
        if not cond:
            failures.append(what)

    round_dir = tempfile.mkdtemp(prefix="kct-bench-smoke-round-")
    try:
        # -- round 1: consolidation's worker wedges (hang chaos armed) ----
        rc, merged = run_bench(round_dir, chaos=HANG)
        check(rc == 0, "wedged round still exits 0")
        check(merged is not None, "wedged round still emits the JSON line")
        if merged is None:
            return 1
        extra = merged.get("extra", {})
        missing = EXPECTED_EXTRA_KEYS - set(extra)
        check(not missing, f"merged schema complete (missing: {sorted(missing)})")
        cons = extra.get("consolidation") or {}
        check(cons.get("degraded") is True, "wedged stage marked degraded")
        wlog = cons.get("wedge_log") or {}
        check(wlog.get("wedged") is True,
              "wedge_log classifies the kill as a wedge (stale heartbeat)")
        check(bool(wlog.get("stderr_tail")),
              "wedge_log carries the killed worker's stderr tail")
        check("latency" not in json.dumps(extra.get("stages", {})),
              "chaos spec not leaked into other stages' workers")
        head = extra.get("stages", {}).get("headline", {})
        check(head.get("status") == "ok", "headline column landed despite the wedge")
        check(extra.get("e2e_p99_ms") is not None,
              "headline e2e numbers present")

        # -- round timeline (ISSUE 15): the wedge is VISIBLE on it --------
        timeline_path = os.path.join(round_dir, "BENCH_timeline.json")
        check(os.path.exists(timeline_path), "BENCH_timeline.json emitted")
        timeline = {}
        if os.path.exists(timeline_path):
            with open(timeline_path) as f:
                timeline = json.load(f)
        names = [e.get("name") for e in timeline.get("traceEvents", [])]
        check("bench.wedge.SIGKILL" in names,
              "the chaos-wedged stage's kill is visible on the timeline")
        check(any(n and n.startswith("bench.stage.") for n in names),
              "timeline carries orchestrator stage slices")
        # byte-stability: re-merging the same store reproduces the file
        sys.path.insert(
            0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        import bench as bench_mod
        from karpenter_core_tpu.utils import supervise as sup

        rebuilt = bench_mod.build_timeline(
            sup.ArtifactStore(os.path.join(round_dir, "stages"))
        )
        check(
            json.dumps(rebuilt, sort_keys=True)
            == json.dumps(timeline, sort_keys=True),
            "timeline is byte-stable across re-merges",
        )

        head_artifact = os.path.join(round_dir, "stages", "headline.json")
        with open(head_artifact, "rb") as f:
            head_bytes_before = f.read()

        # -- round 2: --resume backfills ONLY the degraded stage ----------
        rc2, merged2 = run_bench(round_dir, resume=True)
        check(rc2 == 0, "--resume exits 0")
        check(merged2 is not None, "--resume emits the merged line")
        if merged2 is None:
            return 1
        extra2 = merged2.get("extra", {})
        planned = [
            ln for ln in extra2.get("orchestrator_probe", [])
            if ln.startswith("stages to run:")
        ]
        check(planned == ["stages to run: consolidation"],
              f"resume re-runs ONLY the degraded stage (planned: {planned})")
        cons2 = extra2.get("consolidation") or {}
        check(not cons2.get("degraded"),
              "degraded column backfilled on resume")
        check(cons2.get("replan_med_ms") is not None,
              "backfilled column carries real data")
        with open(head_artifact, "rb") as f:
            check(f.read() == head_bytes_before,
                  "headline artifact untouched by the resume (byte-identical)")
    finally:
        shutil.rmtree(round_dir, ignore_errors=True)

    if failures:
        print(f"bench-smoke UNHEALTHY: {len(failures)} failure(s)",
              file=sys.stderr)
        return 1
    print("bench-smoke ok: wedge degraded one column, resume backfilled it",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
