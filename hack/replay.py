"""Flight-record replay: load a solve record (from /debug/solves or an
auto-dump) and re-run its exact inputs through GreedySolver and TPUSolver,
diffing placements — a field incident becomes a deterministic differential
test (`make replay-demo` smoke-checks the whole loop; wired into
`make verify` as a non-fatal step).

Usage:
    python hack/replay.py RECORD.json            # replay one dumped record
    python hack/replay.py SOLVES.json --index -1 # a /debug/solves download
    python hack/replay.py RECORD.json --solver greedy|tpu|both
    python hack/replay.py --demo                 # live capture -> replay

Consolidation decision records (kind=consolidation, a /debug/consolidations
download or obs/flightrec record_consolidation output) are auto-detected:
the replay re-runs EVERY screened candidate subset through the sequential
simulator and diffs its verdicts — and the command it would have chosen —
against the recorded device-ranked decision (docs/consolidation.md).

Exit status is 0 when the recorded backend's replay reproduces the
recorded placements byte-identically (the determinism bar); the
greedy-vs-tpu diff is informational — the two algorithms may legitimately
produce different, equally valid placements (see
tests/test_differential_fuzz.py for the equivalence bar).

Hermetic: forces the CPU backend in-process (the image's sitecustomize
pins the axon TPU tunnel; env vars can't override it — same treatment as
`make verify`'s compile check).
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")


def _load_record(path: str, index: int) -> dict:
    with open(path) as f:
        body = json.load(f)
    if isinstance(body, dict) and "records" in body:  # /debug/solves download
        records = body["records"]
        if not records:
            raise SystemExit(f"{path}: no records in ring export")
        return records[index]
    if isinstance(body, list):
        return body[index]
    return body


def _describe(record: dict) -> str:
    inputs = record.get("inputs", {})
    return (
        f"backend={record.get('backend')} digest={record.get('digest')} "
        f"pods={len(inputs.get('pods', []))} "
        f"state_nodes={len(inputs.get('stateNodes', []))} "
        f"trace={record.get('trace_id', '-')} "
        f"duration_ms={record.get('duration_ms')}"
    )


def replay_consolidation_record(record: dict, solver: str = "greedy") -> int:
    """Diff a recorded consolidation decision (the device-ranked subset
    evaluator's verdicts + chosen Command, obs/flightrec
    record_consolidation) against the sequential simulator, offline.

    Exit status is 0 when the sequential simulator validates the executed
    command (the parity bar); per-subset verdict differences where the
    relaxing simulator is MORE permissive than the round-0 screen are
    expected and informational."""
    from karpenter_core_tpu.obs import flightrec

    solver = "greedy" if solver == "both" else solver
    chosen = record.get("chosen", {})
    print(
        f"consolidation record: deprovisioner={record.get('deprovisioner')} "
        f"candidates={len(record.get('candidates', []))} "
        f"subsets={len(record.get('subsets', []))} "
        f"chosen={chosen.get('action')}:{chosen.get('nodes')}"
    )
    diff = flightrec.replay_consolidation(record, solver_kind=solver)
    for sub in diff["subsets"]:
        flag = "==" if sub["agrees"] else "!="
        print(
            f"  subset {sub['members']}: device "
            f"(sched={sub['allScheduled']}, new={sub['nNewMachines']}, "
            f"conclusive={sub['conclusive']}, savings={sub['savings']}) "
            f"{flag} sequential({solver}) "
            f"(sched={sub['seqAllScheduled']}, new={sub['seqNewMachines']})"
        )
    print(f"sequential pick by the same objective: {diff['seq_pick']}")
    if diff["chosen_feasible_seq"]:
        print("sequential simulator validates the chosen command")
        return 0
    print("sequential simulator REJECTS the chosen command")
    return 1


def replay_record(record: dict, solver: str = "both") -> int:
    if record.get("kind") == "consolidation":
        return replay_consolidation_record(record, solver)
    from karpenter_core_tpu.obs import flightrec

    print(f"record: {_describe(record)}")
    if record.get("phases_ms"):
        print(f"phases_ms: {record['phases_ms']}")
    if record.get("primary_error"):
        print(f"primary_error: {record['primary_error']}")
    recorded = record.get("outcome", {}).get("placements")

    results = {}
    kinds = ["greedy", "tpu"] if solver == "both" else [solver]
    for kind in kinds:
        placements, res = flightrec.replay(record, kind)
        results[kind] = placements
        print(
            f"{kind}: {len(placements['machines'])} machines, "
            f"{sum(len(m['pods']) for m in placements['machines'])} pods on new, "
            f"{sum(len(e['pods']) for e in placements['existing'])} on existing, "
            f"{len(placements['failed'])} failed (rounds={res.rounds})"
        )

    rc = 0
    if recorded is not None:
        # determinism bar: the recorded backend's replay must reproduce the
        # captured placements byte for byte
        replayer = record.get("replayer", "greedy")
        replayed = results.get(replayer)
        if replayed is None:
            replayed, _ = flightrec.replay(record, replayer)
        if flightrec.placements_json(replayed) == flightrec.placements_json(recorded):
            print(f"replay({replayer}) == recorded placements: byte-identical")
        else:
            rc = 1
            print(f"replay({replayer}) DIVERGED from the recorded placements:")
            for line in flightrec.diff_placements(recorded, replayed):
                print(f"  {line}")
    if "greedy" in results and "tpu" in results:
        diff = flightrec.diff_placements(results["greedy"], results["tpu"])
        if diff:
            print("greedy vs tpu differential (informational):")
            for line in diff:
                print(f"  {line}")
        else:
            print("greedy vs tpu: identical placements")
    return rc


def demo(tmp_dir: str) -> int:
    """Zero-to-replay smoke: capture a record from a live solve through the
    production wrapper (ResilientSolver), dump it, reload it, and replay."""
    from karpenter_core_tpu.cloudprovider import fake
    from karpenter_core_tpu.obs import FLIGHTREC, TRACER
    from karpenter_core_tpu.solver.fallback import ResilientSolver
    from karpenter_core_tpu.solver.tpu_solver import GreedySolver, TPUSolver
    from karpenter_core_tpu.testing import make_pod, make_provisioner

    TRACER.enable()
    FLIGHTREC.enable(dump_dir=tmp_dir)
    FLIGHTREC.clear()
    n_pods = int(os.environ.get("KCT_REPLAY_DEMO_PODS", "48"))
    pods = [
        make_pod(labels={"app": f"demo-{i % 6}"}, requests={"cpu": "1"})
        for i in range(n_pods)
    ]
    provisioners = [make_provisioner(name="default")]
    instance_types = {"default": fake.instance_types(8)}
    solver = ResilientSolver(
        TPUSolver(max_nodes=max(64, n_pods // 4)), GreedySolver(),
        prober=lambda: None,
    )
    result = solver.solve(pods, provisioners, instance_types)
    placed = result.pod_count_new() + result.pod_count_existing()
    record = FLIGHTREC.last()
    if record is None:
        print("replay-demo FAIL: no flight record captured", file=sys.stderr)
        return 1
    if placed != n_pods:
        print(
            f"replay-demo FAIL: live solve placed {placed}/{n_pods} pods",
            file=sys.stderr,
        )
        return 1
    path = FLIGHTREC.dump(record)
    if not path:
        print("replay-demo FAIL: record dump failed", file=sys.stderr)
        return 1
    print(f"captured {path}")
    rc = replay_record(_load_record(path, -1), solver="both")
    print("replay-demo ok" if rc == 0 else "replay-demo FAIL: replay diverged")
    return rc


def main() -> int:
    parser = argparse.ArgumentParser(description="solve flight-record replay")
    parser.add_argument("record", nargs="?", help="record JSON (a dump file or a /debug/solves download)")
    parser.add_argument("--index", type=int, default=-1,
                        help="record index when the file holds a ring export")
    parser.add_argument("--solver", choices=("greedy", "tpu", "both"),
                        default="both")
    parser.add_argument("--demo", action="store_true",
                        help="capture a record from a live solve, then replay it")
    args = parser.parse_args()
    if args.demo:
        import tempfile

        return demo(os.path.join(tempfile.gettempdir(), "karpenter-flightrec"))
    if not args.record:
        parser.error("a record file is required (or --demo)")
    return replay_record(_load_record(args.record, args.index), args.solver)


if __name__ == "__main__":
    sys.exit(main())
