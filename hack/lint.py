#!/usr/bin/env python
"""Static-analysis driver: run every registered pass over karpenter_core_tpu/.

Usage:
  python hack/lint.py                  # all AST passes, fatal on any violation
  python hack/lint.py --list-rules     # rule catalog
  python hack/lint.py --rule no-print --rule layering
  python hack/lint.py --jobs 4         # file-scope passes on a process pool
  python hack/lint.py --changed        # report only files differing from main
  python hack/lint.py --format sarif   # SARIF 2.1.0 for CI PR annotation
  python hack/lint.py --update-baseline  # absorb current violations (debt
                                         # marker — the checked-in baseline
                                         # must ship empty)
  python hack/lint.py --ir             # IR contract sweep (`make irlint`):
                                       # stage the compiled-program family
                                       # on CPU and check jaxpr/HLO
                                       # contracts (rule ids ir-*)
  python hack/lint.py --ir --families solve,prescreen --tiers S

Per-line suppression in source: `# lint: disable=<rule>[,<rule>...]`.
Unused suppressions print as warnings (never fatal). Exit codes: 0 clean,
1 violations, 2 usage error.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from karpenter_core_tpu.analysis import (  # noqa: E402
    all_passes,
    default_config,
    load_baseline,
    run_passes,
)
from karpenter_core_tpu.analysis.core import (  # noqa: E402
    collect_sources,
    run_passes_multiprocessing,
)

DEFAULT_BASELINE = os.path.join(REPO_ROOT, "hack", "lint-baseline.txt")


def _ir_pass(args):
    """Bootstrap the jax CPU environment and build the IR contracts pass.
    Env vars must land BEFORE jax imports: the mesh family needs 8 host
    devices (--xla_force_host_platform_device_count) and the sweep must
    never grab a real accelerator. The persistent compile cache keeps the
    warm sweep to ~a minute (only the tier-S mesh programs compile)."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", 8)
    except Exception:  # noqa: BLE001 — knob absent on older jax
        pass
    from karpenter_core_tpu.utils.compilecache import enable_persistent_cache

    enable_persistent_cache()
    from karpenter_core_tpu.analysis.irlint import IRContractsPass

    families = args.families.split(",") if args.families else None
    tiers = args.tiers.split(",") if args.tiers else None
    return IRContractsPass(tiers=tiers, families=families)


def changed_relpaths(base: str = "main") -> set:
    """Repo-relative paths of files differing from `base` (committed,
    staged, or unstaged) plus untracked files — what a PR's reviewable
    surface is. Raises RuntimeError outside a git checkout."""
    out = set()
    for args in (
        ["git", "diff", "--name-only", base, "--"],
        ["git", "ls-files", "--others", "--exclude-standard"],
    ):
        proc = subprocess.run(
            args, cwd=REPO_ROOT, capture_output=True, text=True
        )
        if proc.returncode != 0:
            raise RuntimeError(
                f"{' '.join(args)} failed: {proc.stderr.strip()}"
            )
        out.update(line.strip() for line in proc.stdout.splitlines() if line.strip())
    return out


def sarif_payload(passes, result) -> dict:
    """SARIF 2.1.0 over the kept violations: one rule entry per rule id,
    one result per violation (region startLine), so CI can annotate PRs."""
    rule_ids = sorted({r for p in passes for r in p.rules})
    return {
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "karpenter-lint",
                        "informationUri": (
                            "docs/static-analysis.md"
                        ),
                        "rules": [
                            {
                                "id": rule,
                                "shortDescription": {"text": rule},
                                "helpUri": "docs/static-analysis.md",
                            }
                            for rule in rule_ids
                        ],
                    }
                },
                "results": [
                    {
                        "ruleId": v.rule,
                        "level": "error",
                        "message": {"text": v.message},
                        "locations": [
                            {
                                "physicalLocation": {
                                    "artifactLocation": {"uri": v.relpath},
                                    "region": {"startLine": max(v.line, 1)},
                                }
                            }
                        ],
                    }
                    for v in result.violations
                ],
            }
        ],
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument(
        "--rule", action="append", default=None,
        help="run only the named rule(s); repeatable",
    )
    parser.add_argument("--baseline", default=DEFAULT_BASELINE)
    parser.add_argument(
        "--update-baseline", action="store_true",
        help="rewrite the baseline file with the current violation set",
    )
    parser.add_argument("--list-rules", action="store_true")
    parser.add_argument(
        "--format", choices=("text", "sarif"), default="text",
        help="violation output format (sarif: SARIF 2.1.0 on stdout)",
    )
    parser.add_argument(
        "--changed", nargs="?", const="main", default=None, metavar="BASE",
        help="report only files differing from BASE (default: main). The "
        "passes still see the whole package (layering needs the global "
        "import graph); only the REPORT is filtered, so per-file findings "
        "are identical to a full run",
    )
    parser.add_argument(
        "--workers", type=int, default=min(8, os.cpu_count() or 1),
        help="thread-pool width for file-scope passes (1 = sequential; "
        "findings are identical either way)",
    )
    parser.add_argument(
        "--jobs", type=int, default=min(4, os.cpu_count() or 1),
        metavar="N",
        help="process-pool width for file-scope passes (takes precedence "
        "over --workers when > 1; findings are byte-identical to the "
        "sequential run — tests/test_analysis_framework.py asserts it)",
    )
    parser.add_argument(
        "--ir", action="store_true",
        help="run the IR contract sweep instead of the AST passes: stage "
        "the whole compiled-program family (solve/prescreen/refresh/"
        "replan/segment across the bucket ladder, mesh variant included) "
        "on the CPU backend and evaluate analysis/irlint/contracts.py "
        "(rule ids ir-*). Needs jax; shares the persistent compile cache",
    )
    parser.add_argument(
        "--families", default=None, metavar="F[,F...]",
        help="(--ir only) comma-separated program families to stage: "
        "prescreen,solve,refresh,replan,segment",
    )
    parser.add_argument(
        "--tiers", default=None, metavar="T[,T...]",
        help="(--ir only) comma-separated bucket-ladder tier names to "
        "stage (e.g. S,M); the mesh/tripwire variants ride with tier S",
    )
    parser.add_argument(
        "-q", "--quiet", action="store_true", help="violations only, no summary"
    )
    args = parser.parse_args(argv)

    if (args.families or args.tiers) and not args.ir:
        print("lint: --families/--tiers require --ir", file=sys.stderr)
        return 2

    if args.ir:
        passes = [_ir_pass(args)]
    else:
        passes = all_passes()
    if args.list_rules:
        for p in passes:
            for rule in p.rules:
                print(f"{rule}  (pass: {p.name})")
        return 0

    rules = set(args.rule) if args.rule else None
    if args.update_baseline and (rules or args.changed):
        # a filtered update would silently drop every other entry
        print("lint: --update-baseline requires a full run "
              "(drop --rule/--changed)", file=sys.stderr)
        return 2
    if rules:
        known = {r for p in passes for r in p.rules}
        unknown = rules - known
        if unknown:
            print(f"lint: unknown rule(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2

    changed = None
    if args.changed is not None:
        try:
            changed = changed_relpaths(args.changed)
        except (RuntimeError, OSError) as e:
            print(f"lint: --changed unavailable: {e}", file=sys.stderr)
            return 2

    config = default_config(REPO_ROOT)
    files = collect_sources(REPO_ROOT, config.package_name)
    baseline = load_baseline(args.baseline) if not args.update_baseline else set()
    if args.ir and not rules:
        # scope the suppression/baseline accounting to the ir-* rules:
        # the AST passes didn't run, so their suppressions must not be
        # reported as unused off a sweep that could never hit them
        rules = {r for p in passes for r in p.rules}
    if not args.ir and args.jobs > 1:
        result = run_passes_multiprocessing(
            files, config, rules=rules, baseline=baseline, jobs=args.jobs
        )
    else:
        result = run_passes(files, config, passes=passes, rules=rules,
                            baseline=baseline, workers=max(1, args.workers))
    if changed is not None:
        result.violations = [
            v for v in result.violations if v.relpath in changed
        ]
        result.suppressed = [
            v for v in result.suppressed if v.relpath in changed
        ]
        result.baselined = [
            v for v in result.baselined if v.relpath in changed
        ]
        result.unused_suppressions = [
            v for v in result.unused_suppressions if v.relpath in changed
        ]

    if args.update_baseline:
        with open(args.baseline, "w", encoding="utf-8") as f:
            f.write("# Static-analysis baseline (hack/lint.py --update-baseline).\n")
            f.write("# Entries are `relpath:line:rule` debt markers; this file\n")
            f.write("# must ship EMPTY — see docs/static-analysis.md.\n")
            for v in result.violations:
                f.write(v.key() + "\n")
        print(f"lint: baseline updated with {len(result.violations)} entr"
              f"{'y' if len(result.violations) == 1 else 'ies'}")
        return 0

    if args.format == "sarif":
        json.dump(sarif_payload(passes, result), sys.stdout, indent=2)
        sys.stdout.write("\n")
        return 1 if result.violations else 0

    for v in result.violations:
        print(v.render())
    for v in result.unused_suppressions:
        # warn-only: dead `# lint: disable=` comments are blind spots but
        # never fail the run — deleting the comment clears the warning
        print(f"warning: {v.render()}")
    if not args.quiet:
        parts = [f"{len(result.violations)} violation(s)"]
        if result.suppressed:
            parts.append(f"{len(result.suppressed)} suppressed")
        if result.baselined:
            parts.append(f"{len(result.baselined)} baselined")
        if result.unused_suppressions:
            parts.append(
                f"{len(result.unused_suppressions)} unused suppression(s)"
            )
        if changed is not None:
            parts.append(f"changed-only: {len(changed)} file(s) vs {args.changed}")
        ran = sorted(rules) if rules else sorted(r for p in passes for r in p.rules)
        print(f"lint: {', '.join(parts)} — rules: {', '.join(ran)}")
    return 1 if result.violations else 0


if __name__ == "__main__":
    sys.exit(main())
