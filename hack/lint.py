#!/usr/bin/env python
"""Static-analysis driver: run every registered pass over karpenter_core_tpu/.

Usage:
  python hack/lint.py                  # all passes, fatal on any violation
  python hack/lint.py --list-rules     # rule catalog
  python hack/lint.py --rule no-print --rule layering
  python hack/lint.py --update-baseline  # absorb current violations (debt
                                         # marker — the checked-in baseline
                                         # must ship empty)

Per-line suppression in source: `# lint: disable=<rule>[,<rule>...]`.
Exit codes: 0 clean, 1 violations, 2 usage error.
"""
from __future__ import annotations

import argparse
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from karpenter_core_tpu.analysis import (  # noqa: E402
    all_passes,
    default_config,
    load_baseline,
    run_passes,
)
from karpenter_core_tpu.analysis.core import collect_sources  # noqa: E402

DEFAULT_BASELINE = os.path.join(REPO_ROOT, "hack", "lint-baseline.txt")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument(
        "--rule", action="append", default=None,
        help="run only the named rule(s); repeatable",
    )
    parser.add_argument("--baseline", default=DEFAULT_BASELINE)
    parser.add_argument(
        "--update-baseline", action="store_true",
        help="rewrite the baseline file with the current violation set",
    )
    parser.add_argument("--list-rules", action="store_true")
    parser.add_argument(
        "-q", "--quiet", action="store_true", help="violations only, no summary"
    )
    args = parser.parse_args(argv)

    passes = all_passes()
    if args.list_rules:
        for p in passes:
            for rule in p.rules:
                print(f"{rule}  (pass: {p.name})")
        return 0

    rules = set(args.rule) if args.rule else None
    if rules and args.update_baseline:
        # a filtered update would silently drop every other rule's entries
        print("lint: --update-baseline requires a full run (drop --rule)",
              file=sys.stderr)
        return 2
    if rules:
        known = {r for p in passes for r in p.rules}
        unknown = rules - known
        if unknown:
            print(f"lint: unknown rule(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2

    config = default_config(REPO_ROOT)
    files = collect_sources(REPO_ROOT, config.package_name)
    baseline = load_baseline(args.baseline) if not args.update_baseline else set()
    result = run_passes(files, config, passes=passes, rules=rules,
                        baseline=baseline)

    if args.update_baseline:
        with open(args.baseline, "w", encoding="utf-8") as f:
            f.write("# Static-analysis baseline (hack/lint.py --update-baseline).\n")
            f.write("# Entries are `relpath:line:rule` debt markers; this file\n")
            f.write("# must ship EMPTY — see docs/static-analysis.md.\n")
            for v in result.violations:
                f.write(v.key() + "\n")
        print(f"lint: baseline updated with {len(result.violations)} entr"
              f"{'y' if len(result.violations) == 1 else 'ies'}")
        return 0

    for v in result.violations:
        print(v.render())
    if not args.quiet:
        parts = [f"{len(result.violations)} violation(s)"]
        if result.suppressed:
            parts.append(f"{len(result.suppressed)} suppressed")
        if result.baselined:
            parts.append(f"{len(result.baselined)} baselined")
        ran = sorted(rules) if rules else sorted(r for p in passes for r in p.rules)
        print(f"lint: {', '.join(parts)} — rules: {', '.join(ran)}")
    return 1 if result.violations else 0


if __name__ == "__main__":
    sys.exit(main())
