"""Chaos smoke: arm a fail-3-then-recover create fault via the
KARPENTER_CHAOS env grammar against a full in-process control plane, and
validate the ISSUE-2 contract end to end (`make chaos-smoke`; wired into
`make verify` as a non-fatal step, same pattern as trace-demo):

  * the env spec parses and arms (seeded, deterministic),
  * the injected faults fire and karpenter_chaos_injected_total appears in
    the /metrics exposition alongside the retry/ICE counters,
  * the loop recovers: a final re-solve needs no new machines and strands
    no pods — degrade, never stall.

Hermetic: forces the CPU backend in-process (the image's sitecustomize pins
the axon TPU tunnel; env vars can't override it — same treatment as `make
verify`'s compile check).
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

N_PODS = int(os.environ.get("KCT_CHAOS_SMOKE_PODS", "12"))
SPEC = os.environ.get(
    "KCT_CHAOS_SMOKE_SPEC", "cloudprovider.create=error:conn,times:3"
)
SEED = os.environ.get("KARPENTER_CHAOS_SEED", "42")


def main() -> int:
    from karpenter_core_tpu import chaos
    from karpenter_core_tpu.api.settings import Settings
    from karpenter_core_tpu.cloudprovider import fake
    from karpenter_core_tpu.metrics.registry import REGISTRY
    from karpenter_core_tpu.operator import new_operator
    from karpenter_core_tpu.testing import make_pod, make_provisioner

    armed = chaos.arm_from_env(
        {"KARPENTER_CHAOS": SPEC, "KARPENTER_CHAOS_SEED": SEED}
    )
    fault = armed[chaos.CLOUDPROVIDER_CREATE]

    cp = fake.FakeCloudProvider(fake.instance_types(8))
    op = new_operator(cp, settings=Settings())
    op.kube_client.create(make_provisioner(name="default"))
    for i in range(N_PODS):
        op.kube_client.create(make_pod(name=f"smoke-{i}", requests={"cpu": "1"}))
    for _ in range(8):
        op.step()

    problems = []
    if fault.injected != 3:
        problems.append(f"expected 3 injected faults, saw {fault.injected}")
    if not op.kube_client.list("Machine"):
        problems.append("no machines launched after the fault recovered")
    op.sync_state()
    result = op.provisioning.schedule()
    if result is not None and (result.new_machines or result.failed_pods):
        problems.append(
            f"loop did not converge: new={len(result.new_machines)} "
            f"failed={len(result.failed_pods)}"
        )

    # the counters the /debug|/metrics exposition must carry
    text = REGISTRY.expose()
    for needle in (
        "karpenter_chaos_injected_total",
        "karpenter_launch_failures_total",
        "karpenter_launch_resolve_retriggers_total",
    ):
        if needle not in text:
            problems.append(f"{needle} missing from the metrics exposition")

    chaos.reset()
    if problems:
        for p in problems:
            print(f"chaos-smoke FAIL: {p}", file=sys.stderr)
        return 1
    print(
        f"chaos-smoke ok: spec={SPEC!r} injected={fault.injected} "
        f"machines={len(op.kube_client.list('Machine'))} pods={N_PODS} "
        "(all scheduled, counters exposed)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
