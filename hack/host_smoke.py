"""Host smoke (`make host-smoke`, ISSUE 12): kill the solver host mid-solve
under the LIVE operator and prove the control plane recovers with parity.

The drill, end to end (~60s budget, typically much faster):

  1. a full in-process control plane runs with the production host-mode
     wiring: HostSolver (supervised sidecar dispatch) under
     ResilientSolver (greedy fallback + breaker), exactly what
     operator/__main__ builds when KARPENTER_SOLVER_HOST is on;
  2. `solver.device.hang` is armed IN THE CHILD (env grammar) so a real
     device dispatch goes heartbeat-silent mid-solve — the parent
     watchdog SIGKILLs the host process group (the zombie dies for
     real), respawns it, and the greedy fallback keeps admitting;
  3. a second drill SIGKILLs the respawned host directly (the crash
     shape — no warning, no staleness);
  4. acceptance: every pod is covered, the host generation advanced for
     BOTH kills, the breaker re-closed (re-admission = host respawned +
     probe passed), /debug/health-shape report shows ZERO live zombies,
     and a post-recovery solve through the host is byte-identical to an
     in-process TPUSolver solve of the same workload.

Hermetic: forces the CPU backend in-process (same treatment as `make
verify`'s compile check). Non-fatal in `make verify`, FATAL in
hack/presubmit.sh — the bench-smoke/soak-smoke pattern.
"""
import os
import signal
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

N_PODS = int(os.environ.get("KCT_HOST_SMOKE_PODS", "8"))
STALE_AFTER = float(os.environ.get("KCT_HOST_SMOKE_STALE", "3.0"))


def main() -> int:
    from karpenter_core_tpu.api.settings import Settings
    from karpenter_core_tpu.cloudprovider import fake
    from karpenter_core_tpu.obs.flightrec import (
        canonical_placements,
        placements_json,
    )
    from karpenter_core_tpu.operator import new_operator
    from karpenter_core_tpu.solver.fallback import (
        SOLVER_WEDGED_TOTAL,
        CircuitBreaker,
        ResilientSolver,
    )
    from karpenter_core_tpu.solver.host import HostSolver
    from karpenter_core_tpu.solver.tpu_solver import GreedySolver, TPUSolver
    from karpenter_core_tpu.testing import make_pod, make_provisioner

    wedged_before = SOLVER_WEDGED_TOTAL.get() or 0.0
    host = HostSolver(
        max_nodes=64, stale_after=STALE_AFTER, solve_timeout=60.0,
        spawn_timeout=120.0,
        child_env={
            "KARPENTER_SOLVER_MODE": "single",
            # the SECOND device dispatch in the child hangs well past the
            # watchdog: a hard wedge mid-solve under the live operator
            "KARPENTER_CHAOS":
                "solver.device.hang=error:none,latency:60,times:1,after:1",
        },
    )
    resilient = ResilientSolver(
        host, GreedySolver(), small_batch_work_max=0,
        solve_timeout=120.0, wedge_stale_after=None,  # the host watches
        reprobe_interval=2.0, probe_timeout=60.0,
    )
    cp = fake.FakeCloudProvider(fake.instance_types(10))
    op = new_operator(
        cp,
        settings=Settings(batch_idle_duration=0.02, batch_max_duration=0.2),
        solver=resilient,
    )
    op.provisioning.fallback_solver = resilient
    op.kube_client.create(make_provisioner(name="default"))

    problems = []
    op.start()
    try:
        for i in range(N_PODS):
            op.kube_client.create(
                make_pod(name=f"hs-{i}", requests={"cpu": "1"})
            )
        # drive until every pod is covered — through the wedge, the kill,
        # the respawn, and the breaker cycle
        deadline = time.monotonic() + 45.0
        covered = False
        while time.monotonic() < deadline and not covered:
            time.sleep(0.1)
            op.sync_state()
            result = op.provisioning.schedule()
            covered = result is None or (
                not result.new_machines and not result.failed_pods
            )
        if not covered:
            problems.append("admission did not cover every pod in budget")
        wedged = (SOLVER_WEDGED_TOTAL.get() or 0.0) - wedged_before
        if wedged < 1:
            problems.append(
                "the armed hang never surfaced as a wedge "
                f"(wedged_total delta {wedged:.0f})"
            )
        gen_after_wedge = host.host.generation
        if gen_after_wedge < 2:
            problems.append(
                f"host generation {gen_after_wedge}: the wedged process "
                "was never killed+respawned"
            )
        # crash drill: SIGKILL the respawned host outright. First disarm
        # the child-env hang — each respawn re-arms from env, and the
        # parity check below must run against a CLEAN child
        host.host.child_env.pop("KARPENTER_CHAOS", None)
        pid = host.host.pid
        if pid is not None:
            try:
                os.kill(pid, signal.SIGKILL)
            except ProcessLookupError:
                # a re-wedge beat us to it and the current child was
                # spawned BEFORE the disarm — kill it so the next respawn
                # picks up the clean env
                pid = host.host.pid
                if pid is not None:
                    try:
                        os.kill(pid, signal.SIGKILL)
                    except ProcessLookupError:
                        pass
        deadline = time.monotonic() + 20.0
        recovered = False
        while time.monotonic() < deadline and not recovered:
            time.sleep(0.2)
            try:
                recovered = (
                    resilient.healthy()
                    and resilient.breaker.state == CircuitBreaker.CLOSED
                )
            except Exception:  # noqa: BLE001 — keep polling
                recovered = False
        if not recovered:
            problems.append(
                "breaker/health did not recover after the crash kill "
                f"(breaker {resilient.breaker.state})"
            )
        if host.host.generation <= gen_after_wedge:
            problems.append("host generation did not advance after SIGKILL")
        report = resilient.health_report()
        if report["abandoned_live"] != 0:
            problems.append(
                f"{report['abandoned_live']} live zombie(s) in the "
                "inventory — host mode must kill them for real"
            )
        if not report["host"] or not report["host"]["alive"]:
            problems.append("health report is missing a live host section")
        # parity: the recovered host serves byte-identical placements
        pods = [make_pod(requests={"cpu": "1"}) for _ in range(10)]
        provisioners = [make_provisioner(name="default")]
        its = {"default": fake.instance_types(10)}
        through_host = resilient.solve(pods, provisioners, its)
        local = TPUSolver(max_nodes=64).solve(pods, provisioners, its)
        if placements_json(
            canonical_placements(through_host)
        ) != placements_json(canonical_placements(local)):
            problems.append(
                "post-recovery host solve is NOT byte-identical to the "
                "in-process solve"
            )
    finally:
        op.stop()
        host.close()

    if problems:
        for p in problems:
            print(f"host-smoke FAIL: {p}", file=sys.stderr)
        return 1
    print(
        f"host-smoke ok: pods={N_PODS} generations={host.host.generation} "
        f"respawns={host.host.respawns} "
        f"(wedge killed mid-solve, crash killed, parity byte-identical, "
        "zero live zombies)"
    )
    return 0


if __name__ == "__main__":
    rc = main()
    sys.stdout.flush()
    sys.stderr.flush()
    # skip interpreter teardown: watch pumps + XLA's thread pool race
    # destructors at exit (same dodge as hack/soak.py)
    os._exit(rc)
