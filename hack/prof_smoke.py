"""Program-inventory + perf-ledger smoke (`make prof-smoke`, ISSUE 18):
the device-cost observability plane, proven live (~45s budget, typically
much faster).

The drill:

  1. a live host-mode operator (HostSolver under ResilientSolver, debug
     HTTP surface served, program exposition registered exactly like
     operator/__main__.run) solves TWO geometries through the sidecar;
     acceptance: `/debug/programs` serves >= 2 solve-family entries with
     compile seconds under `process="solver-host"` (child provenance via
     the PR 15-style inventory merger), plus the local ledger's entries
     under `process="main"`, and the parent `/metrics` exposition carries
     the `karpenter_program_*` families with the child process label;
  2. a CHAOS-WEDGED probe attempt: the real `bench._probe_forensic`
     subprocess path runs against a stub `jax` whose `devices()` hangs, so
     the probe times out mid-device-init; acceptance: the forensic record
     lands in a real TTL'd verdict file NAMING the init phase
     (`device-init`) via the labeled-heartbeat contract, and survives the
     verdict's TTL expiry through `_read_verdict_forensics`;
  3. a tiny two-round bench sequence over a real ArtifactStore: round 1
     appends ledger rows into `PERF_LEDGER.json`, round 2 carries a seeded
     2x slowdown on the same platform; acceptance: the cumulative file is
     byte-stable across a re-append, the backfill REPLACES the round's
     rows, and `ledger_verdict` trips the named regression (warn-only).

Hermetic (CPU forced in-process; the probe chaos uses a stub module, not
the network). Non-fatal in `make verify`, FATAL in hack/presubmit.sh —
the obs-smoke pattern.
"""
import json
import os
import sys
import tempfile
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")


def _get(port: int, path: str) -> bytes:
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=10
    ) as resp:
        return resp.read()


def _drill_programs(problems) -> None:
    """Live host-mode operator: two geometries through the sidecar, then
    the unified inventory + exposition acceptance checks."""
    from karpenter_core_tpu.api.settings import Settings
    from karpenter_core_tpu.cloudprovider import fake
    from karpenter_core_tpu.obs import proghealth
    from karpenter_core_tpu.operator import new_operator
    from karpenter_core_tpu.operator.__main__ import serve_health
    from karpenter_core_tpu.solver.fallback import ResilientSolver
    from karpenter_core_tpu.solver.host import HostSolver
    from karpenter_core_tpu.solver.tpu_solver import GreedySolver, TPUSolver
    from karpenter_core_tpu.testing import make_pod, make_provisioner

    proghealth.ensure_exposition_registered()  # as operator/__main__.run does
    host = HostSolver(
        max_nodes=64, stale_after=60.0, solve_timeout=120.0,
        spawn_timeout=120.0,
        child_env={"KARPENTER_SOLVER_MODE": "single"},
    )
    resilient = ResilientSolver(
        host, GreedySolver(), small_batch_work_max=0,
        solve_timeout=120.0, wedge_stale_after=None,
        reprobe_interval=2.0, probe_timeout=60.0,
    )
    op = new_operator(
        fake.FakeCloudProvider(fake.instance_types(10)),
        settings=Settings(batch_idle_duration=0.02, batch_max_duration=0.2),
        solver=resilient,
    )
    health = serve_health(op, 0, profiling=True, solver=resilient)
    port = health.server_address[1]
    try:
        provisioners = [make_provisioner(name="default")]
        its = {"default": fake.instance_types(10)}
        # two geometries: pod counts straddling an item-tier boundary
        # (8 and 200 pad into different pod-axis buckets) mint two
        # distinct solve programs in the CHILD
        for n_pods in (8, 200):
            pods = [
                make_pod(name=f"prof-{n_pods}-{i}", requests={"cpu": "1"})
                for i in range(n_pods)
            ]
            resilient.solve(pods, provisioners, its)
        # one in-process solve: the local ledger's process="main" entries
        TPUSolver(max_nodes=64).solve(
            [make_pod(requests={"cpu": "1"}) for _ in range(8)],
            provisioners, its,
        )
        # the child's inventory rides the RESULT frame, so it is already
        # folded; the stats frame keeps it fresh between solves
        snap = json.loads(_get(port, "/debug/programs"))
        if not snap.get("enabled"):
            problems.append("/debug/programs reports the ledger disabled")
        child_solves = [
            r for r in snap.get("programs", [])
            if r.get("process") == "solver-host" and r.get("family") == "solve"
        ]
        if len(child_solves) < 2:
            problems.append(
                "/debug/programs lacks the two child solve programs "
                f"(saw {len(child_solves)} under process=solver-host)"
            )
        with_compile = [
            r for r in child_solves if (r.get("compile_seconds") or 0) > 0
        ]
        if not with_compile:
            problems.append(
                "no child solve program carries compile seconds "
                "(live-path compile attribution lost)"
            )
        if not any(
            r.get("process") == "main" for r in snap.get("programs", [])
        ):
            problems.append("/debug/programs lacks the local (main) entries")
        totals = (snap.get("totals") or {}).get("solver-host") or {}
        if not (totals.get("solve") or {}).get("exec_total"):
            problems.append(
                "merged child totals carry no solve executions"
            )
        expo = _get(port, "/metrics").decode()
        if "karpenter_program_count" not in expo:
            problems.append("exposition lacks karpenter_program_count")
        if "karpenter_program_compile_seconds_total" not in expo:
            problems.append(
                "exposition lacks karpenter_program_compile_seconds_total"
            )
        if not any(
            "karpenter_program_" in line and 'process="solver-host"' in line
            for line in expo.splitlines()
        ):
            problems.append(
                "no karpenter_program_* series under process=solver-host"
            )
    finally:
        host.close()
        health.shutdown()


def _drill_probe_forensics(problems, tmp: str) -> None:
    """A chaos-wedged probe: stub jax hangs in devices(), the REAL probe
    subprocess path times out, and the forensic record must name the
    device-init phase in a real TTL'd verdict file."""
    import bench
    from karpenter_core_tpu.utils import supervise

    stub = os.path.join(tmp, "stub")
    os.makedirs(stub, exist_ok=True)
    with open(os.path.join(stub, "jax.py"), "w") as f:
        f.write(
            "import time\n"
            "def devices():\n"
            "    time.sleep(60)  # chaos: the tunnel wedge\n"
        )
    saved = os.environ.get("PYTHONPATH")
    os.environ["PYTHONPATH"] = stub + (
        os.pathsep + saved if saved else ""
    )
    try:
        t0 = time.monotonic()
        ok, note, forensics = bench._probe_forensic(3)
        elapsed = time.monotonic() - t0
    finally:
        if saved is None:
            os.environ.pop("PYTHONPATH", None)
        else:
            os.environ["PYTHONPATH"] = saved
    if ok or not forensics.get("timed_out"):
        problems.append(
            f"chaos probe did not time out (ok={ok}, note={note!r})"
        )
    if forensics.get("phase") != "device-init":
        problems.append(
            "wedged probe forensics do not name the device-init phase "
            f"(phase={forensics.get('phase')!r})"
        )
    if "(in device-init)" not in note:
        problems.append(f"probe note does not name the phase: {note!r}")
    if elapsed > 30:
        problems.append(f"probe watchdog too slow ({elapsed:.0f}s for 3s cap)")
    # the record rides a real verdict file and outlives the TTL
    verdict_path = os.path.join(tmp, "health.json")
    supervise.write_verdict(
        verdict_path, ok, note, ttl_s=0.0,
        extra={"probe_forensics": forensics},
    )
    time.sleep(0.02)
    if supervise.read_verdict(verdict_path) is not None:
        problems.append("stale verdict unexpectedly still gates")
    got = bench._read_verdict_forensics(verdict_path)
    if not got or got.get("phase") != "device-init":
        problems.append(
            "verdict file lost the forensic record across TTL expiry"
        )


def _drill_perf_ledger(problems, tmp: str) -> None:
    """Two tiny rounds over a real ArtifactStore: rows land in a real
    PERF_LEDGER.json, the re-append is byte-stable, and the seeded 2x
    slowdown trips the named regression verdict."""
    import bench
    from karpenter_core_tpu.utils import supervise

    store = supervise.ArtifactStore(os.path.join(tmp, "stages"))
    headline = {
        "pods": bench.N_PODS, "types": bench.N_TYPES,
        "distinct": bench.N_DISTINCT, "existing": bench.N_EXISTING,
        "pods_per_sec": 480.0, "e2e_p50_ms": 260.0, "e2e_p99_ms": 420.0,
        "device_p99_ms_varied": 5.6, "runs": 2,
        "programs_digest": "feedc0ffee42",
    }
    for name in bench.STAGE_NAMES:
        cfg = bench.stage_config(name)
        data = dict(headline) if name == "headline" else {"v": 1}
        store.save(name, cfg, data,
                   meta={"backend": "cpu", "platform": "cpu"})
    ledger_file = os.path.join(tmp, "PERF_LEDGER.json")
    ledger = bench.append_ledger(store, bench._load_ledger(ledger_file), "r01")
    supervise.atomic_write_json(ledger_file, ledger)
    if not ledger["rows"]:
        problems.append("round 1 appended no ledger rows")
    if not any(
        r["programs_digest"] == "feedc0ffee42" for r in ledger["rows"]
    ):
        problems.append("ledger rows lost the program-inventory digest")
    # byte-stable re-append of the unchanged round
    again = bench.append_ledger(store, bench._load_ledger(ledger_file), "r01")
    if json.dumps(again, sort_keys=True) != json.dumps(ledger, sort_keys=True):
        problems.append("re-appending the same round churned the ledger")
    # round 2: the seeded 2x slowdown on the same platform
    slow = dict(headline, e2e_p99_ms=headline["e2e_p99_ms"] * 2.0,
                pods_per_sec=headline["pods_per_sec"] / 2.0)
    store.save("headline", bench.stage_config("headline"), slow,
               meta={"backend": "cpu", "platform": "cpu"})
    ledger = bench.append_ledger(store, bench._load_ledger(ledger_file), "r02")
    supervise.atomic_write_json(ledger_file, ledger)
    verdict = bench.ledger_verdict(ledger, "r02")
    if verdict["ok"]:
        problems.append("seeded 2x slowdown did not trip the tripwire")
    named = {(g["stage"], g["column"]) for g in verdict["regressions"]}
    if ("headline", "e2e_p99_ms") not in named:
        problems.append(
            f"regression verdict does not name e2e_p99_ms (got {named})"
        )
    if not any(
        abs(g["worse_pct"] - 100.0) < 1.0 for g in verdict["regressions"]
    ):
        problems.append("tripwire mis-measured the seeded 2x slowdown")
    rounds = {r["round"] for r in ledger["rows"]}
    if rounds != {"r01", "r02"}:
        problems.append(f"ledger rounds drifted: {sorted(rounds)}")


def main() -> int:
    problems = []
    _drill_programs(problems)
    with tempfile.TemporaryDirectory(prefix="prof-smoke-") as tmp:
        _drill_probe_forensics(problems, tmp)
        _drill_perf_ledger(problems, tmp)

    if problems:
        for p in problems:
            print(f"prof-smoke FAIL: {p}", file=sys.stderr)
        return 1
    print(
        "prof-smoke ok: /debug/programs serves two child solve programs "
        "with compile seconds under process=solver-host plus local "
        "entries, karpenter_program_* families exposed, a chaos-wedged "
        "probe named device-init in the verdict's forensic record, and "
        "the two-round PERF_LEDGER.json tripwired the seeded 2x slowdown"
    )
    return 0


if __name__ == "__main__":
    rc = main()
    sys.stdout.flush()
    sys.stderr.flush()
    # skip interpreter teardown: XLA's thread pool races destructors at
    # exit (same dodge as hack/obs_smoke.py)
    os._exit(rc)
