"""Trace-demo smoke: run a small solve with tracing on, export the Chrome
trace-event JSON, and validate it (`make trace-demo`; wired into `make
verify` as a non-fatal step).

Checks the ISSUE-1 contract end to end in-process:
  * the trace round-trips through json.loads,
  * it contains >0 solver-phase events (solver.phase.*),
  * every duration event is a complete ('X') event carrying a dur
    (instant 'i' markers and 'M' process metadata — ISSUE 15 — are the
    only other phases allowed),
  * the reconcile that triggered the solve is present.

Hermetic: forces the CPU backend in-process (the image's sitecustomize pins
the axon TPU tunnel; env vars can't override it — same treatment as `make
verify`'s compile check).
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

OUT = os.environ.get("KCT_TRACE_DEMO_OUT", "/tmp/karpenter_trace.json")
# 48 keeps the verify smoke fast on CPU; KCT_TRACE_DEMO_PODS=5000 captures
# the acceptance-scale trace (docs/observability.md walkthrough)
N_PODS = int(os.environ.get("KCT_TRACE_DEMO_PODS", "48"))


def main() -> int:
    from karpenter_core_tpu.cloudprovider import fake
    from karpenter_core_tpu.obs import TRACER
    from karpenter_core_tpu.operator import new_operator
    from karpenter_core_tpu.solver.tpu_solver import TPUSolver
    from karpenter_core_tpu.testing import make_pod, make_provisioner

    TRACER.enable()
    cp = fake.FakeCloudProvider(fake.instance_types(8))
    op = new_operator(cp, solver=TPUSolver(max_nodes=max(64, N_PODS // 4)))
    op.kube_client.create(make_provisioner(name="default"))
    for i in range(N_PODS):
        op.kube_client.create(
            make_pod(labels={"app": f"demo-{i % 6}"}, requests={"cpu": "1"})
        )
    op.sync_state()
    op.provisioning.trigger()
    created = op.provisioning.reconcile(wait_timeout=None)

    TRACER.export_chrome_trace(OUT)
    with open(OUT) as f:
        trace = json.load(f)  # round-trip validation

    events = trace["traceEvents"]
    phase_events = [e for e in events if e["name"].startswith("solver.phase.")]
    problems = []
    if created <= 0:
        problems.append(f"demo solve launched no machines (created={created})")
    if not phase_events:
        problems.append("no solver.phase.* events in the trace")
    bad = [
        e for e in events
        if (e.get("ph") == "X" and "dur" not in e)
        or e.get("ph") not in ("X", "i", "M")
    ]
    if bad:
        problems.append(
            f"{len(bad)} events are neither complete ('X' with dur) nor "
            "instant/metadata ('i'/'M')"
        )
    if not any(e["name"] == "provisioner.reconcile" for e in events):
        problems.append("missing provisioner.reconcile span")

    print(TRACER.summary(), file=sys.stderr)
    if problems:
        for p in problems:
            print(f"trace-demo FAIL: {p}", file=sys.stderr)
        return 1
    phases = sorted({e["name"].split(".")[-1] for e in phase_events})
    print(
        f"trace-demo ok: {OUT} ({len(events)} events, machines={created}, "
        f"phases={','.join(phases)})"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
