#!/usr/bin/env bash
# Until-it-fails deflake loop over the concurrency-sensitive suites — the
# analog of the reference's `make deflake` (Makefile:14-20: ginkgo --race
# --randomize-all --until-it-fails). Each iteration re-runs the threaded
# runtime suites with a fresh jitter seed; the loop stops at the FIRST
# failure (preserving the output) or after MAX_ITERS (default: forever).
set -u
cd "$(dirname "$0")/.."
i=0
while :; do
  i=$((i + 1))
  seed=$RANDOM
  echo "=== deflake iteration $i (seed $seed) ==="
  if ! KCT_DEFLAKE_ITERS="${KCT_DEFLAKE_ITERS:-20}" KCT_DEFLAKE_SEED="$seed" \
      python -m pytest tests/test_deflake.py tests/test_operator_runtime.py \
      tests/test_controllers.py -q; then
    echo "=== FAILED on iteration $i (seed $seed) ==="
    exit 1
  fi
  if [ -n "${MAX_ITERS:-}" ] && [ "$i" -ge "$MAX_ITERS" ]; then
    echo "=== $i iterations green ==="
    exit 0
  fi
done
