"""Consolidation-smoke: the batched candidate-subset evaluator end to end
against a LIVE operator, validated by the sequential simulator.

Builds a small consolidatable cluster (a keeper node + under-utilized
candidates, one of them priceless), runs MultiNodeConsolidation's batched
ladder and SingleNodeConsolidation's ranked sweep, and gates on:

  * the ladder decides DELETE for every candidate (the keeper absorbs);
  * validate_command — the sequential simulate_scheduling path — accepts
    the device-ranked command (the parity bar);
  * the flight recorder captured the decision pass and
    replay_consolidation's offline sequential re-run validates it too
    (the `hack/replay.py --consolidation` loop, exercised zero-to-end);
  * replan per-phase spans were recorded and the replan program cache
    stays on the candidate-axis bucket ladder.

Non-fatal in `make verify`, FATAL in hack/presubmit.sh — the same
promotion pattern as prewarm/multichip smoke. Hermetic: forces the CPU
backend in-process (the image's sitecustomize pins the axon tunnel; env
vars can't override it).
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")


def main() -> int:
    from karpenter_core_tpu.api.labels import (
        LABEL_CAPACITY_TYPE,
        LABEL_NODE_INITIALIZED,
        PROVISIONER_NAME_LABEL_KEY,
    )
    from karpenter_core_tpu.api.settings import Settings
    from karpenter_core_tpu.cloudprovider import fake
    from karpenter_core_tpu.controllers.deprovisioning.core import candidate_nodes
    from karpenter_core_tpu.kube.objects import (
        LABEL_INSTANCE_TYPE_STABLE,
        LABEL_TOPOLOGY_ZONE,
    )
    from karpenter_core_tpu.obs import flightrec
    from karpenter_core_tpu.operator import new_operator
    from karpenter_core_tpu.solver.encode import REPLAN_K_BUCKETS
    from karpenter_core_tpu.solver.tpu_solver import TPUSolver
    from karpenter_core_tpu.testing import (
        FakeClock,
        make_node,
        make_pod,
        make_provisioner,
    )

    clock = FakeClock()
    universe = fake.instance_types(8)
    cp = fake.FakeCloudProvider(universe)
    solver = TPUSolver(max_nodes=64)
    op = new_operator(cp, settings=Settings(), solver=solver, clock=clock)
    op.kube_client.create(
        make_provisioner(name="default", consolidation_enabled=True)
    )
    op.kube_client.create(make_provisioner(name="static"))
    keeper = make_node(
        name="keeper",
        labels={PROVISIONER_NAME_LABEL_KEY: "static",
                LABEL_NODE_INITIALIZED: "true"},
        capacity={"cpu": "20", "memory": "40Gi", "pods": "200"},
    )
    op.kube_client.create(keeper)
    n_candidates = int(os.environ.get("KCT_CONS_SMOKE_NODES", "8"))
    for i in range(n_candidates):
        it = universe[-1]
        zone = "test-zone-9" if i == n_candidates - 1 else "test-zone-1"
        node = make_node(
            name=f"lite-{i}",
            labels={
                PROVISIONER_NAME_LABEL_KEY: "default",
                LABEL_NODE_INITIALIZED: "true",
                LABEL_INSTANCE_TYPE_STABLE: it.name,
                LABEL_CAPACITY_TYPE: "on-demand",
                LABEL_TOPOLOGY_ZONE: zone,  # zone-9 = priceless candidate
            },
            capacity={k: str(v) for k, v in it.capacity.items()},
        )
        op.kube_client.create(node)
        pod = make_pod(
            requests={"cpu": "0.1"}, node_name=node.metadata.name,
            unschedulable=False,
        )
        pod.status.phase = "Running"
        op.kube_client.create(pod)
    op.sync_state()

    flightrec.FLIGHTREC.enable()
    flightrec.FLIGHTREC.clear()

    multi = next(
        d for d in op.deprovisioning.deprovisioners
        if type(d).__name__ == "MultiNodeConsolidation"
    )
    multi.validation_ttl = 0.0
    candidates = multi.sort_and_filter_candidates(
        candidate_nodes(
            op.cluster, op.kube_client, cp, multi.should_deprovision, clock
        )
    )
    if len(candidates) != n_candidates:
        print(f"FAIL: expected {n_candidates} candidates, got {len(candidates)}")
        return 1
    if not getattr(op.provisioning.solver, "supports_batched_replan", False):
        print("FAIL: solver does not support batched replan")
        return 1

    cmd = multi.first_n_consolidation_ladder(candidates)
    print(
        f"ladder: action={cmd.action} removed={len(cmd.nodes_to_remove)} "
        f"from_screen={getattr(cmd, 'from_screen', False)}"
    )
    if cmd.action != "delete" or len(cmd.nodes_to_remove) != n_candidates:
        print("FAIL: batched ladder did not delete every absorbable candidate")
        return 1
    if not multi.validate_command(cmd, candidates):
        print("FAIL: sequential simulator rejected the device-ranked command")
        return 1

    phases = dict(solver.last_replan_phase_ms or {})
    print(f"replan phases_ms: {phases}")
    if "device" not in phases or "prescreen" not in phases:
        print("FAIL: replan per-phase spans missing")
        return 1
    k_values = {k for (_key, k) in solver._replan_compiled}
    if not k_values or not k_values.issubset(set(REPLAN_K_BUCKETS)):
        print(f"FAIL: replan programs off the candidate-axis ladder: {k_values}")
        return 1

    record = flightrec.FLIGHTREC.last_consolidation()
    if record is None or "inputs" not in record:
        print("FAIL: no flight-recorded consolidation decision")
        return 1
    diff = flightrec.replay_consolidation(record, solver_kind="greedy")
    agree = sum(1 for s in diff["subsets"] if s["agrees"])
    print(
        f"replay: {agree}/{len(diff['subsets'])} subset verdicts agree, "
        f"chosen_feasible_seq={diff['chosen_feasible_seq']} "
        f"seq_pick={diff['seq_pick']}"
    )
    if not diff["chosen_feasible_seq"]:
        print("FAIL: offline sequential replay rejects the chosen command")
        return 1

    # single-node ranked sweep rides the same program family (cache hit)
    single = next(
        d for d in op.deprovisioning.deprovisioners
        if type(d).__name__ == "SingleNodeConsolidation"
    )
    single.validation_ttl = 0.0
    s_candidates = single.sort_and_filter_candidates(
        candidate_nodes(
            op.cluster, op.kube_client, cp, single.should_deprovision, clock
        )
    )
    order, screens, _scenario = single._ranked_candidates(s_candidates)
    if screens is None or len(screens) != len(s_candidates):
        print("FAIL: single-node ranked sweep did not screen every singleton")
        return 1
    print(
        f"single-node: {len(screens)} singletons screened, "
        f"{len(order)} ranked feasible"
    )
    print("consolidation-smoke ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
