"""No-print guard: fail on bare print() calls inside karpenter_core_tpu/.

The package logs through the structured logger (obs/log) — bare prints
bypass the level gate, the ring (/debug/logs), and the trace-id
correlation, so they are banned from production code. hack/ and tests/
stay free-form (CLI tools and assertions print on purpose).

AST-based, not grep: a `print(` inside a string literal (e.g. the
subprocess probe source in solver/fallback.py) is NOT a violation, and a
real call can't hide behind formatting. Used by hack/check_no_print.sh
(make verify) and tests/test_no_print.py (tier-1).
"""
from __future__ import annotations

import ast
import os
import sys
from typing import List, Tuple

PACKAGE = "karpenter_core_tpu"


def find_print_calls(root: str) -> List[Tuple[str, int]]:
    """(path, lineno) of every print() call under `root`."""
    violations: List[Tuple[str, int]] = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for filename in sorted(filenames):
            if not filename.endswith(".py"):
                continue
            path = os.path.join(dirpath, filename)
            with open(path, encoding="utf-8") as f:
                source = f.read()
            try:
                tree = ast.parse(source, filename=path)
            except SyntaxError as exc:
                violations.append((path, exc.lineno or 0))
                continue
            for node in ast.walk(tree):
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "print"
                ):
                    violations.append((path, node.lineno))
    return violations


def main() -> int:
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    package_root = os.path.join(repo_root, PACKAGE)
    violations = find_print_calls(package_root)
    if violations:
        for path, lineno in violations:
            rel = os.path.relpath(path, repo_root)
            print(f"{rel}:{lineno}: bare print() — use karpenter_core_tpu.obs.log")
        print(f"check_no_print: {len(violations)} violation(s)")
        return 1
    print(f"check_no_print: ok ({PACKAGE}/ is print-free)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
