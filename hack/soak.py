#!/usr/bin/env python
"""Soak bench: sustained seeded churn through the FULL operator loop.

Runs minutes of deterministic pod arrival/termination/resize traffic
(loadgen.ChurnGenerator) against a live operator — background watch pumps,
batcher windows, TPU solves, machine launches — with chaos armed (the
`state.diff` feed fault plus transient cloud-create failures) and the
flight recorder on, then reports the SLO columns the steady-state story is
judged by (docs/PERF.md "churn columns"):

  churn_admission_p50_s / churn_admission_p99_s
      pod admission -> bind-decision latency, read from the provisioner's
      karpenter_admission_to_bind_seconds histogram (REAL exposition,
      baseline-diffed — not bench-side stopwatching)
  churn_pending_max / churn_pending_mean
      batch-queue depth (karpenter_pending_pods gauge samples)
  churn_resolve_ratio, churn_inc_*
      incremental delta re-solve hit ratio by outcome
      (karpenter_incremental_screen_total)
  churn_prescreen_refresh_med_ms vs churn_prescreen_full_med_ms
      median device time of the delta refresh vs the full [N, C] verdict
      precompute at the SAME churn geometry (solver.phase.prescreen spans;
      the solver runs profile_phases so spans cover device execution)

Usage:
  python hack/soak.py                 # 75s soak, chaos armed (make soak)
  python hack/soak.py --smoke         # <=30s seeded smoke (make soak-smoke)
  python hack/soak.py --duration 300 --seed 7 --rate 12

Exits nonzero when the soak is unhealthy: a dead reconcile loop, nothing
bound, or pods stranded unbound at the end.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

# flight recorder ON for the whole run (the operator default; hack scripts
# must opt in before the obs import reads the env)
os.environ.setdefault("KARPENTER_TPU_FLIGHTREC", "1")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_solvers(max_nodes: int, hang_armed: bool = False,
                  host_mode: bool = False):
    """(primary, resilient): the resilient pair is the operator wiring —
    health-gated greedy fallback, small-batch routing OFF (churn batches
    are small by nature; the soak exists to exercise the device path under
    time). The bare primary is returned too so the warmup pass runs
    through the SAME solver instance: geometry programs trace/compile once
    and the measured window starts fully jitted.

    In-process (`make soak`): a stub prober (the backend was chosen by
    JAX_PLATFORMS; a subprocess probe would measure the harness, not the
    loop); with `hang_armed` the dispatch watchdog runs at drill scale —
    a solver.device.hang injection goes heartbeat-stale in ~2s, is
    abandoned as WEDGED, trips the breaker, and the breaker's half-open
    prober re-admits the backend ~3s later.

    Host mode (`make soak-smoke`, ISSUE 12): the primary is the
    HARD-KILLABLE HostSolver — the same hang now wedges the CHILD, whose
    process group the parent watchdog SIGKILLs and respawns; the prober is
    the real host probe (re-admission = host respawned + probe passed),
    and the admission gate runs at drill scale (queue 4, brownout 4,
    per-request deadline) so the overload burst exercises the whole
    brownout ladder."""
    from karpenter_core_tpu.solver.fallback import ResilientSolver
    from karpenter_core_tpu.solver.tpu_solver import GreedySolver, TPUSolver

    if host_mode:
        from karpenter_core_tpu.solver.host import HostSolver

        # stale_after stays at the PRODUCTION threshold even in the drill:
        # the soak mints fresh geometries whose multi-second XLA compiles
        # are legitimately heartbeat-silent, and a drill-scale threshold
        # would kill the child mid-compile — before the persistent cache
        # is written — respawning into the same compile forever. The
        # heartbeat-staleness wedge cycle is drilled where compiles are
        # warm (make host-smoke, tests/test_solver_host.py); the soak
        # drills the CRASH shape, which needs no staleness.
        primary = HostSolver(
            max_nodes=max_nodes,
            stale_after=600.0,
            solve_timeout=60.0,
            spawn_timeout=120.0,
            max_queue=4, brownout_at=4, queue_deadline_s=30.0,
            child_env={"KARPENTER_SOLVER_MODE": "single"},
        )
        return primary, ResilientSolver(
            primary, GreedySolver(), small_batch_work_max=0,
            solve_timeout=120.0, wedge_stale_after=None,  # the HOST watches
            reprobe_interval=3.0 if hang_armed else 300.0,
            probe_timeout=60.0,
        )
    primary = TPUSolver(
        max_nodes=max_nodes, screen_mode="prescreen", profile_phases=True
    )
    watchdog = {}
    if hang_armed:
        watchdog = dict(
            solve_timeout=10.0, wedge_stale_after=2.0, watchdog_poll=0.2,
            reprobe_interval=3.0,
        )
    return primary, ResilientSolver(
        primary, GreedySolver(), prober=lambda: None, small_batch_work_max=0,
        **watchdog,
    )


def overload_burst(resilient, host_primary, n_threads: int = 10):
    """The overload drill (ISSUE 12): a concurrent solve burst against the
    host's drill-scale admission gate. Expected shape: the gate sheds
    (brownout first), every shed request is SERVED by the greedy fallback
    (brownout ladder: device -> greedy, never an error), zero accepted
    requests dispatch past their deadline, and sequential latency
    re-converges once the burst drains."""
    import threading
    import time as _time

    from karpenter_core_tpu.cloudprovider import fake as _fake
    from karpenter_core_tpu.testing import make_pod, make_provisioner

    pods = [make_pod(requests={"cpu": "1"}) for _ in range(12)]
    provisioners = [make_provisioner(name="burst")]
    its = {"burst": _fake.instance_types(8)}

    def timed_solve():
        t0 = _time.monotonic()
        resilient.solve(pods, provisioners, its)
        return _time.monotonic() - t0

    timed_solve()  # compile/warm this geometry out of the measurement
    pre = sorted(timed_solve() for _ in range(3))
    gate = host_primary.admission
    shed_before = sum(gate.stats()["shed"].values())
    errors = []

    def worker():
        try:
            resilient.solve(pods, provisioners, its)
        except Exception as e:  # noqa: BLE001 — counted, asserted zero
            errors.append(f"{type(e).__name__}: {e}")

    threads = [
        threading.Thread(target=worker, daemon=True, name=f"burst-{i}")
        for i in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60.0)
    stats = gate.stats()
    post = sorted(timed_solve() for _ in range(3))
    return {
        "shed": sum(stats["shed"].values()) - shed_before,
        "shed_reasons": stats["shed"],
        "deadline_violations": stats["deadline_violations"],
        "errors": errors,
        "pre_p50_s": round(pre[1], 3),
        "post_p99_s": round(post[-1], 3),
    }


def tenant_flood_drill(resilient, host_primary, quota: int = 2,
                       flood_threads: int = 20, flood_s: float = 6.0):
    """The two-tenant flood drill (ISSUE 17): tenant A floods the gate at
    10x its per-tenant quota while tenant B keeps a steady one-at-a-time
    trickle. The fair-share invariants under assault:

      * every shed is billed to A — ``tenant_quota`` isolates the flooder,
        and B is never quota- or queue-full-shed;
      * B's admission p99 stays within 1.5x its pre-flood baseline (DRR
        gives B its dispatch share no matter how deep A's sub-queue is);
      * ZERO of B's accepted requests expire in queue or dispatch past
        their deadline;
      * the closed SLO loop demotes ONLY A (a drill-scale burn engine over
        the gate's own admission totals drives the brownout ladder), and A
        re-promotes back to the device rung once the flood drains —
        hysteresis, not a latch.

    The gate is temporarily re-armed at drill scale — per-tenant quota 2,
    global queue wide open (so the global bound never sheds B for A's
    sins), depth-band brownout OFF (the ladder owns the brownout decision
    here) — and restored afterwards."""
    import threading
    import time as _time

    from karpenter_core_tpu.cloudprovider import fake as _fake
    from karpenter_core_tpu.obs import reqctx
    from karpenter_core_tpu.obs.slo import Objective, SloEngine
    from karpenter_core_tpu.solver.host import (
        GATE_DEMOTIONS_TOTAL,
        BrownoutLadder,
    )
    from karpenter_core_tpu.testing import make_pod, make_provisioner

    pods = [make_pod(requests={"cpu": "1"}) for _ in range(12)]
    provisioners = [make_provisioner(name="flood")]
    its = {"flood": _fake.instance_types(8)}
    gate = host_primary.admission
    tenant_a, tenant_b = "flood-a", "steady-b"
    errors = []

    def solve_as(tenant, deadline_s, timings=None):
        t0 = _time.monotonic()
        try:
            with reqctx.bind(reqctx.RequestContext(
                    tenant=tenant, deadline_s=deadline_s)):
                resilient.solve(pods, provisioners, its)
        except Exception as e:  # noqa: BLE001 — counted, asserted zero
            errors.append(f"{tenant}: {type(e).__name__}: {e}")
        finally:
            if timings is not None:
                timings.append(_time.monotonic() - t0)

    def pump(tenant, stop, deadline_s):
        while not stop.is_set():
            solve_as(tenant, deadline_s)

    def demotions_of(tenant):
        return sum(
            v for labels, v in GATE_DEMOTIONS_TOTAL.series()
            if labels.get("tenant") == tenant
        )

    solve_as(tenant_b, 60.0)  # compile/warm this geometry out of the drill
    engine = SloEngine(
        [Objective(
            name="gate-admission", histogram=None, threshold_s=0.0,
            target=0.95, collect=gate.admission_totals,
        )],
        windows=(("2s", 2.0), ("10s", 10.0)),
    )
    ladder = BrownoutLadder(
        engine.fast_burn, demote_at=1.0, promote_below=0.5,
        hold_s=2.0, eval_interval_s=0.25,
    )
    saved = (gate.tenant_quota, gate.ladder, gate.max_queue, gate.brownout_at)
    gate.tenant_quota, gate.ladder = quota, ladder
    gate.max_queue, gate.brownout_at = 64, None
    failures = []
    try:
        # baseline: B's sequential p99 with A running WITHIN its quota
        stop = threading.Event()
        base_a = [
            threading.Thread(target=pump, args=(tenant_a, stop, 30.0),
                             daemon=True, name=f"flood-base-a-{i}")
            for i in range(quota)
        ]
        for t in base_a:
            t.start()
        base_b = []
        for _ in range(5):
            solve_as(tenant_b, 60.0, base_b)
        stop.set()
        for t in base_a:
            t.join(timeout=60.0)
        b_base_p99 = sorted(base_b)[-1]

        shed_before = {
            k: dict(v) for k, v in gate.stats()["shed_by_tenant"].items()
        }
        expired_before = dict(gate.stats()["expired_in_queue"])
        violations_before = gate.stats()["deadline_violations"]
        b_demotions_before = demotions_of(tenant_b)

        # flood: A at 10x quota; B keeps its steady trickle throughout
        stop = threading.Event()
        flood = [
            threading.Thread(target=pump, args=(tenant_a, stop, 30.0),
                             daemon=True, name=f"flood-a-{i}")
            for i in range(flood_threads)
        ]
        for t in flood:
            t.start()
        flood_b = []
        flood_end = _time.monotonic() + flood_s
        while _time.monotonic() < flood_end:
            solve_as(tenant_b, 60.0, flood_b)
        stop.set()
        for t in flood:
            t.join(timeout=60.0)
        b_flood_p99 = sorted(flood_b)[-1]

        stats = gate.stats()
        shed_delta = {}
        for key, reasons in stats["shed_by_tenant"].items():
            before = shed_before.get(key, {})
            d = {r: n - before.get(r, 0) for r, n in reasons.items()
                 if n - before.get(r, 0)}
            if d:
                shed_delta[key] = d
        if not shed_delta.get(tenant_a):
            failures.append("flood never shed tenant A (drill vacuous)")
        bystanders = sorted(k for k in shed_delta if k != tenant_a)
        if bystanders:
            failures.append(
                f"sheds billed to bystander tenant(s) {bystanders}: "
                f"{shed_delta}"
            )
        b_expired = (
            stats["expired_in_queue"].get(tenant_b, 0)
            - expired_before.get(tenant_b, 0)
        )
        if b_expired:
            failures.append(
                f"{b_expired} of B's accepted requests expired in queue"
            )
        if stats["deadline_violations"] != violations_before:
            failures.append(
                "accepted request(s) dispatched past their deadline"
            )
        if not flood_b:
            failures.append("tenant B starved: zero solves during the flood")
        if b_flood_p99 > max(1.5 * b_base_p99, 3.0):
            failures.append(
                f"tenant B p99 {b_flood_p99:.3f}s under flood vs "
                f"{b_base_p99:.3f}s baseline (> 1.5x)"
            )
        if errors:
            failures.append(
                "every shed must be served by the greedy ladder: "
                f"{errors[:3]}"
            )
        if ladder.demotions_total < 1:
            failures.append(
                "brownout ladder never demoted the flooding tenant"
            )
        if demotions_of(tenant_b) != b_demotions_before:
            failures.append("brownout ladder demoted bystander tenant B")

        # recovery: A's own probe traffic drives the ladder's review —
        # burn decays out of the fast window, and hysteresis promotes A
        # back to the device rung
        recover_deadline = _time.monotonic() + 20.0
        while (_time.monotonic() < recover_deadline
               and ladder.level(tenant_a) != "device"):
            solve_as(tenant_a, 30.0)
            _time.sleep(0.25)
        if ladder.level(tenant_a) != "device":
            failures.append(
                "flooding tenant never re-promoted to the device rung "
                f"(stuck at {ladder.level(tenant_a)!r})"
            )
        return {
            "b_base_p99_s": round(b_base_p99, 3),
            "b_flood_p99_s": round(b_flood_p99, 3),
            "b_served_in_flood": len(flood_b),
            "b_expired_in_queue": b_expired,
            "shed_delta": shed_delta,
            "demotions": ladder.demotions_total,
            "promotions": ladder.promotions_total,
            "a_final_rung": ladder.level(tenant_a),
            "errors": errors,
            "failures": failures,
        }
    finally:
        (gate.tenant_quota, gate.ladder,
         gate.max_queue, gate.brownout_at) = saved


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--duration", type=float, default=75.0,
                        help="soak length in seconds (default 75)")
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--rate", type=float, default=2.5,
                        help="mean pod-arrival events/s")
    parser.add_argument("--smoke", action="store_true",
                        help="<=30s run for CI: 12s, lighter rates")
    parser.add_argument("--host", action="store_true",
                        help="run the primary through the hard-killable "
                             "solver host (solver/host.py): the smoke "
                             "drill wedges AND crashes the sidecar, and "
                             "an overload burst exercises the admission "
                             "gate's brownout ladder")
    parser.add_argument("--no-chaos", action="store_true")
    parser.add_argument("--no-warmup", action="store_true",
                        help="skip the virtual-time compile warmup pass")
    parser.add_argument("--out", default="",
                        help="also write the report JSON to this path")
    args = parser.parse_args(argv)

    from dataclasses import replace

    from karpenter_core_tpu import chaos
    from karpenter_core_tpu.loadgen import ChurnConfig, SoakDriver
    from karpenter_core_tpu.testing import FakeClock
    from karpenter_core_tpu.utils.compilecache import enable_persistent_cache

    # the production persistent XLA cache (ROADMAP item 3): soak geometries
    # compile once per machine, not once per run
    enable_persistent_cache()

    duration = 12.0 if args.smoke else args.duration
    rate = min(args.rate, 3.0) if args.smoke else args.rate
    # same slot axis for smoke and soak: both draw from one persistent-
    # compile-cache population, so a smoke run pre-warms the soak and vice
    # versa (N=64 machine slots is plenty at these churn rates)
    max_nodes = 64
    config = ChurnConfig(
        seed=args.seed,
        duration_s=duration,
        arrival_rate=rate,
        termination_rate=rate * 0.6,
        resize_rate=rate * 0.08,
        # the longer run carries more live pods: seed the existing axis
        # straight into the pow2 bucket it will occupy (24 -> 32, with pad
        # headroom for launches) so mid-soak machine launches neither cross
        # a bucket edge nor outgrow the hostname pad pool — either would
        # re-mint the solve geometry out from under the resident tensor
        initial_nodes=12 if args.smoke else 24,
    )
    # the wedge drill rides the SMOKE variant (make soak-smoke): one
    # solver.device.hang injection mid-soak, detected by heartbeat
    # staleness, recovered through the breaker's prober-gated half-open
    # (in host mode: through a hard kill + respawn of the sidecar)
    hang_armed = args.smoke and not args.no_chaos
    primary, resilient = build_solvers(
        max_nodes, hang_armed=hang_armed, host_mode=args.host
    )
    if not args.no_warmup:
        # virtual-time dress rehearsal of the schedule's opening window,
        # through the SAME primary solver instance: same seed => same pods
        # => same solve geometries, so the realtime window below starts
        # with its device programs traced + compiled instead of spending
        # its first seconds — or, on a 12s smoke, ALL its seconds — inside
        # XLA. Chaos is armed after, so the rehearsal stays a pure compile
        # pass.
        print("soak: warmup (virtual-time compile pass)", file=sys.stderr)
        SoakDriver(
            replace(config, duration_s=min(duration, 12.0)),
            clock=FakeClock(),
            solver=primary,
            max_nodes=max_nodes,
        ).run_steps()

    if not args.no_chaos:
        # the feed-fault the incremental path must DEGRADE under (full
        # re-encode, never drift) + transient cloud-create failures so the
        # ICE/retry launch path runs too
        chaos.arm(chaos.STATE_DIFF, error="conn", probability=0.05,
                  seed=args.seed)
        chaos.arm(chaos.CLOUDPROVIDER_CREATE, error="conn", probability=0.02,
                  seed=args.seed + 1)
    if hang_armed and not args.host:
        # ONE sleep-past-watchdog hang after the loop is in steady state:
        # the dispatch goes silent for 6s against a 2s staleness
        # threshold — abandoned as wedged, greedy fallback keeps binding,
        # backend re-admitted by the breaker's prober trial ~3s later
        chaos.arm(chaos.SOLVER_DEVICE_HANG, error=None, latency=6.0,
                  times=1, after=2, seed=args.seed + 2)
    if hang_armed and args.host:
        # host-mode drill (ISSUE 12): ONE host crash mid-soak — the
        # parent-side solver.host.crash hook SIGKILLs the sidecar's
        # process group mid-dispatch — and the cycle the gates below
        # assert is crash -> eager respawn -> warm recovery from the
        # persistent compile cache -> byte-identical placements, all
        # inside the live loop. (The heartbeat-staleness WEDGE cycle is
        # drilled in make host-smoke and tests/test_solver_host.py, where
        # compiles are warm and a drill-scale threshold is safe.)
        chaos.arm(chaos.SOLVER_HOST_CRASH, error="runtime", times=1,
                  after=8, seed=args.seed + 3)

    driver = SoakDriver(
        config, max_nodes=max_nodes, solver=resilient,
        # the tail exits EARLY once everything is bound; the budget only
        # bounds the unhealthy case — and must outlast a chaos-tripped
        # launch's exponential-backoff retry window
        tail_timeout_s=25.0 if args.smoke else 30.0,
    )

    def progress(now, report):
        print(
            f"soak t={now:5.1f}s created={report.pods_created} "
            f"binds={report.binds} terminated={report.pods_terminated}",
            file=sys.stderr,
        )

    report = driver.run(on_progress=progress if sys.stderr.isatty() else None)
    columns = report.as_columns()
    columns["churn_seed"] = args.seed
    columns["churn_chaos_armed"] = not args.no_chaos
    drill_failures = []
    if args.host:
        # the burst must start from a HEALTHY primary (a wedge drill may
        # have just fired): wait out the reprobe TTL so sheds measure the
        # GATE, not a breaker fast-fail to greedy
        wait_deadline = time.monotonic() + 15.0
        while time.monotonic() < wait_deadline and not resilient.healthy():
            time.sleep(0.5)
        # overload burst (runs in every host-mode soak, chaos or not):
        # shed > 0, zero deadline violations among accepted requests,
        # every shed request served by the greedy ladder (no errors), and
        # post-burst latency re-converged
        burst = overload_burst(resilient, primary)
        columns["churn_overload"] = burst
        print(
            f"soak overload burst: shed={burst['shed']} "
            f"reasons={burst['shed_reasons']} "
            f"pre_p50={burst['pre_p50_s']}s post_p99={burst['post_p99_s']}s",
            file=sys.stderr,
        )
        if burst["shed"] == 0:
            drill_failures.append(
                "overload burst never shed (gate vacuous)"
            )
        if burst["deadline_violations"] != 0:
            drill_failures.append(
                f"{burst['deadline_violations']} accepted request(s) "
                "dispatched past their deadline"
            )
        if burst["errors"]:
            drill_failures.append(
                "brownout must serve greedy before erroring: "
                f"{burst['errors'][:3]}"
            )
        if burst["post_p99_s"] > max(4.0 * burst["pre_p50_s"], 3.0):
            drill_failures.append(
                f"post-burst p99 {burst['post_p99_s']}s never re-converged "
                f"(pre-burst p50 {burst['pre_p50_s']}s)"
            )
        # two-tenant flood drill (ISSUE 17): fair-share isolation plus the
        # closed SLO->brownout loop, asserted end to end — tenant A floods
        # at 10x quota, only A sheds/demotes, B's p99 and zero-deadline-
        # violation invariants hold, A re-promotes after the flood drains
        flood = tenant_flood_drill(resilient, primary)
        columns["churn_tenant_flood"] = {
            k: v for k, v in flood.items() if k != "failures"
        }
        print(
            f"soak tenant flood: b_p99 {flood['b_base_p99_s']}s -> "
            f"{flood['b_flood_p99_s']}s served={flood['b_served_in_flood']} "
            f"shed={flood['shed_delta']} demotions={flood['demotions']} "
            f"a_rung={flood['a_final_rung']}",
            file=sys.stderr,
        )
        drill_failures.extend(flood["failures"])
    if hang_armed and args.host:
        # host-mode drill gates: the chaos crash fired, the kill
        # respawned, the breaker re-admitted, and nothing leaked
        from karpenter_core_tpu.solver.fallback import CircuitBreaker

        crash_fault = chaos.armed_points().get(chaos.SOLVER_HOST_CRASH)
        crash_injected = crash_fault.injected if crash_fault else 0
        if crash_injected < 1:
            drill_failures.append(
                "solver.host.crash never fired (crash drill vacuous)"
            )
        if primary.host.generation < 2:
            drill_failures.append(
                f"host generation {primary.host.generation} < 2: the "
                "crash kill did not respawn"
            )
        if resilient.breaker.state != CircuitBreaker.CLOSED:
            drill_failures.append(
                f"backend not re-admitted (breaker {resilient.breaker.state})"
            )
        elif resilient._healthy is not True:
            drill_failures.append("solver still unhealthy after host drills")
        health = resilient.health_report()
        if health["abandoned_live"] != 0:
            drill_failures.append(
                f"{health['abandoned_live']} live zombie(s): host mode "
                "must kill the wedged process for real"
            )
        # byte-identical recovery: the respawned host answers exactly as
        # an unwedged in-process solve
        from karpenter_core_tpu.cloudprovider import fake as _fake
        from karpenter_core_tpu.obs.flightrec import (
            canonical_placements,
            placements_json,
        )
        from karpenter_core_tpu.solver.tpu_solver import TPUSolver
        from karpenter_core_tpu.testing import make_pod, make_provisioner

        pods = [make_pod(requests={"cpu": "1"}) for _ in range(10)]
        provisioners = [make_provisioner(name="default")]
        its = {"default": _fake.instance_types(10)}
        through_host = resilient.solve(pods, provisioners, its)
        local = TPUSolver(max_nodes=max_nodes).solve(pods, provisioners, its)
        parity = placements_json(
            canonical_placements(through_host)
        ) == placements_json(canonical_placements(local))
        if not parity:
            drill_failures.append(
                "post-drill host solve NOT byte-identical to in-process"
            )
        columns["churn_host_drill"] = {
            "crash_injected": crash_injected,
            "generations": primary.host.generation,
            "respawns": primary.host.respawns,
            "live_zombies": health["abandoned_live"],
            "parity_byte_identical": parity,
            "readmitted": not drill_failures,
        }
        print(
            f"soak host drill: crash_injected={crash_injected} "
            f"generations={primary.host.generation} parity={parity}",
            file=sys.stderr,
        )
    if hang_armed and not args.host:
        # the wedge drill's own gates: the hang must actually have been
        # detected as a wedge (not silently absorbed), and the backend
        # must have been RE-ADMITTED before the end of the soak
        from karpenter_core_tpu.solver.fallback import (
            SOLVER_WEDGED_TOTAL,
            CircuitBreaker,
        )

        wedged = SOLVER_WEDGED_TOTAL.get() or 0.0
        hang_fault = chaos.armed_points().get(chaos.SOLVER_DEVICE_HANG)
        injected = hang_fault.injected if hang_fault is not None else 0
        if injected == 0:
            drill_failures.append(
                "solver.device.hang never fired (drill vacuous)"
            )
        elif wedged < 1:
            drill_failures.append(
                "hang injected but karpenter_solver_wedged_total never ticked"
            )
        elif resilient.breaker.state != CircuitBreaker.CLOSED:
            drill_failures.append(
                f"backend not re-admitted after the wedge cleared "
                f"(breaker {resilient.breaker.state})"
            )
        elif resilient._healthy is not True:
            drill_failures.append("solver still unhealthy after wedge recovery")
        columns["churn_wedge_drill"] = {
            "injected": injected,
            "wedged_total": wedged,
            "abandoned": resilient._abandon_count,
            "readmitted": not drill_failures,
        }
        print(
            f"soak wedge drill: injected={injected} wedged={wedged:.0f} "
            f"abandoned={resilient._abandon_count} "
            f"readmitted={not drill_failures}",
            file=sys.stderr,
        )
    line = json.dumps(columns, sort_keys=True)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")

    failures = []
    if not report.loops_alive:
        failures.append("a reconcile loop died")
    if report.binds == 0:
        failures.append("no pod was ever bound")
    if report.admission_count == 0:
        failures.append("admission histogram recorded nothing")
    if report.unbound_at_end > 0:
        failures.append(f"{report.unbound_at_end} pods stranded unbound")
    if args.host:
        # the verdict-tensor residency lives in the CHILD (service-side
        # incremental path): read its counters over the stats frame
        try:
            child_inc = primary.host.stats().get("incremental", {})
        except Exception as e:  # noqa: BLE001 — a dead host is its own failure
            child_inc = {}
            failures.append(f"host stats unreadable: {type(e).__name__}: {e}")
        print(f"soak host incremental: {child_inc}", file=sys.stderr)
        if child_inc.get("refresh", 0) == 0:
            failures.append(
                "incremental delta re-solve never engaged in the host child"
            )
    elif report.inc_outcomes.get("refresh", 0) == 0:
        failures.append("incremental delta re-solve never engaged")
    failures.extend(drill_failures)
    if failures:
        print("soak UNHEALTHY: " + "; ".join(failures), file=sys.stderr)
        return 1
    print(
        f"soak ok: {report.binds} binds, admission p99 "
        f"{report.admission_p99_s}s, resolve ratio "
        f"{report.resolve_ratio}",
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    rc = main()
    # skip interpreter teardown: the operator's watch pumps plus the XLA
    # CPU client's own thread pool race destructors at exit and
    # intermittently abort AFTER the report and health verdict are out —
    # the run's result is already decided, so exit without unwinding
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(rc)
