#!/usr/bin/env python
"""Soak bench: sustained seeded churn through the FULL operator loop.

Runs minutes of deterministic pod arrival/termination/resize traffic
(loadgen.ChurnGenerator) against a live operator — background watch pumps,
batcher windows, TPU solves, machine launches — with chaos armed (the
`state.diff` feed fault plus transient cloud-create failures) and the
flight recorder on, then reports the SLO columns the steady-state story is
judged by (docs/PERF.md "churn columns"):

  churn_admission_p50_s / churn_admission_p99_s
      pod admission -> bind-decision latency, read from the provisioner's
      karpenter_admission_to_bind_seconds histogram (REAL exposition,
      baseline-diffed — not bench-side stopwatching)
  churn_pending_max / churn_pending_mean
      batch-queue depth (karpenter_pending_pods gauge samples)
  churn_resolve_ratio, churn_inc_*
      incremental delta re-solve hit ratio by outcome
      (karpenter_incremental_screen_total)
  churn_prescreen_refresh_med_ms vs churn_prescreen_full_med_ms
      median device time of the delta refresh vs the full [N, C] verdict
      precompute at the SAME churn geometry (solver.phase.prescreen spans;
      the solver runs profile_phases so spans cover device execution)

Usage:
  python hack/soak.py                 # 75s soak, chaos armed (make soak)
  python hack/soak.py --smoke         # <=30s seeded smoke (make soak-smoke)
  python hack/soak.py --duration 300 --seed 7 --rate 12

Exits nonzero when the soak is unhealthy: a dead reconcile loop, nothing
bound, or pods stranded unbound at the end.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

# flight recorder ON for the whole run (the operator default; hack scripts
# must opt in before the obs import reads the env)
os.environ.setdefault("KARPENTER_TPU_FLIGHTREC", "1")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_solvers(max_nodes: int, hang_armed: bool = False):
    """(primary, resilient): the resilient pair is the operator wiring —
    health-gated greedy fallback, small-batch routing OFF (churn batches
    are small by nature; the soak exists to exercise the device path under
    time), a stub prober (the backend was chosen by JAX_PLATFORMS; a
    subprocess probe would measure the harness, not the loop). The bare
    primary is returned too so the warmup pass runs through the SAME
    solver instance: geometry programs trace/compile once and the measured
    window starts fully jitted.

    With `hang_armed` (the soak-smoke wedge drill) the dispatch watchdog
    runs at drill scale: a solver.device.hang injection goes heartbeat-
    stale in ~2s, is abandoned as WEDGED, trips the breaker, and the
    breaker's half-open prober re-admits the backend ~3s later — the full
    wedge -> open-breaker -> fallback -> re-admit cycle inside one smoke."""
    from karpenter_core_tpu.solver.fallback import ResilientSolver
    from karpenter_core_tpu.solver.tpu_solver import GreedySolver, TPUSolver

    primary = TPUSolver(
        max_nodes=max_nodes, screen_mode="prescreen", profile_phases=True
    )
    watchdog = {}
    if hang_armed:
        watchdog = dict(
            solve_timeout=10.0, wedge_stale_after=2.0, watchdog_poll=0.2,
            reprobe_interval=3.0,
        )
    return primary, ResilientSolver(
        primary, GreedySolver(), prober=lambda: None, small_batch_work_max=0,
        **watchdog,
    )


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--duration", type=float, default=75.0,
                        help="soak length in seconds (default 75)")
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--rate", type=float, default=2.5,
                        help="mean pod-arrival events/s")
    parser.add_argument("--smoke", action="store_true",
                        help="<=30s run for CI: 12s, lighter rates")
    parser.add_argument("--no-chaos", action="store_true")
    parser.add_argument("--no-warmup", action="store_true",
                        help="skip the virtual-time compile warmup pass")
    parser.add_argument("--out", default="",
                        help="also write the report JSON to this path")
    args = parser.parse_args(argv)

    from dataclasses import replace

    from karpenter_core_tpu import chaos
    from karpenter_core_tpu.loadgen import ChurnConfig, SoakDriver
    from karpenter_core_tpu.testing import FakeClock
    from karpenter_core_tpu.utils.compilecache import enable_persistent_cache

    # the production persistent XLA cache (ROADMAP item 3): soak geometries
    # compile once per machine, not once per run
    enable_persistent_cache()

    duration = 12.0 if args.smoke else args.duration
    rate = min(args.rate, 3.0) if args.smoke else args.rate
    # same slot axis for smoke and soak: both draw from one persistent-
    # compile-cache population, so a smoke run pre-warms the soak and vice
    # versa (N=64 machine slots is plenty at these churn rates)
    max_nodes = 64
    config = ChurnConfig(
        seed=args.seed,
        duration_s=duration,
        arrival_rate=rate,
        termination_rate=rate * 0.6,
        resize_rate=rate * 0.08,
        # the longer run carries more live pods: seed the existing axis
        # straight into the pow2 bucket it will occupy (24 -> 32, with pad
        # headroom for launches) so mid-soak machine launches neither cross
        # a bucket edge nor outgrow the hostname pad pool — either would
        # re-mint the solve geometry out from under the resident tensor
        initial_nodes=12 if args.smoke else 24,
    )
    # the wedge drill rides the SMOKE variant (make soak-smoke): one
    # solver.device.hang injection mid-soak, detected by heartbeat
    # staleness, recovered through the breaker's prober-gated half-open
    hang_armed = args.smoke and not args.no_chaos
    primary, resilient = build_solvers(max_nodes, hang_armed=hang_armed)
    if not args.no_warmup:
        # virtual-time dress rehearsal of the schedule's opening window,
        # through the SAME primary solver instance: same seed => same pods
        # => same solve geometries, so the realtime window below starts
        # with its device programs traced + compiled instead of spending
        # its first seconds — or, on a 12s smoke, ALL its seconds — inside
        # XLA. Chaos is armed after, so the rehearsal stays a pure compile
        # pass.
        print("soak: warmup (virtual-time compile pass)", file=sys.stderr)
        SoakDriver(
            replace(config, duration_s=min(duration, 12.0)),
            clock=FakeClock(),
            solver=primary,
            max_nodes=max_nodes,
        ).run_steps()

    if not args.no_chaos:
        # the feed-fault the incremental path must DEGRADE under (full
        # re-encode, never drift) + transient cloud-create failures so the
        # ICE/retry launch path runs too
        chaos.arm(chaos.STATE_DIFF, error="conn", probability=0.05,
                  seed=args.seed)
        chaos.arm(chaos.CLOUDPROVIDER_CREATE, error="conn", probability=0.02,
                  seed=args.seed + 1)
    if hang_armed:
        # ONE sleep-past-watchdog hang after the loop is in steady state:
        # the dispatch goes silent for 6s against a 2s staleness
        # threshold — abandoned as wedged, greedy fallback keeps binding,
        # backend re-admitted by the breaker's prober trial ~3s later
        chaos.arm(chaos.SOLVER_DEVICE_HANG, error=None, latency=6.0,
                  times=1, after=2, seed=args.seed + 2)

    driver = SoakDriver(
        config, max_nodes=max_nodes, solver=resilient,
        # the tail exits EARLY once everything is bound; the budget only
        # bounds the unhealthy case — and must outlast a chaos-tripped
        # launch's exponential-backoff retry window
        tail_timeout_s=25.0 if args.smoke else 30.0,
    )

    def progress(now, report):
        print(
            f"soak t={now:5.1f}s created={report.pods_created} "
            f"binds={report.binds} terminated={report.pods_terminated}",
            file=sys.stderr,
        )

    report = driver.run(on_progress=progress if sys.stderr.isatty() else None)
    columns = report.as_columns()
    columns["churn_seed"] = args.seed
    columns["churn_chaos_armed"] = not args.no_chaos
    drill_failures = []
    if hang_armed:
        # the wedge drill's own gates: the hang must actually have been
        # detected as a wedge (not silently absorbed), and the backend
        # must have been RE-ADMITTED before the end of the soak
        from karpenter_core_tpu.solver.fallback import (
            SOLVER_WEDGED_TOTAL,
            CircuitBreaker,
        )

        wedged = SOLVER_WEDGED_TOTAL.get() or 0.0
        hang_fault = chaos.armed_points().get(chaos.SOLVER_DEVICE_HANG)
        injected = hang_fault.injected if hang_fault is not None else 0
        if injected == 0:
            drill_failures.append(
                "solver.device.hang never fired (drill vacuous)"
            )
        elif wedged < 1:
            drill_failures.append(
                "hang injected but karpenter_solver_wedged_total never ticked"
            )
        elif resilient.breaker.state != CircuitBreaker.CLOSED:
            drill_failures.append(
                f"backend not re-admitted after the wedge cleared "
                f"(breaker {resilient.breaker.state})"
            )
        elif resilient._healthy is not True:
            drill_failures.append("solver still unhealthy after wedge recovery")
        columns["churn_wedge_drill"] = {
            "injected": injected,
            "wedged_total": wedged,
            "abandoned": resilient._abandon_count,
            "readmitted": not drill_failures,
        }
        print(
            f"soak wedge drill: injected={injected} wedged={wedged:.0f} "
            f"abandoned={resilient._abandon_count} "
            f"readmitted={not drill_failures}",
            file=sys.stderr,
        )
    line = json.dumps(columns, sort_keys=True)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")

    failures = []
    if not report.loops_alive:
        failures.append("a reconcile loop died")
    if report.binds == 0:
        failures.append("no pod was ever bound")
    if report.admission_count == 0:
        failures.append("admission histogram recorded nothing")
    if report.unbound_at_end > 0:
        failures.append(f"{report.unbound_at_end} pods stranded unbound")
    if report.inc_outcomes.get("refresh", 0) == 0:
        failures.append("incremental delta re-solve never engaged")
    failures.extend(drill_failures)
    if failures:
        print("soak UNHEALTHY: " + "; ".join(failures), file=sys.stderr)
        return 1
    print(
        f"soak ok: {report.binds} binds, admission p99 "
        f"{report.admission_p99_s}s, resolve ratio "
        f"{report.resolve_ratio}",
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    rc = main()
    # skip interpreter teardown: the operator's watch pumps plus the XLA
    # CPU client's own thread pool race destructors at exit and
    # intermittently abort AFTER the report and health verdict are out —
    # the run's result is already decided, so exit without unwinding
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(rc)
