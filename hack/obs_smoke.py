"""Observability smoke (`make obs-smoke`, ISSUE 15): the cross-process
span graft + merged metrics, proven on a LIVE operator in host mode.

The drill (~45s budget, typically much faster):

  1. a full in-process control plane runs the production host-mode wiring
     (HostSolver under ResilientSolver) with tracing + flightrec armed and
     the debug HTTP surface served, exactly like operator/__main__;
  2. one solve goes through the sidecar; acceptance: `/debug/trace`
     contains the CHILD's `solver.phase.*` spans grafted under
     `solver.host.request` (tagged pid/generation), the phase SET equals
     an in-process solve's of the same workload, `/debug/timeline` links
     trace ids to flight records, and the parent `/metrics` exposition
     carries the child's phase histogram under process="solver-host" with
     a trace-id exemplar on the solve-duration histogram;
  3. the attribution drill (ISSUE 16): the tenant-less half above must be
     byte-clean — no `tenant="` anywhere in the exposition and no `tenant`
     key in any dispatched frame header (the PR 15 protocol, byte for
     byte); then two tenants solve through the sidecar and the SAME label
     must land on the parent-process series, the merged child series
     (under process="solver-host"), the grafted child span attributes,
     the flight record, a per-tenant `/debug/slo` burn-rate row, and the
     exposition exemplar must link each tenant's solve to its flight
     record through the trace id;
  4. `solver.device.hang` armed in the child wedges a dispatch mid-solve;
     the parent SIGKILLs the host group; acceptance: the wedge lands as a
     `solver.host.kill` instant event NAMING the phase the child died in
     (`solver.phase.device`), and the typed SolverWedgedError carries the
     same phase.

Hermetic (CPU forced in-process). Non-fatal in `make verify`, FATAL in
hack/presubmit.sh — the host-smoke/bench-smoke pattern.
"""
import json
import os
import re
import sys
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

STALE_AFTER = float(os.environ.get("KCT_OBS_SMOKE_STALE", "3.0"))


def _get(port: int, path: str, accept: str = ""):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        headers={"Accept": accept} if accept else {},
    )
    with urllib.request.urlopen(req, timeout=10) as resp:
        return resp.read()


def main() -> int:
    import karpenter_core_tpu.solver.host as host_mod

    from karpenter_core_tpu.api.settings import Settings
    from karpenter_core_tpu.cloudprovider import fake
    from karpenter_core_tpu.metrics.registry import REGISTRY
    from karpenter_core_tpu.obs import TRACER, reqctx
    from karpenter_core_tpu.obs.flightrec import FLIGHTREC
    from karpenter_core_tpu.operator import new_operator
    from karpenter_core_tpu.operator.__main__ import (
        build_slo_engine,
        serve_health,
    )
    from karpenter_core_tpu.solver.fallback import ResilientSolver
    from karpenter_core_tpu.solver.host import HostSolver
    from karpenter_core_tpu.solver.tpu_solver import GreedySolver, TPUSolver
    from karpenter_core_tpu.testing import make_pod, make_provisioner

    TRACER.enable()
    FLIGHTREC.enable()
    # frame-header spy (attribution drill): every header the parent writes
    # to the sidecar, verbatim — proves the tenant key is absent on the
    # tenant-less half and present on the tenanted half
    frame_headers = []
    real_write_frame = host_mod._write_frame

    def spy_write_frame(stream, header, body=b""):
        frame_headers.append(dict(header))
        return real_write_frame(stream, header, body)

    host_mod._write_frame = spy_write_frame
    # stale_after stays GENEROUS (60s) for the clean-solve half: a
    # drill-scale threshold kills children mid-cold-compile before the
    # persistent cache is written and livelocks (measured, PR 11 soak
    # notes). The wedge drill tightens it AFTER the cache is warm.
    host = HostSolver(
        max_nodes=64, stale_after=60.0, solve_timeout=120.0,
        spawn_timeout=120.0,
        child_env={"KARPENTER_SOLVER_MODE": "single"},
    )
    resilient = ResilientSolver(
        host, GreedySolver(), small_batch_work_max=0,
        solve_timeout=120.0, wedge_stale_after=None,  # the host watches
        reprobe_interval=2.0, probe_timeout=60.0,
    )
    cp = fake.FakeCloudProvider(fake.instance_types(10))
    op = new_operator(
        cp,
        settings=Settings(batch_idle_duration=0.02, batch_max_duration=0.2),
        solver=resilient,
    )
    op.provisioning.fallback_solver = resilient
    op.kube_client.create(make_provisioner(name="default"))
    # the production SLO plane, wired exactly like operator/__main__.run():
    # burn-rate gauges computed fresh on every scrape, digest on /debug/slo
    slo_engine = build_slo_engine()
    REGISTRY.add_external(slo_engine)
    health = serve_health(
        op, 0, profiling=True, solver=resilient, slo=slo_engine
    )
    port = health.server_address[1]

    problems = []
    parent_pid = os.getpid()
    op.start()
    try:
        # -- one clean solve through the sidecar -------------------------
        for i in range(8):
            op.kube_client.create(
                make_pod(name=f"obs-{i}", requests={"cpu": "1"})
            )
        deadline = time.monotonic() + 45.0
        covered = False
        while time.monotonic() < deadline and not covered:
            time.sleep(0.1)
            op.sync_state()
            result = op.provisioning.schedule()
            covered = result is None or (
                not result.new_machines and not result.failed_pods
            )
        if not covered:
            problems.append("admission did not cover every pod in budget")

        trace = json.loads(_get(port, "/debug/trace"))
        events = [
            e for e in trace["traceEvents"] if e.get("ph") != "M"
        ]
        child_events = [
            e for e in events
            if e.get("pid") != parent_pid and "generation" in e["args"]
        ]
        child_phases = {
            e["name"] for e in child_events
            if e["name"].startswith("solver.phase.")
        }
        if "solver.phase.device" not in child_phases:
            problems.append(
                "/debug/trace carries no grafted child device phase "
                f"(child phases: {sorted(child_phases)})"
            )
        req = next(
            (e for e in events if e["name"] == "solver.host.request"), None
        )
        disp = next(
            (e for e in child_events
             if e["name"] == "solver.host.dispatch"), None
        )
        if req is None or disp is None or (
            disp["args"].get("parent_id") != req["args"]["span_id"]
        ):
            problems.append(
                "child dispatch span is not grafted under solver.host.request"
            )

        # phase-SET parity vs an in-process solve of the same workload
        pods = [make_pod(requests={"cpu": "1"}) for _ in range(8)]
        provisioners = [make_provisioner(name="default")]
        its = {"default": fake.instance_types(10)}
        mark = TRACER.mark()
        resilient.solve(pods, provisioners, its)
        host_phases = {
            s.name for s in TRACER.spans_since(mark)
            if s.name.startswith("solver.phase.")
        }
        mark = TRACER.mark()
        TPUSolver(max_nodes=64).solve(pods, provisioners, its)
        local_phases = {
            s.name for s in TRACER.spans_since(mark)
            if s.name.startswith("solver.phase.")
        }
        if host_phases != local_phases:
            problems.append(
                f"phase set mismatch: host {sorted(host_phases)} vs "
                f"in-process {sorted(local_phases)}"
            )

        timeline = json.loads(_get(port, "/debug/timeline"))
        if "flight_records" not in timeline.get("otherData", {}):
            problems.append("/debug/timeline lacks the flight-record index")

        expo = _get(port, "/metrics").decode()
        if 'process="solver-host"' not in expo or (
            "karpenter_solver_phase_duration_seconds_bucket" not in expo
        ):
            problems.append(
                "parent exposition lacks child phase histograms under "
                "the process label"
            )
        if "# {trace_id=" in expo:
            problems.append(
                "plain 0.0.4 exposition must NOT carry exemplars (a "
                "stock scraper would fail the whole scrape)"
            )
        om = _get(
            port, "/metrics", accept="application/openmetrics-text"
        ).decode()
        if "# {trace_id=" not in om or not om.rstrip().endswith("# EOF"):
            problems.append(
                "OpenMetrics-negotiated exposition lacks the trace-id "
                "exemplar (or the # EOF terminator)"
            )

        # -- attribution drill: the tenant-less half is byte-clean --------
        # everything above ran with NO bound tenant and no tenant pod
        # labels: the exposition (parent AND merged child series, SLO
        # gauges included) must carry no tenant label at all, and no
        # dispatched frame header may carry the key — the zero-bytes-
        # when-unset contract, same as PR 15's `trace` key
        if 'tenant="' in expo or 'tenant="' in om:
            problems.append(
                "tenant-less run leaked a tenant label into the exposition"
            )
        if any("tenant" in h for h in frame_headers):
            problems.append(
                "a tenant-less dispatch frame header carried the tenant key"
            )

        # -- attribution drill: two tenants, end to end -------------------
        tenants = ("team-blue", "team-green")
        mark = TRACER.mark()
        headers_before = len(frame_headers)
        for tenant in tenants:
            # bind + span mirror the production call site (the scheduler
            # wraps its solve in a span, so the flight record begun inside
            # ResilientSolver.solve joins the same trace the dispatch
            # thread continues — that trace id is the exemplar's payload)
            with reqctx.bind(reqctx.RequestContext(
                tenant=tenant, request_id=f"obs-smoke-{tenant}",
            )), TRACER.span("scheduler.solve", pods=len(pods)):
                resilient.solve(pods, provisioners, its)
        sent = {
            h["tenant"] for h in frame_headers[headers_before:]
            if "tenant" in h
        }
        if sent != set(tenants):
            problems.append(
                f"dispatch frame headers carried tenants {sorted(sent)}, "
                f"expected {sorted(tenants)}"
            )
        grafted_tenants = {
            s.attrs.get("tenant") for s in TRACER.spans_since(mark)
            if "generation" in s.attrs and s.attrs.get("tenant")
        }
        if not set(tenants) <= grafted_tenants:
            problems.append(
                "grafted child spans lack tenant attributes "
                f"(saw {sorted(grafted_tenants)})"
            )
        expo2 = _get(port, "/metrics").decode()
        for tenant in tenants:
            tag = f'tenant="{tenant}"'
            if not any(
                tag in line and 'process="' not in line
                for line in expo2.splitlines()
            ):
                problems.append(
                    f"no parent-process series carries tenant={tenant}"
                )
            if not any(
                tag in line and 'process="solver-host"' in line
                for line in expo2.splitlines()
            ):
                problems.append(
                    f"no merged child series carries tenant={tenant} under "
                    "the process label"
                )
        rec_tenants = {
            r.get("tenant") for r in FLIGHTREC.records() if r.get("tenant")
        }
        if not set(tenants) <= rec_tenants:
            problems.append(
                f"flight records attribute tenants {sorted(rec_tenants)}, "
                f"expected {sorted(tenants)}"
            )
        # exemplar -> flight record: every tenant's solve must be reachable
        # from the exposition through its exemplar trace id
        om2 = _get(
            port, "/metrics", accept="application/openmetrics-text"
        ).decode()
        linked = set()
        for tid in set(re.findall(r'trace_id="([^"]+)"', om2)):
            rec = FLIGHTREC.record_for_trace(tid)
            if rec is not None and rec.get("tenant"):
                linked.add(rec["tenant"])
        if not set(tenants) <= linked:
            problems.append(
                "exposition exemplars do not link every tenant's solve to "
                f"its flight record (linked: {sorted(linked)})"
            )
        slo_digest = json.loads(_get(port, "/debug/slo"))
        burn_tenants = {
            row["tenant"] for row in slo_digest.get("series", [])
            if row["slo"] == "solve-duration" and row["tenant"]
            and any(
                (w.get("traffic") or 0) > 0 for w in row["windows"].values()
            )
        }
        if not set(tenants) <= burn_tenants:
            problems.append(
                "/debug/slo has no per-tenant burn-rate rows with traffic "
                f"(saw {sorted(burn_tenants)})"
            )
        tenants_digest = json.loads(_get(port, "/debug/tenants"))
        if not set(tenants) <= set(tenants_digest.get("tenants", {})):
            problems.append(
                "/debug/tenants lacks the drilled tenants (saw "
                f"{sorted(tenants_digest.get('tenants', {}))})"
            )

        # -- wedge drill: the kill names the phase ------------------------
        # the programs are compiled and disk-cached now; a tight staleness
        # threshold is safe and keeps the drill fast
        host.host.stale_after = STALE_AFTER
        host.host.child_env["KARPENTER_CHAOS"] = (
            "solver.device.hang=error:none,latency:60,times:1,after:0"
        )
        # respawn so the child picks up the armed env
        host.host.call("health", timeout=30.0, watch_heartbeat=False)
        pid = host.host.pid
        if pid is not None:
            import signal as _signal

            try:
                os.kill(pid, _signal.SIGKILL)
            except ProcessLookupError:
                pass
        time.sleep(0.5)
        mark = TRACER.mark()
        wedge_msg = ""
        resilient.solve(pods, provisioners, its)  # wedges, falls back
        report = resilient.health_report()
        hist = report.get("wedge_history") or []
        if hist and hist[-1].get("reason"):
            wedge_msg = str(hist[-1]["reason"])
        kills = [
            s for s in TRACER.spans_since(mark)
            if s.name == "solver.host.kill"
            and s.attrs.get("kind") == "wedged"
        ]
        if not kills:
            problems.append("no solver.host.kill wedge instant event landed")
        elif kills[-1].attrs.get("phase") != "solver.phase.device":
            problems.append(
                "wedge instant event does not name the device phase "
                f"(phase={kills[-1].attrs.get('phase')!r})"
            )
        if "solver.phase.device" not in wedge_msg:
            problems.append(
                "SolverWedgedError/wedge history does not name the phase "
                f"(reason={wedge_msg!r})"
            )
        host.host.child_env.pop("KARPENTER_CHAOS", None)
    finally:
        host_mod._write_frame = real_write_frame
        op.stop()
        host.close()
        health.shutdown()

    if problems:
        for p in problems:
            print(f"obs-smoke FAIL: {p}", file=sys.stderr)
        return 1
    print(
        "obs-smoke ok: child device phases grafted (set parity), merged "
        "metrics under process label with trace-id exemplars, tenant "
        "attribution end to end (frames/spans/metrics/flightrec/SLO burn "
        "rates, tenant-less half byte-clean), wedge kill named "
        "solver.phase.device on the timeline"
    )
    return 0


if __name__ == "__main__":
    rc = main()
    sys.stdout.flush()
    sys.stderr.flush()
    # skip interpreter teardown: watch pumps + XLA's thread pool race
    # destructors at exit (same dodge as hack/host_smoke.py)
    os._exit(rc)
