"""gRPC Solver service: stateless dense-solve execution behind the process
boundary (SURVEY.md section 7.2 — absent in the reference, whose Solve is
in-process at provisioner.go:301).

Server: receives the encoded snapshot tensors + static geometry, runs the
feasibility+packing device program, returns assignment + slot-state tensors.
Client (RemoteSolver): implements the same Solver interface as
TPUSolver/GreedySolver — encodes host-side, ships tensors, decodes locally —
so the control plane can point at an out-of-process TPU solver with one
constructor swap. The service keeps no snapshot state: restarts are trivial.

The gRPC method is registered by hand (grpc.unary_unary_rpc_method_handler);
messages come from service.proto via protoc.
"""
from __future__ import annotations

import copy
import json
import threading
from concurrent import futures
from typing import Dict, List, Optional, Tuple

import numpy as np

from karpenter_core_tpu.solver import service_pb2 as pb
from karpenter_core_tpu.solver.encode import encode_snapshot
from karpenter_core_tpu.solver.tpu_solver import (
    SolveResult,
    decode_solve,
    device_args,
)

SERVICE = "karpenter.solver.v1.Solver"


# ---------------------------------------------------------------------------
# tensor (de)serialization


def tensor_to_pb(name: str, array: np.ndarray) -> pb.Tensor:
    array = np.ascontiguousarray(array)
    return pb.Tensor(
        name=name, dtype=str(array.dtype), shape=list(array.shape), data=array.tobytes()
    )


def tensor_from_pb(t: pb.Tensor) -> np.ndarray:
    return np.frombuffer(t.data, dtype=np.dtype(t.dtype)).reshape(tuple(t.shape))


def _flatten_args(args) -> List[Tuple[str, np.ndarray]]:
    """device_args tuple -> named tensors (dicts flattened with / paths)."""
    out = []

    def walk(prefix, value):
        if isinstance(value, dict):
            for k in sorted(value):
                walk(f"{prefix}/{k}", value[k])
        else:
            out.append((prefix, np.asarray(value)))

    names = [
        "pod_arrays", "tmpl", "tmpl_daemon", "tmpl_type_mask", "types",
        "type_alloc", "type_capacity", "type_offering_ok", "pod_tol_all",
        "exist", "exist_used", "exist_cap", "well_known", "remaining0",
        "topo_counts0", "topo_hcounts0", "topo_doms0", "topo_terms",
    ]
    for name, value in zip(names, args):
        walk(name, value)
    return out


def _unflatten_args(tensors: Dict[str, np.ndarray]):
    def gather(prefix):
        sub = {}
        plain = None
        for name, arr in tensors.items():
            if name == prefix:
                plain = arr
            elif name.startswith(prefix + "/"):
                sub[name[len(prefix) + 1 :]] = arr
        return sub if sub else plain

    names = [
        "pod_arrays", "tmpl", "tmpl_daemon", "tmpl_type_mask", "types",
        "type_alloc", "type_capacity", "type_offering_ok", "pod_tol_all",
        "exist", "exist_used", "exist_cap", "well_known", "remaining0",
        "topo_counts0", "topo_hcounts0", "topo_doms0", "topo_terms",
    ]
    return tuple(gather(n) for n in names)


def geometry_json(snap) -> str:
    topo = None
    if snap.topo_meta is not None:
        topo = [
            {
                "gtype": g.gtype,
                "seg": list(g.seg),
                "key_k": g.key_k,
                "max_skew": g.max_skew,
                "is_hostname": g.is_hostname,
                "is_inverse": g.is_inverse,
                "filter_term_rows": list(g.filter_term_rows),
            }
            for g in snap.topo_meta.groups
        ]
    return json.dumps(
        {
            "segments": [list(snap.dictionary.segment(k)) for k in snap.dictionary.keys],
            "zone_seg": list(snap.zone_seg),
            "ct_seg": list(snap.ct_seg),
            "n_slots": snap.n_slots,
            "topo_groups": topo,
        }
    )


# ---------------------------------------------------------------------------
# server


class SolverService:
    """Stateless executor keyed by geometry (jit cache shared across calls)."""

    def __init__(self):
        self._compiled = {}
        self._mu = threading.Lock()
        self.solves = 0

    def solve(self, request: pb.SolveRequest, context=None) -> pb.SolveResponse:
        import jax

        from karpenter_core_tpu.ops.topology import TopoGroupMeta, TopoMeta

        try:
            geometry = json.loads(request.geometry)
            tensors = {t.name: tensor_from_pb(t) for t in request.tensors}
            args = _unflatten_args(tensors)
            segments = [tuple(s) for s in geometry["segments"]]
            zone_seg = tuple(geometry["zone_seg"])
            ct_seg = tuple(geometry["ct_seg"])
            topo_meta = None
            if geometry.get("topo_groups"):
                topo_meta = TopoMeta(
                    groups=[
                        TopoGroupMeta(
                            gtype=g["gtype"],
                            seg=tuple(g["seg"]),
                            key_k=g["key_k"],
                            max_skew=g["max_skew"],
                            is_hostname=g["is_hostname"],
                            is_inverse=g["is_inverse"],
                            filter_term_rows=list(g["filter_term_rows"]),
                        )
                        for g in geometry["topo_groups"]
                    ]
                )
            key = (request.geometry,)
            with self._mu:
                fn = self._compiled.get(key)
            if fn is None:
                fn = jax.jit(
                    _build_run(segments, zone_seg, ct_seg, topo_meta, geometry["n_slots"])
                )
                with self._mu:
                    self._compiled[key] = fn
            assigned, state = fn(*args)
            out = [tensor_to_pb("assigned", np.asarray(assigned))]
            for field, value in state._asdict().items():
                out.append(tensor_to_pb(f"state/{field}", np.asarray(value)))
            with self._mu:
                self.solves += 1
            return pb.SolveResponse(tensors=out)
        except Exception as e:  # surface errors to the client
            return pb.SolveResponse(error=f"{type(e).__name__}: {e}")

    def health(self, request: pb.HealthRequest, context=None) -> pb.HealthResponse:
        import jax

        return pb.HealthResponse(
            status="ok", device=jax.devices()[0].device_kind, solves=self.solves
        )


def _build_run(segments, zone_seg, ct_seg, topo_meta, n_slots):
    import jax.numpy as jnp

    from karpenter_core_tpu.ops.feasibility import feasibility_static, openable_mask
    from karpenter_core_tpu.ops.pack import PackState, make_pack_kernel

    pack = make_pack_kernel(list(segments), zone_seg, ct_seg, topo_meta=topo_meta)

    def run(pod_arrays, tmpl, tmpl_daemon, tmpl_type_mask, types, type_alloc,
            type_capacity, type_offering_ok, pod_tol_all, exist, exist_used,
            exist_cap, well_known, remaining0, topo_counts0, topo_hcounts0,
            topo_doms0, topo_terms):
        E = exist_used.shape[0]
        N = n_slots
        R = type_alloc.shape[1]
        T = type_alloc.shape[0]
        J = tmpl_daemon.shape[0]
        V = pod_arrays["allow"].shape[1]
        K = pod_arrays["out"].shape[1]
        f_static = feasibility_static(
            {k: pod_arrays[k] for k in ("allow", "out", "defined", "escape")},
            tmpl, types, pod_arrays["tol_tmpl"], tmpl_type_mask,
            type_offering_ok, zone_seg, ct_seg, list(segments), well_known,
        )
        openable = openable_mask(f_static, pod_arrays["requests"], tmpl_daemon, type_alloc)
        state = PackState(
            used=jnp.zeros((N, R), jnp.float32).at[:E].set(exist_used),
            open=jnp.arange(N) < E,
            is_existing=jnp.arange(N) < E,
            tmpl=jnp.zeros(N, jnp.int32),
            tol_idx=jnp.concatenate(
                [J + jnp.arange(E, dtype=jnp.int32), jnp.zeros(N - E, jnp.int32)]
            ),
            pods=jnp.zeros(N, jnp.int32),
            allow=jnp.ones((N, V), bool).at[:E].set(exist["allow"]),
            out=jnp.ones((N, K), bool).at[:E].set(exist["out"]),
            defined=jnp.zeros((N, K), bool).at[:E].set(exist["defined"]),
            tmask=jnp.zeros((N, T), bool),
            cap=jnp.zeros((N, R), jnp.float32).at[:E].set(exist_cap),
            nopen=jnp.int32(E),
            remaining=remaining0,
            tcounts=topo_counts0,
            thost=topo_hcounts0,
            tdoms=topo_doms0,
        )
        pod_arrays2 = dict(pod_arrays)
        pod_arrays2["tol"] = pod_tol_all
        state, assigned = pack(
            state, pod_arrays2, f_static, openable,
            {k: tmpl[k] for k in ("allow", "out", "defined")},
            tmpl_daemon, tmpl_type_mask, types, type_alloc, type_capacity,
            type_offering_ok, well_known=well_known, topo_terms=topo_terms,
        )
        return assigned, state

    return run


def serve(address: str = "127.0.0.1:0", max_workers: int = 4):
    """Start the gRPC server; returns (server, bound_port, service)."""
    import grpc

    service = SolverService()
    handlers = {
        "Solve": grpc.unary_unary_rpc_method_handler(
            service.solve,
            request_deserializer=pb.SolveRequest.FromString,
            response_serializer=pb.SolveResponse.SerializeToString,
        ),
        "Health": grpc.unary_unary_rpc_method_handler(
            service.health,
            request_deserializer=pb.HealthRequest.FromString,
            response_serializer=pb.HealthResponse.SerializeToString,
        ),
    }
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE, handlers),)
    )
    port = server.add_insecure_port(address)
    server.start()
    return server, port, service


# ---------------------------------------------------------------------------
# client


class RemoteSolver:
    """Solver-interface client: encode locally, solve remotely, decode
    locally. Falls back to raising on transport errors (the provisioning
    controller's fallback_solver takes over)."""

    def __init__(self, target: str, max_nodes: int = 1024, max_relax_rounds: int = 3,
                 timeout: float = 120.0):
        import grpc

        self.channel = grpc.insecure_channel(target)
        self.timeout = timeout
        self.max_nodes = max_nodes
        self.max_relax_rounds = max_relax_rounds
        self._solve = self.channel.unary_unary(
            f"/{SERVICE}/Solve",
            request_serializer=pb.SolveRequest.SerializeToString,
            response_deserializer=pb.SolveResponse.FromString,
        )
        self._health = self.channel.unary_unary(
            f"/{SERVICE}/Health",
            request_serializer=pb.HealthRequest.SerializeToString,
            response_deserializer=pb.HealthResponse.FromString,
        )

    def health(self) -> pb.HealthResponse:
        return self._health(pb.HealthRequest(), timeout=5.0)

    def solve(
        self,
        pods,
        provisioners,
        instance_types,
        daemonset_pods=None,
        state_nodes=None,
        kube_client=None,
        cluster=None,
    ) -> SolveResult:
        from karpenter_core_tpu.solver.tpu_solver import solve_with_relaxation

        return solve_with_relaxation(
            lambda p: self._solve_once(
                p, provisioners, instance_types, daemonset_pods, state_nodes,
                kube_client, cluster,
            ),
            pods,
            provisioners,
            instance_types,
            self.max_relax_rounds,
        )

    def _solve_once(self, pods, provisioners, instance_types, daemonset_pods,
                    state_nodes, kube_client, cluster) -> SolveResult:
        snap = encode_snapshot(
            pods, provisioners, instance_types, daemonset_pods, state_nodes,
            kube_client=kube_client, cluster=cluster, max_nodes=self.max_nodes,
        )
        args = device_args(snap, provisioners)
        request = pb.SolveRequest(
            geometry=geometry_json(snap),
            tensors=[tensor_to_pb(n, a) for n, a in _flatten_args(args)],
        )
        response = self._solve(request, timeout=self.timeout)
        if response.error:
            raise RuntimeError(f"solver service error: {response.error}")
        tensors = {t.name: tensor_from_pb(t) for t in response.tensors}
        assigned = tensors["assigned"]
        state = _StateView(
            {k[len("state/"):]: v for k, v in tensors.items() if k.startswith("state/")}
        )
        return decode_solve(snap, assigned, state)


class _StateView:
    """Attribute access over the returned state tensors."""

    def __init__(self, tensors: Dict[str, np.ndarray]):
        self._tensors = tensors

    def __getattr__(self, name):
        try:
            return self._tensors[name]
        except KeyError:
            raise AttributeError(name)
