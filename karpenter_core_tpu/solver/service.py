"""gRPC Solver service: stateless dense-solve execution behind the process
boundary (SURVEY.md section 7.2 — absent in the reference, whose Solve is
in-process at provisioner.go:301).

Server: receives the encoded snapshot tensors + static geometry, runs the
feasibility+packing device program, returns assignment + slot-state tensors.
Client (RemoteSolver): implements the same Solver interface as
TPUSolver/GreedySolver — encodes host-side, ships tensors, decodes locally —
so the control plane can point at an out-of-process TPU solver with one
constructor swap. The service keeps no snapshot state: restarts are trivial.

The gRPC method is registered by hand (grpc.unary_unary_rpc_method_handler);
messages come from service.proto via protoc.
"""
from __future__ import annotations

import contextlib
import copy
import json
import threading
import time
from concurrent import futures
from typing import Dict, List, Optional, Tuple

import numpy as np

from karpenter_core_tpu import chaos
from karpenter_core_tpu.metrics.registry import NAMESPACE, REGISTRY
from karpenter_core_tpu.obs import envflags
from karpenter_core_tpu.obs import proghealth, reqctx
from karpenter_core_tpu.obs import TRACE_HEADER, TRACER
from karpenter_core_tpu.obs.log import get_logger

LOG = get_logger("karpenter.solver.service")
from karpenter_core_tpu.solver import service_pb2 as pb
from karpenter_core_tpu.solver.encode import encode_snapshot
from karpenter_core_tpu.solver.tpu_solver import (
    SolveResult,
    decode_solve,
    device_args,
    make_device_run,
    solve_geometry,
)
from karpenter_core_tpu.utils import supervise

SERVICE = "karpenter.solver.v1.Solver"

SOLVER_RPC_RETRIES = REGISTRY.counter(
    f"{NAMESPACE}_solver_rpc_retries_total",
    "Solver RPCs retried after a transient failure (UNAVAILABLE/"
    "DEADLINE_EXCEEDED)",
)
SOLVER_RETRY_BUDGET_EXHAUSTED = REGISTRY.counter(
    f"{NAMESPACE}_solver_retry_budget_exhausted_total",
    "Solver RPC retries DENIED by the per-tenant retry budget (token "
    "bucket): the original error is raised immediately instead of "
    "retried, so a shed tenant cannot convert rejection into a retry "
    "storm; by tenant when a request context is bound",
)


# ---------------------------------------------------------------------------
# typed RPC errors — what the client raises, what the circuit breaker and
# ResilientSolver classify (ISSUE 2 satellite: no more stringified
# exceptions in the response the caller has to regex)


class SolverRpcError(RuntimeError):
    """Base typed solver-service failure.

    `transient` drives the client's bounded retry + the circuit breaker
    (transport-shaped: the SAME request may succeed on a healthy channel);
    `marks_unhealthy` drives ResilientSolver — a request defect must not
    condemn a healthy backend to the fallback path. `retry_after_s` is the
    server's load-shedding hint (admission gate, ISSUE 12): set on
    RESOURCE_EXHAUSTED sheds so the client retries after the queue has a
    chance to drain instead of re-landing immediately."""

    code_name = "UNKNOWN"
    transient = False
    marks_unhealthy = True
    retry_after_s: Optional[float] = None
    shed_reason: Optional[str] = None


class SolverUnavailableError(SolverRpcError):
    code_name = "UNAVAILABLE"
    transient = True


class SolverDeadlineExceededError(SolverRpcError):
    code_name = "DEADLINE_EXCEEDED"
    transient = True


class SolverInvalidArgumentError(SolverRpcError):
    code_name = "INVALID_ARGUMENT"
    marks_unhealthy = False


class SolverResourceExhaustedError(SolverRpcError):
    code_name = "RESOURCE_EXHAUSTED"
    marks_unhealthy = False


class SolverInternalError(SolverRpcError):
    code_name = "INTERNAL"


_ERROR_BY_CODE = {
    cls.code_name: cls
    for cls in (
        SolverUnavailableError,
        SolverDeadlineExceededError,
        SolverInvalidArgumentError,
        SolverResourceExhaustedError,
        SolverInternalError,
    )
}


def classify_exception(e: Exception) -> Tuple[str, str]:
    """Server-side: exception -> (gRPC status-code name, detail). Request
    defects (malformed geometry/tensors) are INVALID_ARGUMENT; memory/slot
    exhaustion is RESOURCE_EXHAUSTED; everything else INTERNAL."""
    msg = f"{type(e).__name__}: {e}"
    if isinstance(e, (ValueError, KeyError, TypeError, IndexError)):
        return "INVALID_ARGUMENT", msg
    if isinstance(e, MemoryError) or "RESOURCE_EXHAUSTED" in str(e):
        return "RESOURCE_EXHAUSTED", msg
    return "INTERNAL", msg


# metadata key the server sets on admission-gate sheds (lowercase — gRPC
# metadata keys must be); the detail string carries the same hint as
# `retry_after_ms=N` for the legacy/in-process error-field path
RETRY_AFTER_METADATA_KEY = "karpenter-retry-after-ms"


def _parse_retry_after(detail: str) -> Optional[float]:
    import re

    m = re.search(r"retry_after_ms=(\d+)", detail or "")
    return int(m.group(1)) / 1000.0 if m else None


def error_from_string(error: str) -> SolverRpcError:
    """Client-side: the legacy response.error field (populated when the
    server handler runs without a gRPC context, i.e. direct in-process
    calls) -> typed error. The server writes 'CODE: detail'."""
    code = error.split(":", 1)[0].strip()
    cls = _ERROR_BY_CODE.get(code, SolverInternalError)
    err = cls(error)
    err.retry_after_s = _parse_retry_after(error)
    return err


# ---------------------------------------------------------------------------
# tensor (de)serialization


def tensor_to_pb(name: str, array: np.ndarray) -> pb.Tensor:
    array = np.ascontiguousarray(array)
    return pb.Tensor(
        name=name, dtype=str(array.dtype), shape=list(array.shape), data=array.tobytes()
    )


def tensor_from_pb(t: pb.Tensor) -> np.ndarray:
    return np.frombuffer(t.data, dtype=np.dtype(t.dtype)).reshape(tuple(t.shape))


# device_args() tuple element names, in positional order — the wire schema
# (kept equal to tpu_solver.RUN_ARG_NAMES; asserted below so a signature
# change breaks loudly instead of desynchronizing the wire).
from karpenter_core_tpu.solver.tpu_solver import RUN_ARG_NAMES as _ARG_NAMES


def _flatten_args(args) -> List[Tuple[str, np.ndarray]]:
    """device_args tuple -> named tensors (dicts flattened with / paths)."""
    out = []

    def walk(prefix, value):
        if isinstance(value, dict):
            for k in sorted(value):
                walk(f"{prefix}/{k}", value[k])
        else:
            out.append((prefix, np.asarray(value)))

    for name, value in zip(_ARG_NAMES, args):
        walk(name, value)
    return out


def _unflatten_args(tensors: Dict[str, np.ndarray]):
    def gather(prefix):
        sub = {}
        plain = None
        for name, arr in tensors.items():
            if name == prefix:
                plain = arr
            elif name.startswith(prefix + "/"):
                sub[name[len(prefix) + 1 :]] = arr
        return sub if sub else plain

    return tuple(gather(n) for n in _ARG_NAMES)


def geometry_json(snap) -> str:
    topo = None
    if snap.topo_meta is not None:
        topo = [
            {
                "gtype": g.gtype,
                "seg": list(g.seg),
                "key_k": g.key_k,
                "max_skew": g.max_skew,
                "is_hostname": g.is_hostname,
                "is_inverse": g.is_inverse,
                "filter_term_rows": list(g.filter_term_rows),
            }
            for g in snap.topo_meta.groups
        ]
    return json.dumps(
        {
            "segments": [list(snap.dictionary.segment(k)) for k in snap.dictionary.keys],
            "zone_seg": list(snap.zone_seg),
            "ct_seg": list(snap.ct_seg),
            "n_slots": snap.n_slots,
            "screen_v": snap.screen_v or snap.dictionary.V,
            # index 12 = log_len (see solve_geometry's return tuple)
            "log_len": solve_geometry(snap, 0)[12],
            "topo_groups": topo,
        }
    )


def _request_metadata(trace_id: Optional[str]):
    """Outbound gRPC metadata for a solver RPC: the trace id plus the
    calling thread's bound tenant (x-karpenter-tenant). Neither set ->
    None, the PR 15 wire shape — attribution off adds zero metadata."""
    metadata = []
    if trace_id:
        metadata.append((TRACE_HEADER, trace_id))
    tenant = reqctx.current_tenant()
    if tenant is not None:
        metadata.append((reqctx.TENANT_HEADER, tenant))
    return tuple(metadata) if metadata else None


# ---------------------------------------------------------------------------
# server


class SolverService:
    """Stateless executor keyed by geometry (jit cache shared across calls).

    `mesh` (a dp×tp jax.sharding.Mesh, or True to autodetect via
    solver/factory.detect_mesh) routes every Solve through the multi-chip
    GSPMD mesh program — the v5e-4 deployment shape. The mesh program is
    byte-identical to the single-device one (parallel/sharded.py), so the
    wire format is IDENTICAL either way and the client decodes both with
    decode_solve; small batches route through the plain single-device
    program server-side (route_to_mesh).

    The cache is LRU-bounded: geometry embeds the label dictionary, so in a
    live cluster label churn mints new keys — an unbounded map would pin every
    old compiled executable until OOM."""

    MAX_COMPILED = 32

    MAX_REFRESH = 16

    def __init__(self, mesh=None, admission=None):
        from collections import OrderedDict

        if mesh is True:
            from karpenter_core_tpu.solver.factory import detect_mesh

            mesh = detect_mesh()
        self.mesh = mesh
        # deadline-aware admission control (solver/host.AdmissionGate,
        # ISSUE 12): when set, every Solve/Replan dispatch passes the
        # bounded gate — the client's gRPC deadline propagates in, a
        # request whose deadline expires while queued is never dispatched,
        # and a full queue sheds with RESOURCE_EXHAUSTED + retry-after
        # instead of queueing unboundedly in the executor. None (direct
        # in-process construction, the solver-host child) skips the gate —
        # the caller gates.
        self.admission = admission
        self._compiled = OrderedDict()
        # solve keys minted but not yet compile-attributed: the live path
        # pays jit trace + XLA compile at FIRST dispatch, so the first
        # device block's seconds book against the program (ISSUE 18)
        self._prog_fresh = set()
        self._mu = threading.Lock()
        self.solves = 0
        # incremental prescreen residency (solver/incremental.py): the
        # "stateless" contract still holds for CORRECTNESS — a restarted
        # service answers every request identically — but consecutive
        # same-geometry solves keep the [N, C] verdict tensor resident and
        # replay only the plane delta through a refresh program. There is
        # no cluster diff feed at the RPC boundary; the plane fingerprints
        # alone are exact (the feed can only ever be more conservative).
        self._inc_mu = threading.Lock()
        self._inc_screens: Dict[object, object] = {}
        self._refresh_compiled = OrderedDict()
        # batched consolidation replan programs (Replan RPC): one vmapped
        # rung program per (solve key, candidate-axis bucket) — the same
        # program family the in-process TPUSolver.replan_screen compiles,
        # sharing this service's solve-entry prescreen + residency
        self.MAX_REPLAN = 16
        self._replan_compiled = OrderedDict()
        self.replans = 0
        # in-flight dispatch heartbeats (utils/supervise): each Solve/Replan
        # RPC binds a ThreadHeartbeat the TPUSolver phase marks touch; the
        # Health RPC reads the oldest age and reports "wedged" past the
        # threshold, so a control plane probing a service whose XLA runtime
        # hung mid-dispatch learns about it WITHOUT issuing a live solve
        self.wedge_stale_after = 600.0
        self._inflight_mu = threading.Lock()
        self._inflight: Dict[int, supervise.ThreadHeartbeat] = {}
        self._inflight_seq = 0

    @contextlib.contextmanager
    def _dispatch_heartbeat(self):
        """Register a heartbeat for the calling RPC thread's dispatch:
        TPUSolver's phase marks touch it; health() reads the inventory.
        Unregistered on every exit."""
        hb = supervise.ThreadHeartbeat()
        hb.touch()
        with self._inflight_mu:
            self._inflight_seq += 1
            token = self._inflight_seq
            self._inflight[token] = hb
        supervise.bind_heartbeat(hb)
        try:
            yield hb
        finally:
            supervise.bind_heartbeat(None)
            with self._inflight_mu:
                self._inflight.pop(token, None)

    def _stalest_dispatch_age(self) -> Optional[float]:
        with self._inflight_mu:
            ages = [hb.age() for hb in self._inflight.values()]
        ages = [a for a in ages if a is not None]
        return max(ages) if ages else None

    # -- deadline-aware admission (ISSUE 12) --------------------------------

    @staticmethod
    def _context_deadline(context) -> Optional[float]:
        """The caller's remaining gRPC deadline in seconds (None = no
        deadline / no context) — what the admission gate enforces: a
        request whose budget expires while queued is never dispatched."""
        if context is None:
            return None
        tr = getattr(context, "time_remaining", None)
        if not callable(tr):
            return None
        try:
            return tr()
        except Exception:  # noqa: BLE001 — deadline read must never fail a solve
            return None

    def _abort_shed(self, e: SolverRpcError, context) -> pb.SolveResponse:
        """Admission-gate shed -> RESOURCE_EXHAUSTED / DEADLINE_EXCEEDED
        over the wire, with the retry-after hint in trailing metadata (and
        already embedded in the detail as retry_after_ms=N for the legacy
        error-field path)."""
        retry_ms = int((getattr(e, "retry_after_s", None) or 0) * 1000)
        msg = str(e)
        if context is not None:
            import grpc

            if retry_ms:
                try:
                    context.set_trailing_metadata(
                        ((RETRY_AFTER_METADATA_KEY, str(retry_ms)),)
                    )
                except Exception:  # noqa: BLE001 — the abort still sheds
                    pass
            context.abort(getattr(grpc.StatusCode, e.code_name), msg)
        return pb.SolveResponse(error=f"{e.code_name}: {msg}")

    def _gated(self, request: pb.SolveRequest, context,
               traced) -> pb.SolveResponse:
        """Dispatch `traced` through the admission gate (when configured)
        then the heartbeat + status-code mapping shared by Solve/Replan."""
        if self.admission is None:
            return self._dispatch_mapped(request, context, traced)
        deadline_s = self._context_deadline(context)
        try:
            gate = self.admission.admitted(deadline_s)
            gate.__enter__()
        except (SolverResourceExhaustedError,
                SolverDeadlineExceededError) as e:
            return self._abort_shed(e, context)
        try:
            return self._dispatch_mapped(request, context, traced)
        finally:
            gate.__exit__(None, None, None)

    def _dispatch_mapped(self, request: pb.SolveRequest, context,
                         traced) -> pb.SolveResponse:
        try:
            with self._dispatch_heartbeat():
                return traced(request)
        except Exception as e:  # noqa: BLE001 — mapped to a status code
            code_name, msg = classify_exception(e)
            if context is not None:
                import grpc

                # PROPER status codes over the wire (not a stringified
                # exception the client must regex): the client maps the
                # code back to a typed error the circuit breaker and
                # ResilientSolver classify. abort() raises.
                context.abort(getattr(grpc.StatusCode, code_name), msg)
            # no context: direct in-process call (tests, embedding, the
            # solver-host child) — the legacy error field carries the same
            # classification
            return pb.SolveResponse(error=f"{code_name}: {msg}")

    def solve(self, request: pb.SolveRequest, context=None) -> pb.SolveResponse:
        # adopt the client's propagated trace id (metadata interceptor
        # analog): the server-side span joins the control plane's trace so
        # one Perfetto timeline covers both processes
        trace_id = None
        tenant = None
        if context is not None:
            try:
                for k, v in context.invocation_metadata() or ():
                    if k == TRACE_HEADER:
                        trace_id = v
                    elif k == reqctx.TENANT_HEADER:
                        tenant = v
            except Exception:  # noqa: BLE001 — tracing must never fail a solve
                pass
        with contextlib.ExitStack() as stack:
            # adopt the client's tenant (x-karpenter-tenant metadata, the
            # gRPC analog of the frame header's tenant key) BEFORE opening
            # the span, so the span and everything under the gate
            # attributes to it; an in-process caller (the solver-host
            # child) arrives already bound and carries no metadata
            if tenant is not None:
                stack.enter_context(reqctx.bind(
                    reqctx.RequestContext(tenant=str(tenant))
                ))
            stack.enter_context(TRACER.span(
                "solver.service.solve", trace_id=trace_id,
                tensors=len(request.tensors),
            ))
            return self._gated(request, context, self._solve_traced)

    @staticmethod
    def _parse_geometry(geometry: dict):
        """(segments, zone_seg, ct_seg, topo_meta) from the wire geometry."""
        from karpenter_core_tpu.ops.topology import TopoGroupMeta, TopoMeta

        segments = [tuple(s) for s in geometry["segments"]]
        zone_seg = tuple(geometry["zone_seg"])
        ct_seg = tuple(geometry["ct_seg"])
        topo_meta = None
        if geometry.get("topo_groups"):
            topo_meta = TopoMeta(
                groups=[
                    TopoGroupMeta(
                        gtype=g["gtype"],
                        seg=tuple(g["seg"]),
                        key_k=g["key_k"],
                        max_skew=g["max_skew"],
                        is_hostname=g["is_hostname"],
                        is_inverse=g["is_inverse"],
                        filter_term_rows=list(g["filter_term_rows"]),
                    )
                    for g in geometry["topo_groups"]
                ]
            )
        return segments, zone_seg, ct_seg, topo_meta

    def _entry(self, geom_str: str, geometry: dict, screen_mode, layout,
               family: str = "service"):
        """(key, (run, pre)) for one wire geometry — created on first
        sight, LRU-bounded, shared by the Solve and Replan RPCs (the
        replan reuses the solve entry's prescreen program and residency —
        the same program family, exactly like the in-process solver)."""
        import jax

        from karpenter_core_tpu.utils.compilecache import record_lookup

        # key on the trace-time screen mode too: a KCT_PACK_SCREEN flip
        # must mint a new program, not serve the other mode's cache
        key = (
            geom_str, screen_mode,
            layout.key if layout is not None else None,
        )
        with self._mu:
            entry = self._compiled.get(key)
            if entry is not None:
                self._compiled.move_to_end(key)
        record_lookup(family, entry is not None)
        if entry is None:
            segments, zone_seg, ct_seg, topo_meta = self._parse_geometry(
                geometry
            )
            run = jax.jit(
                make_device_run(
                    segments, zone_seg, ct_seg, topo_meta, geometry["n_slots"],
                    log_len=geometry.get("log_len"),
                    screen_v=geometry.get("screen_v"),
                    screen_mode=screen_mode,
                    external_prescreen=screen_mode == "prescreen",
                    spec_layout=layout,
                )
            )
            pre = None
            if screen_mode == "prescreen":
                from karpenter_core_tpu.ops.pack import make_prescreen_kernel

                pre = jax.jit(
                    make_prescreen_kernel(
                        segments, geometry["n_slots"],
                        screen_v=geometry.get("screen_v"),
                        spec_layout=layout,
                    )
                )
            entry = (run, pre)
            retired = []
            with self._mu:
                self._compiled[key] = entry
                self._prog_fresh.add(key)
                while len(self._compiled) > self.MAX_COMPILED:
                    old_key, _ = self._compiled.popitem(last=False)
                    retired.append(("solve", old_key))
                    retired.extend(self._drop_incremental(old_key))
                    self._prog_fresh.discard(old_key)
            # ledger reporting AFTER the cache lock drops, same discipline
            # as the in-process solver's mint sites
            proghealth.record_mint(
                "solve", key, origin="live",
                meta={
                    "tier": f"{geometry.get('n_slots', '?')}slots",
                    "mode": str(screen_mode),
                    "surface": family,
                },
            )
            for prog_family, prog_key in retired:
                proghealth.retire(prog_family, prog_key)
        return key, entry

    def _solve_traced(self, request: pb.SolveRequest) -> pb.SolveResponse:
        import jax

        # dispatch-start heartbeat BEFORE the chaos hooks, labeled with the
        # device phase: the injected hang below models a device wedge, so
        # the staleness window starts here and the wedge verdict the
        # parent/ supervisor produces names the phase it died in (ISSUE 15)
        supervise.touch_heartbeat("solver.phase.device")
        # the accelerator edge's chaos hooks, at the SAME contract as the
        # in-process TPUSolver dispatch (_run_kernels_impl): an injected
        # error routes to the caller's fallback; a hang (error:none +
        # latency past the watchdog) goes heartbeat-silent — which is how
        # host-mode drills (solver/host.py) wedge the sidecar child
        chaos.maybe_fail(chaos.SOLVER_DEVICE)
        chaos.maybe_fail(chaos.SOLVER_DEVICE_HANG)
        # device-side phase marks (ISSUE 15): the SAME solver.phase.* span
        # names the in-process TPUSolver records, emitted from the service
        # dispatch — so a host-mode (or split-gRPC) deployment reports the
        # phases of the process doing the work: pack (program staging),
        # upload, prescreen, device, fetch. The marks feed the phase
        # histogram AND label the heartbeat, exactly like TPUSolver._mark.
        t_phase = time.perf_counter_ns()

        def _mark(name, **attrs):
            nonlocal t_phase
            now = time.perf_counter_ns()
            TRACER.add_span(f"solver.phase.{name}", t_phase, now, **attrs)
            elapsed_ms = (now - t_phase) / 1e6
            t_phase = now
            supervise.touch_heartbeat(f"solver.phase.{name}")
            return elapsed_ms

        geometry = json.loads(request.geometry)
        tensors = {t.name: tensor_from_pb(t) for t in request.tensors}
        args = _unflatten_args(tensors)
        from karpenter_core_tpu.ops import compat as ops_compat

        # the GSPMD mesh layout (parallel/specs.py) when this container
        # serves a multi-chip device set AND the batch clears the
        # small-batch routing floor; None compiles the plain single-device
        # program. Same response shape either way — the mesh program is
        # byte-identical to the single-device one, so the client decodes
        # both with decode_solve.
        layout = self._layout_for(args)
        screen_mode = ops_compat.resolve_screen_mode()
        key, entry = self._entry(
            request.geometry, geometry, screen_mode, layout,
            family="service" if layout is None else "service_sharded",
        )
        fn, pre_fn = entry
        _mark("pack", tensors=len(request.tensors))
        host_args = args
        if layout is not None:
            # pre-sharded upload: each wire tensor device_puts with its
            # canonical NamedSharding (type planes over 'tp', existing-slot
            # planes over 'dp' where the axes divide, everything else
            # replicated) so the mesh program starts from committed inputs
            from karpenter_core_tpu.solver.tpu_solver import RUN_ARG_NAMES

            args = layout.put_args(RUN_ARG_NAMES, args)
        _mark("upload")
        from karpenter_core_tpu.obs import device_profiler

        with device_profiler():
            if pre_fn is not None:
                screen0 = self._prescreen(
                    key, geometry, args, pre_fn, host_args=host_args,
                    layout=layout,
                )
                _mark("prescreen")
                # re-label for the long silent stretch: a wedge inside the
                # XLA compile/execute block names the device phase
                supervise.touch_heartbeat("solver.phase.device")
                log, ptr, state = fn(screen0, *args)
            else:
                # same re-label on the screening-off path — _mark("upload")
                # just overwrote the dispatch-start device label
                supervise.touch_heartbeat("solver.phase.device")
                log, ptr, state = fn(*args)
            jax.block_until_ready(ptr)
        # progress proof for the dispatch watchdogs (the per-RPC thread
        # heartbeat AND — in the solver-host child — the process's file
        # heartbeat the parent's staleness watchdog reads): the longest
        # legit silent stretch is ONE XLA compile/execute block, which is
        # what wedge_stale_after must be sized above
        device_ms = _mark("device")
        # program-ledger accounting (ISSUE 18): every dispatch books its
        # device ms; a first-sight entry also books the block as compile
        # seconds (jit traces + XLA compiles inside that first dispatch)
        with self._mu:
            first_dispatch = key in self._prog_fresh
            self._prog_fresh.discard(key)
        proghealth.record_dispatch("solve", key, device_ms=device_ms)
        if first_dispatch:
            proghealth.record_compile("solve", key, device_ms / 1e3)
        out = [tensor_to_pb("ptr", np.asarray(ptr))]
        for name, value in log.items():
            out.append(tensor_to_pb(f"log/{name}", np.asarray(value)))
        for field, value in state._asdict().items():
            out.append(tensor_to_pb(f"state/{field}", np.asarray(value)))
        _mark("fetch")
        with self._mu:
            self.solves += 1
        return pb.SolveResponse(tensors=out)

    # -- batched consolidation replan (ISSUE 10) ----------------------------

    def replan(self, request: pb.SolveRequest, context=None) -> pb.SolveResponse:
        """Batched candidate-subset evaluation behind the process boundary:
        the split deployment's control plane ships the union snapshot's
        tensors plus the [K, ...] subset planes; the service runs the SAME
        rung-mode program family the in-process TPUSolver.replan_screen
        compiles — sharing this service's solve-entry prescreen program
        and resident verdict tensor — and returns [K, 4] verdicts (and the
        [K, N] slot plane on request)."""
        trace_id = None
        tenant = None
        if context is not None:
            try:
                for k, v in context.invocation_metadata() or ():
                    if k == TRACE_HEADER:
                        trace_id = v
                    elif k == reqctx.TENANT_HEADER:
                        tenant = v
            except Exception:  # noqa: BLE001 — tracing must never fail a replan
                pass
        with contextlib.ExitStack() as stack:
            if tenant is not None:
                stack.enter_context(reqctx.bind(
                    reqctx.RequestContext(tenant=str(tenant))
                ))
            stack.enter_context(TRACER.span(
                "solver.service.replan", trace_id=trace_id,
                tensors=len(request.tensors),
            ))
            return self._gated(request, context, self._replan_traced)

    def _replan_traced(self, request: pb.SolveRequest) -> pb.SolveResponse:
        import jax

        from karpenter_core_tpu.ops import compat as ops_compat
        from karpenter_core_tpu.solver.encode import replan_chunks
        from karpenter_core_tpu.utils.compilecache import record_lookup

        # same accelerator-edge chaos contract (and labeled dispatch-start
        # heartbeat ordering) as _solve_traced
        supervise.touch_heartbeat("solver.phase.replan.device")
        chaos.maybe_fail(chaos.SOLVER_DEVICE)
        chaos.maybe_fail(chaos.SOLVER_DEVICE_HANG)
        geometry = json.loads(request.geometry)
        tensors = {t.name: tensor_from_pb(t) for t in request.tensors}
        count_rows = np.ascontiguousarray(tensors.pop("replan/count_rows"))
        exist_open = np.ascontiguousarray(
            tensors.pop("replan/exist_open").astype(bool)
        )
        # defensive re-pad: the verdict kernel binds n_exist from
        # exist_open's width, so a client shipping an unpadded mask must
        # not crash the dispatch with a broadcast error
        E = int(exist_open.shape[1]) if exist_open.ndim == 2 else 0
        raw_uninit = tensors.pop("replan/uninitialized").astype(bool)
        uninit = np.zeros(E, dtype=bool)
        uninit[: min(len(raw_uninit), E)] = raw_uninit[:E]
        want_slots = bool(
            int(np.asarray(tensors.pop("replan/want_slots")).reshape(-1)[0])
        )
        args = _unflatten_args(tensors)
        # single-device deliberately, like TPUSolver.replan_screen: the
        # candidate axis is a vmap over the rung program, and vmapping the
        # GSPMD mesh program is unproven — the K-way batch recovers the
        # parallelism the mesh would have added
        screen_mode = ops_compat.resolve_screen_mode()
        key, entry = self._entry(
            request.geometry, geometry, screen_mode, None,
            family="service_replan_entry",
        )
        _fn, pre_fn = entry
        screen0 = None
        if pre_fn is not None:
            screen0 = self._prescreen(key, geometry, args, pre_fn)

        verdict_parts, pods_parts = [], []
        for k, kp, sub_counts, sub_open in replan_chunks(
            count_rows, exist_open
        ):
            replan_fn, hit = self._replan_fn(key, geometry, kp, screen_mode)
            record_lookup("service_replan", hit)
            t_chunk = time.perf_counter()
            pods_dev, verd_dev = replan_fn(
                sub_counts, sub_open, uninit, screen0, *args
            )
            if want_slots:
                verd_h, pods_h = jax.device_get((verd_dev, pods_dev))
                pods_parts.append(np.asarray(pods_h)[:k])
            else:
                verd_h = jax.device_get(verd_dev)
            chunk_ms = (time.perf_counter() - t_chunk) * 1e3
            proghealth.record_dispatch(
                "replan", (key, kp), device_ms=chunk_ms
            )
            if not hit:
                # first dispatch of a fresh rung program: the chunk paid
                # the jit trace + XLA compile
                proghealth.record_compile("replan", (key, kp), chunk_ms / 1e3)
            verdict_parts.append(np.asarray(verd_h)[:k])
            # per-chunk progress for the dispatch watchdogs: a K-chunked
            # sweep is many device calls — each completed chunk is proof
            # of life
            supervise.touch_heartbeat()
        verdicts = (
            np.concatenate(verdict_parts)
            if verdict_parts else np.zeros((0, 4), np.int32)
        )
        out = [tensor_to_pb("verdicts", verdicts)]
        if want_slots and pods_parts:
            out.append(tensor_to_pb("pods", np.concatenate(pods_parts)))
        with self._mu:
            self.replans += 1
        return pb.SolveResponse(tensors=out)

    def _replan_fn(self, key, geometry: dict, k_pad: int, screen_mode):
        """(jitted batched replan program for (solve key, candidate-axis
        bucket), cache_hit) — the service-side analog of
        TPUSolver._replan_fn, over unbundled wire tensors."""
        import jax

        rkey = (key, k_pad)
        with self._mu:
            fn = self._replan_compiled.get(rkey)
            if fn is not None:
                self._replan_compiled.move_to_end(rkey)
                return fn, True
        from karpenter_core_tpu.ops.pack import make_batched_replan_kernel

        segments, zone_seg, ct_seg, topo_meta = self._parse_geometry(geometry)
        rung_run = make_device_run(
            segments, zone_seg, ct_seg, topo_meta, geometry["n_slots"],
            log_len=geometry.get("log_len"),
            screen_v=geometry.get("screen_v"),
            screen_mode=screen_mode,
            rung_mode=True,
            external_prescreen=screen_mode == "prescreen",
        )
        # n_exist = the padded existing axis width (exist_used's leading
        # dim rides the wire); resolved at first dispatch via closure
        fn = None

        def build(n_exist):
            kern = make_batched_replan_kernel(
                rung_run, n_exist, screen_mode == "prescreen"
            )
            return jax.jit(
                lambda count_rows, exist_open, uninit, screen0, *args: kern(
                    count_rows, exist_open, uninit, screen0, *args
                )
            )

        class _LazyReplan:
            """Binds n_exist from the first call's exist_open width."""

            def __init__(self):
                self._jit = None

            def __call__(self, count_rows, exist_open, uninit, screen0,
                         *args):
                if self._jit is None:
                    self._jit = build(int(exist_open.shape[1]))
                return self._jit(
                    count_rows, exist_open, uninit, screen0, *args
                )

        fn = _LazyReplan()
        evicted = []
        with self._mu:
            fn = self._replan_compiled.setdefault(rkey, fn)
            self._replan_compiled.move_to_end(rkey)
            while len(self._replan_compiled) > self.MAX_REPLAN:
                evicted.append(self._replan_compiled.popitem(last=False)[0])
        proghealth.record_mint(
            "replan", rkey, origin="live",
            meta={"tier": f"K{k_pad}", "mode": str(screen_mode),
                  "surface": "service"},
        )
        for old in evicted:
            proghealth.retire("replan", old)
        return fn, False

    # -- incremental prescreen (delta re-solve across RPCs) -----------------

    def _prescreen(self, key, geometry: dict, args, pre_fn, host_args=None,
                   layout=None):
        """The verdict tensor for this solve: a delta refresh of the
        resident one when the previous same-geometry RPC left one and the
        plane delta is narrow, the full precompute otherwise. Bit-identical
        either way (the refresh replays the same screen ops over the
        changed rows/columns); any planning or dispatch failure degrades to
        the full path. Serialized under one lock — plan() and adopt() must
        pair, and the gRPC executor runs several workers.

        host_args carries the numpy view when `args` was already
        device_put (the mesh path's pre-sharded upload): the plane
        fingerprints must hash host bytes, not round-trip device arrays."""
        from karpenter_core_tpu.ops import compat as ops_compat
        from karpenter_core_tpu.solver.incremental import IncrementalScreen

        pod_arrays, exist = args[0], args[9]
        if host_args is not None:
            host_pods, host_exist = host_args[0], host_args[9]
        else:
            host_pods, host_exist = pod_arrays, exist
        if ops_compat.resolve_incremental_mode() == "off":
            return pre_fn(pod_arrays, exist)
        # the global lock only guards the residency MAP; planning, the
        # refresh dispatch, and the (possibly multi-second, first-sight)
        # full precompute run under the KEY's own lock — two RPCs at one
        # geometry still serialize (plan/adopt must pair against one
        # resident tensor) but unrelated geometries never head-of-line
        # block behind another key's XLA compile
        with self._inc_mu:
            lock, inc = self._inc_screens.setdefault(
                key, (threading.Lock(), IncrementalScreen())
            )
        with lock:
            delta = None
            try:
                delta = inc.plan(key, host_pods, host_exist)
            except Exception:  # noqa: BLE001 — fingerprints are best-effort
                inc.invalidate()
            screen0 = None
            prev = inc.resident(key)
            if delta is not None and prev is not None:
                try:
                    refresh = self._refresh_fn(
                        key, geometry, delta.rb, delta.cb, layout=layout
                    )
                    row_idx, row_n, col_idx, col_n = delta.padded()
                    t_ref = time.perf_counter()
                    screen0 = refresh(
                        prev, pod_arrays, exist, row_idx, row_n, col_idx, col_n
                    )
                    proghealth.record_dispatch(
                        "refresh", (key, delta.rb, delta.cb),
                        device_ms=(time.perf_counter() - t_ref) * 1e3,
                    )
                    inc.count_refresh()
                except Exception:  # noqa: BLE001 — degrade, never fail the RPC
                    # keep the staged fingerprints: the fallback full
                    # tensor below re-adopts them (see drop_resident)
                    inc.drop_resident()
                    inc.count_degraded()
                    screen0 = None
            if screen0 is None:
                screen0 = pre_fn(pod_arrays, exist)
            inc.adopt(key, screen0)
            return screen0

    def _refresh_fn(self, key, geometry: dict, rb: int, cb: int,
                    layout=None):
        """Jitted delta-refresh program per (solve key, row budget, col
        budget), LRU-bounded; donates the previous verdict tensor so the
        resident buffer updates in place. Takes _inc_mu only around the
        shared-map accesses (the caller holds its key's residency lock;
        jit() construction is cheap — XLA compiles at first dispatch)."""
        import jax

        rkey = (key, rb, cb)
        with self._inc_mu:
            fn = self._refresh_compiled.get(rkey)
            if fn is not None:
                self._refresh_compiled.move_to_end(rkey)
                return fn
        from karpenter_core_tpu.ops.pack import make_screen_refresh_kernel

        segments = [tuple(s) for s in geometry["segments"]]
        fn = jax.jit(
            make_screen_refresh_kernel(
                segments, geometry["n_slots"], rb, cb,
                screen_v=geometry.get("screen_v"),
                # the mesh path's replicated fence (see the kernel's
                # docstring): the resident tensor is a mesh-program output
                spec_layout=layout,
            ),
            donate_argnums=(0,),
        )
        evicted = []
        with self._inc_mu:
            fn = self._refresh_compiled.setdefault(rkey, fn)
            self._refresh_compiled.move_to_end(rkey)
            while len(self._refresh_compiled) > self.MAX_REFRESH:
                evicted.append(self._refresh_compiled.popitem(last=False)[0])
        proghealth.record_mint(
            "refresh", rkey, origin="live",
            meta={"tier": f"rb{rb}xcb{cb}", "surface": "service"},
        )
        for old in evicted:
            proghealth.retire("refresh", old)
        return fn

    def _drop_incremental(self, key):
        """Solve-cache eviction also drops the key's resident tensor and
        refresh programs (they reference the evicted geometry). Returns
        the dropped (family, key) pairs so the caller can retire them in
        the program ledger once the cache locks drop."""
        dropped = []
        with self._inc_mu:
            self._inc_screens.pop(key, None)
            for rkey in [k for k in self._refresh_compiled if k[0] == key]:
                del self._refresh_compiled[rkey]
                dropped.append(("refresh", rkey))
        # replan programs share the evicted solve entry's geometry too
        # (caller holds self._mu on the eviction path: _replan_compiled is
        # guarded by the same lock, so mutate without re-taking it)
        for rkey in [k for k in self._replan_compiled if k[0] == key]:
            del self._replan_compiled[rkey]
            dropped.append(("replan", rkey))
        return dropped

    def _layout_for(self, args):
        """The parallel/specs.SpecLayout this request's programs build
        against: the container's mesh layout for batches that clear the
        small-batch routing floor (parallel/sharded.route_to_mesh — tiny
        batches stop paying collective/mesh-dispatch overhead), None on a
        single-chip container."""
        if self.mesh is None:
            return None
        from karpenter_core_tpu.parallel.sharded import route_to_mesh

        total = int(np.asarray(args[0]["count"]).sum())
        if not route_to_mesh(total, self.mesh.shape["dp"]):
            return None
        from karpenter_core_tpu.parallel.specs import layout_for

        return layout_for(self.mesh)

    def health(self, request: pb.HealthRequest, context=None) -> pb.HealthResponse:
        # wedge gate FIRST, before anything touches jax: a dispatch whose
        # heartbeat went stale means the XLA runtime hung mid-call — a
        # fresh jax query from this thread could hang the Health RPC too.
        # The status string carries the verdict (the proto stays as-is);
        # RemoteSolver.health raises on a non-ok status, which is how the
        # ResilientSolver's out-of-band prober learns the service wedged.
        # the solve counter mutates under _mu on dispatch threads; health
        # runs on the RPC pool — read it there too (racewatch, ISSUE 13)
        with self._mu:
            solves = self.solves
        age = self._stalest_dispatch_age()
        if age is not None and age >= self.wedge_stale_after:
            return pb.HealthResponse(
                status=(
                    f"wedged: dispatch heartbeat stale for {age:.0f}s "
                    f"(threshold {self.wedge_stale_after:.0f}s)"
                ),
                device="", solves=solves,
            )
        import jax

        device = jax.devices()[0].device_kind
        if self.mesh is not None:
            device += (
                f" x{self.mesh.size}"
                f"(dp={self.mesh.shape['dp']},tp={self.mesh.shape['tp']})"
            )
        return pb.HealthResponse(status="ok", device=device, solves=solves)


def serve(address: str = "127.0.0.1:0", max_workers: int = 4, mesh=None,
          maximum_concurrent_rpcs: Optional[int] = None,
          max_queue: Optional[int] = 8, brownout_at: Optional[int] = None,
          tenant_quota: Optional[int] = None,
          weights: Optional[Dict[str, float]] = None):
    """Start the gRPC server; returns (server, bound_port, service).
    mesh=True autodetects a multi-chip mesh (factory.detect_mesh).

    Overload control (ISSUE 12) has two bounded layers instead of the old
    unbounded executor queue:

      * `maximum_concurrent_rpcs` caps what gRPC itself accepts — excess
        RPCs are rejected with RESOURCE_EXHAUSTED at the transport before
        they ever hold an executor slot (default: workers + queue + 4,
        enough to keep the admission gate the binding constraint);
      * the deadline-aware AdmissionGate (`max_queue`, `brownout_at`;
        max_queue=None disables) queues at most max_queue dispatches, sheds
        with RESOURCE_EXHAUSTED + a retry-after hint, and never dispatches
        a request whose gRPC deadline expired while it waited."""
    import grpc

    admission = None
    if max_queue is not None:
        from karpenter_core_tpu.solver.host import AdmissionGate

        admission = AdmissionGate(
            name="service", max_queue=max_queue, brownout_at=brownout_at,
            tenant_quota=tenant_quota, weights=weights,
        )
        # the executor must be able to HOLD every gate waiter plus the
        # dispatching handler plus health-probe headroom, or waiters
        # exhaust the pool and excess RPCs queue unwatched (no deadline
        # slicing, no shed) in the executor's own queue — the exact
        # unbounded-queue failure this gate exists to remove. max_workers
        # is therefore a floor, raised to the gate's capacity.
        max_workers = max(max_workers, max_queue + 1 + 2)
    if maximum_concurrent_rpcs is None:
        maximum_concurrent_rpcs = max_workers + (max_queue or 0) + 4
    service = SolverService(mesh=mesh, admission=admission)
    handlers = {
        "Solve": grpc.unary_unary_rpc_method_handler(
            service.solve,
            request_deserializer=pb.SolveRequest.FromString,
            response_serializer=pb.SolveResponse.SerializeToString,
        ),
        "Replan": grpc.unary_unary_rpc_method_handler(
            service.replan,
            request_deserializer=pb.SolveRequest.FromString,
            response_serializer=pb.SolveResponse.SerializeToString,
        ),
        "Health": grpc.unary_unary_rpc_method_handler(
            service.health,
            request_deserializer=pb.HealthRequest.FromString,
            response_serializer=pb.HealthResponse.SerializeToString,
        ),
    }
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        maximum_concurrent_rpcs=maximum_concurrent_rpcs,
    )
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE, handlers),)
    )
    port = server.add_insecure_port(address)
    server.start()
    return server, port, service


# ---------------------------------------------------------------------------
# client


class RemoteSolver:
    """Solver-interface client: encode locally, solve remotely, decode
    locally.

    Transport hardening (ISSUE 2): every Solve RPC carries a deadline
    (`timeout`), transient failures (UNAVAILABLE / DEADLINE_EXCEEDED)
    retry `rpc_retries` times with exponential backoff + jitter, and a
    consecutive-failure circuit breaker fails fast while the service is
    down — so the ResilientSolver wrapping this client degrades to the
    local fallback in microseconds instead of waiting out a dead
    channel's timeout on every batch. Health RPCs bypass the breaker and
    close it on success (the half-open recovery probe)."""

    def __init__(self, target: str, max_nodes: int = 1024,
                 max_relax_rounds: int = None,
                 timeout: float = 120.0,
                 rpc_retries: int = 2, rpc_retry_base: float = 0.05,
                 breaker=None, retry_budget=None):
        import grpc

        from karpenter_core_tpu.solver.fallback import CircuitBreaker
        from karpenter_core_tpu.utils.backoff import RetryBudget

        self.target = target
        self.channel = grpc.insecure_channel(target)
        self.timeout = timeout
        self.rpc_retries = rpc_retries
        self.rpc_retry_base = rpc_retry_base
        self.breaker = breaker or CircuitBreaker(name="solver.rpc")
        # per-tenant token bucket consulted before EVERY retry (transient
        # and retry-after-hint paths): jitter spreads a retry storm out,
        # the budget stops it — and stops it per tenant, so one shed
        # tenant's storm never drains everyone else's retries
        self.retry_budget = (
            retry_budget if retry_budget is not None else RetryBudget()
        )
        self.max_nodes = max_nodes
        if max_relax_rounds is None:
            from karpenter_core_tpu.solver.tpu_solver import DEFAULT_MAX_RELAX_ROUNDS

            max_relax_rounds = DEFAULT_MAX_RELAX_ROUNDS
        self.max_relax_rounds = max_relax_rounds
        from karpenter_core_tpu.solver.encode import EncodeReuse

        self._encode_reuse = EncodeReuse()
        self._solve = self.channel.unary_unary(
            f"/{SERVICE}/Solve",
            request_serializer=pb.SolveRequest.SerializeToString,
            response_deserializer=pb.SolveResponse.FromString,
        )
        self._replan = self.channel.unary_unary(
            f"/{SERVICE}/Replan",
            request_serializer=pb.SolveRequest.SerializeToString,
            response_deserializer=pb.SolveResponse.FromString,
        )
        self._health = self.channel.unary_unary(
            f"/{SERVICE}/Health",
            request_serializer=pb.HealthRequest.SerializeToString,
            response_deserializer=pb.HealthResponse.FromString,
        )

    def health(self, timeout: float = 30.0) -> pb.HealthResponse:
        # generous default: the server's first jax.devices() call initializes
        # the TPU backend, which can take tens of seconds cold.
        # Deliberately NOT gated by the breaker: this is the half-open
        # recovery probe — ResilientSolver re-probes on its TTL, and a
        # success here closes the breaker so the next solve goes remote.
        try:
            response = self._health(pb.HealthRequest(), timeout=timeout)
        except Exception:
            self.breaker.record_failure()
            raise
        if response.status != "ok":
            # the server answered but reported itself wedged (a hung
            # in-flight dispatch): NOT healthy — the prober must keep the
            # backend out until the wedge clears
            self.breaker.record_failure()
            raise SolverUnavailableError(
                f"solver service unhealthy: {response.status}"
            )
        self.breaker.record_success()
        return response

    def _map_rpc_error(self, e) -> SolverRpcError:
        """grpc.RpcError -> typed error by status code; the server's
        retry-after hint (trailing metadata on an admission-gate shed, or
        retry_after_ms=N in the detail) rides along as retry_after_s."""
        import grpc

        code = e.code() if hasattr(e, "code") else None
        details = e.details() if hasattr(e, "details") else str(e)
        name = code.name if isinstance(code, grpc.StatusCode) else "UNKNOWN"
        cls = _ERROR_BY_CODE.get(name, SolverInternalError)
        err = cls(f"solver service {name}: {details}")
        err.__cause__ = e
        retry_after = None
        tm = getattr(e, "trailing_metadata", None)
        if callable(tm):
            try:
                for k, v in tm() or ():
                    if k == RETRY_AFTER_METADATA_KEY:
                        retry_after = int(v) / 1000.0
            except Exception:  # noqa: BLE001 — hint extraction is best-effort
                retry_after = None
        if retry_after is None:
            retry_after = _parse_retry_after(details or "")
        err.retry_after_s = retry_after
        return err

    def _retry_allowed(self, err) -> bool:
        """Consult the per-tenant retry budget for one more attempt.
        Denial ticks the budget-exhausted counter and means the caller
        raises *err* as-is — the budget bounds retry VOLUME; jitter and
        retry-after hints still shape whatever it allows."""
        key = reqctx.TENANTS.admit(reqctx.current_tenant())
        if self.retry_budget.try_spend(key):
            return True
        SOLVER_RETRY_BUDGET_EXHAUSTED.inc(reqctx.tenant_labels())
        LOG.warning(
            "solver rpc retry budget exhausted, not retrying",
            target=self.target, error=type(err).__name__,
        )
        return False

    def _invoke_solve(self, request: pb.SolveRequest, metadata, stub=None):
        """One Solve/Replan RPC through the breaker + bounded transient
        retry (stub defaults to the Solve method)."""
        import grpc

        stub = stub or self._solve
        attempt = 0
        while True:
            if not self.breaker.allow():
                raise SolverUnavailableError(
                    f"solver circuit breaker open (service at {self.target})"
                )
            try:
                # chaos hook INSIDE the try: injected faults (typed solver
                # errors) exercise the same classification as wire errors
                chaos.maybe_fail(chaos.SOLVER_RPC)
                response = stub(
                    request, timeout=self.timeout, metadata=metadata
                )
            except grpc.RpcError as e:
                err = self._map_rpc_error(e)
            except SolverRpcError as e:
                err = e
            else:
                self.breaker.record_success()
                return response
            if not err.transient and not isinstance(err, SolverInternalError):
                # INVALID_ARGUMENT / RESOURCE_EXHAUSTED are server-PROCESSED
                # responses: the channel is demonstrably up, so a half-open
                # trial ending here must CLOSE the breaker (and a closed one
                # must not drift toward open) even though the request failed
                self.breaker.record_success()
            if err.transient:
                self.breaker.record_failure()
                if attempt < self.rpc_retries and self._retry_allowed(err):
                    SOLVER_RPC_RETRIES.inc()
                    LOG.warning(
                        "solver rpc retrying", target=self.target,
                        attempt=attempt + 1, error=type(err).__name__,
                    )
                    # exponential backoff with full jitter (utils/backoff):
                    # N control planes retrying one dead service must not
                    # re-land in lockstep
                    from karpenter_core_tpu.utils.backoff import full_jitter

                    time.sleep(
                        full_jitter(attempt, self.rpc_retry_base, cap=2.0)
                    )
                    attempt += 1
                    continue
            elif isinstance(err, SolverInternalError):
                # server-side crashes count toward the breaker too — a
                # crash-looping service should fail fast, not be hammered
                self.breaker.record_failure()
            if (
                isinstance(err, SolverResourceExhaustedError)
                and getattr(err, "retry_after_s", None)
                and attempt < self.rpc_retries
                and self._retry_allowed(err)
            ):
                # an admission-gate shed with a retry-after hint: the
                # server is UP but overloaded — wait out the hint (plus
                # jitter so N shed control planes don't re-land in
                # lockstep) and retry within the same bounded budget the
                # transient path uses; a still-full queue then raises and
                # the ResilientSolver serves the greedy fallback
                from karpenter_core_tpu.utils.backoff import full_jitter

                SOLVER_RPC_RETRIES.inc()
                LOG.warning(
                    "solver rpc shed, honoring retry-after",
                    target=self.target, attempt=attempt + 1,
                    retry_after_s=err.retry_after_s,
                )
                time.sleep(
                    min(5.0, err.retry_after_s)
                    + full_jitter(attempt, self.rpc_retry_base, cap=0.5)
                )
                attempt += 1
                continue
            raise err

    # the split deployment runs the same batched-replan program family as
    # the in-process solver (ISSUE 10): consolidation's subset evaluator
    # works against a RemoteSolver unchanged, one Replan RPC per pass
    supports_batched_replan = True

    def encode(self, pods, provisioners, instance_types, daemonset_pods=None,
               state_nodes=None, kube_client=None, cluster=None):
        """Pre-encode off the Solve critical path (pipelined surface,
        same contract as TPUSolver.encode)."""
        return encode_snapshot(
            pods, provisioners, instance_types, daemonset_pods, state_nodes,
            kube_client=kube_client, cluster=cluster, max_nodes=self.max_nodes,
            reuse=self._encode_reuse,
        )

    def replan_screen(self, snap, provisioners, count_rows, exist_open,
                      uninitialized=None, cluster=None,
                      want_slots: bool = False):
        """Batched candidate-subset evaluation over the wire — the same
        contract as TPUSolver.replan_screen (solver/replan.py is the only
        caller). Encodes host-side, ships the union snapshot's device_args
        tensors plus the [K, ...] subset planes, and decodes the [K, 4]
        verdicts (and the [K, N] slot plane when want_slots)."""
        with TRACER.span("solver.phase.replan.args"):
            args = device_args(snap, provisioners)
            tensors = [tensor_to_pb(n, a) for n, a in _flatten_args(args)]
            # pad the uninitialized mask to the bucket-padded existing axis
            # (pad sentinel rows are initialized=False-uninit), the same
            # contract TPUSolver.replan_screen applies: the service-side
            # verdict kernel binds n_exist from exist_open's padded width
            E = snap.exist_used.shape[0]
            uninit = np.zeros(E, dtype=bool)
            if uninitialized is not None:
                src = np.asarray(uninitialized, dtype=bool)
                uninit[: min(len(src), E)] = src[:E]
            tensors.append(
                tensor_to_pb(
                    "replan/count_rows",
                    np.asarray(count_rows, dtype=np.int32),
                )
            )
            tensors.append(
                tensor_to_pb("replan/exist_open", np.asarray(exist_open))
            )
            tensors.append(
                tensor_to_pb("replan/uninitialized", np.asarray(uninit))
            )
            tensors.append(
                tensor_to_pb(
                    "replan/want_slots",
                    np.asarray([1 if want_slots else 0], dtype=np.int32),
                )
            )
            request = pb.SolveRequest(
                geometry=geometry_json(snap), tensors=tensors
            )
        with TRACER.span("solver.service.replan_request") as sp:
            trace_id = getattr(sp, "trace_id", None) or TRACER.current_trace_id()
            metadata = _request_metadata(trace_id)
            response = self._invoke_solve(request, metadata, stub=self._replan)
        if response.error:
            raise error_from_string(response.error)
        tensors = {t.name: tensor_from_pb(t) for t in response.tensors}
        verdicts = np.asarray(tensors["verdicts"])
        pods = (
            np.asarray(tensors["pods"])
            if want_slots and "pods" in tensors
            else None
        )
        return verdicts, pods

    def solve(
        self,
        pods,
        provisioners,
        instance_types,
        daemonset_pods=None,
        state_nodes=None,
        kube_client=None,
        cluster=None,
        encoded=None,
    ) -> SolveResult:
        from karpenter_core_tpu.solver.tpu_solver import solve_with_relaxation

        if encoded is not None and (
            len(encoded.pods) != len(pods)
            or {id(p) for p in encoded.pods} != {id(p) for p in pods}
        ):
            raise ValueError(
                "encoded snapshot was built from a different pod batch"
            )
        relax_ctx = {"encoded": encoded}
        return solve_with_relaxation(
            lambda p: self._solve_once(
                p, provisioners, instance_types, daemonset_pods, state_nodes,
                kube_client, cluster, relax_ctx,
            ),
            pods,
            provisioners,
            instance_types,
            self.max_relax_rounds,
        )

    def _solve_once(self, pods, provisioners, instance_types, daemonset_pods,
                    state_nodes, kube_client, cluster,
                    relax_ctx=None) -> SolveResult:
        snap = relax_ctx.pop("encoded", None) if relax_ctx else None
        if snap is None:
            with TRACER.span("solver.phase.encode", pods=len(pods)):
                snap = encode_snapshot(
                    pods, provisioners, instance_types, daemonset_pods, state_nodes,
                    kube_client=kube_client, cluster=cluster,
                    max_nodes=self.max_nodes, reuse=self._encode_reuse,
                )
        with TRACER.span("solver.phase.args"):
            args = device_args(snap, provisioners)
            request = pb.SolveRequest(
                geometry=geometry_json(snap),
                tensors=[tensor_to_pb(n, a) for n, a in _flatten_args(args)],
            )
        # the RPC carries the current trace id over metadata so the server
        # handler's span lands in the same trace (stub-interceptor analog)
        with TRACER.span("solver.service.request") as sp:
            trace_id = getattr(sp, "trace_id", None) or TRACER.current_trace_id()
            metadata = _request_metadata(trace_id)
            response = self._invoke_solve(request, metadata)
        if response.error:
            raise error_from_string(response.error)
        tensors = {t.name: tensor_from_pb(t) for t in response.tensors}
        log = {k[len("log/"):]: v for k, v in tensors.items() if k.startswith("log/")}
        state = _StateView(
            {k[len("state/"):]: v for k, v in tensors.items() if k.startswith("state/")}
        )
        # the mesh and single-device service programs return the same
        # response shape (the GSPMD program is byte-identical to the
        # single-device one — parallel/sharded.py), so one decode serves
        # both
        ptr = int(np.asarray(tensors["ptr"]).reshape(-1)[0])
        with TRACER.span("solver.phase.bind"):
            return decode_solve(snap, (log, ptr), state)


class _StateView:
    """Attribute access over the returned state tensors."""

    def __init__(self, tensors: Dict[str, np.ndarray]):
        self._tensors = tensors

    def __getattr__(self, name):
        try:
            return self._tensors[name]
        except KeyError:
            raise AttributeError(name)


def main(argv: Optional[List[str]] = None) -> None:
    """Container entrypoint: `python -m karpenter_core_tpu.solver.service`."""
    import argparse
    import signal

    parser = argparse.ArgumentParser(description="karpenter-core-tpu solver service")
    parser.add_argument("--port", type=int, default=8980)
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--max-workers", type=int, default=4)
    args = parser.parse_args(argv)

    # restart-survivable compiled programs (utils/compilecache): a solver
    # container restart reloads executables from disk instead of paying the
    # cold compile while the control plane waits
    from karpenter_core_tpu.utils.compilecache import enable_persistent_cache

    enable_persistent_cache()

    # server-side solve tracing + structured logging, on by default like
    # the operator's (KARPENTER_TPU_TRACE=0 / KARPENTER_TPU_LOG=off opt
    # out); spans adopt the client's propagated trace id so both processes
    # share one timeline
    from karpenter_core_tpu.obs import enable_tracing_from_env
    from karpenter_core_tpu.obs.log import configure_logging_from_env

    enable_tracing_from_env(default_on=True)
    configure_logging_from_env(default_level="info")
    # multi-chip containers (v5e-4) serve every Solve through the sharded
    # program; KARPENTER_SOLVER_MODE=single pins the one-chip path

    from karpenter_core_tpu.solver.factory import detect_mesh

    mode = envflags.raw("KARPENTER_SOLVER_MODE", "auto").lower()
    mesh = None
    if mode != "single":
        mesh = detect_mesh()
        if mesh is None and mode == "sharded":
            # same contract as factory.build_solver: an explicit sharded
            # pin fails fast instead of silently serving one chip
            raise RuntimeError(
                "KARPENTER_SOLVER_MODE=sharded but only one device is visible"
            )
    # boot warmup BEFORE binding the port (i.e. before readiness): load the
    # jax runtime and compile/load a small solve so the first production
    # Solve doesn't eat the backend-init stall; with the persistent cache
    # populated, real-geometry programs load from disk on first request
    if envflags.raw("KARPENTER_SOLVER_WARMUP", "1") != "0":
        import time as _time

        t0 = _time.perf_counter()
        try:  # warmup is best-effort: a flake must not crash-loop the pod
            from karpenter_core_tpu.cloudprovider import fake as _fake
            from karpenter_core_tpu.solver.factory import build_solver
            from karpenter_core_tpu.testing import make_pod, make_provisioner

            warm = build_solver(max_nodes=64)
            warm.solve(
                [make_pod(requests={"cpu": "1"}) for _ in range(32)],
                [make_provisioner(name="default")],
                {"default": _fake.instance_types(4)},
            )
            LOG.info(
                "solver warmup done",
                seconds=round(_time.perf_counter() - t0, 1),
            )
        except Exception as exc:  # noqa: BLE001 — serve anyway
            LOG.warning(
                "solver warmup failed, serving anyway",
                error=type(exc).__name__, error_detail=str(exc),
            )
    server, port, _service = serve(
        f"{args.host}:{args.port}", max_workers=args.max_workers, mesh=mesh
    )
    if mesh is not None:
        LOG.info(
            "solver service mesh", dp=mesh.shape["dp"], tp=mesh.shape["tp"]
        )
    # decode runs in THIS process in a split deployment: apply the shared
    # long-lived-server GC posture (utils/gctuning.py) so gen-2 pauses
    # don't land mid-Solve
    from karpenter_core_tpu.utils.gctuning import apply_server_gc_tuning

    apply_server_gc_tuning()
    LOG.info("solver service listening", host=args.host, port=port)
    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    stop.wait()
    server.stop(grace=5)


if __name__ == "__main__":
    main()
