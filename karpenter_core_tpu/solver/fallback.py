"""ResilientSolver — production backend-failure fallback.

The accelerator link (the axon tunnel especially) is observed to HANG or
fail to initialize, not just error: rounds 1 and 2 both lost their first
bench attempt to `Unable to initialize backend: UNAVAILABLE`. The bench
defends itself with a subprocess probe (bench.py); this module moves that
defense into the PRODUCTION solve path, per the round-2 verdict:

  - backend health is probed OUT-OF-PROCESS with a timeout (a wedged
    backend cannot poison the control-plane process): the local jax
    backend for in-process solvers, the Health RPC for RemoteSolver;
  - health is re-checked on a TTL in BOTH directions — an unhealthy
    backend re-probes for recovery, and a healthy verdict expires so a
    mid-life wedge is detected between solves;
  - optionally, each primary solve runs under a thread watchdog
    (solve_timeout) with a HEARTBEAT (utils/supervise.ThreadHeartbeat,
    touched by the solver's phase marks): a dispatch whose heartbeat goes
    stale is WEDGED and abandoned early — distinct from slow-but-alive,
    which gets its whole budget. For an IN-PROCESS primary the abandoned
    thread still leaks with the hung call (better one leaked thread than
    a stalled control plane) — NAMED (`primary-solve-abandoned-N-<kind>`),
    counted (karpenter_solver_abandoned_total), inventoried for
    /debug/health, and moved to a terminal `reaped` state when it finally
    exits. In HOST mode (solver/host.py, the operator default) the leak is
    closed for real: the dispatch runs in a sidecar process the watchdog
    SIGKILLs on staleness, so the abandoned waiter unblocks within the
    kill window and the live-zombie count returns to zero;
  - a wedge opens the device circuit breaker IMMEDIATELY (no waiting for
    the next reprobe interval) and bumps karpenter_solver_wedged_total;
    re-admission is gated by the out-of-band prober — the breaker's
    half-open trial runs the subprocess probe_backend / Health RPC, never
    a live solve, so a still-wedged backend costs a probe timeout, not a
    stalled reconcile;
  - while unhealthy, Solve() routes to the fallback solver (GreedySolver),
    publishes a deduped event, and bumps karpenter_solver_fallback_total.

Wired by operator.__main__ around TPUSolver/RemoteSolver; the control plane
keeps provisioning through a dead accelerator (reference analog: the whole
design is level-triggered reconciliation — the solver must degrade, never
stall, operator.go:154-169).
"""
from __future__ import annotations

import contextlib
import itertools
import subprocess
import sys
import threading
import time
from collections import deque
from typing import Optional

from karpenter_core_tpu.events import Event
from karpenter_core_tpu.metrics.registry import NAMESPACE, REGISTRY
from karpenter_core_tpu.obs import reqctx
from karpenter_core_tpu.obs.flightrec import FLIGHTREC, recording_suppressed
from karpenter_core_tpu.obs.log import get_logger
from karpenter_core_tpu.obs.tracer import TRACER
from karpenter_core_tpu.utils import supervise

LOG = get_logger("karpenter.solver.fallback")

SOLVER_FALLBACK_TOTAL = REGISTRY.counter(
    f"{NAMESPACE}_solver_fallback_total",
    "Solves routed to the fallback solver because the accelerator backend "
    "was unavailable or the primary solver failed",
)
# routine routing is NOT a failure: it rides its own counter so alerts on
# karpenter_solver_fallback_total keep meaning "something is wrong"
SOLVER_SMALL_BATCH_TOTAL = REGISTRY.counter(
    f"{NAMESPACE}_solver_small_batch_routed_total",
    "Solves routed to the host FFD because the batch was below the "
    "small-batch work product (the device path's fixed cost would dominate)",
)
BREAKER_TRANSITIONS = REGISTRY.counter(
    f"{NAMESPACE}_circuit_breaker_transitions_total",
    "Circuit-breaker state transitions, by breaker name and target state",
)
BREAKER_OPEN = REGISTRY.gauge(
    f"{NAMESPACE}_circuit_breaker_open",
    "1 while the named circuit breaker is open (fast-failing), else 0",
)
SOLVER_WEDGED_TOTAL = REGISTRY.counter(
    f"{NAMESPACE}_solver_wedged_total",
    "Device dispatches abandoned because their heartbeat went stale (the "
    "backend wedged mid-dispatch, distinct from slow-but-alive timeouts)",
)
SOLVER_ABANDONED_TOTAL = REGISTRY.counter(
    f"{NAMESPACE}_solver_abandoned_total",
    "Primary-solve worker threads abandoned by the dispatch watchdog, by "
    "kind (wedged = heartbeat stale, timeout = budget exceeded while alive)",
)


class SolverWedgedError(TimeoutError):
    """The in-flight device dispatch stopped making progress (heartbeat
    staleness), as opposed to merely exceeding its budget while alive."""


class CircuitBreaker:
    """Consecutive-failure circuit breaker with a half-open TTL.

    Protects a remote dependency (the gRPC solver service) the way the
    ResilientSolver's health TTLs protect the accelerator backend: after
    `failure_threshold` consecutive transport failures the breaker OPENS
    and callers fail fast (no RPC, no timeout wait — the local fallback
    takes over immediately); after `reset_timeout` it HALF-OPENS, letting
    exactly one trial call through — success closes it, failure re-opens
    and restarts the TTL. Thread-safe: solves and background health probes
    share one breaker."""

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(self, name: str = "solver.rpc", failure_threshold: int = 3,
                 reset_timeout: float = 30.0, clock=time.time):
        self.name = name
        self.failure_threshold = failure_threshold
        self.reset_timeout = reset_timeout
        self.clock = clock
        self._mu = threading.Lock()
        self._state = self.CLOSED
        self._failures = 0
        self._opened_at = 0.0

    def _transition(self, state: str) -> None:
        if state != self._state:
            was = self._state
            self._state = state
            BREAKER_TRANSITIONS.inc({"breaker": self.name, "to": state})
            BREAKER_OPEN.set(
                1.0 if state == self.OPEN else 0.0, {"breaker": self.name}
            )
            # instant event on the solve timeline (ISSUE 15): the breaker
            # opening/half-opening/closing shows up in /debug/trace and
            # /debug/timeline beside the dispatch it interrupted
            TRACER.instant(
                f"breaker.{self.name}", to=state, from_state=was,
                failures=self._failures,
            )
            LOG.info(
                "circuit breaker transition", breaker=self.name,
                from_state=was, to_state=state, failures=self._failures,
            )

    @property
    def state(self) -> str:
        with self._mu:
            self._maybe_half_open()
            return self._state

    def _maybe_half_open(self) -> None:
        if (
            self._state == self.OPEN
            and self.clock() - self._opened_at >= self.reset_timeout
        ):
            self._transition(self.HALF_OPEN)

    def allow(self) -> bool:
        """May a call proceed? Half-open admits ONE trial (subsequent
        callers stay fast-failed until the trial reports)."""
        with self._mu:
            self._maybe_half_open()
            if self._state == self.CLOSED:
                return True
            if self._state == self.HALF_OPEN:
                # admit one probe; treat the slot as taken by re-opening the
                # TTL window so a hung trial doesn't let callers pile on
                self._transition(self.OPEN)
                self._opened_at = self.clock()
                return True
            return False

    def record_success(self) -> None:
        with self._mu:
            self._failures = 0
            self._transition(self.CLOSED)

    def record_failure(self) -> None:
        with self._mu:
            self._failures += 1
            if self._failures >= self.failure_threshold:
                self._transition(self.OPEN)
                self._opened_at = self.clock()

    def trip(self) -> None:
        """Open IMMEDIATELY, regardless of the consecutive-failure count —
        a wedged dispatch is definitive evidence, not one vote of three."""
        with self._mu:
            self._failures = max(self._failures, self.failure_threshold)
            self._transition(self.OPEN)
            self._opened_at = self.clock()


def probe_backend(timeout: float = 60.0) -> Optional[str]:
    """Probe local accelerator init in a subprocess. Returns None when
    healthy, else a one-line reason. A hung init (the observed failure
    mode) is converted into a timeout instead of wedging the caller."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; d = jax.devices(); print(d[0].platform)"],
            capture_output=True, text=True, timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return f"backend probe timed out after {timeout:.0f}s"
    except OSError as e:
        return f"backend probe failed to launch: {e}"
    if proc.returncode != 0:
        err = (proc.stderr or "").strip().splitlines()
        return err[-1] if err else "backend probe exited nonzero"
    return None


def probe_for(primary, timeout: float = 60.0) -> Optional[str]:
    """Pick the probe matching the primary: RemoteSolver exposes a Health
    RPC (the control-plane pod often has no local accelerator at all —
    that is WHY the solver is remote); in-process solvers probe the local
    backend."""
    health = getattr(primary, "health", None)
    if callable(health):
        try:
            health(timeout=timeout)
            return None
        except Exception as e:  # noqa: BLE001 — any RPC failure = unhealthy
            return f"solver service health check failed: {type(e).__name__}: {e}"
    return probe_backend(timeout)


class ResilientSolver:
    """Solver decorator: primary with health-gated fallback, plus
    small-batch routing — tiny solves go straight to the fallback FFD.

    The device path pays a fixed ~90-100 ms of encode + round trip +
    decode regardless of batch size (BASELINE config 1: 100 pods solve in
    ~10 ms on the host greedy but ~100 ms through the accelerator), while
    the host greedy's cost grows with pods x instance types (measured
    ~0.04 + 0.0035*types ms per pod). Batches whose pods x types work
    product is under small_batch_work_max therefore route to the fallback
    — the same serial-FFD regime where the reference wins tiny cells
    (scheduling_benchmark_test.go:56-76's smallest rungs).

    prober is injectable for tests (defaults to probe_for(primary))."""

    def __init__(self, primary, fallback, recorder=None, clock=time.time,
                 probe_timeout: float = 60.0, reprobe_interval: float = 300.0,
                 healthy_recheck_interval: float = 600.0,
                 solve_timeout: Optional[float] = None, prober=None,
                 small_batch_work_max: int = 20_000,
                 wedge_stale_after: Optional[float] = None,
                 watchdog_poll: float = 1.0):
        self.primary = primary
        self.fallback = fallback
        self.recorder = recorder
        self.clock = clock
        self.probe_timeout = probe_timeout
        self.reprobe_interval = reprobe_interval
        self.healthy_recheck_interval = healthy_recheck_interval
        self.solve_timeout = solve_timeout
        # heartbeat staleness threshold for the dispatch watchdog: the
        # solver's phase marks touch the heartbeat, so the longest LEGIT
        # silent stretch is a cold compile — size the threshold above it
        # (the operator passes 600s; prewarm makes live cold compiles rare)
        self.wedge_stale_after = wedge_stale_after
        self.watchdog_poll = watchdog_poll
        self.prober = prober or (lambda: probe_for(primary, probe_timeout))
        self.small_batch_work_max = small_batch_work_max
        self._healthy: Optional[bool] = None
        self._last_probe = 0.0
        self._reason = ""
        # the device-dispatch circuit breaker: tripped open on wedge or
        # abandonment, re-admitted ONLY through the out-of-band prober (its
        # half-open trial is a probe, never a live solve)
        self.breaker = CircuitBreaker(
            name="solver.device", reset_timeout=reprobe_interval, clock=clock,
        )
        # post-mortem surfaces for /debug/health
        self.wedge_history: deque = deque(maxlen=32)
        # the abandoned-thread inventory (ISSUE 12 satellite): a LIST of
        # records, not a deque — the old deque(maxlen=16) silently dropped
        # older zombies while abandoned_total kept counting, so
        # /debug/health under-reported. A record reaches the terminal
        # `reaped` state when its thread finally exits (checked on every
        # health_report); only REAPED records are ever trimmed — a live
        # zombie is never dropped from the inventory, however old.
        self._abandoned: list = []
        self._abandon_count = 0
        self._reaped_count = 0
        self._abandon_seq = itertools.count(1)
        self._last_hb: Optional[supervise.ThreadHeartbeat] = None
        # serializes the probe + verdict write (concurrent controller
        # threads share one probe instead of racing subprocess probes) and
        # guards the wedge/abandoned inventories. Can be held for a full
        # probe budget (60s), so fast paths must never block on it —
        # verdict FIELD access rides _state_mu below
        self._verdict_lock = threading.Lock()
        # leaf lock for the verdict FIELDS (_healthy/_last_probe/_reason/
        # _last_hb): held only for reads/writes, never across a probe, so
        # the small-batch TTL pre-check and supports_batched_replan stay
        # effectively non-blocking (racewatch, ISSUE 13). Order is always
        # _verdict_lock -> _state_mu, never the reverse.
        self._state_mu = threading.Lock()
        # held while a background probe is scheduled/running. A SEMAPHORE,
        # not a Lock: it is acquired on the solve path and released by the
        # probe WORKER thread — cross-thread release is semaphore
        # semantics, and a Lock here poisons lock-ownership analysis
        # (lockwatch taints handoff locks; racewatch locksets inherit the
        # leak — found by the ISSUE 13 gate)
        self._probe_gate = threading.BoundedSemaphore(1)

    # -- health ------------------------------------------------------------

    def _stale(self) -> bool:
        now = self.clock()
        with self._state_mu:
            healthy, last_probe = self._healthy, self._last_probe
        return (
            healthy is None
            or (not healthy
                and now - last_probe >= self.reprobe_interval)
            or (healthy
                and now - last_probe >= self.healthy_recheck_interval)
        )

    def healthy(self) -> bool:
        with self._verdict_lock:
            # wedge gate first: while the device breaker is OPEN every
            # caller fast-fails to the fallback — no probe, no TTL math.
            # When the breaker half-opens, the one admitted trial is the
            # OUT-OF-BAND PROBER (subprocess probe / Health RPC), never a
            # live solve: re-admission is gated on proof the backend came
            # back, and a still-wedged backend costs one probe timeout.
            state = self.breaker.state
            if state == CircuitBreaker.OPEN:
                return False
            if state == CircuitBreaker.HALF_OPEN:
                if not self.breaker.allow():
                    return False  # another thread holds the trial slot
                with self._state_mu:
                    self._last_probe = self.clock()
                reason = self.prober()
                ok = reason is None
                with self._state_mu:
                    self._healthy = ok
                    self._reason = reason or ""
                if ok:
                    self.breaker.record_success()
                    LOG.info("solver recovered from wedge", probe="backend")
                    self._event("SolverRecovered", "Normal",
                                "accelerator backend recovered after wedge")
                else:
                    # allow() already re-opened the TTL window; count the
                    # failed trial so the transition log tells the story
                    self.breaker.record_failure()
                    LOG.warning(
                        "wedge re-admission probe failed",
                        reason=reason, probe="backend",
                    )
                return ok
            # re-check under the lock: a concurrent caller may have just
            # refreshed the verdict while this thread waited
            if self._stale():
                with self._state_mu:
                    self._last_probe = self.clock()
                    was = self._healthy
                reason = self.prober()
                ok = reason is None
                with self._state_mu:
                    self._healthy = ok
                    self._reason = reason or ""
                if was is not False and not ok:
                    LOG.warning(
                        "solver degraded", reason=reason,
                        probe="backend",
                    )
                    self._event(
                        "SolverDegraded", "Warning",
                        f"accelerator backend unavailable ({reason}); "
                        "falling back to the host solver")
                elif was is False and ok:
                    LOG.info("solver recovered", probe="backend")
                    self._event("SolverRecovered", "Normal",
                                "accelerator backend recovered")
            with self._state_mu:
                return bool(self._healthy)

    def _maybe_bg_probe(self) -> None:
        """Refresh a stale health verdict WITHOUT blocking the caller —
        the small-batch path never waits on a probe, but a cluster whose
        solves are all small must still establish health (batched-replan
        gating), detect a mid-life wedge on the normal healthy-recheck
        TTL, and re-probe a dead backend for recovery."""
        if not self._stale():
            return
        if not self._probe_gate.acquire(blocking=False):
            return  # a probe is already scheduled or running

        def run():
            try:
                self.healthy()
            finally:
                self._probe_gate.release()

        # a failed start (thread exhaustion) must not leak the gate — that
        # would disable every future background probe for the process
        # lifetime. The probe is best-effort: the solve it decorates must
        # still return, and the next stale small-batch solve retries.
        try:
            threading.Thread(
                target=run, daemon=True, name="solver-probe"
            ).start()
        except Exception:  # noqa: BLE001 — best-effort probe
            self._probe_gate.release()
        except BaseException:
            self._probe_gate.release()
            raise

    def _mark_wedged(self, reason: str, kind: str = "wedged") -> None:
        """Abandonment path (wedge OR slow-timeout): mark the backend dead
        AND trip the device breaker open immediately — re-admission now
        runs through the breaker's half-open prober trial, not the plain
        reprobe TTL, so a wedged backend is never handed a live solve to
        prove itself with."""
        with self._verdict_lock:
            with self._state_mu:
                self._healthy = False
                self._last_probe = self.clock()
                self._reason = reason
                hb = self._last_hb
            if kind == "wedged":
                SOLVER_WEDGED_TOTAL.inc()
            self.breaker.trip()
            phase = hb.label() if hb is not None else ""
            self.wedge_history.append({
                "ts": self.clock(),
                "kind": kind,
                "reason": reason[:200],
                "phase": phase,
                "heartbeat_age_s": (
                    round(hb.age(), 1)
                    if hb is not None and hb.age() is not None else None
                ),
            })
            TRACER.instant("solver.wedge", kind=kind, phase=phase)
        LOG.warning("solver wedged", reason=reason, kind=kind, probe="solve")
        self._event("SolverWedged", "Warning",
                    f"device dispatch {kind} ({reason}); breaker open, "
                    "falling back to the host solver until a probe passes")

    MAX_REAPED_RECORDS = 48

    def _reap_abandoned_locked(self) -> None:
        """Move exited abandoned threads to the terminal `reaped` state
        (dropping the thread reference) and trim old REAPED records; live
        zombies are never dropped — the inventory stays exact."""
        for rec in self._abandoned:
            t = rec.get("thread")
            if t is not None and not t.is_alive():
                rec["reaped"] = True
                rec["thread"] = None
                self._reaped_count += 1
        if len(self._abandoned) > self.MAX_REAPED_RECORDS:
            keep = []
            excess = len(self._abandoned) - self.MAX_REAPED_RECORDS
            for rec in self._abandoned:
                if excess > 0 and rec["reaped"]:
                    excess -= 1
                    continue
                keep.append(rec)
            self._abandoned = keep

    def health_report(self) -> dict:
        """The /debug/health payload: heartbeat age of the most recent
        dispatch, breaker state, wedge history, the abandoned-thread
        inventory (with reaped/live accounting — host mode drives the live
        count to zero because the wedged PROCESS is killed), and the
        solver host's pid/generation/queue state when the primary runs
        out-of-process. Reads only — no probe is triggered."""
        with self._state_mu:
            hb = self._last_hb
        age = hb.age() if hb is not None else None
        host_report = None
        hr = getattr(self.primary, "host_report", None)
        if callable(hr):
            try:
                host_report = hr()
            except Exception as e:  # noqa: BLE001 — report, don't fail health
                host_report = {"error": f"{type(e).__name__}: {e}"}
        retry_budget = None
        rb = getattr(self.primary, "retry_budget", None)
        if rb is not None:
            try:
                retry_budget = rb.stats()
            except Exception as e:  # noqa: BLE001 — report, don't fail health
                retry_budget = {"error": f"{type(e).__name__}: {e}"}
        with self._state_mu:
            healthy, reason = self._healthy, self._reason
        with self._verdict_lock:
            self._reap_abandoned_locked()
            live = sum(1 for r in self._abandoned if not r["reaped"])
            return {
                "healthy": healthy,
                "reason": reason,
                "breaker": self.breaker.state,
                "heartbeat_age_s": round(age, 3) if age is not None else None,
                "heartbeat_phase": hb.label() if hb is not None else "",
                "solve_timeout_s": self.solve_timeout,
                "wedge_stale_after_s": self.wedge_stale_after,
                "wedge_history": list(self.wedge_history),
                "abandoned_total": self._abandon_count,
                "abandoned_live": live,
                "abandoned_reaped": self._reaped_count,
                "abandoned_threads": [
                    {
                        "name": r["name"],
                        "kind": r["kind"],
                        "alive": (
                            r["thread"].is_alive()
                            if r["thread"] is not None else False
                        ),
                        "reaped": r["reaped"],
                    }
                    for r in self._abandoned
                ],
                "host": host_report,
                "retry_budget": retry_budget,
            }

    def _mark_dead(self, reason: str) -> None:
        # under the verdict lock: a background probe completing after a
        # primary-solve failure must not overwrite the dead verdict with
        # its (pre-failure-sampled) healthy one; taking the lock orders
        # this write after any in-flight probe, and stamping _last_probe
        # makes the dead verdict fresh so the next healthy() respects the
        # reprobe TTL instead of instantly re-probing
        with self._verdict_lock:
            with self._state_mu:
                self._healthy = False
                self._last_probe = self.clock()
                self._reason = reason
        LOG.warning("solver degraded", reason=reason, probe="solve")
        self._event("SolverDegraded", "Warning",
                    f"primary solver failed ({reason}); "
                    "falling back to the host solver")

    def _event(self, reason: str, etype: str, message: str) -> None:
        if self.recorder is not None:
            self.recorder.publish(
                Event("Solver", "solver", etype, reason, message,
                      dedupe_values=(reason,))
            )

    # -- Solver interface --------------------------------------------------

    @property
    def supports_batched_replan(self) -> bool:
        # cached health only — this property is read every deprovisioning
        # pass and must never block on a probe; _state_mu is a leaf lock
        # held only for field access, never across a probe, so this stays
        # effectively non-blocking. Until the first solve has established
        # health, the sequential replan path is used.
        with self._state_mu:
            healthy = self._healthy
        return healthy is True and getattr(
            self.primary, "supports_batched_replan", False
        )

    @property
    def backend(self):
        return getattr(self.primary, "backend", None)

    @property
    def max_nodes(self):
        # consolidation sizes its ladder screen off the solver's budget
        return getattr(self.primary, "max_nodes", 1024)

    def encode(self, *args, **kwargs):
        """Pipelined-surface passthrough: embedders overlap the next
        batch's encode with the current solve (solve(encoded=snap)); the
        primary owns the snapshot format. Only valid while the primary is
        serving — a fallback-routed solve ignores the snapshot (the host
        FFD re-reads objects), which stays correct because encode() output
        is advisory for the device path only."""
        return self.primary.encode(*args, **kwargs)

    def replan_screen(self, *args, **kwargs):
        """Batched consolidation replan passthrough (solver/replan.py):
        reachable only while supports_batched_replan reads True (cached
        health + primary capability) — the consolidation driver falls back
        to the sequential simulate_scheduling path otherwise, so this
        never routes a replan to a dead backend."""
        return self.primary.replan_screen(*args, **kwargs)

    def _primary_solve(self, *args, **kwargs):
        if self.solve_timeout is None:
            return self.primary.solve(*args, **kwargs)
        box = {}
        done = threading.Event()
        hb = supervise.ThreadHeartbeat()
        # request context and trace are thread-local: the watchdog thread
        # must inherit the caller's binding or attribution dies right here
        # — the gate, the frame header, and the child would all see an
        # unbound context, and the solve span (whose trace id the latency
        # exemplar carries) would start a fresh trace the flight record
        # (begun on the caller's thread) knows nothing about (ISSUE 16)
        ctx = reqctx.current()
        caller_trace = TRACER.current_trace_id() if TRACER.enabled else None
        # under the state lock: health_report/_mark_wedged read _last_hb
        # from other threads — a bare write here was the racewatch gate's
        # founding catch (ISSUE 13)
        with self._state_mu:
            self._last_hb = hb

        def run():
            # bind the heartbeat into this thread: the solver's phase
            # marks (TPUSolver._mark) touch it as the dispatch progresses
            supervise.bind_heartbeat(hb)
            hb.touch()
            try:
                with contextlib.ExitStack() as stack:
                    if ctx is not None:
                        stack.enter_context(reqctx.bind(ctx))
                    if caller_trace is not None:
                        stack.enter_context(TRACER.span(
                            "solver.watchdog.dispatch",
                            trace_id=caller_trace,
                        ))
                    box["result"] = self.primary.solve(*args, **kwargs)
            except BaseException as e:  # noqa: BLE001 — surfaced below
                box["error"] = e
            finally:
                done.set()

        t = threading.Thread(target=run, daemon=True, name="primary-solve")
        t.start()
        deadline = time.monotonic() + self.solve_timeout
        while True:
            remaining = deadline - time.monotonic()
            if done.wait(min(self.watchdog_poll, max(0.02, remaining))):
                break
            age = hb.age()
            if (
                self.wedge_stale_after is not None
                and age is not None
                and age >= self.wedge_stale_after
            ):
                # stale heartbeat = the dispatch stopped making progress:
                # a WEDGE, abandoned before the budget burns down. The
                # heartbeat's phase label names WHERE it died (ISSUE 15)
                phase = hb.label()
                self._abandon(t, "wedged", age)
                raise SolverWedgedError(
                    f"primary solve heartbeat stale for {age:.0f}s "
                    f"(threshold {self.wedge_stale_after:.0f}s)"
                    + (f" during {phase}" if phase else "")
                    + ": backend wedged mid-dispatch"
                )
            if time.monotonic() >= deadline:
                # alive (heartbeat fresh) but over budget: slow, not
                # wedged — the thread still leaks with the running call
                self._abandon(t, "timeout", age)
                raise TimeoutError(
                    f"primary solve exceeded {self.solve_timeout:.0f}s "
                    "watchdog"
                )
        if "error" in box:
            raise box["error"]
        return box["result"]

    def _abandon(self, t: threading.Thread, kind: str,
                 heartbeat_age: Optional[float]) -> None:
        """Account for the thread the watchdog is about to leak: NAME it
        (the thread-discipline rule — an anonymous zombie in a thread dump
        is undiagnosable), keep a bounded reference for /debug/health, and
        count it. The leak itself stays by design; what was a silent
        degradation is now an inventory."""
        n = next(self._abandon_seq)
        t.name = f"primary-solve-abandoned-{n}-{kind}"
        # inventory mutations under the verdict lock: health_report's reap
        # pass rebuilds the list under the same lock, and an append racing
        # that rebuild would silently drop this (live!) record
        with self._verdict_lock:
            self._abandon_count = n
            self._abandoned.append(
                {"name": t.name, "kind": kind, "thread": t, "reaped": False}
            )
        SOLVER_ABANDONED_TOTAL.inc(reqctx.tenant_labels(kind=kind))
        LOG.warning(
            "primary solve thread abandoned", kind=kind, thread=t.name,
            heartbeat_age_s=(
                round(heartbeat_age, 1) if heartbeat_age is not None else None
            ),
        )

    def _small_batch(self, pods, instance_types) -> bool:
        if self.small_batch_work_max <= 0:
            return False
        n_types = sum(len(v) for v in instance_types.values())
        return len(pods) * max(n_types, 1) <= self.small_batch_work_max

    def _fallback_solve(self, pods, provisioners, instance_types,
                        daemonset_pods, state_nodes, kube_client, cluster):
        return self.fallback.solve(
            pods, provisioners, instance_types, daemonset_pods,
            state_nodes, kube_client=kube_client, cluster=cluster,
        )

    def _recorded_fallback(self, rec, backend, dump, pods, provisioners,
                           instance_types, daemonset_pods, state_nodes,
                           kube_client, cluster):
        """Fallback solve with the flight record closed on EVERY exit: a
        fallback that itself raises is the worst incident of all — the
        record is finalized (and dumped) before the exception propagates."""
        try:
            result = self._fallback_solve(
                pods, provisioners, instance_types, daemonset_pods,
                state_nodes, kube_client, cluster,
            )
        except Exception as e:
            if rec is not None:
                rec.finish_error(backend, e)
            raise
        if rec is not None:
            rec.finish(backend, result, dump=dump)
        return result

    def solve(self, pods, provisioners, instance_types, daemonset_pods=None,
              state_nodes=None, kube_client=None, cluster=None, encoded=None):
        # flight recorder (obs/flightrec): snapshot the exact inputs of
        # this Solve so a bad placement replays offline through
        # hack/replay.py. Disabled (the default): one flag check, rec=None.
        # Deprovisioning-simulation re-entries are deliberately NOT
        # recorded (flightrec.suppress_recording, armed by
        # deprovisioning/core.simulate_scheduling): consolidation re-enters
        # this solver every pass and would churn the ring past the
        # provisioning records an incident actually needs.
        rec = None
        if FLIGHTREC.enabled and not recording_suppressed():
            rec = FLIGHTREC.begin(
                pods, provisioners, instance_types, daemonset_pods,
                state_nodes, kube_client=kube_client,
                max_nodes=self.max_nodes,
            )
        # tiny batches: the serial FFD beats the device path's fixed
        # encode/transfer cost — route without blocking on primary health,
        # while _maybe_bg_probe keeps the verdict fresh on the normal TTLs
        # (establish at startup, expire a healthy verdict, re-probe a dead
        # backend) so batched-replan gating and degradation/recovery
        # events work even when every solve is small.
        if self._small_batch(pods, instance_types):
            SOLVER_SMALL_BATCH_TOTAL.inc(reqctx.tenant_labels())
            self._maybe_bg_probe()
            return self._recorded_fallback(
                rec, "host.small_batch", False, pods, provisioners,
                instance_types, daemonset_pods, state_nodes, kube_client,
                cluster,
            )
        if not self.healthy():
            SOLVER_FALLBACK_TOTAL.inc(reqctx.tenant_labels(reason="backend_unavailable"))
            # a fallback trip is an incident worth keeping: dump to disk
            return self._recorded_fallback(
                rec, "host.backend_unavailable", True, pods, provisioners,
                instance_types, daemonset_pods, state_nodes, kube_client,
                cluster,
            )
        try:
            kwargs = {"encoded": encoded} if encoded is not None else {}
            result = self._primary_solve(
                pods, provisioners, instance_types, daemonset_pods,
                state_nodes, kube_client=kube_client, cluster=cluster,
                **kwargs,
            )
            if rec is not None:
                rec.finish("primary", result, replayer="tpu")
            return result
        except Exception as e:  # noqa: BLE001 — degrade, never stall
            # typed solver-RPC errors classify themselves: a REQUEST defect
            # (INVALID_ARGUMENT / RESOURCE_EXHAUSTED) means the backend is
            # fine and must not be marked dead — this solve falls back, the
            # next one goes to the primary again. Transport/internal
            # failures (and everything untyped) mark the backend dead as
            # before.
            if rec is not None:
                rec.note_primary_error(e)
            LOG.error(
                "primary solve failed, routing to fallback",
                error=type(e).__name__, error_detail=str(e),
                pods=len(pods),
            )
            if isinstance(e, SolverWedgedError):
                # heartbeat staleness: wedge — breaker opens now, the
                # prober gates re-admission (no waiting out a reprobe TTL
                # with live solves as the trial balloons)
                self._mark_wedged(f"{type(e).__name__}: {e}", kind="wedged")
                SOLVER_FALLBACK_TOTAL.inc(reqctx.tenant_labels(reason="wedged"))
            elif isinstance(e, TimeoutError):
                # watchdog abandonment (slow, not wedged): the leaked
                # thread is real either way — same immediate breaker trip
                self._mark_wedged(f"{type(e).__name__}: {e}", kind="timeout")
                SOLVER_FALLBACK_TOTAL.inc(reqctx.tenant_labels(reason="primary_error"))
            elif getattr(e, "shed_reason", None) is not None:
                # an admission-gate shed (queue_full, tenant_quota,
                # brownout, deadline_expired, ...): the backend never SAW
                # the request, so nothing here is evidence against it —
                # serve the fallback without marking anything dead. This
                # covers DEADLINE_EXCEEDED sheds too, whose type would
                # otherwise mark unhealthy: a tenant flooding the gate
                # must not condemn the device everyone else depends on.
                SOLVER_FALLBACK_TOTAL.inc(reqctx.tenant_labels(reason="admission_shed"))
            elif getattr(e, "marks_unhealthy", True):
                self._mark_dead(f"{type(e).__name__}: {e}")
                SOLVER_FALLBACK_TOTAL.inc(reqctx.tenant_labels(reason="primary_error"))
            else:
                SOLVER_FALLBACK_TOTAL.inc(reqctx.tenant_labels(reason="request_rejected"))
            # note_primary_error makes the record auto-dump on finish; if
            # the fallback ALSO raises, _recorded_fallback finalizes the
            # record via finish_error before the exception propagates
            return self._recorded_fallback(
                rec, "host.primary_error", False, pods, provisioners,
                instance_types, daemonset_pods, state_nodes, kube_client,
                cluster,
            )
