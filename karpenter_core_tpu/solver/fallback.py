"""ResilientSolver — production backend-failure fallback.

The accelerator link (the axon tunnel especially) is observed to HANG or
fail to initialize, not just error: rounds 1 and 2 both lost their first
bench attempt to `Unable to initialize backend: UNAVAILABLE`. The bench
defends itself with a subprocess probe (bench.py); this module moves that
defense into the PRODUCTION solve path, per the round-2 verdict:

  - backend health is probed in a SUBPROCESS with a timeout (a wedged
    backend cannot poison the control-plane process) and cached with a TTL;
  - while unhealthy — or after a primary solve raises — Solve() routes to
    the fallback solver (GreedySolver by default), publishes a deduped
    event, and bumps a metric;
  - the probe retries after `reprobe_interval`, so a recovered TPU is
    picked back up without a restart.

Wired by operator.__main__ around TPUSolver/RemoteSolver; the control plane
keeps provisioning through a dead accelerator (reference analog: the whole
design is level-triggered reconciliation — the solver must degrade, never
stall, operator.go:154-169).
"""
from __future__ import annotations

import subprocess
import sys
import time
from typing import Optional

from karpenter_core_tpu.events import Event
from karpenter_core_tpu.metrics.registry import Counter

SOLVER_FALLBACK_TOTAL = Counter(
    "karpenter_solver_fallback_total",
    "Solves routed to the fallback solver because the accelerator backend "
    "was unavailable or the primary solver raised",
)


def probe_backend(timeout: float = 60.0) -> Optional[str]:
    """Probe accelerator init in a subprocess. Returns None when healthy,
    else a one-line reason. A hung init (the observed failure mode) is
    converted into a timeout instead of wedging the caller."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; d = jax.devices(); print(d[0].platform)"],
            capture_output=True, text=True, timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return f"backend probe timed out after {timeout:.0f}s"
    except OSError as e:
        return f"backend probe failed to launch: {e}"
    if proc.returncode != 0:
        err = (proc.stderr or "").strip().splitlines()
        return err[-1] if err else "backend probe exited nonzero"
    return None


class ResilientSolver:
    """Solver decorator: primary with health-gated fallback.

    prober is injectable for tests (defaults to probe_backend)."""

    def __init__(self, primary, fallback, recorder=None, clock=time.time,
                 probe_timeout: float = 60.0, reprobe_interval: float = 300.0,
                 prober=None):
        self.primary = primary
        self.fallback = fallback
        self.recorder = recorder
        self.clock = clock
        self.probe_timeout = probe_timeout
        self.reprobe_interval = reprobe_interval
        self.prober = prober or (lambda: probe_backend(probe_timeout))
        self._healthy: Optional[bool] = None
        self._last_probe = 0.0
        self._reason = ""

    # -- health ------------------------------------------------------------

    def healthy(self) -> bool:
        now = self.clock()
        if self._healthy is None or (
            not self._healthy and now - self._last_probe >= self.reprobe_interval
        ):
            self._last_probe = now
            reason = self.prober()
            was = self._healthy
            self._healthy = reason is None
            self._reason = reason or ""
            if was is not False and not self._healthy:
                self._event("SolverDegraded",
                            f"accelerator backend unavailable ({self._reason}); "
                            "falling back to the host solver")
            elif was is False and self._healthy:
                self._event("SolverRecovered", "accelerator backend recovered")
        return bool(self._healthy)

    def _mark_dead(self, reason: str) -> None:
        self._healthy = False
        self._last_probe = self.clock()
        self._reason = reason
        self._event("SolverDegraded",
                    f"primary solver failed ({reason}); "
                    "falling back to the host solver")

    def _event(self, reason: str, message: str) -> None:
        if self.recorder is not None:
            self.recorder.publish(
                Event("Solver", "solver", "Warning" if "Degraded" in reason
                      else "Normal", reason, message,
                      dedupe_values=(reason,))
            )

    # -- Solver interface --------------------------------------------------

    @property
    def supports_batched_replan(self) -> bool:
        return self.healthy() and getattr(
            self.primary, "supports_batched_replan", False
        )

    @property
    def backend(self):
        return getattr(self.primary, "backend", None)

    def solve(self, pods, provisioners, instance_types, daemonset_pods=None,
              state_nodes=None, kube_client=None, cluster=None):
        if not self.healthy():
            SOLVER_FALLBACK_TOTAL.inc({"reason": "backend_unavailable"})
            return self.fallback.solve(
                pods, provisioners, instance_types, daemonset_pods,
                state_nodes, kube_client=kube_client, cluster=cluster,
            )
        try:
            return self.primary.solve(
                pods, provisioners, instance_types, daemonset_pods,
                state_nodes, kube_client=kube_client, cluster=cluster,
            )
        except Exception as e:  # noqa: BLE001 — degrade, never stall
            self._mark_dead(f"{type(e).__name__}: {e}")
            SOLVER_FALLBACK_TOTAL.inc({"reason": "primary_error"})
            return self.fallback.solve(
                pods, provisioners, instance_types, daemonset_pods,
                state_nodes, kube_client=kube_client, cluster=cluster,
            )
