"""Hard-killable solver host: the device dispatch in a supervised sidecar
process, plus deadline-aware admission control (ISSUE 12 tentpole).

PR 10 (ISSUE 11) made wedges *detectable*: heartbeat staleness abandons a
hung in-process dispatch early, the breaker opens, the greedy fallback
serves. But the abandoned thread still LEAKED — the zombie keeps the GIL /
device busy until the hung XLA call returns or the process dies
(solver/fallback.py documents the gap), so one wedge poisons the
accelerator every control plane depends on. This module kills the zombie
for real by moving the dispatch across a process boundary it can SIGKILL:

  * ``host_main`` — the sidecar worker (`python -m
    karpenter_core_tpu.solver.host`): a ``SolverService`` behind
    length-prefixed frames on stdin/stdout, using the SAME pb-tensor
    serialization as the gRPC wire (solver/service.py), with the
    persistent compile cache enabled and a file ``Heartbeat``
    (utils/supervise) registered as the PROCESS heartbeat — the
    ``TPUSolver._mark`` phase marks that already touch the in-process
    thread heartbeat now also touch the file, so the parent's staleness
    watchdog reads the same progress signal.
  * ``SolverHost`` — the parent-side process manager: process-group spawn
    (start_new_session, exactly like ``run_supervised``), heartbeat-file
    staleness watchdog while a dispatch is in flight, hard ``killpg``
    SIGKILL on wedge OR budget overrun, eager respawn, env-redacted
    stderr tails for the post-mortem, and generation/recovery accounting
    (`karpenter_solver_host_{respawn_total,recovery_seconds}`).
  * ``AdmissionGate`` — bounded, deadline-aware admission shared by the
    host facade and the gRPC service: per-request deadlines propagate
    into the dispatch, a request whose deadline expires while queued is
    NEVER dispatched, a full queue sheds with a typed RESOURCE_EXHAUSTED
    carrying a retry-after hint, and a brownout threshold sheds EARLY so
    the caller's ResilientSolver serves the greedy path before anything
    turns into an error (the brownout ladder: device -> greedy -> error).
  * ``HostSolver`` — the in-process Solver facade (same interface as
    TPUSolver/RemoteSolver): encodes host-side, ships tensors over the
    pipe, decodes locally. A wedge now means KILL AND RESPAWN, not
    abandon-and-hope: the respawned host warm-recovers from the
    persistent compile cache (PR 7) and rebuilds verdict-tensor
    residency on its first solve (PR 6), and ``health()`` — the
    ResilientSolver breaker's half-open trial — ensures the host is
    respawned and probes it, so re-admission literally means "host
    respawned and probe passed".

The in-process dispatch path stays available: KARPENTER_SOLVER_HOST=off
(the default outside the operator entrypoint) keeps TPUSolver in-process,
so unit tests and embedders pay nothing.
"""
from __future__ import annotations

import contextlib
import itertools
import json
import os
import select
import signal
import struct
import subprocess
import sys
import tempfile
import threading
import time
from typing import Dict, Optional, Tuple

import numpy as np

from karpenter_core_tpu import chaos
from karpenter_core_tpu.metrics.registry import (
    NAMESPACE,
    REGISTRY,
    ProcessSeriesMerger,
    snapshot_families,
)
from karpenter_core_tpu.obs import TRACER
from karpenter_core_tpu.obs import envflags
from karpenter_core_tpu.obs import proghealth
from karpenter_core_tpu.obs import reqctx
from karpenter_core_tpu.obs.tracer import export_spans
from karpenter_core_tpu.obs.log import get_logger
from karpenter_core_tpu.solver import service_pb2 as pb
from karpenter_core_tpu.solver.fallback import SolverWedgedError
from karpenter_core_tpu.solver.service import (
    SolverDeadlineExceededError,
    SolverResourceExhaustedError,
    SolverUnavailableError,
    _StateView,
    _flatten_args,
    error_from_string,
    geometry_json,
    tensor_from_pb,
    tensor_to_pb,
)
from karpenter_core_tpu.solver.tpu_solver import (
    SolveResult,
    decode_solve,
    device_args,
    solve_with_relaxation,
)
from karpenter_core_tpu.utils import supervise

LOG = get_logger("karpenter.solver.host")

SOLVER_QUEUE_DEPTH = REGISTRY.gauge(
    f"{NAMESPACE}_solver_queue_depth",
    "Solver admission-gate depth (in-flight + queued dispatches), by gate",
)
SOLVER_SHED_TOTAL = REGISTRY.counter(
    f"{NAMESPACE}_solver_shed_total",
    "Solver requests shed by the admission gate instead of queued "
    "unboundedly, by gate and reason (queue_full, tenant_quota, brownout, "
    "brownout_shed, deadline_expired, injected) and tenant when a request "
    "context is bound",
)
SOLVER_QUEUE_WAIT = REGISTRY.histogram(
    f"{NAMESPACE}_solver_queue_wait_seconds",
    "Seconds an admitted request waited in the gate before dispatch, by "
    "gate (and tenant when a request context is bound)",
)
DEADLINE_VIOLATIONS_TOTAL = REGISTRY.counter(
    f"{NAMESPACE}_solver_deadline_violations_total",
    "Requests whose deadline the gate could not honor, by gate, stage and "
    "tenant. stage=queue: expired while waiting and shed, NEVER dispatched "
    "— expected under flood, attributed to the tenant that overran its "
    "budget. stage=dispatch: reached dispatch past the deadline — "
    "structurally zero; any increment is a gate bug dashboards should "
    "page on",
)
GATE_DEMOTIONS_TOTAL = REGISTRY.counter(
    f"{NAMESPACE}_gate_demotions_total",
    "Brownout-ladder demotions at the admission gate, by tenant and the "
    "rung demoted to (greedy = shed to the local fallback, shed = hard "
    "shed with a long retry-after); promotions are tracer instant events "
    "and ladder stats, not a counter",
)
HOST_RESPAWN_TOTAL = REGISTRY.counter(
    f"{NAMESPACE}_solver_host_respawn_total",
    "Solver host processes killed and respawned, by reason "
    "(wedged = heartbeat stale, timeout = budget overrun, crashed = the "
    "host died on its own, chaos = injected crash)",
)
HOST_RECOVERY_SECONDS = REGISTRY.gauge(
    f"{NAMESPACE}_solver_host_recovery_seconds",
    "Seconds from the most recent solver-host spawn to its ready frame "
    "(process boot; the first solve additionally pays the persistent-"
    "compile-cache load for its geometry)",
)


# ---------------------------------------------------------------------------
# deadline-aware admission control

# sub-queue key for requests with no bound tenant: never a metric label
_NO_TENANT = ""
# synthetic tenant the `solver.gate.flood` chaos point attributes traffic
# to: arming the point converts a fraction of live requests into one
# flooding tenant, so quota/brownout isolation can be drilled mid-churn
# without touching real tenants' accounting
CHAOS_FLOOD_TENANT = "chaos-flood"
# DRR weights are clamped into [1/16, 16]: the floor bounds the rotation
# count before some queue accumulates a full dispatch credit, the ceiling
# keeps one tenant from monopolizing every rotation
_MIN_WEIGHT = 1.0 / 16.0
_MAX_WEIGHT = 16.0

_LADDER_RUNGS = ("device", "greedy", "shed")


class BrownoutLadder:
    """Closed SLO->admission loop: a per-tenant brownout ladder.

    PR 16 left ``KARPENTER_SLO_BROWNOUT`` as an off-by-default preference
    hook (budget-exhausted tenants shed first inside the depth band). This
    is the live control loop: ``burn`` (typically ``SloEngine.fast_burn``)
    maps a guarded tenant label to its fast-window burn rate, and the
    ladder walks that tenant down ``device -> greedy -> shed`` one rung at
    a time:

      * burn >= ``demote_at``: demote one rung. The first demotion is
        immediate; escalating further waits out ``hold_s``, so one bad
        window cannot jump a tenant straight to hard shed.
      * burn < ``promote_below`` sustained for ``hold_s``: promote one
        rung back.

    The asymmetric thresholds plus the dwell are the hysteresis: a tenant
    oscillating around the threshold changes rung at most once per
    ``hold_s``. Burn probes are rate-limited to one per
    ``eval_interval_s`` per tenant; between probes the cached rung answers
    in O(1). A failing probe HOLDS the current rung — unlike the depth-band
    preference hook (which fails closed to protect the device), the ladder
    acts on absolute SLO evidence, and a sick probe is not evidence that a
    tenant started burning.

    Demotions tick ``karpenter_gate_demotions_total{tenant,reason}``; every
    transition lands as a ``solver.gate.demote`` / ``solver.gate.promote``
    tracer instant event."""

    def __init__(self, burn, demote_at: float = 1.0,
                 promote_below: float = 0.5, hold_s: float = 30.0,
                 eval_interval_s: float = 1.0, clock=time.monotonic):
        self.burn = burn
        self.demote_at = float(demote_at)
        self.promote_below = float(promote_below)
        self.hold_s = float(hold_s)
        self.eval_interval_s = float(eval_interval_s)
        self._clock = clock
        self._mu = threading.Lock()
        # guarded label -> [rung index, rung-entered ts, last-probe ts, burn]
        self._state: Dict[str, list] = {}
        self.demotions_total = 0
        self.promotions_total = 0

    def review(self, label: str) -> str:
        """Current rung for *label* (a guard-admitted tenant label),
        re-evaluating the burn probe at most once per ``eval_interval_s``."""
        now = self._clock()
        with self._mu:
            st = self._state.get(label)
            if st is None:
                st = self._state[label] = [0, now, float("-inf"), 0.0]
            if now - st[2] < self.eval_interval_s:
                return _LADDER_RUNGS[st[0]]
            st[2] = now
        # the probe samples the SLO engine (histogram walks, its own
        # locks) — never under self._mu
        try:
            burn = float(self.burn(label))
        except Exception:  # noqa: BLE001 — a sick probe holds the rung
            burn = None
        with self._mu:
            st = self._state[label]
            if burn is None:
                return _LADDER_RUNGS[st[0]]
            st[3] = burn
            rung = st[0]
            dwelt = now - st[1]
            if (burn >= self.demote_at and rung < len(_LADDER_RUNGS) - 1
                    and (rung == 0 or dwelt >= self.hold_s)):
                st[0], st[1] = rung + 1, now
                self._transition_locked(label, rung, rung + 1, burn)
            elif (burn < self.promote_below and rung > 0
                    and dwelt >= self.hold_s):
                st[0], st[1] = rung - 1, now
                self._transition_locked(label, rung, rung - 1, burn)
            return _LADDER_RUNGS[st[0]]

    def _transition_locked(self, label: str, frm: int, to: int,
                           burn: float) -> None:
        if to > frm:
            self.demotions_total += 1
            GATE_DEMOTIONS_TOTAL.inc({
                "tenant": reqctx.TENANTS.admit(label),
                "reason": _LADDER_RUNGS[to],
            })
            name = "solver.gate.demote"
        else:
            self.promotions_total += 1
            name = "solver.gate.promote"
        TRACER.instant(
            name, tenant=label, frm=_LADDER_RUNGS[frm],
            to=_LADDER_RUNGS[to], burn=round(burn, 3),
        )

    def level(self, label: str) -> str:
        with self._mu:
            st = self._state.get(label)
            return _LADDER_RUNGS[st[0]] if st is not None else "device"

    def stats(self) -> Dict[str, object]:
        now = self._clock()
        with self._mu:
            return {
                "demote_at": self.demote_at,
                "promote_below": self.promote_below,
                "hold_s": self.hold_s,
                "demotions_total": self.demotions_total,
                "promotions_total": self.promotions_total,
                "tenants": {
                    label: {
                        "level": _LADDER_RUNGS[st[0]],
                        "burn": round(st[3], 3),
                        "dwell_s": round(now - st[1], 3),
                    }
                    for label, st in self._state.items()
                },
            }


class _Ticket:
    """One queued admission. ``order`` is the EDF sort key within the
    tenant's sub-queue: deadline first (None sorts last), arrival breaks
    ties so equal-deadline work stays FIFO."""

    __slots__ = ("key", "deadline", "seq", "order")

    def __init__(self, key: str, deadline: Optional[float], seq: int):
        self.key = key
        self.deadline = deadline
        self.seq = seq
        self.order = (deadline if deadline is not None else float("inf"), seq)


class AdmissionGate:
    """Bounded fair-share admission in front of a serial dispatch resource.

    The device dispatch is one resource; under overload, requests must
    SHED, not queue forever (the reference's level-triggered loop never
    blocks a reconcile behind an unbounded queue). Contract:

      * one bounded sub-queue per RequestContext tenant (PR 16's
        cardinality guard caps the queue count; overflow tenants share
        the ``other`` queue, unbound requests share an unnamed one);
      * dispatch order is weighted deficit-round-robin ACROSS tenants
        (``weights``, default 1.0 per tenant) and earliest-deadline-first
        WITHIN a tenant — a flooding tenant lengthens only its own queue,
        not every tenant's wait;
      * at most ``max_queue`` requests wait in total, and at most
        ``tenant_quota`` (when set) per tenant — quota-full sheds the
        OFFENDING tenant with a typed RESOURCE_EXHAUSTED carrying a
        per-tenant ``retry_after_s`` (its own queue depth x its own
        service-time EMA, global EMA as the cold-start fallback);
      * ``brownout_at`` (< max_queue) sheds EARLY with the same typed
        error — the caller's ResilientSolver classifies it as a request
        defect (marks_unhealthy=False) and serves the greedy fallback,
        so the ladder degrades device -> greedy BEFORE anything errors;
        ``ladder`` (a :class:`BrownoutLadder`) does the same per tenant,
        driven by SLO burn instead of queue depth;
      * a request admitted with a deadline that expires while it waits is
        NEVER dispatched (shed as deadline_expired, a typed
        DEADLINE_EXCEEDED, attributed to the tenant) — expired work
        reaching the device would burn exactly the capacity the overload
        lacks. A bound ``RequestContext.deadline_s`` tightens the gate's
        own budget and orders the request within its sub-queue.

    Thread-safe. ``clock`` is injectable for tests."""

    def __init__(self, name: str = "solver", max_queue: int = 8,
                 brownout_at: Optional[int] = None, max_inflight: int = 1,
                 clock=time.monotonic, brownout_prefer=None,
                 tenant_quota: Optional[int] = None,
                 weights: Optional[Dict[str, float]] = None,
                 ladder: Optional[BrownoutLadder] = None):
        self.name = name
        self.max_queue = int(max_queue)
        self.brownout_at = brownout_at
        self.max_inflight = int(max_inflight)
        self._clock = clock
        # off-by-default observability->control hook: tenant -> bool.
        # True = this tenant sheds in the brownout band (its error budget
        # is spent); False = it rides through to the hard queue bound.
        # None (the default) keeps legacy behavior: brownout sheds everyone.
        self.brownout_prefer = brownout_prefer
        # None = no per-tenant bound (the global max_queue still holds)
        self.tenant_quota = (
            int(tenant_quota) if tenant_quota is not None else None
        )
        # guarded tenant label -> DRR weight (unknown tenants weigh 1.0)
        self.weights: Dict[str, float] = dict(weights or {})
        # burn-driven per-tenant brownout (the closed SLO loop); None = off
        self.ladder = ladder
        self._cond = threading.Condition()
        self._inflight = 0
        self._ema: Optional[float] = None
        self.accepted_total = 0
        self.dispatched_total = 0
        self.deadline_violations = 0  # structurally zero; asserted, not hoped
        self._seq = itertools.count()
        # sub-queue key (guarded label, or "" unbound) -> EDF-ordered tickets
        self._queues: Dict[str, list] = {}
        # DRR rotation ring: keys with a non-empty sub-queue, visit order
        self._ring: list = []
        self._deficit: Dict[str, float] = {}
        # tickets granted a dispatch slot, waiting for their thread to wake
        self._granted: set = set()
        self._shed_counts: Dict[str, int] = {}
        # per-sub-queue accounting (bounded by the tenant cap + unbound)
        self._tenant_ema: Dict[str, float] = {}
        self._dispatched_by: Dict[str, int] = {}
        self._shed_by: Dict[str, Dict[str, int]] = {}
        self._expired_in_queue: Dict[str, int] = {}
        # guarded tenant label -> depth (in-flight + queued), for the
        # per-tenant SOLVER_QUEUE_DEPTH series; bounded by the tenant cap
        self._tenant_depth: Dict[str, int] = {}

    # -- internals (callers hold self._cond) --------------------------------

    def _waiting_locked(self) -> int:
        return sum(len(q) for q in self._queues.values()) + len(self._granted)

    def _depth_locked(self) -> int:
        return self._inflight + self._waiting_locked()

    def _publish_depth_locked(self) -> None:
        SOLVER_QUEUE_DEPTH.set(
            float(self._depth_locked()), {"gate": self.name}
        )

    def _retry_after_locked(self, key: str = _NO_TENANT) -> float:
        """Per-tenant retry-after hint: the requesting tenant's OWN queue
        depth x its OWN service-time EMA (global EMA, then a 0.25 s prior,
        as cold-start fallbacks) — one tenant's 10x-sized solves no longer
        poison the hint for everyone."""
        est = self._tenant_ema.get(key)
        if est is None:
            est = self._ema if self._ema is not None else 0.25
        depth = (
            len(self._queues.get(key, ()))
            + self._inflight + len(self._granted)
        )
        return min(5.0, (depth + 1) * est)

    def _weight_of(self, key: str) -> float:
        try:
            w = float(self.weights.get(key, 1.0))
        except (TypeError, ValueError):
            w = 1.0
        return min(_MAX_WEIGHT, max(_MIN_WEIGHT, w))

    def _enqueue_locked(self, ticket: _Ticket) -> None:
        q = self._queues.get(ticket.key)
        if q is None:
            q = self._queues[ticket.key] = []
            self._ring.append(ticket.key)
        for i, other in enumerate(q):
            if ticket.order < other.order:
                q.insert(i, ticket)
                break
        else:
            q.append(ticket)

    def _retire_queue_locked(self, key: str) -> None:
        self._queues.pop(key, None)
        if key in self._ring:
            self._ring.remove(key)
        self._deficit.pop(key, None)

    def _select_locked(self) -> Optional[_Ticket]:
        """Next ticket to grant: weighted deficit-round-robin across the
        sub-queues, EDF head within each. Every visit deposits the
        tenant's weight; a dispatch spends 1.0. Weights are clamped >=
        _MIN_WEIGHT, so within ceil(1/_MIN_WEIGHT) full rotations some
        queue accumulates a full credit — the visit bound is a hard
        guarantee, not a hope."""
        ring = self._ring
        max_visits = (int(1.0 / _MIN_WEIGHT) + 1) * max(1, len(ring)) + 1
        for _ in range(max_visits):
            if not ring:
                return None
            key = ring[0]
            q = self._queues.get(key)
            if not q:
                self._retire_queue_locked(key)
                continue
            credit = self._deficit.get(key, 0.0) + self._weight_of(key)
            if credit >= 1.0:
                self._deficit[key] = credit - 1.0
                ticket = q.pop(0)
                if not q:
                    self._retire_queue_locked(key)
                else:
                    ring.append(ring.pop(0))
                return ticket
            self._deficit[key] = credit
            ring.append(ring.pop(0))
        return None  # unreachable: the clamp bounds rotations-to-credit

    def _grant_locked(self) -> None:
        granted = False
        while self._inflight + len(self._granted) < self.max_inflight:
            ticket = self._select_locked()
            if ticket is None:
                break
            self._granted.add(ticket)
            granted = True
        if granted:
            self._cond.notify_all()

    def _abandon_locked(self, ticket: _Ticket) -> None:
        if ticket in self._granted:
            self._granted.discard(ticket)
            return
        q = self._queues.get(ticket.key)
        if q is not None:
            try:
                q.remove(ticket)
            except ValueError:
                pass
            if not q:
                self._retire_queue_locked(ticket.key)

    def _tenant_enter_locked(self, tenant: str) -> None:
        label = reqctx.TENANTS.admit(tenant)
        depth = self._tenant_depth.get(label, 0) + 1
        self._tenant_depth[label] = depth
        SOLVER_QUEUE_DEPTH.set(
            float(depth),
            {"gate": self.name, "tenant": reqctx.TENANTS.admit(tenant)},
        )

    def _tenant_exit_locked(self, tenant: str) -> None:
        label = reqctx.TENANTS.admit(tenant)
        depth = self._tenant_depth.get(label, 0) - 1
        if depth <= 0:
            self._tenant_depth.pop(label, None)
            SOLVER_QUEUE_DEPTH.delete(
                {"gate": self.name, "tenant": reqctx.TENANTS.admit(tenant)}
            )
        else:
            self._tenant_depth[label] = depth
            SOLVER_QUEUE_DEPTH.set(
                float(depth),
                {"gate": self.name, "tenant": reqctx.TENANTS.admit(tenant)},
            )

    def _shed_locked(self, reason: str, retry_after: Optional[float],
                     detail: str, tenant: Optional[str] = None):
        self._shed_counts[reason] = self._shed_counts.get(reason, 0) + 1
        key = (
            reqctx.TENANTS.admit(tenant) if tenant is not None else _NO_TENANT
        )
        by = self._shed_by.setdefault(key, {})
        by[reason] = by.get(reason, 0) + 1
        if tenant is not None:
            SOLVER_SHED_TOTAL.inc({
                "gate": self.name, "reason": reason,
                "tenant": reqctx.TENANTS.admit(tenant),
            })
        else:
            SOLVER_SHED_TOTAL.inc({"gate": self.name, "reason": reason})
        if reason == "deadline_expired":
            err: Exception = SolverDeadlineExceededError(detail)
        else:
            err = SolverResourceExhaustedError(detail)
        err.shed_reason = reason
        err.retry_after_s = retry_after
        return err

    def _expired_locked(self, deadline_s: Optional[float], where: str,
                        tenant: Optional[str]):
        """Queue-expiry shed, attributed: PR 16's deadline-violations
        counter gains a ``stage="queue"`` series here, carrying the tenant
        whose request overran its budget while waiting — distinct from the
        structurally-zero ``stage="dispatch"`` series dashboards page on."""
        key = (
            reqctx.TENANTS.admit(tenant) if tenant is not None else _NO_TENANT
        )
        self._expired_in_queue[key] = self._expired_in_queue.get(key, 0) + 1
        if tenant is not None:
            DEADLINE_VIOLATIONS_TOTAL.inc({
                "gate": self.name, "stage": "queue",
                "tenant": reqctx.TENANTS.admit(tenant),
            })
        else:
            DEADLINE_VIOLATIONS_TOTAL.inc(
                {"gate": self.name, "stage": "queue"}
            )
        budget = f"{deadline_s:.2f}s" if deadline_s is not None else "its"
        return self._shed_locked(
            "deadline_expired", None,
            f"deadline expired after {budget} budget {where}",
            tenant=tenant,
        )

    def _brownout_sheds(self, tenant: Optional[str]) -> bool:
        """Whether this request sheds in the brownout band. No preference
        hook (the default): everyone sheds, the pre-hook behavior. With a
        hook (e.g. SloEngine.budget_exhausted), only tenants whose error
        budget is spent shed early — everyone else rides through to the
        hard queue_full bound. Hook failures fail closed (shed): brownout
        exists to protect the device, not to be polite."""
        prefer = self.brownout_prefer
        if prefer is None:
            return True
        try:
            return bool(prefer(tenant))
        except Exception:  # noqa: BLE001 — a sick hook must not widen admission
            return True

    # -- the gate ------------------------------------------------------------

    @contextlib.contextmanager
    def admitted(self, deadline_s: Optional[float] = None):
        """Admit one dispatch. ``deadline_s`` is the request's remaining
        budget in seconds (None = no deadline; a bound
        ``RequestContext.deadline_s`` tightens it). Yields the remaining
        budget at DISPATCH time (never <= 0 — an expired request raises
        instead). Dispatch order is weighted-fair across tenants and EDF
        within one, not FIFO. Raises typed RESOURCE_EXHAUSTED /
        DEADLINE_EXCEEDED on shed; the dispatch itself runs outside the
        gate's lock."""
        tenant = reqctx.current_tenant()
        try:
            # flood injection (chaos `solver.gate.flood`): the armed fault
            # does NOT error the request — it re-attributes it to one
            # synthetic flooding tenant, so arming `p:<frac>` mid-churn
            # turns that fraction of live traffic into a flood that must
            # trip quota/brownout isolation without touching the real
            # tenants' accounting
            chaos.maybe_fail(chaos.SOLVER_GATE_FLOOD)
        except Exception:
            tenant = CHAOS_FLOOD_TENANT
        try:
            # queue-full injection (chaos `solver.rpc.overload`): the
            # injected typed error rides the same shed accounting a real
            # full queue produces
            chaos.maybe_fail(chaos.SOLVER_RPC_OVERLOAD)
        except Exception:
            with self._cond:
                self._shed_counts["injected"] = (
                    self._shed_counts.get("injected", 0) + 1
                )
                key = (
                    reqctx.TENANTS.admit(tenant)
                    if tenant is not None else _NO_TENANT
                )
                by = self._shed_by.setdefault(key, {})
                by["injected"] = by.get("injected", 0) + 1
            if tenant is not None:
                SOLVER_SHED_TOTAL.inc({
                    "gate": self.name, "reason": "injected",
                    "tenant": reqctx.TENANTS.admit(tenant),
                })
            else:
                SOLVER_SHED_TOTAL.inc({"gate": self.name, "reason": "injected"})
            raise
        clock = self._clock
        entered = clock()
        ctx_deadline = reqctx.current_deadline()
        if ctx_deadline is not None:
            deadline_s = (
                ctx_deadline if deadline_s is None
                else min(deadline_s, ctx_deadline)
            )
        deadline = entered + deadline_s if deadline_s is not None else None
        label = reqctx.TENANTS.admit(tenant) if tenant is not None else None
        key = label if label is not None else _NO_TENANT
        ladder = self.ladder
        if ladder is not None and label is not None:
            rung = ladder.review(label)
            if rung != "device":
                with self._cond:
                    if rung == "shed":
                        raise self._shed_locked(
                            "brownout_shed", ladder.hold_s,
                            "tenant browned out (ladder rung shed, "
                            "burn-driven): hard shed; retry_after_ms="
                            f"{int(ladder.hold_s * 1000)}",
                            tenant=tenant,
                        )
                    ra = self._retry_after_locked(key)
                    raise self._shed_locked(
                        "brownout", ra,
                        "tenant browned out (ladder rung greedy, "
                        "burn-driven): serve the local fallback; "
                        f"retry_after_ms={int(ra * 1000)}",
                        tenant=tenant,
                    )
        with self._cond:
            # max_queue bounds WAITERS: a request the idle gate can
            # dispatch immediately never sheds (max_queue=0 = "busy means
            # shed", not "never admit")
            must_wait = (
                self._inflight >= self.max_inflight
                or bool(self._granted) or bool(self._queues)
            )
            waiting = self._waiting_locked()
            if must_wait and waiting >= self.max_queue:
                ra = self._retry_after_locked(key)
                raise self._shed_locked(
                    "queue_full", ra,
                    f"solver admission queue full "
                    f"({waiting} queued, max {self.max_queue}); "
                    f"retry_after_ms={int(ra * 1000)}",
                    tenant=tenant,
                )
            quota = self.tenant_quota
            if quota is not None and must_wait:
                mine = len(self._queues.get(key, ()))
                if mine >= quota:
                    ra = self._retry_after_locked(key)
                    raise self._shed_locked(
                        "tenant_quota", ra,
                        f"per-tenant admission quota full "
                        f"({mine} queued for this tenant, quota {quota}); "
                        f"retry_after_ms={int(ra * 1000)}",
                        tenant=tenant,
                    )
            if (
                self.brownout_at is not None
                and self._depth_locked() >= self.brownout_at
                and self._brownout_sheds(tenant)
            ):
                ra = self._retry_after_locked(key)
                raise self._shed_locked(
                    "brownout", ra,
                    f"solver admission brownout (depth "
                    f"{self._depth_locked()} >= {self.brownout_at}): "
                    "serve the local fallback; retry_after_ms="
                    f"{int(ra * 1000)}",
                    tenant=tenant,
                )
            ticket = _Ticket(key, deadline, next(self._seq))
            self._enqueue_locked(ticket)
            self.accepted_total += 1
            if tenant is not None:
                self._tenant_enter_locked(tenant)
            self._publish_depth_locked()
            self._grant_locked()
            try:
                while ticket not in self._granted:
                    timeout = 0.5
                    if deadline is not None:
                        remaining = deadline - clock()
                        if remaining <= 0:
                            raise self._expired_locked(
                                deadline_s,
                                "while queued; never dispatched", tenant,
                            )
                        timeout = min(timeout, remaining)
                    self._cond.wait(timeout)
                self._granted.discard(ticket)
                # the final pre-dispatch check: an ACCEPTED request must
                # never reach the device past its deadline
                if deadline is not None and deadline - clock() <= 0:
                    raise self._expired_locked(
                        deadline_s, "at dispatch; never dispatched", tenant,
                    )
            except BaseException:
                self._abandon_locked(ticket)
                if tenant is not None:
                    self._tenant_exit_locked(tenant)
                self._publish_depth_locked()
                # the abandoned slot (or grant) must pass to someone else
                self._grant_locked()
                self._cond.notify_all()
                raise
            self._inflight += 1
            self.dispatched_total += 1
            self._dispatched_by[key] = self._dispatched_by.get(key, 0) + 1
            self._publish_depth_locked()
        t0 = clock()
        try:
            if tenant is not None:
                SOLVER_QUEUE_WAIT.observe(t0 - entered, {
                    "gate": self.name,
                    "tenant": reqctx.TENANTS.admit(tenant),
                })
            else:
                SOLVER_QUEUE_WAIT.observe(t0 - entered, {"gate": self.name})
            remaining = (deadline - t0) if deadline is not None else None
            if remaining is not None and remaining <= 0:
                # the structural invariant ("never dispatched past the
                # deadline") broke between the final pre-dispatch check
                # and here — count it where dashboards can page on it,
                # then shed instead of burning device time on dead work
                with self._cond:
                    self.deadline_violations += 1
                    err = self._shed_locked(
                        "deadline_expired", None,
                        f"deadline expired after {deadline_s:.2f}s budget "
                        "between admission and dispatch",
                        tenant=tenant,
                    )
                if tenant is not None:
                    DEADLINE_VIOLATIONS_TOTAL.inc({
                        "gate": self.name, "stage": "dispatch",
                        "tenant": reqctx.TENANTS.admit(tenant),
                    })
                else:
                    DEADLINE_VIOLATIONS_TOTAL.inc(
                        {"gate": self.name, "stage": "dispatch"}
                    )
                raise err
            yield remaining
        finally:
            dt = clock() - t0
            with self._cond:
                self._inflight -= 1
                if tenant is not None:
                    self._tenant_exit_locked(tenant)
                self._ema = (
                    dt if self._ema is None else 0.8 * self._ema + 0.2 * dt
                )
                if label is not None:
                    prev = self._tenant_ema.get(label)
                    self._tenant_ema[label] = (
                        dt if prev is None else 0.8 * prev + 0.2 * dt
                    )
                self._publish_depth_locked()
                self._grant_locked()
                self._cond.notify_all()

    def admission_totals(self) -> Dict[Optional[str], Tuple[int, int]]:
        """(good, total) admission outcomes per guarded tenant label, plus
        a ``None`` aggregate — the SLO engine's ``collect`` source for a
        ratio objective over the gate itself. good = dispatched; bad =
        capacity sheds (queue_full, tenant_quota) plus in-queue deadline
        expiries. Ladder/hook-driven sheds (brownout, brownout_shed) and
        chaos injections are EXCLUDED on purpose: while a tenant is
        demoted its residual traffic sheds at the ladder, and counting
        those sheds as burn would hold the burn rate above the promote
        threshold forever — the closed loop must be able to see the flood
        stop."""
        bad_reasons = ("queue_full", "tenant_quota", "deadline_expired")
        with self._cond:
            out: Dict[Optional[str], Tuple[int, int]] = {}
            agg_good = agg_bad = 0
            for key in set(self._dispatched_by) | set(self._shed_by):
                good = self._dispatched_by.get(key, 0)
                by = self._shed_by.get(key, {})
                bad = sum(by.get(r, 0) for r in bad_reasons)
                agg_good += good
                agg_bad += bad
                if key != _NO_TENANT and (good or bad):
                    out[key] = (good, good + bad)
            out[None] = (agg_good, agg_good + agg_bad)
            return out

    def stats(self) -> Dict[str, object]:
        ladder = self.ladder
        ladder_stats = ladder.stats() if ladder is not None else None
        with self._cond:
            return {
                "name": self.name,
                "inflight": self._inflight,
                "queued": self._waiting_locked(),
                "max_queue": self.max_queue,
                "tenant_quota": self.tenant_quota,
                "brownout_at": self.brownout_at,
                "accepted_total": self.accepted_total,
                "dispatched_total": self.dispatched_total,
                "shed": dict(self._shed_counts),
                "tenants": dict(self._tenant_depth),
                "deadline_violations": self.deadline_violations,
                "service_ema_s": (
                    round(self._ema, 4) if self._ema is not None else None
                ),
                # fair-share plane (sub-queue keys: guarded tenant labels;
                # "" is the unbound-request queue)
                "queues": {k: len(q) for k, q in self._queues.items()},
                "weights": dict(self.weights),
                "service_ema_by_tenant": {
                    k: round(v, 4) for k, v in self._tenant_ema.items()
                },
                "dispatched_by_tenant": {
                    k: v for k, v in self._dispatched_by.items()
                    if k != _NO_TENANT
                },
                "shed_by_tenant": {
                    k: dict(v) for k, v in self._shed_by.items()
                    if k != _NO_TENANT
                },
                "expired_in_queue": {
                    k: v for k, v in self._expired_in_queue.items()
                    if k != _NO_TENANT
                },
                "ladder": ladder_stats,
            }


# ---------------------------------------------------------------------------
# frame protocol (length-prefixed header JSON + body bytes)


def _write_frame(stream, header: Dict[str, object], body: bytes = b"") -> None:
    hdr = json.dumps(header, sort_keys=True).encode()
    stream.write(struct.pack(">II", len(hdr), len(body)))
    stream.write(hdr)
    if body:
        stream.write(body)
    stream.flush()


def _read_exact(stream, n: int) -> bytes:
    chunks = []
    while n > 0:
        chunk = stream.read(n)
        if not chunk:
            raise EOFError("solver host stream closed")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def _read_frame(stream) -> Tuple[Dict[str, object], bytes]:
    hdr_len, body_len = struct.unpack(">II", _read_exact(stream, 8))
    header = json.loads(_read_exact(stream, hdr_len).decode())
    body = _read_exact(stream, body_len) if body_len else b""
    return header, body


class _PipeReader:
    """Deadline-aware reader over the child's stdout fd: select-slices the
    wait so the caller's ``on_tick`` hook (heartbeat staleness, budget,
    child liveness) runs between blocks. Raises EOFError on a closed
    pipe."""

    def __init__(self, f):
        self._fd = f.fileno()
        self._buf = b""

    def read_frame(self, on_tick=None, poll_s: float = 0.25):
        while True:
            if len(self._buf) >= 8:
                hdr_len, body_len = struct.unpack(">II", self._buf[:8])
                total = 8 + hdr_len + body_len
                if len(self._buf) >= total:
                    raw = self._buf[:total]
                    self._buf = self._buf[total:]
                    header = json.loads(raw[8:8 + hdr_len].decode())
                    return header, raw[8 + hdr_len:total]
            ready, _, _ = select.select([self._fd], [], [], poll_s)
            if ready:
                chunk = os.read(self._fd, 1 << 16)
                if not chunk:
                    raise EOFError("solver host stdout closed")
                self._buf += chunk
            elif on_tick is not None:
                on_tick()


# ---------------------------------------------------------------------------
# parent: the supervised host process


class SolverHost:
    """Spawn/supervise/kill the sidecar dispatch process.

    One dispatch in flight at a time (the device is serial); while one is,
    the watchdog reads the heartbeat FILE the child's phase marks touch —
    staleness past ``stale_after`` is a WEDGE (kill the whole process
    group NOW), budget overrun past ``solve_timeout`` is SLOW (same kill,
    different classification: the zombie dies either way, the breaker/
    metrics story distinguishes them). Every kill respawns eagerly so the
    ResilientSolver breaker's half-open probe finds a live host."""

    def __init__(self, *, stale_after: Optional[float] = 600.0,
                 solve_timeout: float = 600.0, spawn_timeout: float = 180.0,
                 probe_timeout: float = 30.0, poll_s: float = 0.25,
                 child_env: Optional[Dict[str, str]] = None,
                 workdir: Optional[str] = None):
        self.stale_after = stale_after
        self.solve_timeout = solve_timeout
        self.spawn_timeout = spawn_timeout
        self.probe_timeout = probe_timeout
        self.poll_s = poll_s
        self.child_env = dict(child_env or {})
        self.workdir = workdir or tempfile.mkdtemp(prefix="kct-solver-host-")
        self.generation = 0
        self.respawns = 0
        self.last_recovery_s: Optional[float] = None
        self.last_kill: Optional[Dict[str, object]] = None
        self._proc: Optional[subprocess.Popen] = None
        self._reader: Optional[_PipeReader] = None
        self._ready = False
        self._hb_path = ""
        self._stderr_path = ""
        self._spawned_at = 0.0
        self._seq = itertools.count(1)
        # merged child-process metrics (ISSUE 15): cumulative counter/
        # histogram snapshots ride every solve/replan/stats response frame;
        # the merger folds them per generation (a dead generation's last
        # snapshot commits exactly once — no double counting across
        # respawns) and registers as an exposition source on first ingest,
        # so the parent /metrics carries the child's series under
        # process="solver-host"
        self.metrics = ProcessSeriesMerger("solver-host")
        self._metrics_registered = False
        # merged child compiled-program inventory (ISSUE 18): snapshots
        # ride the same response/stats frames as the metrics, fold per
        # generation under the identical respawn-idempotency contract, and
        # surface in the unified /debug/programs view under
        # process="solver-host"
        self.programs = proghealth.ProgramInventoryMerger("solver-host")
        self._programs_registered = False
        # serializes frame exchanges (one in-flight dispatch)
        self._mu = threading.Lock()
        # leaf lock for the lifecycle METADATA (generation/_proc/_ready/
        # respawns/last_kill/last_recovery_s/_hb_path): report()/alive()/
        # pid run on health threads and must never wait on _mu — a
        # dispatch holds _mu for its whole budget. Every access to those
        # fields goes through _meta_mu (racewatch, ISSUE 13); order is
        # always _mu -> _meta_mu, never the reverse.
        self._meta_mu = threading.Lock()

    # -- lifecycle -----------------------------------------------------------

    def _spawn_locked(self) -> None:
        with self._meta_mu:
            self.generation += 1
            gen = self.generation
            hb_path = os.path.join(self.workdir, f"hb-{gen}")
            self._hb_path = hb_path
        self._stderr_path = os.path.join(self.workdir, f"stderr-{gen}.log")
        env = dict(envflags.environ())
        env.update(self.child_env)
        # the child must never recurse into building its own host
        env["KARPENTER_SOLVER_HOST"] = "off"
        # trace enablement follows the PARENT (the operator arms tracing
        # programmatically, not via env): an unset child env inherits the
        # parent tracer's current state so span export works out of the
        # box; an explicit KARPENTER_TPU_TRACE (env or child_env) wins
        if not env.get("KARPENTER_TPU_TRACE"):
            env["KARPENTER_TPU_TRACE"] = "1" if TRACER.enabled else "0"
        stderr_f = open(self._stderr_path, "wb")
        try:
            proc = subprocess.Popen(
                [sys.executable, "-m", "karpenter_core_tpu.solver.host",
                 "--heartbeat", hb_path],
                stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                stderr=stderr_f, env=env, start_new_session=True,
            )
        finally:
            stderr_f.close()
        self._reader = _PipeReader(proc.stdout)
        self._spawned_at = time.monotonic()
        with self._meta_mu:
            self._proc = proc
            self._ready = False
            if gen > 1:
                self.respawns += 1
        TRACER.instant(
            "solver.host.spawn", pid=proc.pid, generation=gen,
        )
        LOG.info(
            "solver host spawned", pid=proc.pid, generation=gen,
        )

    def _stderr_tail(self) -> str:
        tail = supervise.tail_bytes_of(self._stderr_path, 4096)
        return supervise.redact_env_text(tail) if tail else ""

    def _kill_locked(self, kind: str, note: str, respawn: bool = True,
                     salvage: bool = False) -> None:
        with self._meta_mu:
            proc = self._proc
            hb_path = self._hb_path
        phase = supervise.Heartbeat(hb_path).read_label() if hb_path else ""
        if proc is not None:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
            try:
                proc.wait(timeout=30)
            except (subprocess.TimeoutExpired, OSError):
                pass
            for stream in (proc.stdin, proc.stdout):
                try:
                    if stream is not None:
                        stream.close()
                except OSError:
                    pass
        tail = self._stderr_tail()
        with self._meta_mu:
            gen = self.generation
            self.last_kill = {
                "generation": gen,
                "kind": kind,
                "note": note,
                "phase": phase,
                "stderr_tail": tail,
            }
            self._proc = None
            self._ready = False
        self._reader = None
        # commit the dead child's last metrics snapshot exactly once: the
        # respawned generation counts from zero ON TOP of it
        self.metrics.retire(gen)
        # same contract for the program inventory: the dead generation's
        # cumulative compile seconds fold into the base exactly once; its
        # live program entries died with the process
        self.programs.retire(gen)
        if salvage:
            # mid-dispatch kill: the response frame (and its span delta)
            # never arrived — graft what the child spilled beside its
            # heartbeat, so the timeline shows the phases of the dispatch
            # that died (tagged salvaged; ISSUE 15)
            self._salvage_spans(gen, proc.pid if proc is not None else None)
        # the kill is an instant event on the solve timeline, naming the
        # phase the child died in — the wedge post-mortem's first fact
        TRACER.instant(
            "solver.host.kill", kind=kind, generation=gen, phase=phase,
        )
        if respawn:
            HOST_RESPAWN_TOTAL.inc({"reason": kind})
        LOG.warning(
            "solver host killed", kind=kind, note=note,
            generation=gen, phase=phase,
        )
        if respawn:
            # eager respawn: the breaker's half-open trial must find a
            # live host to probe — "re-admission = host respawned AND
            # probe passed"
            self._spawn_locked()

    def _spill_path(self) -> str:
        with self._meta_mu:
            hb_path = self._hb_path
        return f"{hb_path}.spans" if hb_path else ""

    def _salvage_spans(self, generation: int, pid: Optional[int]) -> None:
        """Graft the killed child's span spill (best-effort): the file is
        the child tracer's bounded ring of finished solver.* spans since
        its dispatch started, atomically rewritten per span — the last
        thing it proved before going silent."""
        path = self._spill_path()
        if not path:
            return
        try:
            with open(path, "rb") as f:
                payload = json.loads(f.read().decode())
        except (OSError, ValueError):
            return
        try:
            os.unlink(path)  # salvage once — never re-graft on a later kill
        except OSError:
            pass
        TRACER.graft(
            payload, pid=pid, generation=generation, salvaged=True,
        )

    def close(self) -> None:
        """Shut the host down (process-group kill; no respawn)."""
        with self._mu:
            if self._metrics_registered:
                REGISTRY.remove_external(self.metrics)
                self._metrics_registered = False
            proc = self._proc_get()
            if proc is None:
                return
            try:
                _write_frame(proc.stdin, {"op": "shutdown", "id": 0})
            except (OSError, ValueError):
                pass
            self._kill_locked("shutdown", "close() called", respawn=False)

    def _proc_get(self) -> Optional[subprocess.Popen]:
        with self._meta_mu:
            return self._proc

    @property
    def pid(self) -> Optional[int]:
        proc = self._proc_get()
        return proc.pid if proc is not None else None

    def alive(self) -> bool:
        proc = self._proc_get()
        return proc is not None and proc.poll() is None

    def heartbeat_age(self) -> Optional[float]:
        with self._meta_mu:
            hb_path = self._hb_path
        if not hb_path:
            return None
        return supervise.Heartbeat(hb_path).age()

    # -- readiness -----------------------------------------------------------

    def _ensure_running_locked(self) -> None:
        proc = self._proc_get()
        if proc is not None and proc.poll() is not None:
            rc = proc.poll()
            self._kill_locked("crashed", f"host exited rc={rc} between dispatches")
        if self._proc_get() is None:
            self._spawn_locked()
        with self._meta_mu:
            ready = self._ready
        if not ready:
            self._wait_ready_locked()

    def _wait_ready_locked(self) -> None:
        deadline = time.monotonic() + self.spawn_timeout

        def tick():
            proc = self._proc_get()
            if proc is None or proc.poll() is not None:
                raise EOFError("solver host died before ready")
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"solver host not ready within {self.spawn_timeout:.0f}s"
                )

        try:
            while True:
                header, _body = self._reader.read_frame(
                    on_tick=tick, poll_s=self.poll_s
                )
                if header.get("op") == "ready":
                    break
        except (EOFError, TimeoutError, OSError) as e:
            tail = self._stderr_tail()
            self._kill_locked("crashed", f"never became ready: {e}")
            raise SolverUnavailableError(
                f"solver host failed to start: {e}"
                + (f"; stderr tail: {tail[-500:]}" if tail else "")
            ) from e
        recovery = time.monotonic() - self._spawned_at
        with self._meta_mu:
            self._ready = True
            self.last_recovery_s = recovery
            gen = self.generation
        HOST_RECOVERY_SECONDS.set(recovery)
        LOG.info(
            "solver host ready", pid=self.pid, generation=gen,
            recovery_s=round(recovery, 2),
        )

    def ensure_running(self) -> None:
        with self._mu:
            self._ensure_running_locked()

    # -- dispatch ------------------------------------------------------------

    def call(self, op: str, body: bytes = b"",
             expires_in_s: Optional[float] = None,
             timeout: Optional[float] = None,
             watch_heartbeat: bool = True) -> Tuple[Dict[str, object], bytes]:
        """One request/response exchange. Kills + respawns the host on
        heartbeat staleness (SolverWedgedError), budget overrun
        (TimeoutError — the process is killed, nothing leaks), or death
        (SolverUnavailableError)."""
        with self._mu:
            return self._call_locked(
                op, body, expires_in_s, timeout, watch_heartbeat
            )

    def _call_locked(self, op: str, body: bytes,
                     expires_in_s: Optional[float],
                     timeout: Optional[float],
                     watch_heartbeat: bool) -> Tuple[Dict[str, object], bytes]:
        self._ensure_running_locked()
        proc = self._proc_get()
        rid = next(self._seq)
        header: Dict[str, object] = {"op": op, "id": rid}
        if expires_in_s is not None:
            header["expires_in_s"] = round(float(expires_in_s), 3)
        # trace propagation over the frame protocol (ISSUE 15): the
        # parent's trace id rides the request header — the same contract
        # as the gRPC x-karpenter-trace-id metadata — and its PRESENCE is
        # the span-export request. Tracing off = no key = zero extra frame
        # bytes (one enabled check per dispatch, tripwired).
        if TRACER.enabled:
            header["trace"] = TRACER.current_trace_id() or ""
        # tenant propagation (ISSUE 16): same absent-key contract as the
        # trace key — no bound tenant = no key = byte-identical header to
        # the PR 15 protocol (tripwired in test_perf_floor.py). The child
        # binds a RequestContext from it so its spans, flight records, and
        # metric series attribute to the same tenant as the parent's.
        tenant = reqctx.current_tenant()
        if tenant is not None:
            header["tenant"] = tenant
        try:
            _write_frame(proc.stdin, header, body)
        except (OSError, ValueError) as e:
            rc = proc.poll()
            self._kill_locked("crashed", f"write failed ({e}), rc={rc}")
            raise SolverUnavailableError(
                f"solver host crashed before dispatch (rc={rc})"
            ) from e
        # the injected host crash (chaos `solver.host.crash`): SIGKILL
        # the group mid-dispatch so the drill exercises the REAL death
        # path (EOF detection, respawn, typed error), not a shortcut
        try:
            chaos.maybe_fail(chaos.SOLVER_HOST_CRASH)
        except Exception:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
        budget = timeout if timeout is not None else self.solve_timeout
        deadline = time.monotonic() + budget
        with self._meta_mu:
            hb_path = self._hb_path
        hb = supervise.Heartbeat(hb_path)
        dispatch_start = time.monotonic()

        def tick():
            if proc.poll() is not None:
                raise EOFError(f"rc={proc.poll()}")
            now = time.monotonic()
            if watch_heartbeat and self.stale_after is not None:
                age = hb.age()
                silent = (
                    age if age is not None else now - dispatch_start
                )
                if silent >= self.stale_after:
                    raise _Wedge(silent)
            if now >= deadline:
                raise _Overrun(budget)

        try:
            while True:
                rheader, rbody = self._reader.read_frame(
                    on_tick=tick, poll_s=self.poll_s
                )
                if rheader.get("op") == "ready":
                    continue  # a respawn raced this call; skip
                if rheader.get("id") == rid:
                    self._fold_response_locked(rheader)
                    return rheader, rbody
                # a stale response from a pre-kill request: drop it
        except _Wedge as w:
            phase = hb.read_label()
            self._kill_locked(
                "wedged",
                f"dispatch heartbeat stale for {w.age:.1f}s "
                f"(threshold {self.stale_after:.1f}s)"
                + (f" during {phase}" if phase else ""),
                salvage=True,
            )
            raise SolverWedgedError(
                f"solver host dispatch heartbeat stale for "
                f"{w.age:.0f}s (threshold {self.stale_after:.0f}s)"
                + (f" during {phase}" if phase else "")
                + ": host process group killed and respawned "
                f"(generation {self._generation_get()})"
            ) from None
        except _Overrun as o:
            self._kill_locked(
                "timeout",
                f"dispatch exceeded {o.budget:.1f}s budget "
                "(heartbeat fresh — slow, not wedged)",
                salvage=True,
            )
            raise TimeoutError(
                f"solver host dispatch exceeded {o.budget:.0f}s budget: "
                "host process group killed and respawned "
                f"(generation {self._generation_get()})"
            ) from None
        except (EOFError, OSError) as e:
            tail = self._stderr_tail()
            self._kill_locked(
                "crashed", f"died mid-dispatch: {e}", salvage=True
            )
            raise SolverUnavailableError(
                f"solver host crashed mid-dispatch ({e}); respawned as "
                f"generation {self._generation_get()}"
                + (f"; stderr tail: {tail[-500:]}" if tail else "")
            ) from e

    def _fold_response_locked(self, rheader: Dict[str, object]) -> None:
        """Fold a response frame's observability payloads into the parent:
        the child's span delta grafts under the calling thread's live span
        (`solver.host.request` on the dispatch path) tagged pid/generation,
        and the cumulative metrics snapshot feeds the per-generation
        merger. Both are absent-tolerant — an old child or a tracing-off
        exchange simply carries neither key."""
        gen = self._generation_get()
        spans = rheader.get("spans")
        if spans:
            try:
                TRACER.graft(spans, generation=gen)
            except Exception:  # noqa: BLE001 — observability must never fail a solve
                pass
        families = rheader.get("metrics")
        if families:
            try:
                if not self._metrics_registered:
                    REGISTRY.add_external(self.metrics)
                    self._metrics_registered = True
                self.metrics.ingest(gen, families)
            except Exception:  # noqa: BLE001
                pass
        programs = rheader.get("programs")
        if programs:
            try:
                if not self._programs_registered:
                    proghealth.add_source(
                        "solver-host", self.programs.snapshot
                    )
                    proghealth.ensure_exposition_registered()
                    self._programs_registered = True
                self.programs.ingest(gen, programs)
            except Exception:  # noqa: BLE001
                pass

    def _generation_get(self) -> int:
        with self._meta_mu:
            return self.generation

    def probe(self, timeout: Optional[float] = None) -> Dict[str, object]:
        """Health round trip — the breaker's half-open trial: ensure the
        host is (re)spawned, exchange a health frame, raise on anything
        unhealthy. While a dispatch is in flight, a FRESH heartbeat is
        proof of life (the service-side wedge-gate analog): busy-but-
        progressing reports healthy without waiting for the device."""
        timeout = timeout if timeout is not None else self.probe_timeout
        acquired = self._mu.acquire(timeout=min(timeout, 1.0))
        if not acquired:
            age = self.heartbeat_age()
            with self._meta_mu:
                hb_path = self._hb_path
            phase = (
                supervise.Heartbeat(hb_path).read_label() if hb_path else ""
            )
            if (
                self.stale_after is not None
                and age is not None
                and age >= self.stale_after
            ):
                raise SolverUnavailableError(
                    f"solver host busy with a dispatch whose heartbeat is "
                    f"stale ({age:.0f}s)"
                    + (f" during {phase}" if phase else "")
                )
            return {
                "status": "busy", "heartbeat_age_s": age,
                "heartbeat_phase": phase,
            }
        try:
            # the whole probe runs under this ONE bounded acquire: going
            # back through call() would re-take the lock unbounded and a
            # long in-flight dispatch could pin the prober far past its
            # budget
            header, body = self._call_locked(
                "health", b"", None, timeout, False
            )
        finally:
            self._mu.release()
        if not header.get("ok"):
            raise SolverUnavailableError(
                f"solver host health failed: {header.get('error')}"
            )
        info = json.loads(body.decode()) if body else {}
        status = info.get("status", "")
        if status != "ok":
            raise SolverUnavailableError(f"solver host unhealthy: {status}")
        return info

    def stats(self) -> Dict[str, object]:
        header, body = self.call(
            "stats", timeout=self.probe_timeout, watch_heartbeat=False
        )
        if not header.get("ok"):
            raise SolverUnavailableError(
                f"solver host stats failed: {header.get('error')}"
            )
        return json.loads(body.decode()) if body else {}

    def report(self) -> Dict[str, object]:
        """/debug/health payload: pid/generation/liveness/respawn counts.
        Reads only — no frame exchange, and never a wait on the dispatch
        lock: the metadata snapshot comes off the leaf _meta_mu in one
        critical section, so a concurrent respawn can't tear the view
        (None mid-kill is exactly when this report matters most)."""
        with self._meta_mu:
            proc = self._proc
            generation = self.generation
            ready = self._ready
            respawns = self.respawns
            recovery = self.last_recovery_s
            last_kill = self.last_kill
            hb_path = self._hb_path
        hb = supervise.Heartbeat(hb_path) if hb_path else None
        age = hb.age() if hb is not None else None
        return {
            "pid": proc.pid if proc is not None else None,
            "generation": generation,
            "alive": proc is not None and proc.poll() is None,
            "ready": ready,
            "respawn_total": respawns,
            "last_recovery_s": (
                round(recovery, 3) if recovery is not None else None
            ),
            "heartbeat_age_s": round(age, 3) if age is not None else None,
            "heartbeat_phase": hb.read_label() if hb is not None else "",
            "stale_after_s": self.stale_after,
            "solve_timeout_s": self.solve_timeout,
            "last_kill": last_kill,
        }


class _Wedge(Exception):
    def __init__(self, age: float):
        self.age = age


class _Overrun(Exception):
    def __init__(self, budget: float):
        self.budget = budget


# ---------------------------------------------------------------------------
# the in-process Solver facade


class HostSolver:
    """Solver interface over the supervised sidecar: encode locally, solve
    in the host process, decode locally — RemoteSolver's shape, with the
    pipe + heartbeat watchdog + admission gate where the gRPC channel +
    breaker would be. ResilientSolver wraps this exactly as it wraps a
    RemoteSolver (``health`` is callable, so the operator wiring disables
    its own in-process wedge watchdog — staleness detection lives HERE,
    where it can actually kill the zombie)."""

    supports_batched_replan = True

    def __init__(self, max_nodes: int = 1024,
                 max_relax_rounds: Optional[int] = None,
                 solve_timeout: float = 600.0,
                 stale_after: Optional[float] = 600.0,
                 spawn_timeout: float = 180.0,
                 max_queue: int = 8, brownout_at: Optional[int] = None,
                 queue_deadline_s: Optional[float] = None,
                 tenant_quota: Optional[int] = None,
                 weights: Optional[Dict[str, float]] = None,
                 child_env: Optional[Dict[str, str]] = None,
                 admission: Optional[AdmissionGate] = None,
                 host: Optional[SolverHost] = None):
        self.max_nodes = max_nodes
        if max_relax_rounds is None:
            from karpenter_core_tpu.solver.tpu_solver import (
                DEFAULT_MAX_RELAX_ROUNDS,
            )

            max_relax_rounds = DEFAULT_MAX_RELAX_ROUNDS
        self.max_relax_rounds = max_relax_rounds
        self.queue_deadline_s = queue_deadline_s
        self.host = host or SolverHost(
            stale_after=stale_after, solve_timeout=solve_timeout,
            spawn_timeout=spawn_timeout, child_env=child_env,
        )
        self.admission = admission or AdmissionGate(
            name="host", max_queue=max_queue, brownout_at=brownout_at,
            tenant_quota=tenant_quota, weights=weights,
        )
        from karpenter_core_tpu.solver.encode import EncodeReuse

        self._encode_reuse = EncodeReuse()

    # -- health / debug ------------------------------------------------------

    def health(self, timeout: float = 30.0) -> Dict[str, object]:
        """The ResilientSolver prober's entry (probe_for): respawn the
        host if it is dead, probe it, raise on failure — the breaker's
        half-open trial is literally 'host respawned and probe passed'."""
        return self.host.probe(timeout=timeout)

    def host_report(self) -> Dict[str, object]:
        report = self.host.report()
        report["admission"] = self.admission.stats()
        return report

    def close(self) -> None:
        self.host.close()

    # -- Solver interface ----------------------------------------------------

    def encode(self, pods, provisioners, instance_types, daemonset_pods=None,
               state_nodes=None, kube_client=None, cluster=None):
        from karpenter_core_tpu.solver.encode import encode_snapshot

        return encode_snapshot(
            pods, provisioners, instance_types, daemonset_pods, state_nodes,
            kube_client=kube_client, cluster=cluster,
            max_nodes=self.max_nodes, reuse=self._encode_reuse,
        )

    def _dispatch(self, op: str, request: pb.SolveRequest) -> pb.SolveResponse:
        body = request.SerializeToString()
        with self.admission.admitted(self.queue_deadline_s) as remaining:
            header, rbody = self.host.call(
                op, body, expires_in_s=remaining,
            )
        if not header.get("ok") and header.get("error"):
            return pb.SolveResponse(error=str(header["error"]))
        return pb.SolveResponse.FromString(rbody)

    def solve(self, pods, provisioners, instance_types, daemonset_pods=None,
              state_nodes=None, kube_client=None, cluster=None,
              encoded=None) -> SolveResult:
        if encoded is not None and (
            len(encoded.pods) != len(pods)
            or {id(p) for p in encoded.pods} != {id(p) for p in pods}
        ):
            raise ValueError(
                "encoded snapshot was built from a different pod batch"
            )
        relax_ctx = {"encoded": encoded}
        return solve_with_relaxation(
            lambda p: self._solve_once(
                p, provisioners, instance_types, daemonset_pods, state_nodes,
                kube_client, cluster, relax_ctx,
            ),
            pods, provisioners, instance_types, self.max_relax_rounds,
        )

    def _solve_once(self, pods, provisioners, instance_types, daemonset_pods,
                    state_nodes, kube_client, cluster,
                    relax_ctx=None) -> SolveResult:
        snap = relax_ctx.pop("encoded", None) if relax_ctx else None
        if snap is None:
            from karpenter_core_tpu.solver.encode import encode_snapshot

            with TRACER.span("solver.phase.encode", pods=len(pods)):
                snap = encode_snapshot(
                    pods, provisioners, instance_types, daemonset_pods,
                    state_nodes, kube_client=kube_client, cluster=cluster,
                    max_nodes=self.max_nodes, reuse=self._encode_reuse,
                )
        with TRACER.span("solver.phase.args"):
            args = device_args(snap, provisioners)
            request = pb.SolveRequest(
                geometry=geometry_json(snap),
                tensors=[tensor_to_pb(n, a) for n, a in _flatten_args(args)],
            )
        with TRACER.span("solver.host.request"):
            response = self._dispatch("solve", request)
        if response.error:
            raise error_from_string(response.error)
        tensors = {t.name: tensor_from_pb(t) for t in response.tensors}
        log = {
            k[len("log/"):]: v for k, v in tensors.items()
            if k.startswith("log/")
        }
        state = _StateView(
            {
                k[len("state/"):]: v for k, v in tensors.items()
                if k.startswith("state/")
            }
        )
        ptr = int(np.asarray(tensors["ptr"]).reshape(-1)[0])
        with TRACER.span("solver.phase.bind"):
            return decode_solve(snap, (log, ptr), state)

    def prewarm_snapshot(self, snap, provisioners) -> str:
        """The startup bucket-ladder prewarm (solver/prewarm.py), host
        edition: dispatch one synthetic solve at the tier's geometry so
        the CHILD compiles (or disk-loads) the solve + prescreen programs
        and writes the persistent cache — the warm-recovery budget every
        later respawn rides. Returns 'compiled' when the child paid a
        service-site cache miss, 'cached' otherwise."""
        args = device_args(snap, provisioners)
        request = pb.SolveRequest(
            geometry=geometry_json(snap),
            tensors=[tensor_to_pb(n, a) for n, a in _flatten_args(args)],
        )
        before = self.host.stats().get(
            "compile_cache_misses", {}
        ).get("service", 0)
        response = self._dispatch("solve", request)
        if response.error:
            raise error_from_string(response.error)
        after = self.host.stats().get(
            "compile_cache_misses", {}
        ).get("service", 0)
        return "compiled" if after > before else "cached"

    def replan_screen(self, snap, provisioners, count_rows, exist_open,
                      uninitialized=None, cluster=None,
                      want_slots: bool = False):
        """Batched candidate-subset evaluation through the host — the same
        wire shape as RemoteSolver.replan_screen (one pb request carrying
        the union snapshot's tensors + the [K, ...] subset planes)."""
        with TRACER.span("solver.phase.replan.args"):
            args = device_args(snap, provisioners)
            tensors = [tensor_to_pb(n, a) for n, a in _flatten_args(args)]
            E = snap.exist_used.shape[0]
            uninit = np.zeros(E, dtype=bool)
            if uninitialized is not None:
                src = np.asarray(uninitialized, dtype=bool)
                uninit[: min(len(src), E)] = src[:E]
            tensors.append(
                tensor_to_pb(
                    "replan/count_rows", np.asarray(count_rows, np.int32)
                )
            )
            tensors.append(
                tensor_to_pb("replan/exist_open", np.asarray(exist_open))
            )
            tensors.append(
                tensor_to_pb("replan/uninitialized", np.asarray(uninit))
            )
            tensors.append(
                tensor_to_pb(
                    "replan/want_slots",
                    np.asarray([1 if want_slots else 0], np.int32),
                )
            )
            request = pb.SolveRequest(
                geometry=geometry_json(snap), tensors=tensors
            )
        with TRACER.span("solver.host.replan_request"):
            response = self._dispatch("replan", request)
        if response.error:
            raise error_from_string(response.error)
        tensors = {t.name: tensor_from_pb(t) for t in response.tensors}
        verdicts = np.asarray(tensors["verdicts"])
        pods = (
            np.asarray(tensors["pods"])
            if want_slots and "pods" in tensors
            else None
        )
        return verdicts, pods


# ---------------------------------------------------------------------------
# child: the sidecar worker process


def _counter_by_label(counter, label: str) -> Dict[str, float]:
    out: Dict[str, float] = {}
    for labels, value in counter.series():
        key = labels.get(label, "")
        out[key] = out.get(key, 0.0) + value
    return out


def host_main(argv=None) -> int:
    """`python -m karpenter_core_tpu.solver.host --heartbeat <path>`: serve
    solve/replan/health/stats frames on stdin/stdout until EOF/shutdown.

    Warm recovery is this function's whole startup story: the persistent
    compile cache is enabled BEFORE any jit dispatch, so a respawned host
    reloads its geometry's compiled executables from disk instead of
    re-paying the cold compile, and the SolverService's incremental
    residency rebuilds on the first delta solve — the recovery budget a
    respawn pays is process boot + cache load, a fraction of cold start
    (tests/test_solver_host.py tripwires it)."""
    import argparse

    parser = argparse.ArgumentParser(description="karpenter solver host")
    parser.add_argument("--heartbeat", required=True)
    args = parser.parse_args(argv)

    start = time.monotonic()
    # the frame pipe owns fd 1; redirect EVERYTHING else that might write
    # to stdout (XLA banners, vendored libs) onto stderr so a stray print
    # can never corrupt a frame
    out = os.fdopen(os.dup(1), "wb")
    os.dup2(2, 1)
    sys.stdout = sys.stderr

    from karpenter_core_tpu.obs import enable_tracing_from_env
    from karpenter_core_tpu.obs.log import configure_logging_from_env
    from karpenter_core_tpu.utils.compilecache import enable_persistent_cache

    configure_logging_from_env(default_level="info")
    enable_tracing_from_env(default_on=False)
    enable_persistent_cache()

    # the process heartbeat: TPUSolver phase marks (and the service's
    # per-dispatch marks) touch this FILE through supervise.touch_heartbeat
    # — the parent's staleness watchdog reads its mtime, and the label the
    # marks write is the phase name a wedge verdict reports (ISSUE 15)
    hb = supervise.Heartbeat(args.heartbeat)
    supervise.set_process_heartbeat(hb)
    hb.touch()

    if TRACER.enabled:
        # killed-child salvage (ISSUE 15): finished solver.* spans spill
        # beside the heartbeat, atomically rewritten per span; the parent
        # grafts the file after a mid-dispatch SIGKILL — the phases this
        # dispatch completed before going silent
        TRACER.set_spill(f"{args.heartbeat}.spans")

    from karpenter_core_tpu.solver.service import SolverService

    mode = envflags.raw("KARPENTER_SOLVER_MODE", "auto").lower()
    mesh = None
    if mode != "single":
        try:
            from karpenter_core_tpu.solver.factory import detect_mesh

            mesh = detect_mesh()
        except Exception:  # noqa: BLE001 — auto degrades to single-device
            if mode == "sharded":
                raise
            mesh = None
    service = SolverService(mesh=mesh)
    _write_frame(
        out,
        {
            "op": "ready", "id": 0, "pid": os.getpid(),
            "startup_s": round(time.monotonic() - start, 3),
        },
    )
    LOG.info(
        "solver host worker ready", pid=os.getpid(),
        startup_s=round(time.monotonic() - start, 3),
    )
    stdin = sys.stdin.buffer
    while True:
        try:
            header, body = _read_frame(stdin)
        except EOFError:
            return 0
        op = header.get("op")
        rid = header.get("id", 0)
        if op == "shutdown":
            return 0
        hb.touch()
        try:
            if op in ("solve", "replan"):
                expires = header.get("expires_in_s")
                if expires is not None and float(expires) <= 0:
                    # deadline backstop: a request that arrives expired is
                    # never dispatched (the parent gate already enforces
                    # this; the child re-checks so a queued frame can't
                    # slip through)
                    _write_frame(
                        out,
                        {"op": "result", "id": rid, "ok": False,
                         "error": "DEADLINE_EXCEEDED: deadline expired "
                                  "before host dispatch"},
                    )
                    continue
                request = pb.SolveRequest.FromString(body)
                handler = service.solve if op == "solve" else service.replan
                # trace binding (ISSUE 15): the parent's trace id rides the
                # request header — bind it exactly like the gRPC
                # x-karpenter-trace-id path, so the child's phase spans
                # join the parent's trace; the span-ring DELTA since this
                # mark rides back in the result header, bounded by
                # export_spans' count+byte caps
                trace_id = header.get("trace")
                want_spans = trace_id is not None and TRACER.enabled
                # tenant binding (ISSUE 16): the parent's bound tenant rode
                # the request header; re-bind it here so the child's spans,
                # flight records, and metric series (which flow back to the
                # parent exposition via the merger) attribute to the same
                # tenant. Absent key = nothing bound = zero overhead.
                tenant = header.get("tenant")
                with contextlib.ExitStack() as dispatch_ctx:
                    if tenant is not None:
                        dispatch_ctx.enter_context(reqctx.bind(
                            reqctx.RequestContext(tenant=str(tenant))
                        ))
                    if want_spans:
                        TRACER.reset_spill()
                        mark = TRACER.mark()
                        dispatch_ctx.enter_context(TRACER.span(
                            "solver.host.dispatch",
                            trace_id=str(trace_id) or None, op=op,
                        ))
                    response = handler(request, context=None)
                rheader: Dict[str, object] = {
                    "op": "result", "id": rid,
                    "ok": not bool(response.error),
                    "error": response.error or "",
                }
                if want_spans:
                    rheader["spans"] = export_spans(
                        TRACER.spans_since(mark)
                    )
                # cumulative counter/histogram snapshot: the parent's
                # per-generation merger folds it into the ONE exposition
                rheader["metrics"] = snapshot_families(REGISTRY)
                # compiled-program inventory rides beside it (ISSUE 18) —
                # absent-key when the ledger is disabled or empty, so the
                # off posture adds zero frame bytes (same contract as the
                # trace/tenant keys)
                progs = proghealth.LEDGER.snapshot()
                if progs["programs"] or progs["totals"]:
                    rheader["programs"] = progs
                _write_frame(out, rheader, response.SerializeToString())
                # the spill must only ever hold spans of an UNANSWERED
                # dispatch: clear it once the response (which carried any
                # spans) is on the wire, so a kill landing BEFORE the next
                # dispatch starts can never re-salvage delivered spans
                TRACER.reset_spill()
            elif op == "health":
                age = service._stalest_dispatch_age()
                if age is not None and age >= service.wedge_stale_after:
                    status = (
                        f"wedged: dispatch heartbeat stale for {age:.0f}s"
                    )
                    info = {"status": status, "solves": service.solves}
                else:
                    import jax

                    dev = jax.devices()[0]
                    info = {
                        "status": "ok",
                        "platform": dev.platform,
                        "device": dev.device_kind,
                        "solves": service.solves,
                        "replans": service.replans,
                        "pid": os.getpid(),
                    }
                _write_frame(
                    out, {"op": "result", "id": rid, "ok": True},
                    json.dumps(info, sort_keys=True).encode(),
                )
            elif op == "stats":
                from karpenter_core_tpu.solver.incremental import (
                    INCREMENTAL_SCREEN_TOTAL,
                )
                from karpenter_core_tpu.utils.compilecache import (
                    CACHE_HITS,
                    CACHE_MISSES,
                )

                info = {
                    "pid": os.getpid(),
                    "solves": service.solves,
                    "replans": service.replans,
                    "incremental": _counter_by_label(
                        INCREMENTAL_SCREEN_TOTAL, "outcome"
                    ),
                    "compile_cache_hits": _counter_by_label(
                        CACHE_HITS, "site"
                    ),
                    "compile_cache_misses": _counter_by_label(
                        CACHE_MISSES, "site"
                    ),
                }
                sheader: Dict[str, object] = {
                    "op": "result", "id": rid, "ok": True,
                    # the stats frame carries the same snapshot the
                    # solve/replan responses do (the canonical metrics
                    # ride, ISSUE 15) — a parent polling stats between
                    # dispatches keeps the exposition fresh
                    "metrics": snapshot_families(REGISTRY),
                }
                progs = proghealth.LEDGER.snapshot()
                if progs["programs"] or progs["totals"]:
                    sheader["programs"] = progs
                _write_frame(
                    out, sheader,
                    json.dumps(info, sort_keys=True).encode(),
                )
            else:
                _write_frame(
                    out,
                    {"op": "result", "id": rid, "ok": False,
                     "error": f"INVALID_ARGUMENT: unknown op {op!r}"},
                )
        except Exception as e:  # noqa: BLE001 — classified, never fatal
            from karpenter_core_tpu.solver.service import classify_exception

            code, msg = classify_exception(e)
            LOG.error(
                "solver host request failed", op=op,
                error=type(e).__name__, error_detail=str(e),
            )
            try:
                _write_frame(
                    out,
                    {"op": "result", "id": rid, "ok": False,
                     "error": f"{code}: {msg}"},
                )
            except OSError:
                return 1


if __name__ == "__main__":
    sys.exit(host_main() or 0)
