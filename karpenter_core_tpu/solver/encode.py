"""Snapshot → dense tensor encoding for the TPU solver.

The reference evaluates the constraint algebra object-by-object inside the
serial Solve loop (scheduler.go:96-133, machine.go:137-159). Here the whole
snapshot is lowered ONCE into dense arrays over a closed label dictionary, so
pod×instance-type feasibility and packing run as tensor kernels on the MXU.

Key encoding idea: every Requirement becomes
  - allow[V]   : for each dictionary value of its key, requirement.has(value)
                 (evaluates In/NotIn/Exists/DoesNotExist/Gt/Lt uniformly,
                 including integer bounds — the host oracle IS the encoder)
  - out[K]     : complement flag — values OUTSIDE the dictionary allowed
  - defined[K] : key constrained at all
  - escape[K]  : operator ∈ {NotIn, DoesNotExist} (the Intersects/Compatible
                 escape hatch, requirements.go:195-201)
Because concrete In-sets are dictionary-closed by construction, set
intersection nonemptiness is exactly
  (outA & outB) | any_v(allowA[v] & allowB[v])                (within one key)
which vectorizes to segment matmuls.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from karpenter_core_tpu.api import labels as api_labels
from karpenter_core_tpu.api.provisioner import Provisioner
from karpenter_core_tpu.cloudprovider.types import InstanceType
from karpenter_core_tpu.scheduling.machinetemplate import MachineTemplate
from karpenter_core_tpu.kube.objects import (
    LABEL_HOSTNAME,
    LABEL_TOPOLOGY_ZONE,
    Pod,
    ResourceList,
    Taint,
)
from karpenter_core_tpu.scheduling import taints as taints_mod
from karpenter_core_tpu.scheduling.requirement import (
    OP_DOES_NOT_EXIST,
    OP_NOT_IN,
    Requirement,
)
from karpenter_core_tpu.scheduling.requirements import Requirements
from karpenter_core_tpu.utils import resources as resources_util

# resource axis order: fixed core resources then discovered extended ones
CORE_RESOURCES = ["cpu", "memory", "pods", "ephemeral-storage"]


def bucket_pow2(n: int, lo: int) -> int:
    """Round n up to a power-of-two bucket (min lo); 0 stays 0. Batch-size
    axes are padded to buckets so solves at never-seen sizes reuse the
    compiled program — p99 must be a solve, not a compile."""
    if n <= 0:
        return 0
    b = lo
    while b < n:
        b *= 2
    return b


# -- geometry bucket ladder (ISSUE 7) ----------------------------------------
# The solve-shaping batch axes (pods, items, instance types, existing nodes)
# pad to values from the FIXED ladder in api/settings.py instead of open-
# ended power-of-two buckets: compiled_programs is then bounded by the
# ladder (O(tiers), not O(observed geometries)) and — because the tier
# table is known before the first pod arrives — the startup prewarm
# (solver/prewarm.py) can AOT-compile every program the operator will need.
# Sizes past the top rung continue power-of-two (an "overflow" geometry,
# counted below); the provisioning batcher's pass cap is clamped to the top
# rung (Settings.effective_batch_max_pods) so production passes never
# overflow the pods axis.

BUCKET_OVERFLOW = None  # lazily bound counter (metrics import stays light)


def _count_overflow(axis: str) -> None:
    global BUCKET_OVERFLOW
    if BUCKET_OVERFLOW is None:
        from karpenter_core_tpu.metrics.registry import NAMESPACE, REGISTRY

        BUCKET_OVERFLOW = REGISTRY.counter(
            f"{NAMESPACE}_bucket_overflow_total",
            "Geometry axes padded past the configured bucket ladder's top "
            "rung (power-of-two fallback: a compile the prewarm never "
            "covered), by axis",
        )
    BUCKET_OVERFLOW.inc({"axis": axis})


def resolve_ladder(ladder=None):
    """The geometry tier table in effect: an explicit argument wins, else
    the process-wide Settings. Returns a (possibly empty) tuple; empty
    disables ladder snapping (pure power-of-two padding, the pre-ladder
    behavior)."""
    if ladder is not None:
        return tuple(ladder)
    from karpenter_core_tpu.api import settings as api_settings

    return tuple(api_settings.current().bucket_ladder or ())


def ladder_pad(n: int, ladder, axis: str, lo: int) -> int:
    """Round n up to the smallest tier value on `axis`; 0 stays 0. Past the
    top rung, continue power-of-two from it (overflow — counted, because
    it mints a geometry the prewarm never compiled). With no ladder,
    plain bucket_pow2(n, lo)."""
    if n <= 0:
        return 0
    values = sorted(getattr(t, axis) for t in ladder) if ladder else ()
    if not values:
        return bucket_pow2(n, lo)
    for v in values:
        if n <= v:
            return v
    _count_overflow(axis)
    b = values[-1]
    while b < n:
        b *= 2
    return b


# -- candidate-subset axis (ISSUE 10) ----------------------------------------
# The batched consolidation replan vmaps K candidate subsets through one
# rung-mode solve program (solver/replan.py). K is a compiled-program axis
# like any other batch axis, so it rides its own small fixed ladder: the
# multi-node prefix ladder is <= 8 rungs (the bottom bucket), single-node
# sweeps chunk at the top bucket, and the program set per geometry stays
# bounded by len(REPLAN_K_BUCKETS) instead of O(observed subset counts).

REPLAN_K_BUCKETS = (8, 16, 32, 64)


def replan_k_pad(k: int) -> int:
    """Round a subset count up to the replan candidate-axis ladder. Counts
    above the top bucket are a caller error — dispatchers chunk at
    REPLAN_K_BUCKETS[-1] (replan_chunks)."""
    if k <= 0:
        return REPLAN_K_BUCKETS[0]
    for v in REPLAN_K_BUCKETS:
        if k <= v:
            return v
    raise ValueError(
        f"subset axis {k} exceeds the replan chunk cap "
        f"{REPLAN_K_BUCKETS[-1]} (callers must chunk)"
    )


# -- segmented pack-scan axes (ISSUE 14) -------------------------------------
# The segmented dispatch vmaps the pack scan over S conflict-independent
# lanes of at most M items each (TPUSolver._try_segmented). Both are
# compiled-program axes, so they ride small fixed ladders: the lane axis a
# two-value bucket (load-balanced lane counts are capped well below it),
# the per-lane item axis a pow2 bucket bounded by the snapshot's item tier
# — so the segmented program family per geometry stays
# len(SEGMENT_LANE_BUCKETS) x O(log items), not O(observed partitions).

SEGMENT_LANE_BUCKETS = (4, 8, 16)


def segment_lane_pad(s: int) -> int:
    """Round a lane count up to the segment lane-axis ladder."""
    for v in SEGMENT_LANE_BUCKETS:
        if s <= v:
            return v
    raise ValueError(
        f"lane axis {s} exceeds the segment lane cap "
        f"{SEGMENT_LANE_BUCKETS[-1]} (the dispatcher load-balances into "
        f"fewer lanes)"
    )


def segment_item_pad(m: int, item_pad: int) -> int:
    """Round a max-lane item count up to its pow2 bucket, capped at the
    snapshot's item tier (a lane can never hold more than every item)."""
    return min(bucket_pow2(max(m, 1), 32), max(item_pad, 32))


def replan_chunks(count_rows, exist_open):
    """Yield (k_real, k_pad, counts, open) dispatch chunks along the
    candidate axis: slices of at most REPLAN_K_BUCKETS[-1] subsets, padded
    up to the bucket ladder. Pad rungs are no-op subsets — zero active
    pods, nothing closed — so they cost one cheap scan each and never
    perturb real verdicts. ONE definition of the padding contract, shared
    by TPUSolver.replan_screen and the gRPC service's Replan handler so
    the in-process and remote replan paths can never desynchronize."""
    K = int(count_rows.shape[0])
    CH = REPLAN_K_BUCKETS[-1]
    for lo in range(0, K, CH):
        counts = np.ascontiguousarray(count_rows[lo: lo + CH])
        opened = np.ascontiguousarray(exist_open[lo: lo + CH])
        k = counts.shape[0]
        kp = replan_k_pad(k)
        if kp > k:
            counts = np.concatenate(
                [counts, np.zeros((kp - k,) + counts.shape[1:], counts.dtype)]
            )
            opened = np.concatenate(
                [opened, np.ones((kp - k,) + opened.shape[1:], opened.dtype)]
            )
        yield k, kp, counts, opened


def _ids(lst):
    return tuple(map(id, lst))


def _aff_duo(x):
    # identity of the LEAF term objects: producers share them across a
    # deployment's pods even when each pod gets fresh wrapper objects
    return None if x is None else (_ids(x.required), _ids(x.preferred))


def _aff_key(a):
    return (
        _aff_duo(a.node_affinity),
        _aff_duo(a.pod_affinity),
        _aff_duo(a.pod_anti_affinity),
    )


def _pod_spec_signature(p: Pod, _repr_memo: Optional[Dict[int, str]] = None) -> Tuple:
    """Content key for pod spec-equivalence: covers exactly what the encoder
    derives per pod — namespace+labels (topology selection/ownership),
    node_selector + affinity (Requirements.from_pod, topology groups),
    tolerations, spread constraints, and container resources (requests
    ceiling). Pods with equal signatures are interchangeable to the solver.
    Affinity/spread objects are keyed by repr (dataclass reprs are
    content-recursive); the common no-affinity case stays cheap.

    _repr_memo (id -> repr) dedups the recursive reprs when producers share
    constraint objects across pods (deployment-expanded batches do) — at 50k
    pods the reprs otherwise dominate encode time. The body is deliberately
    flat (no closures, inlined memo gets, single-container fast path): this
    runs once per pod and is the encoder's hottest Python loop."""
    if _repr_memo is None:
        _repr_memo = {}
    mget = _repr_memo.get
    s = p.spec
    md = p.metadata

    aff = s.affinity
    if aff is None:
        aff_r = None
    else:
        k = ("aff",) + _aff_key(aff)
        aff_r = mget(k)
        if aff_r is None:
            aff_r = _repr_memo[k] = repr(aff)
    tol = s.tolerations
    if tol:
        k = ("tol",) + _ids(tol)
        tol_r = mget(k)
        if tol_r is None:
            tol_r = _repr_memo[k] = repr(tol)
    else:
        tol_r = None
    tsc = s.topology_spread_constraints
    if tsc:
        k = ("tsc",) + _ids(tsc)
        tsc_r = mget(k)
        if tsc_r is None:
            tsc_r = _repr_memo[k] = repr(tsc)
    else:
        tsc_r = None

    # host ports + volumes are per-slot constraints the kernel enforces:
    # pods differing only in them must NOT share an equivalence class
    cts = s.containers
    if len(cts) == 1:
        c = cts[0]
        res = (
            (tuple(c.resources.requests.items()), tuple(c.resources.limits.items())),
        )
        ports = (
            tuple(
                (pt.host_ip, pt.host_port, pt.protocol)
                for pt in c.ports
                if pt.host_port
            )
            if c.ports
            else ()
        )
    else:
        res = tuple(
            (tuple(c.resources.requests.items()), tuple(c.resources.limits.items()))
            for c in cts
        )
        ports = tuple(
            (pt.host_ip, pt.host_port, pt.protocol)
            for c in cts
            for pt in c.ports
            if pt.host_port
        )
    ic = s.init_containers
    ic_r = (
        tuple(
            (tuple(c.resources.requests.items()), tuple(c.resources.limits.items()))
            for c in ic
        )
        if ic
        else None
    )
    vols = s.volumes
    vol_r = (
        tuple(
            v.persistent_volume_claim.claim_name
            for v in vols
            if v.persistent_volume_claim is not None
        )
        if vols
        else None
    )
    return (
        md.namespace,
        tuple(md.labels.items()),
        tuple(s.node_selector.items()),
        ports,
        vol_r,
        aff_r,
        tol_r,
        tsc_r,
        res,
        ic_r,
    )


class LabelDictionary:
    """Closed (key, value) universe: every value any requirement or node label
    mentions. Flat value axis V with per-key contiguous segments."""

    def __init__(self):
        self.keys: List[str] = []
        self.key_index: Dict[str, int] = {}
        self._values: List[Dict[str, int]] = []  # per key: value -> local idx

    def add_key(self, key: str) -> int:
        if key not in self.key_index:
            self.key_index[key] = len(self.keys)
            self.keys.append(key)
            self._values.append({})
        return self.key_index[key]

    def add_value(self, key: str, value: str) -> None:
        k = self.add_key(key)
        vals = self._values[k]
        if value not in vals:
            vals[value] = len(vals)

    def freeze(self) -> None:
        """Assign flat offsets."""
        self.offsets = np.zeros(len(self.keys) + 1, dtype=np.int32)
        for k in range(len(self.keys)):
            self.offsets[k + 1] = self.offsets[k] + len(self._values[k])
        self.V = int(self.offsets[-1])
        self.K = len(self.keys)
        self.key_of_value = np.zeros(self.V, dtype=np.int32)
        for k in range(self.K):
            self.key_of_value[self.offsets[k] : self.offsets[k + 1]] = k

    def flat_index(self, key: str, value: str) -> Optional[int]:
        k = self.key_index.get(key)
        if k is None:
            return None
        local = self._values[k].get(value)
        if local is None:
            return None
        return int(self.offsets[k]) + local

    def values_of(self, key: str) -> List[str]:
        k = self.key_index.get(key)
        if k is None:
            return []
        return [v for v, _ in sorted(self._values[k].items(), key=lambda kv: kv[1])]

    def canonicalize(self, last_key: Optional[str] = None) -> None:
        """Sort keys and values (with `last_key`'s segment forced last) and
        freeze. Insertion order is batch-dependent — whichever pod mentioned
        a value first — and value order is load-bearing: domain tie-breaks
        resolve by flat index, so two encodes of the SAME vocabulary in
        different orders pack differently. Canonical order makes the
        dictionary a pure function of its content: batches with equal
        vocabularies share a geometry key (and a compiled program), and
        cross-solve dictionary carryover can never smuggle one batch's
        insertion history into another's placements."""
        order = sorted(self.keys)
        if last_key is not None and last_key in order:
            order.remove(last_key)
            order.append(last_key)
        self._values = [
            {v: i for i, v in enumerate(sorted(self._values[self.key_index[key]]))}
            for key in order
        ]
        self.keys = order
        self.key_index = {name: i for i, name in enumerate(order)}
        self.freeze()

    def segment(self, key: str) -> Tuple[int, int]:
        k = self.key_index[key]
        return int(self.offsets[k]), int(self.offsets[k + 1])


def dictionary_covers(carrier: LabelDictionary, fresh: LabelDictionary) -> bool:
    """True when `carrier` (a previous batch's frozen dictionary) can encode
    everything `fresh` (this batch's closure) mentions: every key and value
    already mapped, the hostname segment still last (the screens' tail-
    elision contract), and the carrier not bloated past twice the live
    vocabulary — extra values behave exactly like pad values, but unbounded
    staleness (hostnames of long-replaced nodes) would grow V forever."""
    if carrier.V > max(2 * fresh.V, fresh.V + 32):
        return False
    if LABEL_HOSTNAME in carrier.key_index:
        lo, hi = carrier.segment(LABEL_HOSTNAME)
        if hi != carrier.V:
            return False
    for key in fresh.keys:
        k = carrier.key_index.get(key)
        if k is None:
            return False
        have = carrier._values[k]
        for value in fresh._values[fresh.key_index[key]]:
            if value not in have:
                return False
    return True


def dictionary_rebind_hostnames(carrier: LabelDictionary,
                                fresh: LabelDictionary) -> bool:
    """Second-chance adoption for a growing cluster: when the ONLY values
    `carrier` is missing are hostnames (a machine launched, a node was
    replaced), rebind them onto hostname-segment entries `fresh` no longer
    references — pad sentinels and hostnames of departed nodes. A value
    index is just a column; renaming an unused one changes plane CONTENT,
    never V/K/segments, so the compiled program (and the incremental
    path's resident tensor, guarded by its plane fingerprints) survives
    node churn instead of being re-minted per launch. Mutates `carrier` in
    place on success; False leaves it untouched (caller rebuilds fresh)."""
    k_host = carrier.key_index.get(LABEL_HOSTNAME)
    if k_host is None:
        return False
    lo, hi = carrier.segment(LABEL_HOSTNAME)
    if hi != carrier.V:
        return False  # tail-elision contract: hostname segment stays last
    missing = []
    for key in fresh.keys:
        k = carrier.key_index.get(key)
        if k is None:
            return False
        have = carrier._values[k]
        for value in fresh._values[fresh.key_index[key]]:
            if value not in have:
                if key != LABEL_HOSTNAME:
                    return False
                missing.append(value)
    if not missing:
        return True  # plain coverage (caller usually checked already)
    host_vals = carrier._values[k_host]
    fresh_hosts = fresh._values[fresh.key_index[LABEL_HOSTNAME]]
    rebindable = [v for v in host_vals if v not in fresh_hosts]
    if len(missing) > len(rebindable):
        return False
    for value, stale in zip(missing, rebindable):
        host_vals[value] = host_vals.pop(stale)
    return True


@dataclass
class ReqSetArrays:
    """Dense form of a batch of Requirements (one row each)."""

    allow: np.ndarray  # [N, V] bool
    out: np.ndarray  # [N, K] bool — complement: outside-dictionary allowed
    defined: np.ndarray  # [N, K] bool
    escape: np.ndarray  # [N, K] bool — operator in {NotIn, DoesNotExist}


def _collect_requirement_values(reqs: Requirements, dictionary: LabelDictionary) -> None:
    for key, r in reqs.items():
        dictionary.add_key(key)
        for v in r.values:
            dictionary.add_value(key, v)


def encode_reqsets(
    req_list: Sequence[Requirements], dictionary: LabelDictionary
) -> ReqSetArrays:
    n = len(req_list)
    allow = np.zeros((n, dictionary.V), dtype=bool)
    out = np.zeros((n, dictionary.K), dtype=bool)
    defined = np.zeros((n, dictionary.K), dtype=bool)
    escape = np.zeros((n, dictionary.K), dtype=bool)
    # undefined keys read as Exists: allow everything incl. outside
    allow[:] = True
    out[:] = True
    for i, reqs in enumerate(req_list):
        for key, r in reqs.items():
            k = dictionary.key_index.get(key)
            if k is None:
                continue
            lo, hi = dictionary.segment(key)
            # concrete In/NotIn sets touch only their own values — O(|values|)
            # instead of O(segment width), which matters for wide segments
            # (instance-type names, hostnames)
            if r.greater_than is None and r.less_than is None:
                local = dictionary._values[k]
                if not r.complement:
                    allow[i, lo:hi] = False
                    for v in r.values:
                        li = local.get(v)
                        if li is not None:
                            allow[i, lo + li] = True
                else:
                    for v in r.values:
                        li = local.get(v)
                        if li is not None:
                            allow[i, lo + li] = False
            else:
                allow[i, lo:hi] = [r.has(v) for v in dictionary.values_of(key)]
            out[i, k] = r.complement
            defined[i, k] = True
            escape[i, k] = r.operator() in (OP_NOT_IN, OP_DOES_NOT_EXIST)
    return ReqSetArrays(allow=allow, out=out, defined=defined, escape=escape)


@dataclass
class EncodedSnapshot:
    """Everything the device kernels need, as numpy arrays (moved to device by
    the solver). Axes: P pods, T instance types, J templates, K keys, V flat
    values, R resources, Q distinct taints, Z zones, C capacity types.

    Multi-chip note (ISSUE 8): these arrays are what the GSPMD mesh
    programs shard — each device_args tensor has a canonical PartitionSpec
    family (parallel/specs.RUN_ARG_FAMILIES keyed by
    tpu_solver.RUN_ARG_NAMES; docs/sharding.md has the table). The ladder
    padding below is also what keeps the sharded axes mesh-divisible in
    practice: tier values for instance_types/existing_nodes are even
    powers of two, so the gRPC service's pre-sharded upload
    (SpecLayout.put_args) rarely needs its replicated fallback."""

    dictionary: LabelDictionary
    resource_names: List[str]

    # pods — stored at CLASS level ([U] spec-equivalence classes) with the
    # per-pod gather map `uidx` [P]; the [P, ...] views below are lazy
    # cached properties. The device path reads only item-representative
    # rows, so materializing 50k-row arrays to feed a ~1k-row gather cost
    # ~0.3s of encode time per solve (measured at the north-star config).
    pod_reqs_u: ReqSetArrays  # [U, ...]
    pod_requests_u: np.ndarray  # [U, R] float32 (incl. pods=1)
    pod_tol_u: np.ndarray  # [U, J] bool — tolerates template j's taints
    uidx: np.ndarray  # [P] int32 class of sorted pod i

    # templates (one per provisioner, weight-ordered)
    tmpl_reqs: ReqSetArrays  # [J, ...]
    tmpl_daemon: np.ndarray  # [J, R] float32 daemon overhead
    tmpl_type_mask: np.ndarray  # [J, T] bool — types offered by provisioner j

    # instance types (deduped global list)
    type_reqs: ReqSetArrays  # [T, ...]
    type_alloc: np.ndarray  # [T, R] float32 allocatable
    type_capacity: np.ndarray  # [T, R] float32
    type_offering_ok: np.ndarray  # [T, Z, C] bool (available)
    type_offering_price: np.ndarray  # [T, Z, C] float32 (inf when unavailable)
    type_min_price: np.ndarray  # [T] float32 cheapest available offering

    # label geometry
    well_known: np.ndarray  # [K] bool
    zone_seg: Tuple[int, int]
    ct_seg: Tuple[int, int]

    # existing nodes (pre-seeded slots [0, E))
    exist_reqs: ReqSetArrays = None  # [E, ...] label requirements
    exist_used: np.ndarray = None  # [E, R] remaining daemon overhead
    exist_cap: np.ndarray = None  # [E, R] available()
    # pod x existing toleration, factored (class, taint-signature): column
    # S is the all-False sentinel for bucket-pad slots
    tol_exist_us: np.ndarray = None  # [U, S+1] bool
    sig_of_node: np.ndarray = None  # [E_pad] int64 -> signature (S = pad)

    # host ports (Q distinct (ip, port, proto) entries; 0 when none in batch)
    # and CSI volumes (W distinct claims, D drivers; existing-slot only —
    # the reference enforces volume limits only in ExistingNode.Add,
    # existingnode.go:62-115, while ports apply to machines too,
    # machine.go:69)
    pod_ports_u: np.ndarray = None  # [U, Q] entries a pod OCCUPIES
    pod_port_conflict_u: np.ndarray = None  # [U, Q] entries it CONFLICTS with
    exist_ports: np.ndarray = None  # [E_pad, Q]
    pod_vols_u: np.ndarray = None  # [U, W]
    exist_vols: np.ndarray = None  # [E_pad, W] already-mounted claims
    exist_vol_limits: np.ndarray = None  # [E_pad, D] (inf = unlimited)
    vol_driver_onehot: np.ndarray = None  # [W, D]

    # topology (None when the batch has no topology constraints)
    topo_meta: object = None  # ops.topology.TopoMeta
    topo_arrays: object = None  # ops.topology.TopoArrays
    n_slots: int = 0  # E + machine slot budget (hostname identity width)
    # screens run on allow[:, :screen_v]: V minus the (last) hostname
    # segment when nothing on the pod/type side constrains hostname
    screen_v: int = 0

    # pod equivalence classes ("items") — the packing scan's work axis.
    # Pods with identical constraint rows collapse into one item with a
    # count; the kernel commits whole replica groups per step instead of one
    # pod (real batches are deployment-dominated, so this shrinks the
    # sequential axis 10-100x). Classes involved in value-key anti-affinity
    # are expanded back to count=1 items to keep the reference's per-pod
    # domain-choice semantics exact (_build_items; hostname anti stays bulk).
    item_of_pod: np.ndarray = None  # [P] int32 item index per (sorted) pod
    item_counts: np.ndarray = None  # [I] int32
    item_rep: np.ndarray = None  # [I] int32 representative pod row
    item_members: List[List[int]] = None  # host: pod rows per item, in order

    # prescreen verdict-tensor layout (ops/pack.py): the tensor's column
    # axis is the UNIQUE requirement class among items, not the item axis —
    # value-key anti-affinity expansion blows I up toward P (count=1 items)
    # while the class count stays put, and every expanded replica shares its
    # class's verdict column. item_scls maps item -> column; scls_items
    # names one item per column so the kernel can gather the column planes
    # from the (already item-gathered) pod arrays.
    item_scls: np.ndarray = None  # [I] int32 verdict column of item i
    scls_items: np.ndarray = None  # [C] int32 one item index per column

    # geometry-ladder bookkeeping (ISSUE 7): padded item / verdict-column
    # axis widths chosen at encode time from the tier table, read by
    # solve_geometry / device_args / replan so every consumer pads
    # identically (0 = pre-ladder snapshot: fall back to pow2)
    item_pad: int = 0
    cls_pad: int = 0
    ladder: object = None  # the tier tuple in effect at encode time

    # segmented pack-scan metadata (ISSUE 14): structural eligibility (no
    # topology groups / host ports / volumes in the batch — the global
    # couplings the segment partition cannot express) and the per-class
    # plane-neutrality mask (no defined keys inside the screen width).
    # Neutrality does NOT gate segmentation — plane-mutating classes stay
    # segmentable because their mutations land inside their own conflict
    # component (ops/pack.make_segment_partition_kernel) — it only selects
    # the frozen read-only-verdict lane variant when EVERY class is
    # neutral. Dispatch additionally requires infinite provisioner limits
    # (device_args).
    seg_eligible: bool = False
    seg_plane_neutral: np.ndarray = None  # [U] bool

    # host-side back-references for decode
    instance_types: List[InstanceType] = field(default_factory=list)
    templates: List[MachineTemplate] = field(default_factory=list)
    pods: List[Pod] = field(default_factory=list)
    state_nodes: List = field(default_factory=list)
    pod_order: np.ndarray = None  # FFD order applied to pod axis

    # -- lazy [P, ...] views (native packer / host consumers only) ---------

    def _gather(self, name: str, arr_u: np.ndarray) -> np.ndarray:
        cache = self.__dict__.setdefault("_pod_view_cache", {})
        got = cache.get(name)
        if got is None:
            got = cache[name] = (
                arr_u[self.uidx]
                if len(self.pods)
                else np.zeros((0,) + arr_u.shape[1:], dtype=arr_u.dtype)
            )
        return got

    @property
    def pod_reqs(self) -> ReqSetArrays:
        cache = self.__dict__.setdefault("_pod_view_cache", {})
        got = cache.get("pod_reqs")
        if got is None:
            u = self.pod_reqs_u
            idx = self.uidx
            got = cache["pod_reqs"] = ReqSetArrays(
                allow=u.allow[idx],
                out=u.out[idx],
                defined=u.defined[idx],
                escape=u.escape[idx],
            )
        return got

    @property
    def pod_requests(self) -> np.ndarray:
        return self._gather("pod_requests", self.pod_requests_u)

    @property
    def pod_tol(self) -> np.ndarray:
        return self._gather("pod_tol", self.pod_tol_u)

    @property
    def pod_tol_exist(self) -> np.ndarray:
        cache = self.__dict__.setdefault("_pod_view_cache", {})
        got = cache.get("pod_tol_exist")
        if got is None:
            got = cache["pod_tol_exist"] = (
                self.tol_exist_us[self.uidx[:, None], self.sig_of_node[None, :]]
                if len(self.pods)
                else np.zeros((0, len(self.sig_of_node)), dtype=bool)
            )
        return got

    @property
    def pod_ports(self) -> np.ndarray:
        return self._gather("pod_ports", self.pod_ports_u)

    @property
    def pod_port_conflict(self) -> np.ndarray:
        return self._gather("pod_port_conflict", self.pod_port_conflict_u)

    @property
    def pod_vols(self) -> np.ndarray:
        return self._gather("pod_vols", self.pod_vols_u)


class EncodeReuse:
    """Cross-solve carrier for encode work whose inputs are stable between
    batches (round-5 verdict #2: "cluster state and dictionaries change
    little between batches — reuse").

    The INSTANCE-TYPE planes are the reusable unit: a cluster's type
    universe is the same objects solve after solve (the cloud provider
    caches them), and their encoded planes depend only on (type objects,
    dictionary content, resource names, offering state) — all captured in
    the cache key, so a label-universe, extended-resource, or
    offering-availability change simply misses and re-encodes. The carrier
    holds a strong reference to the keyed type objects (an id()-only key
    could collide after the originals are freed) and is thread-safe: the
    pipelined production loop encodes batch N+1 on a worker thread while a
    relaxation round re-encodes on the main thread. Hold one per solver
    (TPUSolver/ShardedSolver/RemoteSolver own one) and pass it to
    encode_snapshot(reuse=...)."""

    def __init__(self):
        import threading

        self._lock = threading.Lock()
        self._key = None
        self._planes = None
        self._keyed_types = None  # strong refs: keeps the id() key valid

    def get(self, key):
        with self._lock:
            return self._planes if self._key == key else None

    def put(self, key, planes, all_types) -> None:
        with self._lock:
            self._key = key
            self._planes = planes
            self._keyed_types = list(all_types)

    @staticmethod
    def dict_signature(dictionary: "LabelDictionary") -> Tuple:
        return tuple(
            (k, tuple(dictionary.values_of(k))) for k in dictionary.keys
        )

    @staticmethod
    def offering_signature(all_types) -> Tuple:
        # Offering.available/price are mutable in place (the provider flips
        # availability between solves); they must key the cache
        return tuple(
            tuple((o.zone, o.capacity_type, o.available, o.price)
                  for o in it.offerings)
            for it in all_types
        )

    @staticmethod
    def resource_signature(all_types) -> Tuple:
        # capacity/overhead are plain mutable attributes on the same cached
        # type objects — a provider refreshing them in place must miss the
        # cache, or the solver packs against stale per-type resources.
        # Requirements objects are keyed by identity (reassignment misses;
        # the reference's providers build requirements at type construction
        # and never mutate them in place).
        return tuple(
            (
                tuple(sorted(it.capacity.items())),
                tuple(sorted(it.allocatable().items())),
                id(it.requirements),
            )
            for it in all_types
        )


def encode_snapshot(
    pods: List[Pod],
    provisioners: List[Provisioner],
    instance_types: Dict[str, List[InstanceType]],
    daemonset_pods: Optional[List[Pod]] = None,
    state_nodes: Optional[List] = None,
    kube_client=None,
    cluster=None,
    max_nodes: int = 1024,
    reuse_dictionary: Optional[LabelDictionary] = None,
    reuse: Optional[EncodeReuse] = None,
    carry_dictionary: Optional[LabelDictionary] = None,
    ladder=None,
) -> EncodedSnapshot:
    """Lower a provisioning snapshot to tensors.

    Pods are sorted FFD (cpu desc, mem desc — queue.go:74-110) so the packing
    scan consumes them in reference order.

    reuse_dictionary: a dictionary from an earlier encode of the SAME
    snapshot whose value universe is a superset of this batch's (relaxation
    only removes requirements) — reusing it keeps V/K/segments identical so
    relaxation re-solves hit the compiled program instead of recompiling.

    reuse: an EncodeReuse carried across solves; stable instance-type
    planes are reused instead of re-encoded when types, dictionary content,
    and resource names all match the previous batch.

    ladder: geometry tier table override (tests); defaults to
    Settings.bucket_ladder via resolve_ladder(). Every solve-shaping axis
    (existing nodes, instance types, machine-slot budget, and — stored on
    the snapshot for solve_geometry/device_args — the item/class axes)
    pads to a tier value so the compiled-program set stays bounded by the
    ladder and startup prewarm can enumerate it.

    carry_dictionary: the PREVIOUS solve's dictionary, offered across
    batches (steady-state churn, ISSUE 6). Unlike reuse_dictionary it is
    not trusted: the fresh closure is built first and the carrier is
    adopted only when it COVERS it (every fresh key/value already mapped —
    a superset dictionary is always valid) and hasn't bloated past twice
    the live vocabulary (stale hostnames from replaced nodes accumulate;
    past the bound a rebuild re-compacts). Adoption keeps V/K/segments —
    and with them the compiled-program key and the incremental path's
    resident verdict tensor — identical across consecutive churn batches
    whose vocabulary has saturated; any unseen value falls back to the
    fresh build, which becomes the next carrier.
    """
    from karpenter_core_tpu.api.provisioner import order_by_weight

    daemonset_pods = daemonset_pods or []
    # only nodes launched by us participate (scheduler.go:226-229)
    state_nodes = [n for n in (state_nodes or []) if n.owned()]
    # CSI attach limits ride the state nodes; snapshots that bypassed the
    # cluster informer (gRPC boundary, direct API use) resolve them here
    from karpenter_core_tpu.state.node import resolve_volume_limits

    resolve_volume_limits(state_nodes, kube_client)
    provisioners = [
        p for p in order_by_weight(provisioners) if p.metadata.deletion_timestamp is None
    ]
    templates = [MachineTemplate(p) for p in provisioners]

    ladder = resolve_ladder(ladder)

    # global dedup of instance types by object identity
    all_types: List[InstanceType] = []
    type_ids: Dict[int, int] = {}
    tmpl_type_mask_rows = []
    for p in provisioners:
        row: Set[int] = set()
        for it in instance_types.get(p.name, []):
            tid = type_ids.get(id(it))
            if tid is None:
                tid = len(all_types)
                type_ids[id(it)] = tid
                all_types.append(it)
            row.add(tid)
        tmpl_type_mask_rows.append(row)
    # the instance-type axis pads to its ladder tier: pad columns are
    # unoffered (no template offers them — tmpl_type_mask gates all of
    # f_static — no offerings, allocatable -1 so fits() rejects), so a
    # provider adding a few types stays inside one compiled program
    T_real = len(all_types)
    T_pad = ladder_pad(T_real, ladder, "instance_types", 1) if ladder else T_real

    # -- pod spec-equivalence classes (the 50k-scale lever) ----------------
    # Real batches are deployment-dominated: thousands of pods share a
    # handful of specs. Everything the encoder derives from a pod —
    # Requirements.from_pod, requests, toleration columns, topology
    # ownership/selection — is a pure function of (namespace, labels, spec),
    # so it is computed once per distinct signature and GATHERED to the pod
    # axis with numpy indexing. This replaces the reference's per-pod
    # constraint evaluation (scheduler.go:96-133) with per-CLASS evaluation.
    P0 = len(pods)
    sig_of: Dict[Tuple, int] = {}
    uidx0 = np.empty(P0, dtype=np.int32)
    uniq_pods: List[Pod] = []
    repr_memo: Dict = {}
    for i, p in enumerate(pods):
        sig = _pod_spec_signature(p, repr_memo)
        u = sig_of.get(sig)
        if u is None:
            u = len(uniq_pods)
            sig_of[sig] = u
            uniq_pods.append(p)
        uidx0[i] = u
    U = len(uniq_pods)

    req_u = [resources_util.requests_for_pods(p) for p in uniq_pods]

    # FFD sort (cpu desc, mem desc, creation, uid — queue.go:74-110) done as
    # one vectorized lexsort over gathered per-class request columns
    cpu_u = np.array([rl.get("cpu", 0.0) for rl in req_u], dtype=np.float64)
    mem_u = np.array([rl.get("memory", 0.0) for rl in req_u], dtype=np.float64)
    ts = np.array(
        [p.metadata.creation_timestamp or 0.0 for p in pods], dtype=np.float64
    )
    uids = np.array([p.metadata.uid for p in pods])
    order = (
        np.lexsort((uids, ts, -mem_u[uidx0], -cpu_u[uidx0])).astype(np.int32)
        if P0
        else np.zeros(0, np.int32)
    )
    pods_sorted = [pods[i] for i in order]
    uidx = uidx0[order]

    def ffd_key_of_class(u):
        return (-cpu_u[u], -mem_u[u])

    pod_reqs_u = [Requirements.from_pod(p) for p in uniq_pods]
    tmpl_reqs_list = [t.requirements for t in templates]
    type_reqs_list = [it.requirements for it in all_types]
    exist_reqs_list = []
    for node in state_nodes:
        reqs = Requirements.from_labels(node.labels())
        reqs.add(Requirement(LABEL_HOSTNAME, "In", [node.hostname()]))
        exist_reqs_list.append(reqs)

    # -- host topology (seeds domain counts incl. cluster pods) -----------
    from karpenter_core_tpu.controllers.provisioning.scheduling.scheduler import build_domains
    from karpenter_core_tpu.controllers.provisioning.scheduling.topology import (
        Topology as HostTopology,
    )

    domains = build_domains(provisioners, instance_types)
    host_topology = HostTopology(
        kube_client, cluster, domains, pods_sorted, update_pods=uniq_pods
    )
    topo_groups = list(host_topology.topologies.values()) + list(
        host_topology.inverse_topologies.values()
    )

    # -- dictionary closure ------------------------------------------------
    # the EXISTING-NODE axis is padded to a power-of-two bucket (closed
    # sentinel slots, see below) so batches with varying node counts share a
    # compiled program; hostname values pad in step so the segment width
    # tracks the bucket, not the live count
    E_real = len(state_nodes)
    E_pad = ladder_pad(E_real, ladder, "existing_nodes", 8)
    if reuse_dictionary is not None:
        dictionary = reuse_dictionary
    else:
        dictionary = LabelDictionary()
        for reqs in pod_reqs_u + tmpl_reqs_list + type_reqs_list + exist_reqs_list:
            _collect_requirement_values(reqs, dictionary)
        for tg in topo_groups:
            if tg.key == LABEL_HOSTNAME:
                dictionary.add_key(tg.key)  # hostname domains live on slot identity
            else:
                dictionary.add_key(tg.key)
                for d in tg.domains:
                    dictionary.add_value(tg.key, d)
            for term in tg.node_filter.terms:
                _collect_requirement_values(term, dictionary)
        # zone/capacity-type always present for offering logic
        dictionary.add_key(LABEL_TOPOLOGY_ZONE)
        dictionary.add_key(api_labels.LABEL_CAPACITY_TYPE)
        for it in all_types:
            for o in it.offerings:
                dictionary.add_value(LABEL_TOPOLOGY_ZONE, o.zone)
                dictionary.add_value(api_labels.LABEL_CAPACITY_TYPE, o.capacity_type)
        if E_real:
            for i in range(E_real, E_pad):
                dictionary.add_value(LABEL_HOSTNAME, f"__exist-pad-{i}")
        # canonical order (sorted keys/values — placements must be a pure
        # function of the vocabulary SET, not of which pod mentioned a
        # value first), with hostname's (large) segment LAST so the
        # screens can slice it off when no pod constrains hostname
        dictionary.canonicalize(last_key=LABEL_HOSTNAME)
        if carry_dictionary is not None and (
            dictionary_covers(carry_dictionary, dictionary)
            or (
                # same size-bloat bound as plain coverage, then try
                # rebinding new node hostnames onto unused pad/stale
                # entries (growing cluster inside one existing bucket)
                carry_dictionary.V <= max(2 * dictionary.V, dictionary.V + 32)
                and dictionary_rebind_hostnames(carry_dictionary, dictionary)
            )
        ):
            dictionary = carry_dictionary

    # -- resources ---------------------------------------------------------
    extended = sorted(
        set().union(
            *[set(rl) for rl in req_u] or [set()],
            *[set(it.allocatable()) for it in all_types] or [set()],
        )
        - set(CORE_RESOURCES)
    )
    resource_names = CORE_RESOURCES + extended
    R = len(resource_names)
    r_index = {r: i for i, r in enumerate(resource_names)}

    def encode_resources(rl: ResourceList) -> np.ndarray:
        out = np.zeros(R, dtype=np.float32)
        for name, q in rl.items():
            if name in r_index:
                out[r_index[name]] = q
        return out

    P, J, T, K, V = len(pods_sorted), len(templates), T_pad, dictionary.K, dictionary.V

    pod_requests_u = (
        np.stack([encode_resources(rl) for rl in req_u])
        if U
        else np.zeros((0, R), np.float32)
    )

    # daemon overhead per template (scheduler.go:253-270)
    tmpl_daemon = np.zeros((J, R), dtype=np.float32)
    for j, template in enumerate(templates):
        daemons = [
            p
            for p in daemonset_pods
            if taints_mod.tolerates(template.taints, p) is None
            and template.requirements.compatible(Requirements.from_pod(p)) is None
        ]
        tmpl_daemon[j] = encode_resources(
            resources_util.requests_for_pods(*daemons) if daemons else {"pods": 0.0}
        )

    tmpl_type_mask = np.zeros((J, T), dtype=bool)
    for j, row in enumerate(tmpl_type_mask_rows):
        for tid in row:
            tmpl_type_mask[j, tid] = True

    zlo, zhi = dictionary.segment(LABEL_TOPOLOGY_ZONE)
    clo, chi = dictionary.segment(api_labels.LABEL_CAPACITY_TYPE)

    # -- instance-type planes (reusable across solves) ---------------------
    # pure function of (type objects, dictionary content, resource names):
    # the type universe is stable between production batches, so these
    # planes are the first thing incremental encode skips
    type_key = (
        _ids(all_types),
        T_pad,
        EncodeReuse.dict_signature(dictionary),
        tuple(resource_names),
        EncodeReuse.offering_signature(all_types),
        EncodeReuse.resource_signature(all_types),
    )
    cached = reuse.get(type_key) if reuse is not None else None
    if cached is not None:
        (type_reqs_arr, type_alloc, type_capacity, type_offering_ok,
         type_offering_price, type_min_price) = cached
    else:
        # rows [T_real, T_pad) are closed pad types: allocatable -1 (fits()
        # rejects negatives), capacity 0, no offerings, offered by no
        # template — unreachable by the kernel, present only to keep the
        # type axis on a ladder tier
        type_alloc = np.full((T, R), -1.0, dtype=np.float32)
        type_capacity = np.zeros((T, R), dtype=np.float32)
        if T_real:
            type_alloc[:T_real] = np.stack(
                [encode_resources(it.allocatable()) for it in all_types]
            )
            type_capacity[:T_real] = np.stack(
                [encode_resources(it.capacity) for it in all_types]
            )

        # -- offerings -----------------------------------------------------
        Z, C = zhi - zlo, chi - clo
        zones = dictionary.values_of(LABEL_TOPOLOGY_ZONE)
        cts = dictionary.values_of(api_labels.LABEL_CAPACITY_TYPE)
        z_index = {z: i for i, z in enumerate(zones)}
        c_index = {c: i for i, c in enumerate(cts)}
        type_offering_ok = np.zeros((T, Z, C), dtype=bool)
        type_offering_price = np.full((T, Z, C), np.inf, dtype=np.float32)
        for t, it in enumerate(all_types):
            for o in it.offerings:
                if not o.available:
                    continue
                zi, ci = z_index.get(o.zone), c_index.get(o.capacity_type)
                if zi is None or ci is None:
                    continue
                type_offering_ok[t, zi, ci] = True
                type_offering_price[t, zi, ci] = min(type_offering_price[t, zi, ci], o.price)
        type_min_price = np.where(
            type_offering_ok.any(axis=(1, 2)),
            np.min(type_offering_price, axis=(1, 2)),
            np.inf,
        ).astype(np.float32)
        type_reqs_arr = encode_reqsets(
            type_reqs_list + [Requirements() for _ in range(T_pad - T_real)],
            dictionary,
        )
        if reuse is not None:
            reuse.put(
                type_key,
                (type_reqs_arr, type_alloc, type_capacity, type_offering_ok,
                 type_offering_price, type_min_price),
                all_types,
            )

    # -- taints ------------------------------------------------------------
    pod_tol_u = np.zeros((U, J), dtype=bool)
    for j, template in enumerate(templates):
        for u, p in enumerate(uniq_pods):
            pod_tol_u[u, j] = taints_mod.tolerates(template.taints, p) is None

    well_known = np.array(
        [k in api_labels.WELL_KNOWN_LABELS or k == LABEL_HOSTNAME for k in dictionary.keys],
        dtype=bool,
    )

    # -- existing nodes ----------------------------------------------------
    # pod x node toleration is evaluated once per (spec class,
    # taint-signature): cluster nodes overwhelmingly share a handful of
    # taint sets, so the P x E double loop becomes #classes x #signatures.
    # Rows [E_real, E_pad) are closed sentinels: cap=-1 never fits
    # (compat.fits rejects negative allocatable) and tolerations are False,
    # so the kernel can never place onto them — they exist only to keep the
    # array geometry on a bucket boundary.
    E = E_real
    exist_used = np.zeros((E_pad, R), dtype=np.float32)
    exist_cap = np.full((E_pad, R), -1.0, dtype=np.float32)
    exist_cap[:E] = 0.0
    exist_reqs_list = exist_reqs_list + [
        Requirements() for _ in range(E_pad - E_real)
    ]
    # tolerations evaluate once per (spec class, taint signature), then ONE
    # two-axis numpy gather builds [P, E_pad] — per-column writes cost ~0.6s
    # of host time at 50k x 1k (measured), the gather ~0.1s. Signature index
    # S is the sentinel all-False row for the pad slots.
    taint_sig_ids: Dict[Tuple, int] = {}
    tol_rows_u: List[np.ndarray] = []
    sig_of_node = np.empty(E_pad, dtype=np.int64)
    for e, node in enumerate(state_nodes):
        node_taints = node.taints()
        # daemons that would schedule to this node (scheduler.go:231-240)
        daemons = [
            p
            for p in daemonset_pods
            if taints_mod.tolerates(node_taints, p) is None
            and Requirements.from_labels(node.labels()).compatible(Requirements.from_pod(p))
            is None
        ]
        daemon_req = resources_util.requests_for_pods(*daemons) if daemons else {"pods": 0.0}
        remaining = resources_util.subtract(daemon_req, node.total_daemonset_requests())
        remaining = {k: max(v, 0.0) for k, v in remaining.items()}
        exist_used[e] = encode_resources(remaining)
        exist_cap[e] = encode_resources(node.available())
        sig = tuple(
            sorted((t.key, t.value, t.effect) for t in node_taints)
        )
        s = taint_sig_ids.get(sig)
        if s is None:
            s = taint_sig_ids[sig] = len(tol_rows_u)
            tol_rows_u.append(
                np.fromiter(
                    (taints_mod.tolerates(node_taints, p) is None for p in uniq_pods),
                    dtype=bool,
                    count=U,
                )
            )
        sig_of_node[e] = s
    S = len(tol_rows_u)
    sig_of_node[E_real:] = S
    tol_exist_us = np.zeros((U, S + 1), dtype=bool)  # [:, S] all-False (pad)
    if S:
        tol_exist_us[:, :S] = np.stack(tol_rows_u, axis=1)

    # -- host ports + CSI volumes -----------------------------------------
    # lowered only when the batch/cluster actually uses them (Q = W = 0 is
    # the common case and compiles to nothing)
    from karpenter_core_tpu.scheduling.hostportusage import host_ports
    from karpenter_core_tpu.scheduling.volumeusage import VolumeUsage

    pod_ports_u_list = [host_ports(p) for p in uniq_pods]
    port_index: Dict[Tuple, int] = {}
    port_entries: List = []

    def _port_id(entry):
        key = (entry.ip, entry.port, entry.protocol)
        q = port_index.get(key)
        if q is None:
            q = port_index[key] = len(port_entries)
            port_entries.append(entry)
        return q

    for entries in pod_ports_u_list:
        for entry in entries:
            _port_id(entry)
    exist_port_rows: List[List[int]] = []
    for node in state_nodes:
        row = []
        for entries in node.hostport_usage.reserved.values():
            for entry in entries:
                row.append(_port_id(entry))
        exist_port_rows.append(row)
    # pad to a bucket like every other batch-size axis: new distinct entries
    # must not recompile the solve program (pad columns are all-False, so
    # they can never conflict or count)
    Q = bucket_pow2(len(port_entries), 8)
    pod_ports_u = np.zeros((U, Q), dtype=bool)
    for u, entries in enumerate(pod_ports_u_list):
        for entry in entries:
            pod_ports_u[u, port_index[(entry.ip, entry.port, entry.protocol)]] = True
    conflict = np.zeros((Q, Q), dtype=bool)
    for a in range(len(port_entries)):
        for b in range(len(port_entries)):
            conflict[a, b] = port_entries[a].matches(port_entries[b])
    pod_port_conflict_u = pod_ports_u @ conflict  # [U, Q] bool via matmul
    exist_ports = np.zeros((E_pad, Q), dtype=bool)
    for e, row in enumerate(exist_port_rows):
        exist_ports[e, row] = True

    vu = VolumeUsage(kube_client)
    pod_vols_u_list = [vu._resolve(p) for p in uniq_pods]
    vol_index: Dict[Tuple[str, str], int] = {}
    driver_index: Dict[str, int] = {}

    def _vol_id(driver, pvc_id):
        w = vol_index.get((driver, pvc_id))
        if w is None:
            w = vol_index[(driver, pvc_id)] = len(vol_index)
            if driver not in driver_index:
                driver_index[driver] = len(driver_index)
        return w

    for vols in pod_vols_u_list:
        for driver, ids in vols.items():
            for pvc_id in ids:
                _vol_id(driver, pvc_id)
    for node in state_nodes:
        for driver, ids in node.volume_usage.volumes.items():
            for pvc_id in ids:
                _vol_id(driver, pvc_id)
        for driver in node.volume_limits:
            if driver not in driver_index:
                driver_index[driver] = len(driver_index)
    W = bucket_pow2(len(vol_index), 8)
    D = bucket_pow2(len(driver_index), 2)
    pod_vols_u = np.zeros((U, W), dtype=bool)
    for u, vols in enumerate(pod_vols_u_list):
        for driver, ids in vols.items():
            for pvc_id in ids:
                pod_vols_u[u, vol_index[(driver, pvc_id)]] = True
    exist_vols = np.zeros((E_pad, W), dtype=bool)
    exist_vol_limits = np.full((E_pad, D), np.inf, dtype=np.float32)
    for e, node in enumerate(state_nodes):
        for driver, ids in node.volume_usage.volumes.items():
            for pvc_id in ids:
                exist_vols[e, vol_index[(driver, pvc_id)]] = True
        for driver, limit in node.volume_limits.items():
            if limit is not None:
                exist_vol_limits[e, driver_index[driver]] = float(limit)
    vol_driver_onehot = np.zeros((W, D), dtype=np.float32)
    for (driver, _pvc), w in vol_index.items():
        vol_driver_onehot[w, driver_index[driver]] = 1.0

    # -- topology arrays ---------------------------------------------------
    from karpenter_core_tpu.ops.topology import encode_topology

    # machine-slot budget on a pow2 bucket (NOT the pods ladder: the ladder
    # rungs are coarse, and doubling every small geometry's slot axis costs
    # real compile+scan time; pow2-of-batch stays bounded because the
    # batcher's pass cap clamps to the ladder's top rung)
    n_slots = E_pad + min(max_nodes, bucket_pow2(max(P, 1), 64))
    topo_meta, topo_arrays = encode_topology(
        host_topology,
        pods_sorted,
        dictionary,
        n_slots,
        [n.hostname() for n in state_nodes],
        uidx=uidx,
        uniq_pods=uniq_pods,
    )

    # -- pod requirement rows: encoded per class; [P] views are lazy -------
    pod_reqs_u_arr = encode_reqsets(pod_reqs_u, dictionary)

    # screens may run on a prefix of the value axis: when no pod (and no
    # instance type) constrains hostname, every hostname term in
    # Compatible/Intersects resolves through ~shared regardless of the
    # segment's content, and the segment — one value per existing node +
    # pad, roughly half of V on a real cluster — sits LAST by construction
    if reuse_dictionary is not None:
        # sticky across relaxation rounds: dropping a pod's hostname term
        # mid-solve must not change the screen width (and recompile)
        screen_v = getattr(dictionary, "screen_v", dictionary.V)
    else:
        screen_v = dictionary.V
        if LABEL_HOSTNAME in dictionary.key_index:
            hlo, hhi = dictionary.segment(LABEL_HOSTNAME)
            hostname_last = hhi == dictionary.V
            k_h = dictionary.key_index[LABEL_HOSTNAME]
            pods_constrain = (
                bool(pod_reqs_u_arr.defined[:, k_h].any()) if U else False
            )
            types_constrain = any(
                LABEL_HOSTNAME in it.requirements for it in all_types
            )
            if hostname_last and not pods_constrain and not types_constrain:
                screen_v = hlo
        dictionary.screen_v = screen_v

    # -- pod equivalence classes (items) -----------------------------------
    item_of_pod, item_counts, item_rep, item_members = _build_items(
        uidx, topo_meta, topo_arrays,
        # resource components only (drop creation-time/uid tie-breakers so
        # same-sized classes form one ordering group)
        ffd_key_of_class=ffd_key_of_class,
    )

    # verdict-column dedup: items of one class (anti-affinity expansion)
    # share one prescreen column — requirement verdicts depend only on the
    # class planes, so the dedup is exact (ops/pack.py gathers by item_scls)
    cls_of_item = uidx[item_rep] if len(item_rep) else item_rep
    _ucls, scls_items, item_scls = np.unique(
        cls_of_item, return_index=True, return_inverse=True
    )

    # item / verdict-column axis pads, chosen HERE so every consumer
    # (solve_geometry, device_args, the replan rung builder) pads to the
    # same ladder tier; heavy anti-affinity expansion can push the item
    # axis a rung above the batch's pods tier — still a listed value
    item_pad = ladder_pad(max(len(item_counts), 1), ladder, "items", 32)
    cls_pad = ladder_pad(max(len(scls_items), 1), ladder, "items", 32)

    # segmented pack-scan metadata (ISSUE 14): structural eligibility and
    # the per-class plane-neutrality mask, computed here (pure functions of
    # the encoded planes) so the dispatch gate is one flag read and the
    # partitioner's host-side mirror never drifts from the encoder
    seg_key_scr = np.array(
        [dictionary.segment(k)[0] < screen_v for k in dictionary.keys],
        dtype=bool,
    )
    seg_plane_neutral = ~(
        pod_reqs_u_arr.defined & seg_key_scr[None, :]
    ).any(axis=1)
    seg_eligible = (
        (topo_meta is None or len(topo_meta.groups) == 0)
        and (pod_ports_u is None or pod_ports_u.shape[1] == 0)
        and (pod_vols_u is None or pod_vols_u.shape[1] == 0)
    )

    return EncodedSnapshot(
        dictionary=dictionary,
        resource_names=resource_names,
        pod_reqs_u=pod_reqs_u_arr,
        pod_requests_u=pod_requests_u,
        pod_tol_u=pod_tol_u,
        uidx=uidx,
        tmpl_reqs=encode_reqsets(tmpl_reqs_list, dictionary),
        tmpl_daemon=tmpl_daemon,
        tmpl_type_mask=tmpl_type_mask,
        type_reqs=type_reqs_arr,
        type_alloc=type_alloc,
        type_capacity=type_capacity,
        type_offering_ok=type_offering_ok,
        type_offering_price=type_offering_price,
        type_min_price=type_min_price,
        well_known=well_known,
        zone_seg=(zlo, zhi),
        ct_seg=(clo, chi),
        exist_reqs=encode_reqsets(exist_reqs_list, dictionary),
        exist_used=exist_used,
        exist_cap=exist_cap,
        tol_exist_us=tol_exist_us,
        sig_of_node=sig_of_node,
        pod_ports_u=pod_ports_u,
        pod_port_conflict_u=pod_port_conflict_u,
        exist_ports=exist_ports,
        pod_vols_u=pod_vols_u,
        exist_vols=exist_vols,
        exist_vol_limits=exist_vol_limits,
        vol_driver_onehot=vol_driver_onehot,
        topo_meta=topo_meta,
        topo_arrays=topo_arrays,
        n_slots=n_slots,
        screen_v=screen_v,
        item_of_pod=item_of_pod,
        item_counts=item_counts,
        item_rep=item_rep,
        item_members=item_members,
        item_scls=item_scls.astype(np.int32),
        scls_items=scls_items.astype(np.int32),
        item_pad=item_pad,
        cls_pad=cls_pad,
        ladder=ladder,
        seg_eligible=seg_eligible,
        seg_plane_neutral=seg_plane_neutral,
        instance_types=all_types,
        templates=templates,
        pods=pods_sorted,
        state_nodes=state_nodes,
        pod_order=order,
    )


def _build_items(uidx, topo_meta, topo_arrays, ffd_key_of_class=None):
    """Group FFD-sorted pods into items by spec-equivalence class (uidx[i] =
    pod i's class). Classes involved in a VALUE-KEY anti-affinity group are
    expanded back to count=1 items: each placement's "block out all possible
    domains" record (topology.go:120-143) changes the next placement's
    viability, so the reference's per-pod re-evaluation (scheduler.go:96-133)
    must be preserved. Hostname anti-affinity (the one-replica-per-node
    service pattern) is slot-local — thost[g, n] tracks it per slot exactly —
    so those classes stay bulk (kernel caps takes at 1/slot; the
    machine-region bulk fill commits whole replica groups per iteration),
    except owners that don't match their own selector (see inline comment).
    Spread and affinity owners stay bulk: hostname groups are governed by the
    kernel's skew-headroom cap, and value-key spread owners by its
    per-iteration water-fill domain allocation, both of which reproduce the
    per-pod greedy's final counts for identical replicas.

    Returns (item_of_pod [P], item_counts [I], item_rep [I], members)."""
    from karpenter_core_tpu.ops.topology import TOPO_ANTI

    P = len(uidx)
    if P == 0:
        return (
            np.zeros(0, np.int32),
            np.zeros(0, np.int32),
            np.zeros(0, np.int32),
            [],
        )
    expand_pod = np.zeros(P, dtype=bool)
    if topo_meta is not None:
        owner = topo_arrays.owner  # [G, P]
        sel = topo_arrays.sel
        for g, gm in enumerate(topo_meta.groups):
            if gm.gtype != TOPO_ANTI:
                continue
            applies = sel[g] if gm.is_inverse else owner[g]
            if not gm.is_hostname or len(gm.filter_term_rows) > 0:
                # value-key anti: a placement in domain d registers every
                # possible domain and kills all of d's slots — per-pod
                # re-evaluation required. Filter terms: nf_ok is per merged
                # slot row, outside the bulk paths.
                expand_pod |= applies
            elif not gm.is_inverse:
                # hostname anti is SLOT-LOCAL (the domain is the node):
                # thost[g, n] tracks it per slot exactly, the kernel caps
                # bulk takes at 1/slot and the machine-region bulk fill
                # commits a whole replica group in one iteration — the class
                # stays bulk. Exception: an owner that does NOT match its
                # own selector (replicas may legally co-locate, the 1-cap
                # would diverge) keeps the reference's per-pod items.
                expand_pod |= owner[g] & ~sel[g]
    class_item: Dict[int, int] = {}
    item_of_pod = np.zeros(P, dtype=np.int32)
    counts: List[int] = []
    reps: List[int] = []
    members: List[List[int]] = []
    for i in range(P):
        if expand_pod[i]:
            item = len(counts)
            counts.append(1)
            reps.append(i)
            members.append([i])
        else:
            u = int(uidx[i])
            item = class_item.get(u)
            if item is None:
                item = len(counts)
                class_item[u] = item
                counts.append(0)
                reps.append(i)
                members.append([])
            counts[item] += 1
            members[item].append(i)
        item_of_pod[i] = item

    # Within an FFD tie group, hostname-spread and hostname-anti owners go
    # first: each of their replicas opens (or claims) a near-empty node, and
    # the reference's interleaved per-pod loop lets same-sized pods that
    # follow co-locate onto those nodes (machines rank by ascending pod
    # count, scheduler.go:186-193). Processing them after a bulk class would
    # open the one-replica-per-node seeds too late to be reused — measured
    # ~20% extra nodes on the config-3 mix when anti classes went last.
    if topo_meta is not None and ffd_key_of_class is not None:
        from karpenter_core_tpu.ops.topology import TOPO_SPREAD

        hs_groups = [
            g
            for g, gm in enumerate(topo_meta.groups)
            if (gm.gtype == TOPO_SPREAD or gm.gtype == TOPO_ANTI)
            and gm.is_hostname
            and not gm.is_inverse
        ]
        if hs_groups:
            owner = topo_arrays.owner
            owns_hs = [
                any(owner[g, reps[it]] for g in hs_groups)
                for it in range(len(counts))
            ]
            order = sorted(
                range(len(counts)),
                key=lambda it: (
                    ffd_key_of_class(uidx[reps[it]]),
                    0 if owns_hs[it] else 1,
                    it,
                ),
            )
            inv = np.zeros(len(counts), dtype=np.int32)
            for new, old in enumerate(order):
                inv[old] = new
            item_of_pod = inv[item_of_pod]
            counts = [counts[old] for old in order]
            reps = [reps[old] for old in order]
            members = [members[old] for old in order]
    return (
        item_of_pod,
        np.asarray(counts, dtype=np.int32),
        np.asarray(reps, dtype=np.int32),
        members,
    )
