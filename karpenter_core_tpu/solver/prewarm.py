"""Startup AOT prewarm: compile the bucket ladder before the pods arrive.

The geometry bucket ladder (api/settings.py GeometryTier) makes the set of
solve programs the operator can ever need ENUMERABLE: every batch axis pads
to a tier value, so one (solve, prescreen, refresh, batched-replan) program
family per tier — against the cluster's real provisioners and
instance-type universe — covers every generic steady-state batch AND the
first consolidation pass (the replan program compiles at the smallest
candidate-axis bucket, the multi-node ladder's shape —
docs/consolidation.md). This module synthesizes a vocabulary-neutral
workload per tier and AOT-compiles the family through
TPUSolver.prewarm_snapshot (jax.jit(...).lower().compile()), so:

  * a live solve that lands on a prewarmed tier is a cache HIT — no
    compile stall, even on the very first Solve() after a restart;
  * a live solve arriving MID-prewarm blocks only on its own tier's
    per-key lock (TPUSolver._entry_for) — never a duplicate compile;
  * every compile writes the persistent disk cache (utils/compilecache),
    so the NEXT restart deserializes in seconds even for tiers this
    process never finished warming.

What prewarm cannot cover: batches whose pods add label vocabulary or
topology constraints (spread/anti-affinity groups are static kernel
parameters) mint their own geometry — those fall back to the persistent
disk cache populated by earlier live traffic. The synthetic workload is
built from the REAL provisioners and instance types precisely so the
dictionary layout (key set, segment widths, zone/capacity-type values)
matches what real vocabulary-neutral batches produce.

Ordering: the steady-state tier (Settings.steady_state_tier — the rung the
batcher's pass cap lands on) compiles FIRST, then the remaining tiers
ascending, so the common case is warm earliest. Observability:
karpenter_prewarm_* metrics and a `solver.prewarm` trace span per tier.

Multi-chip (ISSUE 8): ShardedSolver inherits prewarm_snapshot, and its
_layout_for routing decides per tier exactly as live traffic would — so
a multi-chip operator AOT-prewarms its GSPMD MESH programs (cache keys
carry the mesh shape) for tiers that route to the mesh, and the plain
single-device programs for tiers below the small-batch floor
(docs/compile-cache.md#sharded-prewarm-keys).
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence

from karpenter_core_tpu.metrics.registry import NAMESPACE, REGISTRY
from karpenter_core_tpu.obs import TRACER
from karpenter_core_tpu.obs.log import get_logger

LOG = get_logger("karpenter.solver.prewarm")

PREWARM_TOTAL = REGISTRY.counter(
    f"{NAMESPACE}_prewarm_total",
    "Bucket-ladder prewarm outcomes, by tier and outcome (compiled = this "
    "thread paid the AOT compile, cached = a live solve or a previous run "
    "got there first, error = compile failed, skipped = stopped early)",
)
PREWARM_SECONDS = REGISTRY.histogram(
    f"{NAMESPACE}_prewarm_seconds",
    "Seconds spent AOT-compiling one tier's program triple (includes the "
    "persistent-cache disk load when the entry already existed on disk)",
)
PREWARM_READY = REGISTRY.gauge(
    f"{NAMESPACE}_prewarm_ready",
    "1 once every requested tier finished prewarming (0 while in flight)",
)


def synthetic_workload(tier, provisioners, instance_types,
                       pods_count: Optional[int] = None):
    """A vocabulary-neutral (pods, state_nodes) pair that encodes to the
    tier's geometry against the REAL provisioner/type universe.

    Pods carry only distinct metadata labels (spec-equivalence classes
    WITHOUT touching the label dictionary — pod labels only enter the
    dictionary through topology selection, and these pods declare none) and
    uniform requests; nodes carry the standard provisioned-node label set
    with synthetic hostnames (hostname VALUES differ from the live
    cluster's, but the geometry key depends only on segment widths).

    pods_count overrides the default tier-top sizing: the pods-DERIVED
    axes (commit log, slot budget) are fine pow2 of the LIVE batch size,
    so the steady-state tier must prewarm at the batcher's actual pass cap
    — prewarm() passes batch_max_pods — or the live pass lands one pow2
    rung away from the warmed program and misses it."""
    from karpenter_core_tpu.api import labels as api_labels
    from karpenter_core_tpu.kube.objects import (
        LABEL_INSTANCE_TYPE_STABLE,
        LABEL_TOPOLOGY_ZONE,
        Condition,
        Container,
        Node,
        ObjectMeta,
        Pod,
        PodSpec,
        ResourceRequirements,
    )
    from karpenter_core_tpu.state.node import StateNode

    # default: the top of the rung minus the commit-log headroom (so
    # log_len lands on the tier's own pow2), spread over tier.items
    # distinct spec classes
    n_pods = max(pods_count or (tier.pods - 64), 1)
    n_items = max(min(tier.items, n_pods), 1)
    pods: List[Pod] = []
    for i in range(n_pods):
        pods.append(
            Pod(
                metadata=ObjectMeta(
                    name=f"prewarm-{i}",
                    labels={"app": f"prewarm-{i % n_items}"},
                    creation_timestamp=0.0,
                ),
                spec=PodSpec(
                    containers=[
                        Container(
                            resources=ResourceRequirements(
                                requests={"cpu": 0.1, "memory": 128 * 2**20}
                            )
                        )
                    ]
                ),
            )
        )

    all_types = [it for its in instance_types.values() for it in its]
    prov_name = provisioners[0].name if provisioners else "default"
    nodes = []
    for e in range(tier.existing_nodes):
        it = all_types[e % len(all_types)] if all_types else None
        offering = it.offerings[0] if it is not None and it.offerings else None
        labels = {
            api_labels.PROVISIONER_NAME_LABEL_KEY: prov_name,
            api_labels.LABEL_NODE_INITIALIZED: "true",
        }
        if it is not None:
            labels[LABEL_INSTANCE_TYPE_STABLE] = it.name
        if offering is not None:
            labels[LABEL_TOPOLOGY_ZONE] = offering.zone
            labels[api_labels.LABEL_CAPACITY_TYPE] = offering.capacity_type
        node = Node(metadata=ObjectMeta(name=f"prewarm-node-{e}", labels=labels))
        node.spec.provider_id = f"prewarm:///{node.metadata.name}"
        if it is not None:
            node.status.capacity = dict(it.capacity)
            node.status.allocatable = dict(it.allocatable())
        node.status.conditions.append(Condition(type="Ready", status="True"))
        nodes.append(StateNode(node=node))
    return pods, nodes


def _order_tiers(ladder, settings) -> List:
    """Steady-state tier first, then the rest ascending."""
    tiers = list(ladder)
    steady = settings.steady_state_tier() if settings is not None else None
    if steady is not None and steady in tiers:
        tiers.remove(steady)
        tiers.insert(0, steady)
    return tiers


def prewarm(
    solver,
    provisioners: Sequence,
    instance_types: Dict[str, List],
    settings=None,
    tiers: Optional[Sequence[str]] = None,
    stop: Optional[threading.Event] = None,
) -> Dict[str, str]:
    """AOT-compile the ladder's programs on `solver` (must expose
    encode-compatible prewarm_snapshot — TPUSolver does; other backends
    are skipped by the caller). Returns {tier name: outcome}. Honors
    `stop` between tiers so operator shutdown never waits on a compile
    that hasn't started."""
    from karpenter_core_tpu.api import settings as api_settings
    from karpenter_core_tpu.solver.encode import encode_snapshot

    settings = settings or api_settings.current()
    ladder = tuple(settings.bucket_ladder or ())
    if tiers is not None:
        wanted = set(tiers)
        ladder = tuple(t for t in ladder if t.name in wanted)
    outcomes: Dict[str, str] = {}
    PREWARM_READY.set(0.0)
    if not ladder:
        # nothing selected (empty ladder, or KARPENTER_PREWARM_TIERS names
        # no configured tier): leave ready at 0 and say so — an empty
        # outcome set must never read as "fully warm"
        LOG.warning(
            "prewarm selected no tiers",
            requested=",".join(tiers) if tiers is not None else "",
        )
        return outcomes
    steady = settings.steady_state_tier()
    for tier in _order_tiers(ladder, settings):
        if stop is not None and stop.is_set():
            outcomes[tier.name] = "skipped"
            PREWARM_TOTAL.inc({"tier": tier.name, "outcome": "skipped"})
            continue
        t0 = time.perf_counter()
        try:
            with TRACER.span(
                "solver.prewarm", tier=tier.name, pods=tier.pods,
                items=tier.items, types=tier.instance_types,
                existing=tier.existing_nodes,
            ):
                pods, nodes = synthetic_workload(
                    tier, provisioners, instance_types,
                    # the steady-state tier warms at the batcher's REAL
                    # pass size: the pods-derived pow2 axes (commit log,
                    # slot budget) must match the live capped pass or the
                    # common case misses the warmed program
                    pods_count=(
                        settings.batch_max_pods
                        if tier is steady and settings.batch_max_pods
                        and settings.batch_max_pods <= tier.pods
                        else None
                    ),
                )
                snap = encode_snapshot(
                    list(pods), list(provisioners), instance_types,
                    state_nodes=nodes, max_nodes=solver.max_nodes,
                    ladder=ladder or None,
                )
                outcomes[tier.name] = solver.prewarm_snapshot(
                    snap, list(provisioners)
                )
        except Exception as exc:  # noqa: BLE001 — prewarm must never kill the operator
            outcomes[tier.name] = "error"
            LOG.warning(
                "prewarm tier failed", tier=tier.name,
                error=type(exc).__name__, error_detail=str(exc)[:200],
            )
        seconds = time.perf_counter() - t0
        PREWARM_TOTAL.inc({"tier": tier.name, "outcome": outcomes[tier.name]})
        PREWARM_SECONDS.observe(seconds)
        LOG.info(
            "prewarm tier done", tier=tier.name,
            outcome=outcomes[tier.name], seconds=round(seconds, 1),
        )
    if all(o in ("compiled", "cached") for o in outcomes.values()):
        PREWARM_READY.set(1.0)
    return outcomes


def start_prewarm_thread(
    solver,
    provisioners_fn,
    instance_types_fn,
    settings=None,
    tiers: Optional[Sequence[str]] = None,
    stop: Optional[threading.Event] = None,
    wait_seconds: float = 600.0,
) -> Optional[threading.Thread]:
    """Run prewarm on a named daemon thread, overlapped with the watch-
    cache sync: provisioners_fn/instance_types_fn are polled until the
    cluster has a provisioner (a fresh cluster has none yet — nothing to
    prewarm against), then the ladder compiles priority-ordered. Returns
    the thread, or None when the solver has no prewarm surface (gRPC
    RemoteSolver, host greedy)."""
    if not hasattr(solver, "prewarm_snapshot"):
        LOG.info(
            "prewarm skipped: solver has no prewarm surface",
            solver=type(solver).__name__,
        )
        return None

    def _run():
        deadline = time.monotonic() + wait_seconds
        provisioners = []
        while time.monotonic() < deadline:
            if stop is not None and stop.is_set():
                return
            try:
                provisioners = list(provisioners_fn() or [])
            except Exception:  # noqa: BLE001 — watch cache still syncing
                provisioners = []
            if provisioners:
                break
            if stop is not None:
                stop.wait(3.0)
            else:
                time.sleep(3.0)
        if not provisioners:
            LOG.info("prewarm skipped: no provisioners appeared in time")
            return
        try:
            instance_types = instance_types_fn(provisioners) or {}
        except Exception as exc:  # noqa: BLE001
            LOG.warning(
                "prewarm skipped: instance types unavailable",
                error=type(exc).__name__, error_detail=str(exc)[:200],
            )
            return
        prewarm(
            solver, provisioners, instance_types,
            settings=settings, tiers=tiers, stop=stop,
        )

    thread = threading.Thread(target=_run, daemon=True, name="solver-prewarm")
    thread.start()
    return thread
