"""Batched consolidation replan: the whole prefix ladder as ONE device
dispatch.

The reference evaluates multi-node consolidation by binary-searching the
candidate prefix with O(log N) sequential full scheduling simulations
(multinodeconsolidation.go:87-113). Round 1 replaced that with a host loop
over ladder rungs — still one encode + one dispatch PER RUNG. Here the union
scenario is encoded ONCE — every candidate stays in the snapshot as an
existing slot, every candidate's pods enter the pod axis with a candidate
tag — and all rungs run as one jit(vmap) over (count_row, exist_open):

  rung r: candidates[:size_r] close their slots (exist_open) and activate
  their pods' replica counts (count_row); everything else is shared.

The screen returns per-rung (all_scheduled, n_new_machines, conclusive);
the caller confirms the winning prefix through the exact solve path (price
rules, relaxation) — one batched dispatch plus one confirming solve instead
of up to 8 sequential solves.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Tuple

import numpy as np

from karpenter_core_tpu.utils import podutils


@dataclass
class RungScreen:
    size: int
    all_scheduled: bool
    n_new_machines: int
    conclusive: bool  # False when an uninitialized existing node took pods


def batched_ladder_screen(
    kube_client,
    cluster,
    provisioning,
    candidates,
    sizes: List[int],
    max_nodes: int = 1024,
) -> List[RungScreen]:
    """One union encode + one vmapped dispatch screening every ladder rung.

    Raises CandidateNodeDeletingError under the same conditions as
    simulate_scheduling (a candidate is already mid-delete)."""
    from karpenter_core_tpu.obs import TRACER

    with TRACER.span(
        "deprovisioning.ladder_screen",
        candidates=len(candidates), rungs=len(sizes),
    ):
        return _ladder_screen_traced(
            kube_client, cluster, provisioning, candidates, sizes, max_nodes
        )


def _ladder_screen_traced(
    kube_client,
    cluster,
    provisioning,
    candidates,
    sizes: List[int],
    max_nodes: int,
) -> List[RungScreen]:
    import jax

    from karpenter_core_tpu.controllers.deprovisioning.core import (
        CandidateNodeDeletingError,
    )
    from karpenter_core_tpu.solver.encode import encode_snapshot
    from karpenter_core_tpu.solver.tpu_solver import make_device_run, solve_geometry

    candidate_names = {c.name for c in candidates}
    state_nodes = []
    deleting_nodes = []
    for node in cluster.nodes():
        if node.is_marked_for_deletion():
            deleting_nodes.append(node)
        elif node.name() not in candidate_names:
            state_nodes.append(node)
    if any(n.name() in candidate_names for n in deleting_nodes):
        raise CandidateNodeDeletingError()

    # pod axis: pending + deleting-node pods (always active) + candidate
    # pods (active from the rung that removes their node)
    pods: List = []
    cand_of: List[int] = []
    for p in provisioning.get_pending_pods():
        pods.append(p)
        cand_of.append(-1)
    for node in deleting_nodes:
        for p in kube_client.list(
            "Pod",
            field_filter=lambda p, n=node: p.spec.node_name == n.name(),
            copy_objects=False,  # read-only below; see clone note
        ):
            if not podutils.is_terminal(p) and not podutils.is_owned_by_daemonset(p):
                pods.append(p)
                cand_of.append(-1)
    for ci, c in enumerate(candidates):
        for p in c.pods:
            if not podutils.is_owned_by_daemonset(p):
                pods.append(p)
                cand_of.append(ci)
    # NO clone_for_simulation here, unlike simulate_scheduling. INVARIANT:
    # this path must stay strictly read-only over live Pod objects —
    # encode_snapshot consumes specs/labels without normalizing them, the
    # device path never reads spec.node_name, and the round-0 kernel does
    # no preference relaxation (the only mutating step in the exact path).
    # Any future consumer of snap.pods that mutates or reads node_name must
    # reinstate the shallow clone. Measured (2026-07-30, config-4 profile):
    # even the SHALLOW clone of 10k shared Pods cost 97-309ms per replan —
    # comparable to the ~170ms device dispatch it feeds.
    cand_of_pod: Dict[str, int] = {
        p.metadata.uid: ci for p, ci in zip(pods, cand_of)
    }

    provisioners = [
        p for p in kube_client.list("Provisioner")
        if p.metadata.deletion_timestamp is None
    ]
    if not provisioners:
        return [
            RungScreen(size=s, all_scheduled=not pods, n_new_machines=0,
                       conclusive=True)
            for s in sizes
        ]
    instance_types = {
        p.name: provisioning.cloud_provider.get_instance_types(p)
        for p in provisioners
    }

    # candidate slots appended AFTER the regular nodes so their indices are
    # stable under encode's owned() filter (candidates are always owned)
    all_nodes = state_nodes + [c.state_node for c in candidates]
    snap = encode_snapshot(
        pods,
        provisioners,
        instance_types,
        provisioning.get_daemonset_pods(),
        all_nodes,
        kube_client=kube_client,
        cluster=cluster,
        max_nodes=max_nodes,
    )
    E = snap.exist_used.shape[0]  # bucket-padded existing axis
    name_to_slot = {n.name(): e for e, n in enumerate(snap.state_nodes)}
    cand_slot = np.full(len(candidates), -1, dtype=np.int64)
    for ci, c in enumerate(candidates):
        cand_slot[ci] = name_to_slot.get(c.name, -1)
    uninitialized = np.zeros(E, dtype=bool)  # padded sentinel rows: False
    uninitialized[: len(snap.state_nodes)] = [
        not n.initialized() for n in snap.state_nodes
    ]

    # per-row candidate tag on the FFD-sorted pod axis
    cand_of_row = np.array(
        [cand_of_pod.get(p.metadata.uid, -1) for p in snap.pods], dtype=np.int64
    )
    members = snap.item_members or [[i] for i in range(len(snap.pods))]
    I = len(snap.item_counts) if snap.item_counts is not None else len(snap.pods)

    Rn = len(sizes)
    from karpenter_core_tpu.solver.encode import bucket_pow2

    # count axis padded like device_args pads the item axis (the snapshot's
    # ladder tier when present)
    count_rows = np.zeros(
        (Rn, snap.item_pad or bucket_pow2(max(I, 1), 32)), dtype=np.int32
    )
    exist_open = np.ones((Rn, E), dtype=bool)
    for r, size in enumerate(sizes):
        for it in range(I):
            count_rows[r, it] = sum(
                1
                for m in members[it]
                if cand_of_row[m] < 0 or cand_of_row[m] < size
            )
        for ci in range(min(size, len(candidates))):
            if cand_slot[ci] >= 0:
                exist_open[r, cand_slot[ci]] = False

    geom = solve_geometry(snap, max_nodes)
    (_P, _J, _T, _E, _R, _K, _V, N, segments_t, zone_seg, ct_seg, _sig,
     log_len, _Q, _W, _D, screen_v) = geom
    cache = getattr(provisioning.solver, "_replan_compiled", None)
    if cache is None:
        cache = {}
        try:
            provisioning.solver._replan_compiled = cache
        except AttributeError:
            pass
    backend = getattr(provisioning.solver, "backend", None)
    key = (geom, Rn, backend)
    fn = cache.get(key)
    from karpenter_core_tpu.utils.compilecache import record_lookup

    record_lookup("replan", fn is not None)
    if fn is None:
        rung_run = make_device_run(
            segments_t, zone_seg, ct_seg, snap.topo_meta, N, log_len=log_len,
            rung_mode=True, backend=backend, screen_v=screen_v,
        )
        from karpenter_core_tpu.solver.tpu_solver import RUN_ARG_NAMES

        fn = jax.jit(
            jax.vmap(rung_run, in_axes=(0, 0) + (None,) * len(RUN_ARG_NAMES))
        )
        cache[key] = fn

    from karpenter_core_tpu.solver.tpu_solver import device_args

    args = device_args(snap, provisioners)
    log, ptr, state = fn(count_rows, exist_open, *args)
    pods_per_slot = np.asarray(state.pods)  # [Rn, N]

    screens = []
    for r, size in enumerate(sizes):
        scheduled = int(pods_per_slot[r].sum())
        expected = int(count_rows[r].sum())
        n_new = int((pods_per_slot[r, E:] > 0).sum())
        inconclusive = bool(
            (pods_per_slot[r, :E][uninitialized] > 0).any()
        )
        screens.append(
            RungScreen(
                size=size,
                all_scheduled=scheduled >= expected,
                n_new_machines=n_new,
                conclusive=not inconclusive,
            )
        )
    return screens
