"""Batched consolidation replan: K candidate node-subsets as ONE device
dispatch (ISSUE 10 tentpole).

The reference evaluates multi-node consolidation by binary-searching the
candidate prefix with O(log N) sequential full scheduling simulations
(multinodeconsolidation.go:87-113), and single-node consolidation with one
simulation PER candidate (singlenodeconsolidation.go:44-86). Earlier rounds
replaced the multi-node search with a host loop over ladder rungs, then
with a prefix-only vmapped screen. This module generalizes that screen to
ARBITRARY candidate subsets and adds a real objective, so the whole
deprovisioning search — the multi-node prefix ladder, every single-node
singleton, and the all-empty-nodes subset — evaluates as a handful of
device dispatches:

  * the union scenario is encoded ONCE: every candidate stays in the
    snapshot as an existing slot, every candidate's pods enter the pod
    axis tagged with their candidate index;
  * subset k closes its victims' slots (exist_open) and activates their
    pods' replica counts (count_row); everything else — feasibility
    planes, the [N, C] prescreen verdict tensor, instance types — is
    shared across subsets and traces once under the vmap
    (ops/pack.make_batched_replan_kernel);
  * the dispatch goes through TPUSolver.replan_screen, which stages the
    call through the same _bundle_args path as a live solve — so the
    prescreen program, the RESIDENT verdict tensor, and the delta-refresh
    machinery (solver/incremental.py) are shared with the provisioning
    path, and consecutive consolidation passes re-screen only the churned
    rows/columns;
  * each subset comes back with (all_scheduled, n_new_machines,
    conclusive) plus a host-computed objective — the subset's current
    price (deprovisioning.core.node_prices per candidate), its disruption
    cost, and the savings bound — so the caller ranks subsets by real
    savings instead of first-feasible-prefix.

The caller confirms winners through the exact solve path
(simulate_scheduling — price rules, relaxation), which stays the parity
oracle and the fallback when no batched-replan solver is attached.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from karpenter_core_tpu.utils import podutils


@dataclass
class SubsetScreen:
    """One candidate subset's device verdict + host objective."""

    subset: Tuple[int, ...]  # candidate indices (into the caller's list)
    all_scheduled: bool
    n_new_machines: int
    conclusive: bool  # False when an uninitialized existing node took pods
    # objective (host-computed): the subset's current offering price sum,
    # its eviction-cost disruption, and the savings bound used for ranking
    # (price minus the cheapest possible replacement when any new machine
    # is needed; deletes save the full price)
    price: float = 0.0
    disruption: float = 0.0
    savings: float = 0.0
    # True when any member node's current offering is unknown (the price
    # contribution is 0 — rank-conservative; the exact path still applies
    # the reference's price rules to any REPLACE)
    priceless: bool = False
    # [N] per-slot re-pack pod counts, fetched only on request
    # (parity tests / smoke — production reads only the verdict scalars)
    pods_per_slot: Optional[np.ndarray] = None

    @property
    def size(self) -> int:
        return len(self.subset)


@dataclass
class UnionScenario:
    """The union-encoded replan scenario: everything a subset dispatch (or
    a flight-record of the pass) needs beyond the subsets themselves."""

    snap: object  # EncodedSnapshot
    candidates: Sequence
    pods: List  # union pod axis (pending + deleting-node + candidate pods)
    cand_of_pod: Dict[str, int]  # pod uid -> candidate index (-1 = always on)
    provisioners: List
    instance_types: Dict
    daemonset_pods: List
    state_nodes: List  # residual nodes EXCLUDING candidates
    cand_slot: np.ndarray  # [C] candidate -> existing-slot index (-1 = none)
    uninitialized: np.ndarray  # [E] uninitialized existing-slot mask
    counts_base: np.ndarray  # [I] always-active replica count per item
    counts_per_cand: np.ndarray  # [I, C] per-candidate replica counts
    item_pad: int
    prices: List[Optional[float]] = field(default_factory=list)
    replacement_floor: float = 0.0

    def subset_rows(self, subsets: Sequence[Tuple[int, ...]]):
        """(count_rows [K, I_pad] int32, exist_open [K, E] bool) for K
        subsets — the only candidate-axis-batched planes."""
        E = self.snap.exist_used.shape[0]
        K = len(subsets)
        count_rows = np.zeros((K, self.item_pad), dtype=np.int32)
        exist_open = np.ones((K, E), dtype=bool)
        I = len(self.counts_base)
        for r, subset in enumerate(subsets):
            row = self.counts_base.copy()
            for ci in subset:
                row += self.counts_per_cand[:, ci]
                if self.cand_slot[ci] >= 0:
                    exist_open[r, self.cand_slot[ci]] = False
            count_rows[r, :I] = row
        return count_rows, exist_open

    def objective(self, subset: Tuple[int, ...], n_new: int):
        """(price, disruption, savings, priceless) for one subset. Savings
        = what the cluster stops paying (node_prices) minus an optimistic
        floor for any replacement launch (the cheapest worst_launch_price
        in the universe) — a sound RANKING bound: the exact confirming
        path still enforces the reference's strictly-cheaper price filter
        before any REPLACE executes."""
        price = 0.0
        priceless = False
        for ci in subset:
            p = self.prices[ci]
            if p is None:
                priceless = True
            else:
                price += p
        disruption = sum(
            self.candidates[ci].disruption_cost for ci in subset
        )
        savings = price - (self.replacement_floor if n_new > 0 else 0.0)
        return price, disruption, savings, priceless


def build_union_scenario(
    kube_client,
    cluster,
    provisioning,
    candidates,
    max_nodes: int = 1024,
) -> UnionScenario:
    """Encode the union scenario once. Raises CandidateNodeDeletingError
    under the same conditions as simulate_scheduling (a candidate is
    already mid-delete)."""
    from karpenter_core_tpu.controllers.deprovisioning.core import (
        CandidateNodeDeletingError,
        candidate_price,
        replacement_price_floor,
    )
    from karpenter_core_tpu.solver.encode import bucket_pow2, encode_snapshot

    candidate_names = {c.name for c in candidates}
    state_nodes = []
    deleting_nodes = []
    for node in cluster.nodes():
        if node.is_marked_for_deletion():
            deleting_nodes.append(node)
        elif node.name() not in candidate_names:
            state_nodes.append(node)
    if any(n.name() in candidate_names for n in deleting_nodes):
        raise CandidateNodeDeletingError()

    # pod axis: pending + deleting-node pods (always active) + candidate
    # pods (active in the subsets that remove their node)
    pods: List = []
    cand_of: List[int] = []
    for p in provisioning.get_pending_pods():
        pods.append(p)
        cand_of.append(-1)
    for node in deleting_nodes:
        for p in kube_client.list(
            "Pod",
            field_filter=lambda p, n=node: p.spec.node_name == n.name(),
            copy_objects=False,  # read-only below; see clone note
        ):
            if not podutils.is_terminal(p) and not podutils.is_owned_by_daemonset(p):
                pods.append(p)
                cand_of.append(-1)
    for ci, c in enumerate(candidates):
        for p in c.pods:
            if not podutils.is_owned_by_daemonset(p):
                pods.append(p)
                cand_of.append(ci)
    # NO clone_for_simulation here, unlike simulate_scheduling. INVARIANT:
    # this path must stay strictly read-only over live Pod objects —
    # encode_snapshot consumes specs/labels without normalizing them, the
    # device path never reads spec.node_name, and the round-0 kernel does
    # no preference relaxation (the only mutating step in the exact path).
    # Any future consumer of snap.pods that mutates or reads node_name must
    # reinstate the shallow clone. Measured (2026-07-30, config-4 profile):
    # even the SHALLOW clone of 10k shared Pods cost 97-309ms per replan —
    # comparable to the ~170ms device dispatch it feeds.
    cand_of_pod: Dict[str, int] = {
        p.metadata.uid: ci for p, ci in zip(pods, cand_of)
    }

    provisioners = [
        p for p in kube_client.list("Provisioner")
        if p.metadata.deletion_timestamp is None
    ]
    instance_types = {
        p.name: provisioning.cloud_provider.get_instance_types(p)
        for p in provisioners
    }
    daemonset_pods = provisioning.get_daemonset_pods()

    # candidate slots appended AFTER the regular nodes so their indices are
    # stable under encode's owned() filter (candidates are always owned)
    all_nodes = state_nodes + [c.state_node for c in candidates]
    snap = encode_snapshot(
        pods,
        provisioners,
        instance_types,
        daemonset_pods,
        all_nodes,
        kube_client=kube_client,
        cluster=cluster,
        max_nodes=max_nodes,
    ) if provisioners else None

    if snap is None:
        return UnionScenario(
            snap=None, candidates=candidates, pods=pods,
            cand_of_pod=cand_of_pod, provisioners=[], instance_types={},
            daemonset_pods=daemonset_pods, state_nodes=state_nodes,
            cand_slot=np.full(len(candidates), -1, np.int64),
            uninitialized=np.zeros(0, bool),
            counts_base=np.zeros(0, np.int32),
            counts_per_cand=np.zeros((0, len(candidates)), np.int32),
            item_pad=0,
            prices=[candidate_price(c) for c in candidates],
        )

    E = snap.exist_used.shape[0]  # bucket-padded existing axis
    name_to_slot = {n.name(): e for e, n in enumerate(snap.state_nodes)}
    cand_slot = np.full(len(candidates), -1, dtype=np.int64)
    for ci, c in enumerate(candidates):
        cand_slot[ci] = name_to_slot.get(c.name, -1)
    uninitialized = np.zeros(E, dtype=bool)  # padded sentinel rows: False
    uninitialized[: len(snap.state_nodes)] = [
        not n.initialized() for n in snap.state_nodes
    ]

    # per-item replica counts, factored by candidate membership so K
    # subset rows build by vectorized gather instead of a K x P host scan
    members = snap.item_members or [[i] for i in range(len(snap.pods))]
    I = len(snap.item_counts) if snap.item_counts is not None else len(snap.pods)
    item_of = np.zeros(len(snap.pods), dtype=np.int64)
    for it, mem in enumerate(members):
        for m in mem:
            item_of[m] = it
    cand_of_row = np.array(
        [cand_of_pod.get(p.metadata.uid, -1) for p in snap.pods],
        dtype=np.int64,
    )
    counts_base = np.zeros(I, dtype=np.int32)
    counts_per_cand = np.zeros((I, max(len(candidates), 1)), dtype=np.int32)
    if len(snap.pods):
        base_sel = cand_of_row < 0
        np.add.at(counts_base, item_of[base_sel], 1)
        cand_sel = ~base_sel
        if cand_sel.any():
            np.add.at(
                counts_per_cand,
                (item_of[cand_sel], cand_of_row[cand_sel]),
                1,
            )

    item_pad = snap.item_pad or bucket_pow2(max(I, 1), 32)
    return UnionScenario(
        snap=snap, candidates=candidates, pods=list(snap.pods),
        cand_of_pod=cand_of_pod, provisioners=provisioners,
        instance_types=instance_types, daemonset_pods=daemonset_pods,
        state_nodes=state_nodes, cand_slot=cand_slot,
        uninitialized=uninitialized, counts_base=counts_base,
        counts_per_cand=counts_per_cand, item_pad=item_pad,
        prices=[candidate_price(c) for c in candidates],
        replacement_floor=replacement_price_floor(instance_types),
    )


def batched_subset_screen(
    kube_client,
    cluster,
    provisioning,
    candidates,
    subsets: Sequence[Sequence[int]],
    max_nodes: int = 1024,
    want_slots: bool = False,
    scenario: Optional[UnionScenario] = None,
) -> Tuple[List[SubsetScreen], UnionScenario]:
    """One union encode + batched device dispatches screening every
    candidate subset, with the per-subset objective attached. Returns
    (screens in input order, the union scenario — reusable for further
    dispatches in the same pass and for flight-recording the decision).

    Raises CandidateNodeDeletingError like simulate_scheduling."""
    from karpenter_core_tpu.obs import TRACER

    with TRACER.span(
        "deprovisioning.subset_screen",
        candidates=len(candidates), subsets=len(subsets),
    ):
        if scenario is None:
            scenario = build_union_scenario(
                kube_client, cluster, provisioning, candidates,
                max_nodes=max_nodes,
            )
        return (
            _screen_subsets(provisioning, scenario, subsets, want_slots),
            scenario,
        )


def _screen_subsets(provisioning, scenario: UnionScenario,
                    subsets: Sequence[Sequence[int]],
                    want_slots: bool) -> List[SubsetScreen]:
    subsets = [tuple(s) for s in subsets]
    if scenario.snap is None:
        # no live provisioners: nothing can re-pack anywhere — feasible
        # only when the union scenario strands no pods at all (the same
        # verdict simulate_scheduling returns, helpers.go:41-105)
        screens = []
        for subset in subsets:
            price, disruption, savings, priceless = scenario.objective(
                subset, 0
            )
            screens.append(
                SubsetScreen(
                    subset=subset, all_scheduled=not scenario.pods,
                    n_new_machines=0, conclusive=True, price=price,
                    disruption=disruption, savings=savings,
                    priceless=priceless,
                )
            )
        return screens

    count_rows, exist_open = scenario.subset_rows(subsets)
    verdicts, pods = provisioning.solver.replan_screen(
        scenario.snap, scenario.provisioners, count_rows, exist_open,
        uninitialized=scenario.uninitialized, cluster=None,
        want_slots=want_slots,
    )
    screens = []
    for r, subset in enumerate(subsets):
        scheduled, expected, n_new, incon = (int(v) for v in verdicts[r])
        price, disruption, savings, priceless = scenario.objective(
            subset, n_new
        )
        screens.append(
            SubsetScreen(
                subset=subset,
                all_scheduled=scheduled >= expected,
                n_new_machines=n_new,
                conclusive=not incon,
                price=price,
                disruption=disruption,
                savings=savings,
                priceless=priceless,
                pods_per_slot=pods[r] if pods is not None else None,
            )
        )
    return screens


