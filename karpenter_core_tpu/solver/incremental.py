"""Incremental re-solve bookkeeping: the host half of the delta path.

ROADMAP open item 2: production is a churn stream, not one-shot Solve()
calls, and consecutive steady-state solves see nearly the same world —
the same instance-type universe (already reused via encode.EncodeReuse),
nearly the same existing nodes (a few freed / narrowed by bindings and
terminations since the last batch), and a batch of mostly-new items. The
prescreen verdict tensor (PR 5) is the expensive device precompute whose
inputs factor EXACTLY along that delta: verdict[n, c] depends only on
(slot row n's planes, class column c's planes). This module computes the
delta between the previous solve's planes and the current ones, and
decides whether replaying it through ops.pack.make_screen_refresh_kernel
beats recomputing the tensor from scratch.

Two layers guard correctness:

  * the STATE-DIFF GATE (state.Cluster.changes_since, chaos fault point
    `state.diff`): a feed fault or history gap forces the full path for
    one solve and drops the resident tensor — the subsystem degrades to
    full re-encode instead of trusting a feed that may have dropped or
    duplicated deltas;
  * PLANE FINGERPRINTS: the actual delta is computed by comparing the
    previous and current encoded planes byte-for-byte (bit-packed rows),
    never inferred from the feed. The feed can only ever make the path
    MORE conservative; it can never cause a stale verdict to survive.
    Refreshed entries are recomputed by the same screen ops the full
    precompute uses, so the refreshed tensor — and every placement decoded
    downstream of it — is byte-identical to the full path's
    (tests/test_incremental_parity.py holds the two to flightrec-canonical
    equality over seeded churn sequences).

Multi-chip (ISSUE 8): the residency map keys off the compiled-program
key, which embeds the mesh shape — so a GSPMD mesh solve keeps its own
resident verdict tensor and refresh programs, and the delta path serves
multi-chip steady-state churn exactly as it serves single-device
(docs/sharding.md).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import numpy as np

from karpenter_core_tpu.metrics.registry import NAMESPACE, REGISTRY

INCREMENTAL_SCREEN_TOTAL = REGISTRY.counter(
    f"{NAMESPACE}_incremental_screen_total",
    "Prescreen dispatch decisions on the incremental solve path, by outcome"
    " (refresh = delta replay, full_* = full precompute with the reason)",
)

# delta budgets: a refresh only wins while the changed row/column sets are
# small relative to the tensor; beyond these the full precompute is
# dispatched (and re-fingerprinted). Budgets are also the compiled-program
# signature, bucketed pow2 so steady-state churn reuses one refresh program.
MAX_ROW_DELTA = 128
MAX_COL_DELTA = 128

_COL_KEYS = ("allow", "out", "defined", "escape", "custom_deny")


def _pack_rows(arr: np.ndarray) -> np.ndarray:
    """Row-wise bit-packed fingerprint of a bool plane ([B, W] -> [B, ~W/8])."""
    return np.packbits(np.ascontiguousarray(arr), axis=1)


def exist_fingerprint(exist: Dict[str, np.ndarray]) -> Tuple[np.ndarray, ...]:
    """Per-slot-row fingerprint of the existing planes. escape is derived
    in-kernel from allow/out/defined, so those three determine the row."""
    return tuple(_pack_rows(exist[k]) for k in ("allow", "out", "defined"))


def col_fingerprint(pod_arrays: Dict[str, np.ndarray]) -> Tuple[np.ndarray, ...]:
    """Per-verdict-column fingerprint: the class planes each column screens
    with, gathered exactly the way the prescreen kernel gathers them."""
    sf = pod_arrays["scls_first"]
    return tuple(_pack_rows(pod_arrays[k][sf]) for k in _COL_KEYS)


def _changed_rows(old: Tuple[np.ndarray, ...], new: Tuple[np.ndarray, ...]):
    """Indices whose fingerprint rows differ, or None on any shape drift
    (shouldn't happen under a matched geometry key — full path then)."""
    changed = None
    for o, n in zip(old, new):
        if o.shape != n.shape:
            return None
        d = (o != n).any(axis=1)
        changed = d if changed is None else (changed | d)
    return np.nonzero(changed)[0].astype(np.int32) if changed is not None else None


@dataclass
class ScreenDelta:
    """A refresh plan: changed existing-row / verdict-column indices plus
    the padded budgets the compiled refresh program is specialized on."""

    rows: np.ndarray
    cols: np.ndarray
    rb: int
    cb: int

    def padded(self) -> Tuple[np.ndarray, int, np.ndarray, int]:
        row_idx = np.zeros(self.rb, np.int32)
        row_idx[: len(self.rows)] = self.rows
        col_idx = np.zeros(self.cb, np.int32)
        col_idx[: len(self.cols)] = self.cols
        return row_idx, len(self.rows), col_idx, len(self.cols)


class IncrementalScreen:
    """Per-solver carrier of the resident verdict tensor + fingerprints,
    keyed by the solver's compiled-program cache key (which embeds the full
    geometry: every axis width the tensor's shape and contents depend on).

    Not thread-safe by design: TPUSolver serializes its own solves (the
    pipelined production loop overlaps ENCODE with the device window, not
    two device solves), and each solver owns one carrier."""

    def __init__(self):
        self._key = None
        self._screen_dev = None  # device [N, C] verdict tensor
        self._exist_fp = None
        self._col_fp = None
        # fingerprints computed by plan() but committed only by adopt():
        # a solve that dies between the two must leave the carrier's
        # (tensor, fingerprints) pair consistent, else the NEXT delta
        # would refresh a tensor older than the planes it diffs against
        self._pending = None

    # -- planning ----------------------------------------------------------

    def plan(self, key, pod_arrays, exist,
             gate_ok: bool = True) -> Optional[ScreenDelta]:
        """Decide refresh vs full for this solve. Returns a ScreenDelta to
        replay, or None (caller dispatches the full precompute). Either
        way the caller hands the resulting tensor to adopt(), which is
        what commits this plan's fingerprints."""
        from karpenter_core_tpu.solver.encode import bucket_pow2

        new_exist_fp = exist_fingerprint(exist)
        new_col_fp = col_fingerprint(pod_arrays)
        outcome = None
        delta = None
        resident = self._key == key and self._screen_dev is not None
        if not gate_ok:
            # full_gated only when the gate actually DROPPED live residency;
            # a bad feed verdict with nothing resident is just a miss
            outcome = "full_gated" if resident else "full_miss"
            self.invalidate()
        elif not resident:
            outcome = "full_miss"
        else:
            rows = _changed_rows(self._exist_fp, new_exist_fp)
            cols = _changed_rows(self._col_fp, new_col_fp)
            if rows is None or cols is None:
                outcome = "full_shape"
            elif len(rows) > MAX_ROW_DELTA or len(cols) > MAX_COL_DELTA:
                outcome = "full_wide"
            else:
                E = exist["allow"].shape[0]
                C = pod_arrays["scls_first"].shape[0]
                delta = ScreenDelta(
                    rows=rows,
                    cols=cols,
                    # budgets bucket pow2 (min 8) and never exceed the
                    # axis; an EMPTY side is budget 0 — the refresh kernel
                    # statically omits that whole half, which is what keeps
                    # a row-only (or col-only) delta cheaper than the full
                    # precompute at small geometries
                    rb=(0 if len(rows) == 0
                        else min(bucket_pow2(len(rows), 8), max(E, 1))),
                    cb=(0 if len(cols) == 0
                        else min(bucket_pow2(len(cols), 8), max(C, 1))),
                )
                outcome = "refresh"
        if outcome != "refresh":
            # a planned refresh is NOT yet a refresh: the dispatch can still
            # fail and degrade to the full precompute, and the soak health
            # gate / resolve-ratio read this counter — so the caller counts
            # `refresh` on dispatch SUCCESS (count_refresh) and `full_deg`
            # on failure (count_degraded), never the plan
            INCREMENTAL_SCREEN_TOTAL.inc({"outcome": outcome})
        self._pending = (key, new_exist_fp, new_col_fp)
        return delta

    @staticmethod
    def count_refresh() -> None:
        INCREMENTAL_SCREEN_TOTAL.inc({"outcome": "refresh"})

    @staticmethod
    def count_degraded() -> None:
        INCREMENTAL_SCREEN_TOTAL.inc({"outcome": "full_deg"})

    # -- tensor residency --------------------------------------------------

    def adopt(self, key, screen_dev) -> None:
        """Adopt this solve's verdict tensor (full-precompute output or
        refresh output) as the resident one, committing the matching
        fingerprints staged by plan()."""
        pend = self._pending
        if pend is None or pend[0] != key:
            # adopt without a matching plan (incremental re-enabled
            # mid-run): no fingerprints to pair — drop residency
            self.invalidate()
            return
        self._key, self._exist_fp, self._col_fp = pend
        self._screen_dev = screen_dev
        self._pending = None

    def resident(self, key):
        return self._screen_dev if self._key == key else None

    def drop_resident(self) -> None:
        """Drop the resident tensor + fingerprints but KEEP the fingerprints
        staged by this solve's plan(): the refresh-dispatch failure path —
        the donated previous tensor may be gone, but the fallback full
        precompute is computed from exactly the planes plan() fingerprinted,
        so it can still adopt and the NEXT solve refreshes instead of
        paying a second full_miss."""
        self._key = None
        self._screen_dev = None
        self._exist_fp = None
        self._col_fp = None

    def invalidate(self) -> None:
        """Drop the resident tensor AND fingerprints — the degrade path
        (state-diff fault, refresh dispatch failure, geometry eviction)."""
        self._key = None
        self._screen_dev = None
        self._exist_fp = None
        self._col_fp = None
        self._pending = None


class DiffGate:
    """Consumes state.Cluster.changes_since between solves. gate() is True
    when the feed proves continuous history since the previous consult; a
    feed fault (chaos `state.diff`) or history gap returns False — and the
    caller must invalidate its resident state, not just skip one reuse."""

    def __init__(self):
        self._cursor: Optional[int] = None

    def gate(self, cluster) -> bool:
        if cluster is None or not hasattr(cluster, "changes_since"):
            # no feed in scope (direct solver use, gRPC boundary): plane
            # fingerprints alone are exact — reuse stays allowed
            return True
        try:
            cursor, changed = cluster.changes_since(self._cursor)
        except Exception:
            # injected/real feed fault: degrade to the full path and
            # restart history from scratch
            self._cursor = None
            return False
        self._cursor = cursor
        return changed is not None
