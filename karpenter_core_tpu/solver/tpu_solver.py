"""TPUSolver — the tensor execution backend for Scheduler.Solve.

Pipeline per solve:
  1. encode_snapshot: objects -> dense arrays (host, numpy)
  2. feasibility_static + pack kernels under jit (device)
  3. decode: slot assignments -> SolvedMachine / existing-node placements
  4. host-side relaxation rounds for failed pods (preferences.go order), each
     followed by a fresh device solve — replaces the reference's per-pod
     relax-and-requeue (scheduler.go:114-123) with <= max_relax_rounds full
     re-solves, which is cheap because a solve is one fused device program.

The Solver interface (solve(pods, ...) -> SolveResult) is what the
provisioning controller calls; GreedySolver (host path) and TPUSolver are
interchangeable, and the gRPC service (solver/service.py) exposes the same
boundary out-of-process.
"""
from __future__ import annotations

import copy
from dataclasses import dataclass, field
from functools import partial
from typing import Dict, List, Optional, Tuple

import numpy as np

from karpenter_core_tpu import chaos
from karpenter_core_tpu.api.provisioner import Provisioner
from karpenter_core_tpu.cloudprovider.types import InstanceType
from karpenter_core_tpu.scheduling.machinetemplate import MachineTemplate
from karpenter_core_tpu.scheduling.preferences import Preferences
from karpenter_core_tpu.kube.objects import Pod, ResourceList
from karpenter_core_tpu.obs import TRACER, device_profiler, profile_dir
from karpenter_core_tpu.obs import proghealth
from karpenter_core_tpu.scheduling.requirements import Requirements
from karpenter_core_tpu.solver.encode import EncodedSnapshot, ReqSetArrays, encode_snapshot
from karpenter_core_tpu.utils import resources as resources_util
from karpenter_core_tpu.utils import supervise


@dataclass
class SolvedMachine:
    """A new node computed by the solver (analog of scheduling.Machine after
    FinalizeScheduling).

    `requirements` and `instance_type_options` may be passed as zero-arg
    thunks: reconstructing them from slot masks costs Python time per
    machine, and most machines (bench runs, failed relax rounds) never read
    them — the thunk defers that to first access and is dropped after
    materialization so held machines don't pin snapshot/state arrays."""

    provisioner_name: str
    template: MachineTemplate
    pods: List[Pod]
    instance_type_options: object
    requests: ResourceList
    requirements: object

    _LAZY = ("requirements", "instance_type_options")

    def __post_init__(self):
        import threading

        object.__setattr__(self, "_lazy_lock", threading.Lock())
        for field_name in self._LAZY:
            value = getattr(self, field_name)
            if callable(value):
                # deleting the instance attribute routes the next access
                # through __getattr__ (no per-access interception otherwise)
                object.__setattr__(self, f"_{field_name}_thunk", value)
                object.__delattr__(self, field_name)

    def __getattr__(self, name):
        # locked: concurrent readers (launch fan-out threads, scrapers) must
        # not race the thunk pop — the loser would see AttributeError
        if name in type(self)._LAZY:
            with self.__dict__["_lazy_lock"]:
                if name in self.__dict__:
                    return self.__dict__[name]
                thunk = self.__dict__.get(f"_{name}_thunk")
                if thunk is not None:
                    # materialize BEFORE dropping the thunk: a transient
                    # device fetch error must stay retryable, not decay
                    # into a permanent AttributeError
                    object.__setattr__(self, name, thunk())
                    del self.__dict__[f"_{name}_thunk"]
                    return self.__dict__[name]
        raise AttributeError(name)


@dataclass
class SolveResult:
    new_machines: List[SolvedMachine] = field(default_factory=list)
    existing_assignments: List[Tuple[object, List[Pod]]] = field(default_factory=list)
    failed_pods: List[Pod] = field(default_factory=list)
    rounds: int = 1
    # pod uid -> failure cause, when the solver knows it (the host scheduler
    # records exact per-pod errors; the device path leaves this empty and
    # the provisioner's explain probe fills the gap)
    errors: Dict[str, str] = field(default_factory=dict)

    def pod_count_new(self) -> int:
        return sum(len(m.pods) for m in self.new_machines)

    def pod_count_existing(self) -> int:
        return sum(len(p) for _, p in self.existing_assignments)


def _reqset_to_dict(rs: ReqSetArrays) -> Dict[str, np.ndarray]:
    return {"allow": rs.allow, "out": rs.out, "defined": rs.defined, "escape": rs.escape}


# run()'s positional argument order — device_args() produces this tuple and
# donate/shard specs index into it by name through this list, so a signature
# change breaks loudly (asserted in make_device_run) instead of donating the
# wrong buffer.
RUN_ARG_NAMES = (
    "pod_arrays", "tmpl", "tmpl_daemon", "tmpl_type_mask", "types",
    "type_alloc", "type_capacity", "type_offering_ok", "pod_tol_all",
    "exist", "exist_used", "exist_cap", "well_known", "remaining0",
    "topo_counts0", "topo_hcounts0", "topo_doms0", "topo_terms",
    "exist_ports", "exist_vols", "exist_vol_limits", "vol_driver",
)
# arrays that flow through the scan carry unchanged in shape/dtype
# (remaining0 -> state.remaining, topo_* -> state.tcounts/thost/tdoms):
# donating lets XLA alias them instead of allocating fresh HBM.
# _run_kernels derives the per-leaf donation positions from this tuple.
DONATE_ARG_NAMES = ("remaining0", "topo_counts0", "topo_hcounts0", "topo_doms0")
assert all(n in RUN_ARG_NAMES for n in DONATE_ARG_NAMES)

# safety cap on relaxation re-solve rounds; sized above the ~6 preference
# tiers (preferences.go:36-56) so the fixpoint, not the cap, terminates —
# shared by TPUSolver, RemoteSolver, and NativeSolver
DEFAULT_MAX_RELAX_ROUNDS = 16


def _segment_tmpl_fingerprint(raw_args) -> bytes:
    """Digest of the template-side partitioner inputs (tmpl planes +
    well_known mask). The incremental verdict fingerprints only cover the
    pod/existing planes, so segment-label residency must separately prove
    these unchanged before reusing cached labels."""
    import hashlib

    h = hashlib.blake2b(digest_size=16)
    tmpl = raw_args[RUN_ARG_NAMES.index("tmpl")]
    for k in sorted(tmpl):
        h.update(k.encode())
        h.update(np.ascontiguousarray(tmpl[k]).tobytes())
    h.update(
        np.ascontiguousarray(
            raw_args[RUN_ARG_NAMES.index("well_known")]
        ).tobytes()
    )
    return h.digest()


def solve_with_relaxation(solve_once, pods, provisioners, instance_types,
                          max_relax_rounds: int) -> "SolveResult":
    """Shared driver: guard degenerate inputs, run solve_once, relax EVERY
    failed pod between rounds (preferences.go order) — used by TPUSolver,
    RemoteSolver, and any other Solver implementation.

    Relaxation mutates pod specs, so a failed pod is deep-copied ON FIRST
    RELAX (lazily, identity-tracked across rounds) instead of deep-copying
    the whole batch up front — at 50k pods the wholesale copy costs seconds
    per solve while the common case never relaxes at all. Caller-passed
    objects are never mutated.

    Termination matches the reference (scheduler.go:114-123): rounds continue
    until no failed pod can relax further (Preferences.relax fixpoint);
    max_relax_rounds is only a safety cap and is sized (16) above the ~6
    relaxation tiers so real workloads always reach exhaustion."""
    if not pods:
        return SolveResult()
    if not provisioners or not any(instance_types.values()):
        return SolveResult(failed_pods=list(pods))
    from karpenter_core_tpu.utils.gctuning import gc_paused

    # the solve-path ROOT span: every phase span below (encode/args/pack/
    # upload/device/fetch/bind) nests under it, and its completion feeds
    # the solve-duration histogram + batch-size gauge (obs/tracer bridge).
    # context tells a provisioning solve from a deprovisioning-simulation
    # re-entry (parented under a deprovisioning.* span) so simulation
    # batches never pollute the provisioning-latency metric series.
    parent = TRACER.current_span_name() or ""
    context = "simulation" if parent.startswith("deprovisioning.") else "provisioning"
    with TRACER.span("solver.solve", pods=len(pods), context=context) as sp, \
            gc_paused():
        result = _solve_with_relaxation_inner(
            solve_once, pods, provisioners, max_relax_rounds
        )
        sp.set(rounds=result.rounds, failed=len(result.failed_pods))
        return result


def _solve_with_relaxation_inner(solve_once, pods, provisioners,
                                 max_relax_rounds: int) -> "SolveResult":
    pods = list(pods)
    # an object may appear at several indices (caller-deduped replicas):
    # map id -> ALL its indices so each list entry relaxes independently
    indices_of: Dict[int, List[int]] = {}
    for i, p in enumerate(pods):
        indices_of.setdefault(id(p), []).append(i)
    is_copy = [False] * len(pods)
    preferences = Preferences(
        any(t.effect == "PreferNoSchedule" for p in provisioners for t in p.spec.taints)
    )
    result = solve_once(pods)
    rounds = 1
    while result.failed_pods and rounds < max_relax_rounds:
        relaxed_any = False
        for pod in result.failed_pods:
            key = id(pod)
            idxs = indices_of.get(key)
            if not idxs:
                continue  # defensive: not a pod of this batch
            if len(idxs) == 1 and is_copy[idxs[0]]:
                i = idxs[0]  # a copy relaxes again every round it fails
            else:
                # CONSUME one index still holding the original: it becomes a
                # copy with its own identity, so aliased entries relax
                # independently and originals are never mutated
                i = idxs.pop()
                pods[i] = copy.deepcopy(pod)
                indices_of[id(pods[i])] = [i]
                is_copy[i] = True
            relaxed_any |= preferences.relax(pods[i])
        if not relaxed_any:
            break
        result = solve_once(pods)
        rounds += 1
    result.rounds = rounds
    return result


def solve_geometry(snap: EncodedSnapshot, max_nodes: int):
    from karpenter_core_tpu.solver.encode import bucket_pow2

    dictionary = snap.dictionary
    segments = [dictionary.segment(k) for k in dictionary.keys]
    # item axis padded to the snapshot's ladder tier (device_args pads with
    # valid=False rows) and existing/type axes pre-padded at encode: the
    # geometry key — and with it the compiled program — is stable across
    # every batch inside one tier, and the tier table bounds the program
    # set (pre-ladder snapshots fall back to open-ended pow2 buckets)
    I_real = len(snap.item_counts) if snap.item_counts is not None else len(snap.pods)
    P = snap.item_pad or bucket_pow2(max(I_real, 1), 32)
    J = len(snap.templates)
    # the PADDED type-axis width (encode pads to the ladder tier); the real
    # type list is shorter
    T = snap.type_alloc.shape[0] if snap.type_alloc is not None else len(snap.instance_types)
    E = snap.exist_used.shape[0] if snap.exist_used is not None else 0
    R = len(snap.resource_names)
    K, V = dictionary.K, dictionary.V
    # the slot budget is fixed at encode time (snapshot topo arrays are sized
    # to it); max_nodes only applies when the snapshot didn't record one
    N = snap.n_slots or (E + min(max_nodes, max(P, 1)))
    topo_sig = ()
    if snap.topo_meta is not None:
        topo_sig = tuple(
            (g.gtype, g.seg, g.key_k, g.max_skew, g.is_hostname, g.is_inverse,
             tuple(g.filter_term_rows))
            for g in snap.topo_meta.groups
        )
    # commit-log capacity: total pods rounded to a power-of-two bucket so
    # repeat solves at nearby batch sizes reuse the compiled program (like
    # the slot budget, this pods-derived axis stays pow2 — bounded by the
    # batcher's ladder-clamped pass cap, and far finer-grained than the
    # ladder rungs so small geometries don't inflate)
    log_len = 128
    while log_len < len(snap.pods) + 64:
        log_len *= 2
    # host-port / volume axes (0 in the common no-port/no-volume batch)
    Q = snap.pod_ports_u.shape[1] if snap.pod_ports_u is not None else 0
    W = snap.pod_vols_u.shape[1] if snap.pod_vols_u is not None else 0
    D = snap.exist_vol_limits.shape[1] if snap.exist_vol_limits is not None else 0
    return (
        P, J, T, E, R, K, V, N, tuple(segments), snap.zone_seg, snap.ct_seg,
        topo_sig, log_len, Q, W, D, snap.screen_v or V,
    )


def make_device_run(segments, zone_seg, ct_seg, topo_meta, n_slots,
                    log_len: Optional[int] = None, rung_mode: bool = False,
                    backend: Optional[str] = None,
                    screen_v: Optional[int] = None,
                    screen_mode: Optional[str] = None,
                    external_prescreen: bool = False,
                    spec_layout=None,
                    segment_mode: bool = False,
                    seg_frozen: bool = False):
    """Build the jittable device program — the whole Solve() as ONE program:
    feasibility + openable + packing scan. Pure function of the device arrays
    produced by device_args(); all dims except n_slots derive from shapes.
    Shared by build_device_solve (in-process) and the gRPC SolverService.

    rung_mode=True prepends two args (count_row [I], exist_open [E]) that
    override the per-item replica counts and the open-existing-slot mask —
    the vmap axis of the batched consolidation ladder (solver/replan.py).

    segment_mode=True (ISSUE 14) builds the SEGMENTED pack program instead:
    seg_run(item_sel [S, M], exist_open [S, E], screen0, *run_args) vmaps
    the pack scan over S conflict-independent lanes. Each lane gathers M
    items (item_sel row; -1 pads skip), opens only its own existing slots
    (exist_open row — the partitioner proved the rows disjoint), and packs
    machine slots into its own private region [E, N). With seg_frozen=True
    (every class in the snapshot plane-neutral, encode.seg_plane_neutral)
    the verdict tensor is READ-ONLY: one scan constant shared across lanes
    with opened machine rows reading the precomputed template rows, and
    the refresh machinery compiles away; otherwise (e.g. selector-scoped
    pods, which define their selector keys) each lane carries its own
    tensor copy and runs the full in-scan refresh machinery. The scan
    length is M — the segment bucket — not I: that is the whole point (the
    last O(items) sequential wall becomes O(max-segment)). The host merge
    (TPUSolver._try_segmented) interleaves the per-lane commit logs back
    into global item order and renumbers machine slots in first-open
    order, which reproduces the sequential kernel's numbering exactly.

    screen_mode picks the pack kernel's slot-screen strategy (prescreen vs
    tiered, compat.resolve_screen_mode default). With external_prescreen
    (in-process TPUSolver only) the prescreen verdict tensor is NOT
    computed inside this program: run takes it as a leading `screen0`
    argument, produced by the companion make_prescreen_kernel program that
    the solver dispatches (and times as solver.phase.prescreen) first.

    spec_layout (parallel/specs.SpecLayout) makes this the multi-chip GSPMD
    mesh program: the static-feasibility contraction computes sharded
    (item rows over 'dp', type columns over 'tp' — docs/sharding.md) and is
    reassembled by an XLA-inserted all_gather before the sequential pack
    scan, which runs replicated. Byte-identical to the layout=None program
    by construction: sharding only ever tiles contraction OUTPUT axes."""
    import jax.numpy as jnp

    from karpenter_core_tpu.ops import compat
    from karpenter_core_tpu.ops.feasibility import feasibility_static, openable_mask
    from karpenter_core_tpu.ops.pack import PackState, make_pack_kernel

    segments = list(segments)
    screen_mode = screen_mode or compat.resolve_screen_mode()
    external_prescreen = external_prescreen and screen_mode == "prescreen"
    pack = make_pack_kernel(
        segments, zone_seg, ct_seg, topo_meta=topo_meta, backend=backend,
        screen_v=screen_v, screen_mode=screen_mode,
    )

    def run_impl(count_row, exist_open, screen0, pod_arrays, tmpl, tmpl_daemon,
                 tmpl_type_mask, types, type_alloc, type_capacity,
                 type_offering_ok, pod_tol_all, exist, exist_used, exist_cap,
                 well_known, remaining0, topo_counts0, topo_hcounts0,
                 topo_doms0, topo_terms, exist_ports, exist_vols,
                 exist_vol_limits, vol_driver, item_sel=None):
        E = exist_used.shape[0]
        N = n_slots
        R = type_alloc.shape[1]
        T = type_alloc.shape[0]
        J = tmpl_daemon.shape[0]
        V = pod_arrays["allow"].shape[1]
        K = pod_arrays["out"].shape[1]
        if count_row is not None:
            pod_arrays = dict(pod_arrays)
            pod_arrays["count"] = count_row
        if exist_open is None:
            open0 = jnp.arange(N) < E
        else:
            open0 = (jnp.arange(N) < E) & jnp.pad(exist_open, (0, N - E))
        pods_f = {k: pod_arrays[k] for k in ("allow", "out", "defined", "escape")}
        types_f, tmask_f, offer_f = types, tmpl_type_mask, type_offering_ok
        if spec_layout is not None:
            # sharded precompute seam: item rows over dp, type columns over
            # tp — the [J, I, T] contraction tiles with no communication,
            # then gathers ONCE for the replicated scan (docs/sharding.md)
            ly = spec_layout
            pods_f = ly.shard_reqset(pods_f, ly.slot_plane())
            types_f = ly.shard_reqset(dict(types), ly.type_plane())
            tmask_f = ly.constrain(tmpl_type_mask, ly.type_cols())
            offer_f = ly.constrain(type_offering_ok, ly.type_plane(rank=3))
        f_static = feasibility_static(
            pods_f,
            tmpl,
            types_f,
            pod_arrays["tol_tmpl"],
            tmask_f,
            offer_f,
            zone_seg,
            ct_seg,
            segments,
            well_known,
        )
        if spec_layout is not None:
            f_static = spec_layout.constrain(f_static, spec_layout.feasibility())
        openable = openable_mask(f_static, pod_arrays["requests"], tmpl_daemon, type_alloc)
        if spec_layout is not None:
            # the all_gather seam: the scan consumes replicated planes.
            # EVERY tensor entering the pack scan is pinned replicated —
            # not just the sharded precompute outputs — so GSPMD's
            # propagation can never push a sharding into the scan carry
            # (a per-step collective at best; with committed mesh inputs
            # the auto-partitioned scan was observed to MISCOMPUTE the
            # bulk-fill region on the CPU backend — the explicit pins are
            # a correctness fence, not just a perf choice)
            g = spec_layout.gather
            f_static = g(f_static)
            # process-unique persistent-cache key on CPU (semantic no-op;
            # XLA:CPU reloads of mesh executables are nondeterministic —
            # specs.SpecLayout.cache_salt)
            openable = spec_layout.cache_salt(g(openable))
            screen0 = g(screen0) if screen0 is not None else None
            pod_arrays = {k: g(v) for k, v in pod_arrays.items()}
            tmpl = {k: g(v) for k, v in tmpl.items()}
            exist = {k: g(v) for k, v in exist.items()}
            types = {k: g(v) for k, v in types.items()}
            (tmpl_daemon, tmpl_type_mask, type_alloc, type_capacity,
             type_offering_ok, pod_tol_all, exist_used, exist_cap,
             well_known, remaining0, topo_counts0, topo_hcounts0,
             topo_doms0, exist_ports, exist_vols, exist_vol_limits,
             vol_driver) = map(g, (
                 tmpl_daemon, tmpl_type_mask, type_alloc, type_capacity,
                 type_offering_ok, pod_tol_all, exist_used, exist_cap,
                 well_known, remaining0, topo_counts0, topo_hcounts0,
                 topo_doms0, exist_ports, exist_vols, exist_vol_limits,
                 vol_driver,
             ))
            topo_terms = {k: g(v) for k, v in topo_terms.items()}
        class_planes = None
        if item_sel is not None:
            # segmented lane: the scan consumes only this lane's items —
            # gather the per-item planes (and the feasibility columns,
            # which were computed ONCE over the full axis above and stay
            # unbatched under vmap) down to the [M] segment bucket. Pads
            # (-1) gather row 0 with valid=False/count=0, so they skip the
            # whole step body exactly like the item-axis tier padding.
            # The verdict-COLUMN planes are gathered from the FULL item
            # axis first: scls_first indexes the original axis, and the
            # lanes' refresh machinery re-screens written slot rows
            # against every class (other lanes' columns included — they
            # are never read here, but the tensor layout is shared).
            sf = pod_arrays.get("scls_first")
            if sf is None:
                sf = jnp.arange(
                    pod_arrays["allow"].shape[0], dtype=jnp.int32
                )
            class_planes = {
                k: jnp.asarray(pod_arrays[k])[jnp.asarray(sf)]
                for k in ("allow", "out", "defined", "escape",
                          "custom_deny")
            }
            gi = jnp.maximum(item_sel, 0)
            onsel = item_sel >= 0
            pa = dict(pod_arrays)
            pa.pop("scls_first", None)
            pa = {k: jnp.asarray(v)[gi] for k, v in pa.items()}
            pa["valid"] = pa["valid"] & onsel
            pa["count"] = jnp.where(onsel, pa["count"], 0)
            pod_arrays = pa
            pod_tol_all = jnp.asarray(pod_tol_all)[gi]
            f_static = f_static[:, gi, :]
            openable = openable[:, gi]
        # initial state: existing slots [0, E), machine slots open later
        state = PackState(
            used=jnp.zeros((N, R), jnp.float32).at[:E].set(exist_used),
            open=open0,
            is_existing=open0,
            tmpl=jnp.zeros(N, jnp.int32),
            tol_idx=jnp.concatenate(
                [J + jnp.arange(E, dtype=jnp.int32), jnp.zeros(N - E, jnp.int32)]
            ),
            pods=jnp.zeros(N, jnp.int32),
            allow=jnp.ones((N, V), bool).at[:E].set(exist["allow"]),
            out=jnp.ones((N, K), bool).at[:E].set(exist["out"]),
            defined=jnp.zeros((N, K), bool).at[:E].set(exist["defined"]),
            tmask=jnp.zeros((N, T), bool),
            cap=jnp.zeros((N, R), jnp.float32).at[:E].set(exist_cap),
            nopen=jnp.int32(E),
            remaining=remaining0,
            tcounts=topo_counts0,
            thost=topo_hcounts0,
            tdoms=topo_doms0,
            ports=jnp.zeros((N, exist_ports.shape[1]), bool).at[:E].set(exist_ports),
            vols=exist_vols,
        )
        pod_arrays = dict(pod_arrays)
        pod_arrays["tol"] = pod_tol_all
        state, log, ptr = pack(
            state,
            pod_arrays,
            f_static,
            openable,
            {k: tmpl[k] for k in ("allow", "out", "defined")},
            tmpl_daemon,
            tmpl_type_mask,
            types,
            type_alloc,
            type_capacity,
            type_offering_ok,
            well_known=well_known,
            topo_terms=topo_terms,
            log_len=log_len,
            n_exist=E,
            vol_limits=exist_vol_limits,
            vol_driver=vol_driver,
            # rung mode never decodes the log (the ladder screen reads only
            # state.pods): skip every log write and its space gating, which
            # keeps the vmapped bulk-take matrices at one row AND lets the
            # bulk existing-fill fast path run per rung
            log_commits=not rung_mode,
            screen0=screen0,
            item_ids=item_sel,
            # frozen lanes (every dispatched class plane-neutral, proven
            # host-side by encode.seg_plane_neutral): the verdict tensor is
            # a read-only scan constant shared across lanes and the refresh
            # machinery compiles away
            screen_frozen=bool(seg_frozen and item_sel is not None),
            class_planes=class_planes,
            bulk_len=(
                min(2 * item_sel.shape[0] + 64, 4096)
                if item_sel is not None
                else None
            ),
        )
        return log, ptr, state

    if segment_mode:
        assert screen_mode == "prescreen", (
            "segmented packing requires the prescreen verdict tensor"
        )
        import jax

        def seg_run(item_sel, exist_open, screen0, *rest):
            if spec_layout is not None:
                # the mesh-path segment fence (docs/sharding.md): the LANE
                # axis shards over dp — the scan stops being the replicated
                # part of the mesh program — while run_impl's existing
                # gather fence keeps every WITHIN-lane scan input pinned
                # replicated, exactly as on the sequential mesh path
                seg2 = spec_layout.segment_axis(rank=2)
                item_sel = spec_layout.constrain(item_sel, seg2)
                exist_open = spec_layout.constrain(exist_open, seg2)

            def one(sel, eo):
                return run_impl(None, eo, screen0, *rest, item_sel=sel)

            out = jax.vmap(one)(item_sel, exist_open)
            if spec_layout is not None:
                out = jax.tree_util.tree_map(
                    lambda t: spec_layout.constrain(
                        t,
                        spec_layout.segment_axis(rank=max(t.ndim, 1)),
                    ),
                    out,
                )
                # process-unique persistent-cache key on CPU (semantic
                # no-op; specs.SpecLayout.cache_salt)
                log_o, ptr_o, state_o = out
                out = (log_o, spec_layout.cache_salt(ptr_o), state_o)
            return out

        return seg_run

    if rung_mode:
        if external_prescreen:
            # the batched consolidation evaluator's form (solver/replan.py):
            # the caller dispatches the prescreen as its own program (or
            # replays a delta into the RESIDENT verdict tensor) and threads
            # it through every vmapped subset unbatched — the verdict is
            # candidate-invariant, so one tensor serves all K re-packs
            def rung_run(count_row, exist_open, screen0, *rest):
                return run_impl(count_row, exist_open, screen0, *rest)
        else:
            def rung_run(count_row, exist_open, *rest):
                # internal prescreen: the vmapped rungs share the (unbatched)
                # slot planes, so the verdict tensor traces once and
                # broadcasts (the tiered-fallback and service legacy form)
                return run_impl(count_row, exist_open, None, *rest)

        return rung_run

    import inspect

    if external_prescreen:
        def run(screen0, pod_arrays, tmpl, tmpl_daemon, tmpl_type_mask, types,
                type_alloc, type_capacity, type_offering_ok, pod_tol_all,
                exist, exist_used, exist_cap, well_known, remaining0,
                topo_counts0, topo_hcounts0, topo_doms0, topo_terms,
                exist_ports, exist_vols, exist_vol_limits, vol_driver):
            return run_impl(
                None, None, screen0, pod_arrays, tmpl, tmpl_daemon,
                tmpl_type_mask, types, type_alloc, type_capacity,
                type_offering_ok, pod_tol_all, exist, exist_used, exist_cap,
                well_known, remaining0, topo_counts0, topo_hcounts0,
                topo_doms0, topo_terms, exist_ports, exist_vols,
                exist_vol_limits, vol_driver,
            )

        assert tuple(inspect.signature(run).parameters) == (
            ("screen0",) + RUN_ARG_NAMES
        )
        return run

    def run(pod_arrays, tmpl, tmpl_daemon, tmpl_type_mask, types, type_alloc,
            type_capacity, type_offering_ok, pod_tol_all, exist, exist_used,
            exist_cap, well_known, remaining0, topo_counts0, topo_hcounts0,
            topo_doms0, topo_terms, exist_ports, exist_vols, exist_vol_limits,
            vol_driver):  # order must match RUN_ARG_NAMES
        return run_impl(
            None, None, None, pod_arrays, tmpl, tmpl_daemon, tmpl_type_mask,
            types, type_alloc, type_capacity, type_offering_ok, pod_tol_all,
            exist, exist_used, exist_cap, well_known, remaining0, topo_counts0,
            topo_hcounts0, topo_doms0, topo_terms, exist_ports, exist_vols,
            exist_vol_limits, vol_driver,
        )

    assert tuple(inspect.signature(run).parameters) == RUN_ARG_NAMES
    return run


def build_device_solve(snap: EncodedSnapshot, max_nodes: int = 1024,
                       backend: Optional[str] = None,
                       screen_mode: Optional[str] = None,
                       external_prescreen: bool = False,
                       spec_layout=None):
    """Returns (geometry_key, run_fn) for a snapshot's geometry. backend
    picks the kernel lowering (compat.resolve_backend default); tests force
    'mxu' on CPU to exercise the exact TPU code path. screen_mode picks the
    slot-screen strategy (prescreen/tiered). spec_layout builds the GSPMD
    mesh program instead of the single-device one (parallel/specs.py)."""
    geom = solve_geometry(snap, max_nodes)
    (_P, _J, _T, _E, _R, _K, _V, N, segments_t, zone_seg, ct_seg, _topo_sig,
     log_len, _Q, _W, _D, screen_v) = geom
    run = make_device_run(
        segments_t, zone_seg, ct_seg, snap.topo_meta, N, log_len=log_len,
        backend=backend, screen_v=screen_v, screen_mode=screen_mode,
        external_prescreen=external_prescreen, spec_layout=spec_layout,
    )
    return geom, run


def device_args(snap: EncodedSnapshot, provisioners: Optional[List[Provisioner]] = None):
    """Host arrays (numpy) in run_fn's argument order. The work axis is the
    ITEM (pod equivalence class) axis: rows are gathered through
    snap.item_rep and each carries its replica count."""
    provisioners = provisioners or []
    J = len(snap.templates)
    rep = (
        snap.item_rep
        if snap.item_rep is not None
        else np.arange(len(snap.pods), dtype=np.int32)
    )
    counts = (
        snap.item_counts
        if snap.item_counts is not None
        else np.ones(len(snap.pods), dtype=np.int32)
    )
    I = len(rep)
    # gather item rows straight from the CLASS-level arrays ([U, ...]) —
    # going through the lazy [P, ...] views would materialize 50k rows to
    # read ~1k (the r03 encode-time fix)
    cls = snap.uidx[rep] if len(snap.pods) else rep
    u = snap.pod_reqs_u
    custom_deny_u = ~snap.well_known[None, :] & u.defined & ~u.escape
    pod_arrays = {
        "allow": u.allow[cls],
        "out": u.out[cls],
        "defined": u.defined[cls],
        "escape": u.escape[cls],
        "custom_deny": custom_deny_u[cls],
        "requests": snap.pod_requests_u[cls],
        "tol_tmpl": snap.pod_tol_u[cls],
        "valid": np.ones(I, dtype=bool),
        "count": counts.astype(np.int32),
        # prescreen verdict column per item (encode's class dedup; identity
        # when the snapshot predates it or items were built 1:1)
        "scls": (
            snap.item_scls.astype(np.int32)
            if snap.item_scls is not None
            else np.arange(I, dtype=np.int32)
        ),
    }
    if snap.topo_meta is not None:
        pod_arrays["topo_own"] = snap.topo_arrays.owner.T[rep].copy()  # [I, G]
        pod_arrays["topo_sel"] = snap.topo_arrays.sel.T[rep].copy()
    # host-port / volume rows ride the item axis (zero-width when unused)
    pod_arrays["ports"] = snap.pod_ports_u[cls]
    pod_arrays["port_conflict"] = snap.pod_port_conflict_u[cls]
    pod_arrays["vols"] = snap.pod_vols_u[cls]
    pod_tol_all = np.concatenate(
        [snap.pod_tol_u[cls], snap.tol_exist_us[cls[:, None], snap.sig_of_node[None, :]]],
        axis=1,
    )

    # pad the item axis to the snapshot's ladder tier (valid=False, count=0
    # rows never commit — the scan pays one cheap step each); must mirror
    # solve_geometry's bucket, which reads the same snapshot field
    from karpenter_core_tpu.solver.encode import bucket_pow2

    I_pad = snap.item_pad or bucket_pow2(max(I, 1), 32)
    if I_pad > I:
        pad = I_pad - I

        def pad_rows(a):
            return np.concatenate(
                [a, np.zeros((pad,) + a.shape[1:], dtype=a.dtype)], axis=0
            )

        pod_arrays = {k: pad_rows(v) for k, v in pod_arrays.items()}
        pod_tol_all = pad_rows(pod_tol_all)

    # verdict-column -> item map, bucketed like the item axis so the
    # compiled geometry is stable across nearby batches (pad columns alias
    # item 0 — harmless duplicates of its verdict column). Added AFTER the
    # item padding: its leading axis is the column count C, not I.
    scls_items = (
        snap.scls_items.astype(np.int32)
        if snap.scls_items is not None
        else np.arange(I, dtype=np.int32)
    )
    C_pad = snap.cls_pad or bucket_pow2(max(len(scls_items), 1), 32)
    pod_arrays["scls_first"] = np.pad(
        scls_items, (0, C_pad - len(scls_items))
    )

    # provisioner limits -> remaining resources [J, R] (scheduler.go:70-75)
    remaining0 = np.full((J, len(snap.resource_names)), np.float32(1e30))
    for j, template in enumerate(snap.templates):
        prov = next((p for p in provisioners if p.name == template.provisioner_name), None)
        if prov is not None and prov.spec.limits is not None:
            for r_i, rname in enumerate(snap.resource_names):
                if rname in prov.spec.limits.resources:
                    remaining0[j, r_i] = prov.spec.limits.resources[rname]
    # subtract existing owned capacity (scheduler.go:243-249)
    from karpenter_core_tpu.api.labels import PROVISIONER_NAME_LABEL_KEY

    for node in snap.state_nodes:
        pname = node.labels().get(PROVISIONER_NAME_LABEL_KEY, "")
        for j, template in enumerate(snap.templates):
            if template.provisioner_name == pname:
                cap = node.capacity()
                for r_i, rname in enumerate(snap.resource_names):
                    if remaining0[j, r_i] < 1e29:
                        remaining0[j, r_i] -= cap.get(rname, 0.0)

    V = snap.dictionary.V
    if snap.topo_meta is not None:
        ta = snap.topo_arrays
        topo_counts0 = ta.counts0
        topo_hcounts0 = ta.hcounts0
        topo_doms0 = ta.domain_mask0
        topo_terms = {
            "allow": ta.term_allow,
            "out": ta.term_out,
            "defined": ta.term_defined,
            "escape": ta.term_escape,
        }
    else:
        topo_counts0 = np.zeros((0, V), np.float32)
        topo_hcounts0 = np.zeros((0, snap.n_slots or 1), np.float32)
        topo_doms0 = np.zeros((0, V), bool)
        topo_terms = {
            "allow": np.zeros((0, V), bool),
            "out": np.zeros((0, snap.dictionary.K), bool),
            "defined": np.zeros((0, snap.dictionary.K), bool),
            "escape": np.zeros((0, snap.dictionary.K), bool),
        }

    return (
        pod_arrays,
        _reqset_to_dict(snap.tmpl_reqs),
        snap.tmpl_daemon,
        snap.tmpl_type_mask,
        _reqset_to_dict(snap.type_reqs),
        snap.type_alloc,
        snap.type_capacity,
        snap.type_offering_ok,
        pod_tol_all,
        _reqset_to_dict(snap.exist_reqs),
        snap.exist_used,
        snap.exist_cap,
        snap.well_known,
        remaining0,
        topo_counts0,
        topo_hcounts0,
        topo_doms0,
        topo_terms,
        snap.exist_ports,
        snap.exist_vols,
        snap.exist_vol_limits,
        snap.vol_driver_onehot,
    )


def _prog_meta(geom, **extra):
    """Program-ledger record metadata for one geometry: the bucketed tier
    axes that identify a compiled program's shape class (items x types x
    existing x slots) without shipping the full cache key."""
    meta = {"tier": f"P{geom[0]}xT{geom[2]}xE{geom[3]}xN{geom[7]}"}
    meta.update(extra)
    return meta


class _Dispatchable:
    """A jit-wrapped program that prefers its AOT-compiled executable when
    the prewarm path produced one: jax.jit(...).lower().compile() does NOT
    populate the jit object's call cache, so without this a live dispatch
    after prewarm would re-trace and re-compile (or, with the persistent
    cache on, re-deserialize). The executable is shape-exact by the
    geometry key; any mismatch falls back to the jit path for good."""

    __slots__ = ("jit", "aot")

    def __init__(self, jit_fn):
        self.jit = jit_fn
        self.aot = None

    def __call__(self, *args):
        aot = self.aot
        if aot is not None:
            try:
                return aot(*args)
            except (TypeError, ValueError):
                # signature/layout drift, rejected at argument processing
                # BEFORE execution (donated inputs not yet consumed): drop
                # the executable for good and let the jit path recover.
                # Execution-time errors (XlaRuntimeError etc.) propagate —
                # a retry would dereference consumed donated buffers and
                # bury the real failure under a deleted-array error.
                self.aot = None
        return self.jit(*args)


@dataclass
class _StagedCall:
    """Everything one device call at one geometry needs before dispatch:
    the bundled host args, the compiled-program cache key derived from
    them, and the bundle-leaf reconstruction closure the programs share.

    Staging is a pure function of (snapshot arrays, solver config), so the
    prewarm thread staging a SYNTHETIC snapshot computes byte-for-byte the
    same key a live solve at that geometry computes — which is what lets
    AOT-prewarmed cache entries be hit by real traffic (solver/prewarm.py)
    and lets a live solve arriving mid-prewarm block on exactly its own
    tier's compile instead of duplicating it."""

    geom: tuple
    run: object
    key: tuple
    spec: tuple
    treedef: object
    layout: tuple
    bundle: np.ndarray
    donated_leaves: list
    donated_meta: list
    rebuild: object  # (bundle, donated_iter) -> run-arg pytree, traceable
    # parallel/specs.SpecLayout when this call targets the GSPMD mesh
    # program; None on the single-device path. Its .key rides the cache
    # key, so mesh programs age in the same LRU without ever colliding
    # with single-device entries at the same geometry.
    spec_layout: object = None


def _bundle_args(args, geom, run, backend, screen_mode, spec_layout=None):
    """Pack device_args output into the upload bundle (see the layout
    comments inline) and derive the compiled-program cache key. Shared by
    TPUSolver._run_kernels (live path) and TPUSolver.prewarm_snapshot."""
    import jax
    import jax.numpy as jnp

    # upload shrinkage, two layers:
    # 1. large bool planes bit-pack on the host and unpack INSIDE the
    #    jitted program — ~8x fewer bytes over a link that runs tens
    #    of MB/s;
    # 2. all non-donated leaves CONCATENATE into one uint8 bundle —
    #    one transfer instead of ~40, on a link that charges
    #    per-transfer latency. Leaves are sliced + bitcast back inside
    #    the program (static offsets). Donated leaves (float32 planes
    #    aliasing into the scan carry) stay separate buffers so
    #    donation still works.
    leaves, treedef = jax.tree_util.tree_flatten(args)
    donate_set = set()
    off = 0
    for name, arg in zip(RUN_ARG_NAMES, args):
        n_leaves = len(jax.tree_util.tree_leaves(arg))
        if name in DONATE_ARG_NAMES:
            donate_set.update(range(off, off + n_leaves))
        off += n_leaves
    # donated leaves must stay unpacked AND unbundled: they alias into
    # the scan carry verbatim (topo_doms0 is a large bool plane that
    # would otherwise trip the packing threshold and reach the kernel
    # as uint8 with the wrong shape)
    spec = tuple(
        a.shape[-1]
        if (
            i not in donate_set
            and a.dtype == np.bool_
            and a.ndim >= 1
            and a.size > 4096
        )
        else None
        for i, a in enumerate(leaves)
    )
    packed = [
        np.packbits(a, axis=-1) if w is not None else a
        for a, w in zip(leaves, spec)
    ]
    # bundle layout: (byte offset, nbytes, dtype str, stored shape) per
    # non-donated leaf; None marks a donated (separate) leaf
    layout = []
    chunks: List[np.ndarray] = []
    off_b = 0
    for i, a in enumerate(packed):
        if i in donate_set:
            layout.append(None)
            continue
        b = np.ascontiguousarray(a).reshape(-1).view(np.uint8)
        pad = (-len(b)) % 4  # keep every segment 4-byte aligned
        layout.append((off_b, len(b), str(a.dtype), a.shape))
        chunks.append(b)
        if pad:
            chunks.append(np.zeros(pad, np.uint8))
        off_b += len(b) + pad
    bundle = np.concatenate(chunks) if chunks else np.zeros(0, np.uint8)
    donated_leaves = [packed[i] for i in sorted(donate_set)]
    donated_meta = [
        (packed[i].shape, packed[i].dtype) for i in sorted(donate_set)
    ]
    key = (
        geom, backend, screen_mode, spec, treedef, tuple(layout),
        spec_layout.key if spec_layout is not None else None,
    )

    # bundle-leaf reconstruction, shared by the solve program, the
    # prescreen precompute, and the (lazily compiled, possibly on a
    # solve-cache HIT) delta refresh program
    def _rebuild(bundle, donated_iter):
        rebuilt = []
        for w, lay in zip(spec, layout):
            if lay is None:
                rebuilt.append(next(donated_iter))
                continue
            o, nbytes, dt_s, shape = lay
            dt = np.dtype(dt_s)
            sl = jax.lax.slice(bundle, (o,), (o + nbytes,))
            if dt == np.bool_:
                arr = sl.astype(bool).reshape(shape)
            elif dt.itemsize == 1:
                arr = sl.astype(dt).reshape(shape)
            else:
                arr = jax.lax.bitcast_convert_type(
                    sl.reshape((-1, dt.itemsize)), jnp.dtype(dt)
                ).reshape(shape)
            if w is not None:
                arr = jnp.unpackbits(arr, axis=-1, count=w).astype(bool)
            rebuilt.append(arr)
        return jax.tree_util.tree_unflatten(treedef, rebuilt)

    return _StagedCall(
        geom=geom, run=run, key=key, spec=spec, treedef=treedef,
        layout=tuple(layout), bundle=bundle, donated_leaves=donated_leaves,
        donated_meta=donated_meta, rebuild=_rebuild, spec_layout=spec_layout,
    )


class TPUSolver:
    """Stateless dense solver; jit-compiled per label geometry.

    max_nodes bounds the slot budget for NEW machines (existing nodes get
    their own slots on top). Geometry bucketing (solve_geometry/device_args)
    pads every batch axis to power-of-two buckets internally, so repeated
    solves at varying sizes reuse the compiled program.
    """

    # consolidation's prefix ladder screens all rungs in one vmapped
    # dispatch against this solver (solver/replan.py)
    supports_batched_replan = True

    def __init__(self, max_nodes: int = 1024,
                 max_relax_rounds: int = DEFAULT_MAX_RELAX_ROUNDS,
                 donate: bool = True, backend: Optional[str] = None,
                 profile_phases: bool = False,
                 screen_mode: Optional[str] = None,
                 incremental: Optional[str] = None,
                 pack_scan: Optional[str] = None):
        self.max_nodes = max_nodes
        self.max_relax_rounds = max_relax_rounds
        self.donate = donate
        self.backend = backend  # kernel lowering override (compat.resolve_backend)
        # slot-screen strategy override (compat.resolve_screen_mode):
        # 'prescreen' = batched class×slot verdict precompute + in-scan
        # incremental refresh, 'tiered' = the per-step full screen fallback
        self.screen_mode = screen_mode
        # delta re-solve policy override (compat.resolve_incremental_mode):
        # 'on' keeps the verdict tensor resident across solves and replays
        # only the state-store delta through the refresh program; 'off'
        # always runs the full precompute
        self.incremental = incremental
        # pack-scan strategy override (compat.resolve_pack_scan):
        # 'segmented' partitions items into conflict-independent segments
        # and packs them in parallel vmapped lanes, byte-identical to —
        # and degrading to — the 'sequential' scan (ISSUE 14)
        self.pack_scan = pack_scan
        # opt-in: barrier after upload so last_phase_ms attributes transfer
        # time separately (costs cold solves the serialized upload)
        self.profile_phases = profile_phases
        # LRU-bounded like the gRPC service's cache: geometry embeds the
        # label dictionary, so live-cluster label churn mints new keys — an
        # unbounded map would pin every old compiled executable (HBM + host)
        from collections import OrderedDict
        import threading

        self.MAX_COMPILED = 32
        self._compiled = OrderedDict()
        # _cache_lock guards the compiled-program LRU and its satellite
        # maps (_fetch_buckets/_refresh_compiled/_inc_screens): the live
        # solve path shares them with the startup prewarm thread.
        # _key_locks serializes program CREATION per geometry key so a live
        # solve arriving while prewarm compiles its tier blocks on exactly
        # that compile instead of duplicating it (and solves at other
        # geometries don't contend at all).
        self._cache_lock = threading.Lock()
        self._key_locks = {}
        # per-geometry (ptr_b, bulk_b, nopen_b, nnz_b) from the previous
        # solve: the speculative single-round-trip fetch slices with these
        self._fetch_buckets = {}
        # incremental encode: stable instance-type planes carry across
        # solves (encode.EncodeReuse)
        from karpenter_core_tpu.solver.encode import EncodeReuse

        self._encode_reuse = EncodeReuse()
        # incremental re-solve: resident verdict tensor + plane fingerprints
        # and the state-diff gate (solver/incremental.py); refresh programs
        # cache per (solve key, row budget, col budget) and are evicted with
        # their solve entry
        from karpenter_core_tpu.solver.incremental import DiffGate

        # one residency carrier PER solve key (steady-state churn alternates
        # among a handful of geometries — topology-signature variants of one
        # dictionary — and a single carrier would evict on every flip)
        self._inc_screens = OrderedDict()
        self.MAX_INC_SCREENS = 8
        self._diff_gate = DiffGate()
        self.MAX_REFRESH = 8
        self._refresh_compiled = OrderedDict()
        # batched consolidation replan programs (replan_screen): one
        # vmapped rung program per (solve key, candidate-axis bucket),
        # LRU-bounded like the refresh family and evicted with the solve
        # entry whose prescreen/residency they share
        self.MAX_REPLAN = 16
        self._replan_compiled = OrderedDict()
        # segmented-scan program family (ISSUE 14): the partitioner program
        # (one per solve key) and the vmapped lane programs (one per
        # (solve key, lane bucket, segment bucket)), LRU-bounded and keyed
        # with the scan mode so sequential-only runs mint NOTHING here
        self.MAX_SEGMENT = 16
        self._segment_compiled = OrderedDict()
        # partition-label residency: (labels, slot_label, tmpl_fp) per
        # solve key, reused across steady-churn solves whose incremental
        # refresh reported an EMPTY verdict delta AND whose template-side
        # digest matches (segment boundaries recomputed only on conflict-
        # structure delta — rides PR 6's residency). Accessed under
        # _cache_lock like every other per-key cache; LRU-bounded on its
        # own so a store racing the solve-entry eviction can never pin a
        # dead key's label arrays forever
        self._segment_labels = OrderedDict()
        # observability for bench/smoke: mode, segment count, max segment,
        # fixup fraction of the LAST dispatch through _run_kernels
        self.last_segment_stats = None
        # per-phase host timings of the last replan_screen dispatch
        # (bench.py consolidation columns read these, mirroring
        # last_phase_ms on the solve path)
        self.last_replan_phase_ms = {}
        self._gate_ok = True
        self.last_prescreen_mode = None
        # the SpecLayout the last _run_kernels dispatch built against:
        # None = single-device program, a layout = the GSPMD mesh program
        # (observability + the sharded small-batch routing tests)
        self.last_spec_layout = None
        # cross-solve dictionary carryover (encode.dictionary_covers):
        # consecutive churn batches whose vocabulary has saturated adopt the
        # previous solve's dictionary, pinning V/K/segments — and with them
        # the compiled-program key the resident verdict tensor lives under
        self._carry_dictionary = None

    # -- public API --------------------------------------------------------

    def encode(
        self,
        pods: List[Pod],
        provisioners: List[Provisioner],
        instance_types: Dict[str, List[InstanceType]],
        daemonset_pods: Optional[List[Pod]] = None,
        state_nodes: Optional[List] = None,
        kube_client=None,
        cluster=None,
    ):
        """Pre-encode a batch into a snapshot off the Solve critical path.
        The production loop overlaps this with the PREVIOUS solve's device
        window + fetch (both host-idle waits): pass the result to
        solve(..., encoded=snap) and the ~encode-sized slice of e2e latency
        disappears from the next Solve (round-3 PERF.md: encode was the
        largest host cost at the north-star config)."""
        return encode_snapshot(
            pods, provisioners, instance_types, daemonset_pods, state_nodes,
            kube_client=kube_client, cluster=cluster, max_nodes=self.max_nodes,
            reuse=self._encode_reuse,
        )

    def prewarm_snapshot(self, snap: EncodedSnapshot,
                         provisioners: List[Provisioner]) -> str:
        """AOT-compile the solve + prescreen (and, when the incremental
        path is enabled, the steady-churn delta refresh) programs for a
        snapshot's geometry WITHOUT dispatching a solve — the startup
        prewarm path (solver/prewarm.py). The staged call computes the
        exact cache key a live solve at this geometry computes, so real
        traffic hits the prewarmed entry; the lower().compile() also
        writes the persistent disk cache (utils/compilecache) so the NEXT
        process restart deserializes instead of recompiling. Thread-safe
        against concurrent live solves ( _entry_for's per-key locks).
        Returns 'compiled' when this call paid the compile, 'cached' when
        the entry already existed."""
        from karpenter_core_tpu.ops import compat as ops_compat
        from karpenter_core_tpu.utils.compilecache import record_lookup

        screen_mode = self.screen_mode or ops_compat.resolve_screen_mode()
        layout = self._layout_for(snap)
        geom, run = build_device_solve(
            snap, self.max_nodes, backend=self.backend,
            screen_mode=screen_mode, external_prescreen=True,
            spec_layout=layout,
        )
        args = device_args(snap, provisioners)
        staged = _bundle_args(
            args, geom, run, self.backend, screen_mode, spec_layout=layout
        )
        entry, cache_hit = self._entry_for(staged, screen_mode, aot=True)
        record_lookup("prewarm", cache_hit)
        if not cache_hit and self._inc_enabled(screen_mode) and layout is None:
            # mesh entries skip the refresh AOT: an executable lowered from
            # host avals would be single-device committed, and the first
            # mesh dispatch (committed replicated arrays) would just
            # discard it — let the live path jit the mesh refresh (which
            # DOES carry the spec_layout replicated fence + cache salt, see
            # make_screen_refresh_kernel); the solve+prescreen pair above
            # is where the compile time is anyway
            self._prewarm_refresh(staged, entry)
        if not cache_hit:
            # the consolidation/replan program family rides the same tier:
            # without this the first deprovisioning pass after a restart
            # paid a cold compile the solve prewarm never covered (replan
            # always dispatches the single-device program — see
            # replan_screen — so mesh solvers prewarm it here too, keyed
            # spec_layout=None like their live replans). The mesh branch
            # stages the single-device twin WITHOUT minting a solve cache
            # entry: _compiled stays "programs live traffic asked for".
            replan_staged = staged
            pre_jit = entry[1].jit if entry[1] is not None else None
            if staged.spec_layout is not None:
                geom_s, run_s = build_device_solve(
                    snap, self.max_nodes, backend=self.backend,
                    screen_mode=screen_mode, external_prescreen=True,
                    spec_layout=None,
                )
                replan_staged = _bundle_args(
                    args, geom_s, run_s, self.backend, screen_mode,
                    spec_layout=None,
                )
                pre_jit = None
                if screen_mode == "prescreen":
                    import jax

                    from karpenter_core_tpu.ops.pack import make_prescreen_kernel

                    (_P, _J, _T, _E, _R, _K, _V, N_s, segs_s, _zs, _cs,
                     _ts, _ll, _Q, _W, _D, scrv_s) = replan_staged.geom
                    pre_single = make_prescreen_kernel(
                        segs_s, N_s, backend=self.backend, screen_v=scrv_s,
                    )
                    rebuild_s = replan_staged.rebuild
                    meta_s = replan_staged.donated_meta

                    def _pre_bundled(bundle):
                        import jax.numpy as jnp

                        dummies = iter(
                            jnp.zeros(s, d) for s, d in meta_s
                        )
                        named = dict(
                            zip(RUN_ARG_NAMES, rebuild_s(bundle, dummies))
                        )
                        return pre_single(named["pod_arrays"], named["exist"])

                    pre_jit = jax.jit(_pre_bundled)
            self._prewarm_replan(replan_staged, pre_jit, snap.topo_meta)
        return "cached" if cache_hit else "compiled"

    def _prewarm_refresh(self, staged: _StagedCall, entry) -> None:
        """AOT-compile the delta-refresh program at the minimum (8, 8)
        budget — the steady-churn common case (solver/incremental.py pads
        narrow deltas to 8); wider budgets compile on demand. Abstract
        avals only: no tensor is materialized."""
        import jax

        _fn, pre_fn = entry
        if pre_fn is None:
            return
        refresh_fn, _minted = self._refresh_fn(
            staged.key, staged.geom, 8, 8, staged.rebuild,
            staged.donated_meta, spec_layout=staged.spec_layout,
        )
        bundle_sds = jax.ShapeDtypeStruct(
            staged.bundle.shape, staged.bundle.dtype
        )
        screen_sds = jax.eval_shape(pre_fn.jit, bundle_sds)
        idx = np.zeros(8, np.int32)
        # the count operands lower as weak-typed scalars, matching the
        # python ints ScreenDelta.padded() passes on the live path
        refresh_fn.aot = refresh_fn.jit.lower(
            bundle_sds, screen_sds, idx, 0, idx, 0
        ).compile()

    def solve(
        self,
        pods: List[Pod],
        provisioners: List[Provisioner],
        instance_types: Dict[str, List[InstanceType]],
        daemonset_pods: Optional[List[Pod]] = None,
        state_nodes: Optional[List] = None,
        kube_client=None,
        cluster=None,
        encoded=None,
    ) -> SolveResult:
        if encoded is not None:
            # the snapshot must be OF this batch: round 1 solves the
            # snapshot's arrays while relax rounds re-encode from the call
            # arguments, and relaxation matches failed pods by identity —
            # a mismatched snapshot would silently mix cluster states and
            # no-op every relaxation
            if len(encoded.pods) != len(pods) or (
                {id(p) for p in encoded.pods} != {id(p) for p in pods}
            ):
                raise ValueError(
                    "encoded snapshot was built from a different pod batch"
                )
        # state-diff gate, consulted ONCE per Solve (relax rounds see no
        # state churn): a feed fault or history gap forces this solve's
        # prescreen down the full path and drops the resident tensor —
        # degrade, never drift (chaos fault point state.diff)
        if self._inc_enabled():
            self._gate_ok = self._diff_gate.gate(cluster)
        # relaxation rounds reuse round 1's dictionary: dropping a preferred
        # term would shrink the value universe, change V/K, and force a
        # recompile mid-solve — a superset dictionary is always valid
        relax_ctx = {"dictionary": None, "encoded": encoded}
        return solve_with_relaxation(
            lambda p: self._solve_once(
                p, provisioners, instance_types, daemonset_pods, state_nodes,
                kube_client, cluster, relax_ctx,
            ),
            pods,
            provisioners,
            instance_types,
            self.max_relax_rounds,
        )

    # -- internals ---------------------------------------------------------

    def _solve_once(self, pods, provisioners, instance_types, daemonset_pods,
                    state_nodes, kube_client=None, cluster=None, relax_ctx=None):
        snap = relax_ctx.pop("encoded", None) if relax_ctx else None
        if snap is None:
            with TRACER.span("solver.phase.encode", pods=len(pods)):
                snap = encode_snapshot(
                    pods, provisioners, instance_types, daemonset_pods, state_nodes,
                    kube_client=kube_client, cluster=cluster, max_nodes=self.max_nodes,
                    reuse_dictionary=relax_ctx.get("dictionary") if relax_ctx else None,
                    reuse=self._encode_reuse,
                    # offered, not trusted: adopted only when it covers this
                    # batch's closure (steady-state churn geometry pinning)
                    carry_dictionary=(
                        self._carry_dictionary if self._inc_enabled() else None
                    ),
                )
        if relax_ctx is not None:
            relax_ctx["dictionary"] = snap.dictionary
        self._carry_dictionary = snap.dictionary
        log, ptr, state = self._run_kernels(snap, provisioners)
        # "bind": decode slot assignments back into machines / placements
        with TRACER.span("solver.phase.bind"):
            return decode_solve(snap, (log, ptr), state)

    def _layout_for(self, snap) -> object:
        """The parallel/specs.SpecLayout this snapshot's programs build
        against — None on the single-device solver. ShardedSolver
        (parallel/sharded.py) overrides this with its mesh layout plus
        the small-batch single-device routing, so the whole compile /
        prewarm / incremental machinery below serves both paths."""
        return None

    def _inc_enabled(self, screen_mode: Optional[str] = None) -> bool:
        """Delta re-solve policy for this solver: prescreen mode only
        (there is no resident tensor to refresh under tiered), gated by
        the KCT_INCREMENTAL env / constructor override."""
        from karpenter_core_tpu.ops import compat as ops_compat

        if screen_mode is None:
            screen_mode = self.screen_mode or ops_compat.resolve_screen_mode()
        if screen_mode != "prescreen":
            return False
        mode = self.incremental or ops_compat.resolve_incremental_mode()
        return mode != "off"

    def _refresh_fn(self, key, geom, rb, cb, rebuild, donated_meta,
                    spec_layout=None):
        """The jitted delta-refresh program for (solve key, row budget,
        col budget), lazily compiled and LRU-bounded, plus whether this
        call MINTED it (the dispatch that follows pays the compile — the
        prescreen span is tagged cold so steady-state medians exclude it).
        It reads the same uploaded bundle as the solve program (donated
        slots rebuild as zero dummies that DCE away) and DONATES the
        previous verdict tensor so XLA updates the resident buffer in
        place."""
        rkey = (key, rb, cb)
        with self._cache_lock:
            fn = self._refresh_compiled.get(rkey)
            if fn is not None:
                self._refresh_compiled.move_to_end(rkey)
                return fn, False
        fn = _Dispatchable(self._build_refresh(
            geom, rb, cb, rebuild, donated_meta, spec_layout=spec_layout,
        ))
        evicted = []
        with self._cache_lock:
            self._refresh_compiled[rkey] = fn
            while len(self._refresh_compiled) > self.MAX_REFRESH:
                evicted.append(self._refresh_compiled.popitem(last=False)[0])
        proghealth.record_mint(
            "refresh", rkey,
            meta=_prog_meta(geom, rb=rb, cb=cb),
        )
        for old in evicted:
            proghealth.retire("refresh", old)
        return fn, True

    def _build_refresh(self, geom, rb, cb, rebuild, donated_meta,
                       spec_layout=None):
        """The raw refresh jit for one (geometry, row budget, col budget)
        — no cache writes, no proghealth mints: the staging seam irlint
        uses to lower the family without touching live state. _refresh_fn
        wraps this with the LRU + mint accounting."""
        import jax
        import jax.numpy as jnp

        from karpenter_core_tpu.ops.pack import make_screen_refresh_kernel

        (_P, _J, _T, _E, _R, _K, _V, N_, segments_t, _zs, _cs, _tsig, _ll,
         _Q, _W, _D, scr_v) = geom
        kern = make_screen_refresh_kernel(
            segments_t, N_, rb, cb, backend=self.backend, screen_v=scr_v,
            spec_layout=spec_layout,
        )

        def refresh_bundled(bundle, prev_screen, row_idx, row_n, col_idx,
                            col_n):
            dummies = iter(jnp.zeros(s, d) for s, d in donated_meta)
            named = dict(zip(RUN_ARG_NAMES, rebuild(bundle, dummies)))
            return kern(
                prev_screen, named["pod_arrays"], named["exist"],
                row_idx, row_n, col_idx, col_n,
            )

        return jax.jit(refresh_bundled, donate_argnums=(1,))

    def _dispatch_prescreen(self, staged: _StagedCall, pre_fn,
                            host_pod_arrays, host_exist, bundle_dev,
                            cache_hit, layout, screen_mode):
        """The [N, C] verdict tensor for one dispatch: a delta refresh of
        the RESIDENT tensor when one is live at this key and the plane
        delta is narrow (solver/incremental.py), the full precompute
        otherwise. Returns (screen0, mode, cold, delta) for span
        attribution.

        Shared by the live solve path (_run_kernels_impl) and the batched
        consolidation replan (replan_screen): residency keys off the
        staged call's compiled-program key, so consecutive consolidation
        passes at a stable union geometry refresh only the churned
        rows/columns — and, when the union snapshot lands on the same
        geometry as the steady-state provisioning solves, the replan
        inherits their resident tensor outright. Bit-identical to the full
        precompute either way; any planning or dispatch failure degrades
        to the full path. Consumes the one-shot state-diff gate verdict
        (self._gate_ok).

        `cold` = this dispatch pays a program compile (first sight of the
        solve geometry, or a freshly minted refresh program): consumers
        comparing refresh-vs-full device time must bucket these apart or
        one-time XLA cost poisons the medians."""
        key, geom = staged.key, staged.geom
        screen0 = None
        scr_mode = "full"
        cold = not cache_hit
        delta = None
        inc = None
        if self._inc_enabled(screen_mode):
            from karpenter_core_tpu.solver.incremental import IncrementalScreen

            gate_ok, self._gate_ok = self._gate_ok, True
            if not gate_ok:
                # a feed fault poisons EVERY key's residency, not just
                # the one this dispatch happens to land on
                for other in self._inc_screens.values():
                    other.invalidate()
            with self._cache_lock:
                inc = self._inc_screens.setdefault(key, IncrementalScreen())
                self._inc_screens.move_to_end(key)
                while len(self._inc_screens) > self.MAX_INC_SCREENS:
                    self._inc_screens.popitem(last=False)
            try:
                delta = inc.plan(
                    key, host_pod_arrays, host_exist, gate_ok=gate_ok
                )
            except Exception:
                inc.invalidate()
                delta = None
            if delta is not None:
                prev = inc.resident(key)
                if prev is not None:
                    try:
                        refresh_fn, cold = self._refresh_fn(
                            key, geom, delta.rb, delta.cb, staged.rebuild,
                            staged.donated_meta, spec_layout=layout,
                        )
                        row_idx, row_n, col_idx, col_n = delta.padded()
                        screen0 = refresh_fn(
                            bundle_dev, prev, row_idx, row_n, col_idx, col_n
                        )
                        scr_mode = "refresh"
                        inc.count_refresh()
                        proghealth.record_dispatch(
                            "refresh", (key, delta.rb, delta.cb)
                        )
                    except Exception:
                        # refresh dispatch failed (the donated tensor may
                        # be gone): drop residency but keep the staged
                        # fingerprints — the fallback full tensor below
                        # re-adopts them
                        inc.drop_resident()
                        inc.count_degraded()
                        screen0 = None
        if screen0 is None:
            screen0 = pre_fn(bundle_dev)
        if inc is not None:
            inc.adopt(key, screen0)
        return screen0, scr_mode, cold, delta

    # -- segmented pack scan (ISSUE 14 tentpole) ----------------------------

    def _segment_eligible(self, snap: EncodedSnapshot, geom, raw_args):
        """Host-side structural gate for the segmented scan: the global
        couplings the segment partition cannot express (topology counts,
        host-port planes, volume limits, finite provisioner limits) force
        the sequential kernel. Returns (ok, reason)."""
        if not getattr(snap, "seg_eligible", False):
            return False, "structure"  # topology / ports / volumes
        remaining0 = raw_args[RUN_ARG_NAMES.index("remaining0")]
        if not bool((remaining0 >= np.float32(1e29)).all()):
            return False, "finite-limits"
        C = raw_args[0]["scls_first"].shape[0]
        if C > 4096:
            # the [C, C] conflict matrix is the partitioner's one quadratic
            # cost; cap it well below where it would rival the scan itself
            return False, "class-axis"
        if len(geom[8]) > 128:
            # the deny-lift channel unrolls one [C, C]-scale term per
            # dictionary KEY at trace time; a pathological label vocabulary
            # must not stall the first segmented solve compiling the
            # partitioner (production dictionaries are a few dozen keys)
            return False, "key-axis"
        return True, ""

    def _partition_fn(self, staged: _StagedCall, screen_mode):
        """The jitted segment-partition program for one solve key (reads
        the solve bundle + the verdict tensor; ops/pack.
        make_segment_partition_kernel), LRU-bounded in the scan-mode-keyed
        segment family; returns (fn, minted)."""
        rkey = (staged.key, "segmented", "partition")
        with self._cache_lock:
            fn = self._segment_compiled.get(rkey)
            if fn is not None:
                self._segment_compiled.move_to_end(rkey)
                return fn, False
        fn = _Dispatchable(self._build_partition(staged, screen_mode))
        evicted = []
        with self._cache_lock:
            fn = self._segment_compiled.setdefault(rkey, fn)
            self._segment_compiled.move_to_end(rkey)
            while len(self._segment_compiled) > self.MAX_SEGMENT:
                evicted.append(self._segment_compiled.popitem(last=False)[0])
        proghealth.record_mint(
            "segment", rkey,
            meta=_prog_meta(staged.geom, scan="segmented", role="partition"),
        )
        for old in evicted:
            proghealth.retire("segment", old)
        return fn, True

    def _build_partition(self, staged: _StagedCall, screen_mode):
        """The raw segment-partition jit for one staged call — no cache
        writes, no proghealth mints (the irlint staging seam).
        _partition_fn wraps this with the LRU + mint accounting."""
        import jax
        import jax.numpy as jnp

        from karpenter_core_tpu.ops.pack import make_segment_partition_kernel

        (_P, _J, _T, E, _R, _K, _V, _N, segments_t, _zs, _cs, _ts, _ll,
         _Q, _W, _D, scr_v) = staged.geom
        kern = make_segment_partition_kernel(
            segments_t, E, screen_v=scr_v, backend=self.backend,
            spec_layout=staged.spec_layout,
        )
        rebuild = staged.rebuild
        meta = staged.donated_meta

        def part_bundled(bundle, screen0):
            dummies = iter(jnp.zeros(s, d) for s, d in meta)
            named = dict(zip(RUN_ARG_NAMES, rebuild(bundle, dummies)))
            return kern(
                screen0, named["pod_arrays"], named["tmpl"],
                named["well_known"],
            )

        return jax.jit(part_bundled)

    def _segment_fn(self, staged: _StagedCall, s_pad: int, m_pad: int,
                    screen_mode, frozen: bool = False):
        """The jitted vmapped lane program for (solve key, lane bucket,
        segment bucket, frozen) — make_device_run(segment_mode=True) over
        the shared bundle; returns (fn, minted). `frozen` (every class in
        the snapshot plane-neutral, per encode.seg_plane_neutral) compiles
        the read-only-verdict lane variant: the tensor is a shared scan
        constant instead of one mutable copy per lane and the refresh
        machinery compiles away. Never donates: the batched lane carries
        cannot alias the shared planes (same rule as the replan family)."""
        rkey = (staged.key, "segmented", s_pad, m_pad, bool(frozen))
        with self._cache_lock:
            fn = self._segment_compiled.get(rkey)
            if fn is not None:
                self._segment_compiled.move_to_end(rkey)
                return fn, False
        fn = _Dispatchable(
            self._build_segment(staged, s_pad, m_pad, screen_mode, frozen)
        )
        evicted = []
        with self._cache_lock:
            fn = self._segment_compiled.setdefault(rkey, fn)
            self._segment_compiled.move_to_end(rkey)
            while len(self._segment_compiled) > self.MAX_SEGMENT:
                evicted.append(self._segment_compiled.popitem(last=False)[0])
        proghealth.record_mint(
            "segment", rkey,
            meta=_prog_meta(
                staged.geom, scan="segmented", lanes=s_pad,
                segment_bucket=m_pad, frozen=bool(frozen),
            ),
        )
        for old in evicted:
            proghealth.retire("segment", old)
        return fn, True

    def _build_segment(self, staged: _StagedCall, s_pad: int, m_pad: int,
                       screen_mode, frozen: bool = False):
        """The raw vmapped-lane jit for one (staged call, lane bucket,
        segment bucket, frozen) — no cache writes, no proghealth mints
        (the irlint staging seam). _segment_fn wraps this with the LRU +
        mint accounting. s_pad/m_pad only key the cache; the traced
        shapes come from the dispatch arguments."""
        import jax

        del s_pad, m_pad  # cache-key only; shapes arrive with the args
        (_P, _J, _T, _E, _R, _K, _V, N_, segments_t, zone_seg, ct_seg,
         _ts, log_len, _Q, _W, _D, scr_v) = staged.geom
        seg_run = make_device_run(
            segments_t, zone_seg, ct_seg, None, N_, log_len=log_len,
            backend=self.backend, screen_v=scr_v, screen_mode=screen_mode,
            external_prescreen=True, spec_layout=staged.spec_layout,
            segment_mode=True, seg_frozen=bool(frozen),
        )
        rebuild = staged.rebuild

        def seg_bundled(item_sel, exist_open, screen0, bundle, *donated):
            return seg_run(
                item_sel, exist_open, screen0,
                *rebuild(bundle, iter(donated)),
            )

        return jax.jit(seg_bundled)

    def _try_segmented(self, snap: EncodedSnapshot, staged: _StagedCall,
                       geom, args, screen0, raw_args, layout, screen_mode,
                       scr_mode, delta, _mark):
        """One segmented pack dispatch: partition -> vmapped lanes ->
        host merge. Returns decode-ready (log, ptr, state) on success,
        None to degrade to the sequential dispatch (self.last_segment_stats
        records which). Byte-identity argument, in three steps:

        1. The partitioner's conflict predicate (ops/pack.
           make_segment_partition_kernel) is a conservative superset of
           every cross-item interaction the sequential scan can express at
           this eligibility level, so items in different components never
           read or write each other's slots — each lane's trajectory IS
           the sequential trajectory restricted to its items and slots.
        2. Machine-slot NUMBERING is the one sequential artifact lanes
           cannot see: the merge replays per-lane commit logs in global
           item order and assigns global machine slots in first-open
           order, which is exactly the order the sequential kernel's
           nopen counter would have assigned them.
        3. Anything the lanes cannot prove — total opens exceeding the
           shared slot budget, a commit-log overflow — aborts the merge
           and re-packs EVERYTHING through the sequential kernel (the
           fixup pass is the sequential kernel itself: fixup_fraction 1.0,
           correctness degrades to the proven path, never past it)."""
        import time as _time

        import jax

        from karpenter_core_tpu.solver.encode import (
            SEGMENT_LANE_BUCKETS,
            bucket_pow2,
            segment_item_pad,
            segment_lane_pad,
        )
        from karpenter_core_tpu.obs import envflags

        E, N = geom[3], geom[7]
        L = geom[12]

        def _fallback(reason, segments=0, max_segment=0):
            self.last_segment_stats = {
                "mode": "sequential-fallback", "reason": reason,
                "segments": int(segments), "max_segment": int(max_segment),
                "fixup_fraction": 1.0,
            }
            return None

        ok, reason = self._segment_eligible(snap, geom, raw_args)
        if not ok:
            return _fallback(reason)

        t_seg = _time.perf_counter()
        key = staged.key
        # partition-label residency: an incremental refresh that reported
        # an EMPTY verdict delta proves the pod/existing side of the
        # conflict structure unchanged — but the conflict matrix ALSO reads
        # the template planes and the well-known mask, which the verdict
        # fingerprints never cover (a provisioner edit can re-weld pools
        # with zero pod/node churn), so reuse additionally requires the
        # template-side fingerprint to match; any mismatch (or a full
        # precompute) recomputes the labels from the refreshed tensor
        tmpl_fp = _segment_tmpl_fingerprint(raw_args)
        with self._cache_lock:
            cached = self._segment_labels.get(key)
            if cached is not None:
                self._segment_labels.move_to_end(key)
        if (
            cached is not None
            and scr_mode == "refresh"
            and delta is not None
            and len(delta.rows) == 0
            and len(delta.cols) == 0
            and cached[2] == tmpl_fp
        ):
            labels, slot_label = cached[:2]
            part_cold = False
        else:
            part_fn, part_cold = self._partition_fn(staged, screen_mode)
            labels_d, _neutral_d, slot_label_d = part_fn(args[0], screen0)
            proghealth.record_dispatch(
                "segment", (key, "segmented", "partition")
            )
            labels, slot_label = jax.device_get((labels_d, slot_label_d))
            labels = np.asarray(labels)
            slot_label = np.asarray(slot_label)
            with self._cache_lock:
                self._segment_labels[key] = (labels, slot_label, tmpl_fp)
                self._segment_labels.move_to_end(key)
                while len(self._segment_labels) > self.MAX_SEGMENT:
                    self._segment_labels.popitem(last=False)

        # -- host grouping: items -> components -> load-balanced lanes ----
        pa = raw_args[0]
        scls = np.asarray(pa["scls"])
        valid = np.asarray(pa["valid"])
        real = np.nonzero(valid)[0]
        if len(real) == 0:
            return _fallback("empty")
        labs = labels[scls[real]]
        sort_i = np.argsort(labs, kind="stable")
        sorted_labs = labs[sort_i]
        cuts = np.nonzero(np.diff(sorted_labs))[0] + 1
        group_items = np.split(real[sort_i], cuts)
        group_labels = sorted_labs[np.concatenate(([0], cuts))] if len(
            sorted_labs
        ) else np.zeros(0, np.int64)
        s_real = len(group_items)
        if s_real <= 1:
            return _fallback("single-segment", segments=s_real,
                             max_segment=len(real))

        # clamp to the lane-axis ladder top: an oversized (or unparseable)
        # KCT_SEGMENT_LANES must tune DOWN to the compiled bucket, not raise
        # into the degrade handler and silently disable segmentation on
        # every solve
        try:
            lanes_req = int(envflags.raw("KCT_SEGMENT_LANES", "8") or 8)
        except ValueError:
            lanes_req = 8
        max_lanes = min(max(lanes_req, 2), SEGMENT_LANE_BUCKETS[-1])
        lanes_n = min(s_real, max_lanes)
        # LPT load balance by item count (the scan length is what a lane
        # pays); merging components into one lane is always sound — the
        # lane is a sequential scan over the union, and independence
        # across lanes is what the partition proves
        order_sz = sorted(
            range(s_real), key=lambda g: -len(group_items[g])
        )
        lane_members = [[] for _ in range(lanes_n)]
        loads = [0] * lanes_n
        lane_of_label = {}
        for g in order_sz:
            tgt = min(range(lanes_n), key=lambda x: loads[x])
            lane_members[tgt].append(group_items[g])
            loads[tgt] += len(group_items[g])
            lane_of_label[int(group_labels[g])] = tgt
        m_real = max(loads)
        s_pad = segment_lane_pad(lanes_n)
        m_pad = segment_item_pad(m_real, geom[0])

        item_sel = np.full((s_pad, m_pad), -1, np.int32)
        for s, members in enumerate(lane_members):
            # global item order WITHIN the lane: the lane's scan must
            # process its items in the same relative order the sequential
            # scan would
            rows = np.sort(np.concatenate(members))
            item_sel[s, : len(rows)] = rows
        exist_open = np.zeros((s_pad, E), bool)
        if E:
            lane_of = np.full(len(labels) + 1, -1, np.int32)
            for lab, tgt in lane_of_label.items():
                lane_of[lab] = tgt
            sl = np.asarray(slot_label[:E])
            owner = np.where(sl >= 0, lane_of[np.maximum(sl, 0)], -1)
            for s in range(lanes_n):
                exist_open[s] = owner == s
        _mark(
            "segment", segments=s_real, lanes=lanes_n,
            max_segment=m_real, cold=part_cold,
        )

        # -- vmapped lane dispatch ----------------------------------------
        # frozen lanes: the encoder proved every class plane-neutral (no
        # defined keys inside the screen width), so no commit can change
        # any verdict — the lane program keeps the tensor as a shared
        # read-only scan constant (opened machine rows read the
        # precomputed template rows instead)
        neutral = getattr(snap, "seg_plane_neutral", None)
        frozen = bool(
            neutral is not None
            and np.asarray(neutral).size
            and bool(np.asarray(neutral).all())
        )
        t_dispatch = _time.perf_counter()
        seg_fn, seg_cold = self._segment_fn(
            staged, s_pad, m_pad, screen_mode, frozen
        )
        log_s, ptr_s, state_s = seg_fn(
            item_sel, exist_open, screen0, args[0], *args[1:]
        )
        ptr_a, nopen_a, bulkn_a = (
            np.asarray(v)
            for v in jax.device_get(
                (ptr_s, state_s.nopen, log_s["bulk_n"])
            )
        )
        self.last_device_ms = (_time.perf_counter() - t_dispatch) * 1e3
        _mark("device", compile_cache="miss" if seg_cold else "hit",
              lanes=lanes_n)
        proghealth.record_dispatch(
            "segment",
            (staged.key, "segmented", s_pad, m_pad, bool(frozen)),
            self.last_device_ms,
        )
        opens = np.maximum(nopen_a - E, 0)
        lane_lb = min(2 * m_pad + 64, 4096) if E else 1
        if int(opens.sum()) > N - E:
            # the disjointness proof cannot cover the SHARED machine-slot
            # budget: the sequential scan would have exhausted it mid-run,
            # and from there its trajectory is order-dependent across
            # segments — re-pack everything through the proven kernel
            return _fallback("slot-budget", segments=s_real,
                             max_segment=m_real)
        if bool((ptr_a >= L).any()) or bool((bulkn_a >= lane_lb).any()):
            return _fallback("log-overflow", segments=s_real,
                             max_segment=m_real)

        # -- slice fetch ---------------------------------------------------
        pb = min(bucket_pow2(max(int(ptr_a.max()), 1), 256), L)
        nb = min(bucket_pow2(max(int(nopen_a.max()), 1), 256), N)
        bb = min(bucket_pow2(max(int(bulkn_a.max()), 1), 64), lane_lb)
        eager = (
            {k: log_s[k][:, :pb]
             for k in ("item", "slot", "ns", "k", "k_last")},
            log_s["bulk_take"][:, :bb] if E else None,
            {f: getattr(state_s, f)[:, :nb]
             for f in ("tmpl", "used", "pods", "tmask", "allow", "out",
                       "defined")},
        )
        log_h, bulk_h, st_h = jax.device_get(eager)
        log_h = {k: np.asarray(v) for k, v in log_h.items()}
        st_h = {k: np.asarray(v) for k, v in st_h.items()}
        _mark("fetch")

        # -- merge: interleave lanes into item order, renumber opens ------
        lane_ptr = [int(p) for p in ptr_a]
        items_c = np.concatenate(
            [log_h["item"][s, : lane_ptr[s]] for s in range(s_pad)]
        )
        slots_c = np.concatenate(
            [log_h["slot"][s, : lane_ptr[s]] for s in range(s_pad)]
        )
        ns_c = np.concatenate(
            [log_h["ns"][s, : lane_ptr[s]] for s in range(s_pad)]
        )
        k_c = np.concatenate(
            [log_h["k"][s, : lane_ptr[s]] for s in range(s_pad)]
        )
        kl_c = np.concatenate(
            [log_h["k_last"][s, : lane_ptr[s]] for s in range(s_pad)]
        )
        lane_c = np.concatenate(
            [np.full(lane_ptr[s], s, np.int32) for s in range(s_pad)]
        )
        order = np.argsort(items_c, kind="stable")
        slot_map = {}
        next_g = E
        m_item, m_slot, m_ns, m_k, m_kl = [], [], [], [], []
        bulk_rows = []
        for e in order:
            ln, ns, sl = int(lane_c[e]), int(ns_c[e]), int(slots_c[e])
            kk, kl = int(k_c[e]), int(kl_c[e])
            if ns == -1:
                bulk_rows.append(np.asarray(bulk_h[ln, kk]))
                kk = len(bulk_rows) - 1
                sl = 0
            elif sl >= E:
                for j in range(ns):
                    lk = (ln, sl + j)
                    if lk not in slot_map:
                        slot_map[lk] = next_g
                        next_g += 1
                sl = slot_map[(ln, sl)]
            m_item.append(int(items_c[e]))
            m_slot.append(sl)
            m_ns.append(ns)
            m_k.append(kk)
            m_kl.append(kl)
        merged_log = {
            "item": np.asarray(m_item, np.int32),
            "slot": np.asarray(m_slot, np.int32),
            "ns": np.asarray(m_ns, np.int32),
            "k": np.asarray(m_k, np.int32),
            "k_last": np.asarray(m_kl, np.int32),
            "bulk_take": (
                np.stack(bulk_rows)
                if bulk_rows
                else np.zeros((0, E), np.int32)
            ),
            "bulk_n": len(bulk_rows),
        }
        ptr_m = len(order)

        # -- merged slot state (decode reads machine rows only) -----------
        total = next_g
        fields = {}
        for f, arr in st_h.items():
            out = np.zeros((total,) + arr.shape[2:], dtype=arr.dtype)
            if slot_map:
                gl = np.asarray(list(slot_map.values()), np.int64)
                ls = np.asarray([k[0] for k in slot_map], np.int64)
                lc = np.asarray([k[1] for k in slot_map], np.int64)
                out[gl] = arr[ls, lc]
            fields[f] = out
        state_h = _MergedSlotState(**fields)
        # the host merge is real per-solve cost sequential mode never pays:
        # it gets its OWN phase mark so the bench A/B window can include it
        # (docs/solver-perf.md "honest CPU expectations")
        _mark("merge", entries=int(ptr_m))
        self.last_segment_stats = {
            "mode": "segmented",
            "segments": int(s_real),
            "lanes": int(lanes_n),
            "max_segment": int(m_real),
            "frozen": bool(frozen),
            "fixup_fraction": 0.0,
            "opens": int(opens.sum()),
            "segment_ms": round((_time.perf_counter() - t_seg) * 1e3, 1),
        }
        return merged_log, ptr_m, state_h

    # -- batched consolidation replan (ISSUE 10 tentpole) -------------------

    def replan_screen(self, snap: EncodedSnapshot,
                      provisioners: List[Provisioner],
                      count_rows: np.ndarray, exist_open: np.ndarray,
                      uninitialized: Optional[np.ndarray] = None,
                      cluster=None, want_slots: bool = False):
        """Evaluate K candidate node-subsets as ONE vmapped device call —
        the deprovisioning counterpart of _run_kernels.

        Per subset k, exist_open[k] closes the victims' existing slots and
        count_rows[k] activates their evicted pods on the item axis; the
        rung-mode solve program re-packs them against the residual cluster
        (ops/pack.make_batched_replan_kernel). The call shares the whole
        solve-path machinery: _bundle_args staging (so the compiled-program
        key — and with it the prescreen program, the resident verdict
        tensor, and the refresh programs — is the SAME key family a live
        solve at this geometry uses), the geometry bucket ladder, and the
        K axis's own bucket ladder (encode.REPLAN_K_BUCKETS) so the replan
        program set stays bounded and prewarmable.

        Returns (verdicts [K, 4] int32 — (scheduled, expected, n_new,
        inconclusive) per subset — and pods_per_slot [K, N] int32 when
        want_slots, else None). The caller (solver/replan.py) turns these
        into ranked SubsetScreens."""
        import time as _time

        import jax

        from karpenter_core_tpu.ops import compat as ops_compat
        from karpenter_core_tpu.solver.encode import replan_chunks
        from karpenter_core_tpu.utils.compilecache import record_lookup

        # dispatch-start heartbeat (same contract as _run_kernels_impl):
        # staleness counts from the replan dispatch, not the last solve
        supervise.touch_heartbeat("solver.phase.replan.device")
        chaos.maybe_fail(chaos.SOLVER_DEVICE)
        # hang-shaped chaos (sleep-past-watchdog): models the wedge, where
        # the dispatch stops progressing instead of erroring
        chaos.maybe_fail(chaos.SOLVER_DEVICE_HANG)
        phases = self.last_replan_phase_ms = {}
        t_phase = _time.perf_counter_ns()

        def _mark(name, **attrs):
            nonlocal t_phase
            now = _time.perf_counter_ns()
            phases[name] = round((now - t_phase) / 1e6, 1)
            TRACER.add_span(f"solver.phase.replan.{name}", t_phase, now,
                            **attrs)
            t_phase = now
            # progress proof for the dispatch watchdog (ResilientSolver /
            # bench stage supervisor): a wedged dispatch stops marking
            supervise.touch_heartbeat(f"solver.phase.replan.{name}")

        screen_mode = self.screen_mode or ops_compat.resolve_screen_mode()
        # single-device deliberately: the candidate axis is a vmap over the
        # rung program, and vmapping the GSPMD mesh program is unproven —
        # a ShardedSolver's replan therefore runs the plain program (the
        # K-way batch recovers the parallelism the mesh would have added)
        geom, solve_run = build_device_solve(
            snap, self.max_nodes, backend=self.backend,
            screen_mode=screen_mode, external_prescreen=True,
            spec_layout=None,
        )
        args = device_args(snap, provisioners)
        _mark("args")
        staged = _bundle_args(
            args, geom, solve_run, self.backend, screen_mode,
            spec_layout=None,
        )
        _mark("pack")
        if self._inc_enabled(screen_mode):
            # same one-shot feed gate as solve(): a diff-feed fault forces
            # the full prescreen and drops residency — degrade, never drift
            self._gate_ok = self._diff_gate.gate(cluster)
        # the solve-path cache entry at this key: its prescreen program and
        # residency serve this replan; the solve program itself stays an
        # undispatched jit object until real provisioning traffic needs it
        entry, cache_hit = self._entry_for(staged, screen_mode)
        _solve_fn, pre_fn = entry

        K = int(count_rows.shape[0])
        E = staged.geom[3]
        uninit = np.zeros(E, dtype=bool)
        if uninitialized is not None:
            uninit[: min(len(uninitialized), E)] = uninitialized[:E]

        dev = jax.device_put((staged.bundle, *staged.donated_leaves))
        _mark("upload")
        if pre_fn is not None:
            screen0, scr_mode, cold, delta = self._dispatch_prescreen(
                staged, pre_fn, args[0], args[9], dev[0], cache_hit,
                None, screen_mode,
            )
            _mark(
                "prescreen", slots=geom[7], mode=scr_mode, cold=cold,
                delta_rows=len(delta.rows) if delta is not None else -1,
                delta_cols=len(delta.cols) if delta is not None else -1,
            )
            self.last_prescreen_mode = scr_mode
        else:
            screen0 = None

        # chunk over the candidate-axis ladder: one staging + prescreen
        # serves every chunk, so a 1000-candidate single-node sweep costs
        # ceil(1000/64) dispatches of ONE compiled program — never 1000
        # sequential simulate_scheduling solves
        t_dispatch = _time.perf_counter()
        any_miss = False
        verdict_parts, pods_parts = [], []
        for k, Kp, sub_counts, sub_open in replan_chunks(
            count_rows, exist_open
        ):
            fn, minted = self._replan_fn(
                staged, Kp, screen_mode, snap.topo_meta
            )
            record_lookup("replan", not minted)
            any_miss |= minted
            t_chunk = _time.perf_counter()
            with device_profiler():
                pods_dev, verd_dev = fn(
                    sub_counts, sub_open, uninit, screen0, dev[0], *dev[1:]
                )
                if profile_dir():
                    jax.block_until_ready(verd_dev)
            if want_slots:
                verd_h, pods_h = jax.device_get((verd_dev, pods_dev))
                pods_parts.append(np.asarray(pods_h)[:k])
            else:
                # the verdict reduction ran on device: fetch [K, 4]
                # scalars, never the [K, N] slot plane
                # (make_replan_verdict_kernel)
                verd_h = jax.device_get(verd_dev)
            verdict_parts.append(np.asarray(verd_h)[:k])
            proghealth.record_dispatch(
                "replan", (staged.key, Kp),
                (_time.perf_counter() - t_chunk) * 1e3,
            )
        self.last_device_ms = (_time.perf_counter() - t_dispatch) * 1e3
        _mark(
            "device", compile_cache="miss" if any_miss else "hit",
            subsets=K,
        )
        verdicts = (
            np.concatenate(verdict_parts)
            if verdict_parts else np.zeros((0, 4), np.int32)
        )
        pods = np.concatenate(pods_parts) if want_slots and pods_parts else None
        _mark("fetch")
        return verdicts, pods

    def _replan_fn(self, staged: _StagedCall, k_pad: int, screen_mode,
                   topo_meta):
        """The jitted batched replan program for (solve key, candidate-axis
        bucket), lazily built and LRU-bounded; returns (fn, minted). The
        program reads the same uploaded bundle as the solve/prescreen pair
        and never donates (the batched carry cannot alias the shared
        planes)."""
        rkey = (staged.key, k_pad)
        with self._cache_lock:
            fn = self._replan_compiled.get(rkey)
            if fn is not None:
                self._replan_compiled.move_to_end(rkey)
                return fn, False
        fn = _Dispatchable(
            self._build_replan(staged, k_pad, screen_mode, topo_meta)
        )
        evicted = []
        with self._cache_lock:
            fn = self._replan_compiled.setdefault(rkey, fn)
            self._replan_compiled.move_to_end(rkey)
            while len(self._replan_compiled) > self.MAX_REPLAN:
                evicted.append(self._replan_compiled.popitem(last=False)[0])
        proghealth.record_mint(
            "replan", rkey,
            meta=_prog_meta(staged.geom, k_bucket=k_pad),
        )
        for old in evicted:
            proghealth.retire("replan", old)
        return fn, True

    def _build_replan(self, staged: _StagedCall, k_pad: int, screen_mode,
                      topo_meta):
        """The raw batched-replan jit for one (staged call, candidate-axis
        bucket) — no cache writes, no proghealth mints (the irlint staging
        seam). _replan_fn wraps this with the LRU + mint accounting. k_pad
        only keys the cache; the traced K comes from the dispatch args."""
        import jax

        from karpenter_core_tpu.ops.pack import make_batched_replan_kernel

        del k_pad  # cache-key only; shapes arrive with the args
        (_P, _J, _T, E, _R, _K, _V, N_, segments_t, zone_seg, ct_seg,
         _tsig, log_len, _Q, _W, _D, scr_v) = staged.geom
        rung_run = make_device_run(
            segments_t, zone_seg, ct_seg, topo_meta, N_, log_len=log_len,
            rung_mode=True, backend=self.backend, screen_v=scr_v,
            screen_mode=screen_mode,
            external_prescreen=(screen_mode == "prescreen"),
        )
        kern = make_batched_replan_kernel(
            rung_run, E, screen_mode == "prescreen"
        )
        rebuild = staged.rebuild

        def replan_bundled(count_rows, exist_open, uninit, screen0, bundle,
                           *donated):
            return kern(
                count_rows, exist_open, uninit, screen0,
                *rebuild(bundle, iter(donated)),
            )

        return jax.jit(replan_bundled)

    def _prewarm_replan(self, staged: _StagedCall, pre_jit, topo_meta) -> None:
        """AOT-compile the batched consolidation replan program for this
        tier at the smallest candidate-axis bucket (the multi-node prefix
        ladder's shape, encode.REPLAN_K_BUCKETS[0]) so the first
        deprovisioning pass after a restart dispatches a warm program —
        the solve/prescreen/refresh triple alone left consolidation paying
        the cold compile. pre_jit is the bundled prescreen jit whose output
        shape the replan program's screen0 argument mirrors (None under
        tiered). Abstract avals except the staged synthetic bundle
        (concrete, like the solve AOT)."""
        import jax

        from karpenter_core_tpu.ops import compat as ops_compat
        from karpenter_core_tpu.solver.encode import REPLAN_K_BUCKETS

        screen_mode = self.screen_mode or ops_compat.resolve_screen_mode()
        fn, _minted = self._replan_fn(
            staged, REPLAN_K_BUCKETS[0], screen_mode, topo_meta
        )
        if fn.aot is not None:
            return
        P, E = staged.geom[0], staged.geom[3]
        k = REPLAN_K_BUCKETS[0]
        count_sds = jax.ShapeDtypeStruct((k, P), np.int32)
        open_sds = jax.ShapeDtypeStruct((k, E), np.bool_)
        uninit_sds = jax.ShapeDtypeStruct((E,), np.bool_)
        screen_sds = None
        if pre_jit is not None:
            bundle_sds = jax.ShapeDtypeStruct(
                staged.bundle.shape, staged.bundle.dtype
            )
            screen_sds = jax.eval_shape(pre_jit, bundle_sds)
        import time as _time

        t_aot = _time.perf_counter()
        fn.aot = fn.jit.lower(
            count_sds, open_sds, uninit_sds, screen_sds,
            staged.bundle, *staged.donated_leaves,
        ).compile()
        proghealth.record_compile(
            "replan", (staged.key, k),
            _time.perf_counter() - t_aot, compiled=fn.aot,
        )

    # -- compiled-program cache (shared with the prewarm thread) -----------

    def _entry_for(self, staged: _StagedCall, screen_mode,
                   aot: bool = False):
        """(entry, cache_hit) for a staged call's geometry key. Creation is
        serialized per key: the winner builds the (solve, prescreen) jit
        pair — and, on the prewarm path (aot=True), pays the XLA compile
        right here via jax.jit(...).lower().compile(), which also writes
        the persistent disk cache — while losers block and then hit."""
        import threading
        import time as _time

        key = staged.key
        with self._cache_lock:
            entry = self._compiled.get(key)
            if entry is not None:
                self._compiled.move_to_end(key)
                return entry, True
            lock = self._key_locks.setdefault(key, threading.Lock())
        with lock:
            with self._cache_lock:
                entry = self._compiled.get(key)
                if entry is not None:  # lost the race: the other thread built it
                    self._compiled.move_to_end(key)
                    return entry, True
            entry = self._build_entry(staged, screen_mode)
            compile_s = 0.0
            if aot:
                t_aot = _time.perf_counter()
                self._aot_compile(entry, staged)
                compile_s = _time.perf_counter() - t_aot
            retired = []
            with self._cache_lock:
                self._compiled[key] = entry
                self._key_locks.pop(key, None)
                while len(self._compiled) > self.MAX_COMPILED:
                    old_key, _ = self._compiled.popitem(last=False)
                    retired.append(("solve", old_key))
                    self._fetch_buckets.pop(old_key, None)
                    for rk in [k for k in self._refresh_compiled
                               if k[0] == old_key]:
                        del self._refresh_compiled[rk]
                        retired.append(("refresh", rk))
                    for rk in [k for k in self._replan_compiled
                               if k[0] == old_key]:
                        del self._replan_compiled[rk]
                        retired.append(("replan", rk))
                    for rk in [k for k in self._segment_compiled
                               if k[0] == old_key]:
                        del self._segment_compiled[rk]
                        retired.append(("segment", rk))
                    self._segment_labels.pop(old_key, None)
                    self._inc_screens.pop(old_key, None)
            proghealth.record_mint(
                "solve", key,
                origin="aot" if aot else "live",
                compile_s=compile_s,
                compiled=entry[0].aot,
                meta=_prog_meta(
                    staged.geom, screen_mode=str(screen_mode),
                    prescreen=entry[1] is not None,
                ),
            )
            for family, rk in retired:
                proghealth.retire(family, rk)
        return entry, False

    def _build_entry(self, staged: _StagedCall, screen_mode):
        """The (solve, prescreen) jit pair for one geometry — jit objects
        only; the XLA compile is paid at first dispatch (live path) or by
        _aot_compile (prewarm path)."""
        import jax
        import jax.numpy as jnp

        run = staged.run
        _rebuild = staged.rebuild
        donated_meta = staged.donated_meta
        n_donated = len(staged.donated_leaves)
        if screen_mode == "prescreen":
            def run_bundled(bundle, screen0, *donated):
                return run(screen0, *_rebuild(bundle, iter(donated)))

            # screen0 sits at position 1, shifting the donated planes
            # one right; it is NOT donated itself — the scan's final
            # verdict carry is discarded, so no output buffer can ever
            # alias it and XLA would just warn "donated buffer not
            # usable" on every compile
            donate_nums = (
                tuple(range(2, 2 + n_donated)) if self.donate else ()
            )
        else:
            def run_bundled(bundle, *donated):
                return run(*_rebuild(bundle, iter(donated)))

            donate_nums = (
                tuple(range(1, 1 + n_donated)) if self.donate else ()
            )
        fn = _Dispatchable(jax.jit(run_bundled, donate_argnums=donate_nums))

        pre_fn = None
        if screen_mode == "prescreen":
            # the batched class×slot precompute as its OWN program,
            # cached under the same LRU entry as the solve program so
            # the pair ages out together and the bucketed compile cache
            # stays at 2 programs per geometry (guarded by
            # tests/test_perf_floor.py's tripwire). It reads only
            # non-donated bundle leaves; donated slots rebuild as
            # zero dummies that DCE away.
            from karpenter_core_tpu.ops.pack import make_prescreen_kernel

            (_P, _J, _T, _E, _R, _K, _V, N_, segments_t, _zs, _cs,
             _tsig, _ll, _Q, _W, _D, scr_v) = staged.geom
            prescreen_run = make_prescreen_kernel(
                segments_t, N_, backend=self.backend, screen_v=scr_v,
                spec_layout=staged.spec_layout,
            )

            def prescreen_bundled(bundle):
                dummies = iter(
                    jnp.zeros(s, d) for s, d in donated_meta
                )
                named = dict(
                    zip(RUN_ARG_NAMES, _rebuild(bundle, dummies))
                )
                return prescreen_run(named["pod_arrays"], named["exist"])

            pre_fn = _Dispatchable(jax.jit(prescreen_bundled))
        return (fn, pre_fn)

    def _aot_compile(self, entry, staged: _StagedCall) -> None:
        """AOT-compile an entry's programs against the staged (synthetic)
        args — jax.jit(...).lower(...).compile() pays the full XLA compile
        NOW and writes the persistent disk cache. The executables attach
        to the entry's _Dispatchable wrappers so the first live dispatch
        at this geometry runs them directly (no re-trace, no re-compile,
        no disk deserialize)."""
        import jax

        fn, pre_fn = entry
        bundle = staged.bundle
        if pre_fn is not None:
            pre_fn.aot = pre_fn.jit.lower(bundle).compile()
            # the solve program's screen0 argument has the prescreen
            # output's shape/dtype; lower with the abstract value so no
            # tensor is materialized
            screen_sds = jax.eval_shape(pre_fn.jit, bundle)
            fn.aot = fn.jit.lower(
                bundle, screen_sds, *staged.donated_leaves
            ).compile()
        else:
            fn.aot = fn.jit.lower(bundle, *staged.donated_leaves).compile()

    def _run_kernels(self, snap: EncodedSnapshot, provisioners: List[Provisioner]):
        return self._run_kernels_impl(
            snap, provisioners, self._layout_for(snap)
        )

    def _run_kernels_impl(self, snap: EncodedSnapshot,
                          provisioners: List[Provisioner], layout):
        import time as _time

        import jax
        import jax.numpy as jnp

        # dispatch-start heartbeat: staleness counts from HERE, so a hang
        # injected (or a backend wedge hit) before the first phase mark is
        # still measured against the dispatch, not whatever touched the
        # heartbeat last (the solver-host watchdog reads the same mark).
        # Labeled "solver.phase.device": everything from here to the fetch
        # IS the device dispatch pipeline, and the hang chaos right below
        # models a device wedge — so the wedge verdict a drill produces
        # names the phase it injects (the _marks refine the label as real
        # phases complete)
        supervise.touch_heartbeat("solver.phase.device")
        # chaos hook: the accelerator edge — an injected fault here is the
        # wedged-backend failure that cost two bench rounds, and must route
        # the solve to ResilientSolver's fallback, never stall the loop
        chaos.maybe_fail(chaos.SOLVER_DEVICE)
        # hang-shaped chaos (sleep-past-watchdog): the wedge failure mode —
        # the dispatch goes silent, the heartbeat goes stale, and the
        # ResilientSolver watchdog must abandon + trip the breaker
        chaos.maybe_fail(chaos.SOLVER_DEVICE_HANG)

        phases = self.last_phase_ms = {}
        t_phase = _time.perf_counter_ns()

        def _mark(name, **attrs):
            # retroactive span per phase boundary: the kernel pipeline is
            # sequential marks, not nested blocks (obs.Tracer.add_span)
            nonlocal t_phase
            now = _time.perf_counter_ns()
            phases[name] = round((now - t_phase) / 1e6, 1)
            TRACER.add_span(f"solver.phase.{name}", t_phase, now, **attrs)
            t_phase = now
            # progress proof for the dispatch watchdog (ResilientSolver /
            # bench stage supervisor): a wedged dispatch stops marking.
            # The label names the phase just finished, so a later wedge
            # verdict reports the last phase activity seen (ISSUE 15)
            supervise.touch_heartbeat(f"solver.phase.{name}")

        from karpenter_core_tpu.ops import compat as ops_compat

        screen_mode = self.screen_mode or ops_compat.resolve_screen_mode()
        self.last_spec_layout = layout
        geom, run = build_device_solve(
            snap, self.max_nodes, backend=self.backend,
            screen_mode=screen_mode, external_prescreen=True,
            spec_layout=layout,
        )
        args = device_args(snap, provisioners)
        raw_args = args  # host numpy view (incremental plane fingerprints)
        _mark("args")
        staged = _bundle_args(
            args, geom, run, self.backend, screen_mode, spec_layout=layout
        )
        _mark("pack")
        from karpenter_core_tpu.utils.compilecache import (
            record_compile_seconds,
            record_lookup,
        )

        key = staged.key
        # thread-safe keyed lookup: the prewarm thread AOT-compiles through
        # the same path, so a live solve arriving mid-prewarm blocks only
        # on its own tier's per-key lock and never duplicates a compile
        entry, cache_hit = self._entry_for(staged, screen_mode)
        record_lookup("tpu_solver", cache_hit)
        fn, pre_fn = entry
        # one transfer for the bundle + one per donated plane; on the mesh
        # path the upload lands committed to the mesh (NamedSharding,
        # replicated — the bundle is opaque bytes; per-family sharding
        # engages at the in-program constraint seams)
        if layout is not None:
            args = layout.put_replicated((staged.bundle, *staged.donated_leaves))
        else:
            args = jax.device_put((staged.bundle, *staged.donated_leaves))
        if self.profile_phases:
            # barrier ONLY under opt-in phase profiling: it serializes the
            # upload with jit trace/compile, costing cold solves the full
            # transfer time for timing attribution
            jax.block_until_ready(args)
        _mark("upload")

        if pre_fn is not None:
            # class×slot feasibility precompute: dispatched ahead of the
            # scan program, which takes the verdict tensor as its (non-
            # donated — see donate_nums) leading argument. Dispatch is
            # async, so outside profile_phases this span mostly attributes
            # the dispatch itself; the execution overlaps into the device
            # window either way. The residency/refresh machinery is shared
            # with the batched consolidation replan (replan_screen), which
            # reuses the same resident tensor across its K simulated
            # re-packs — _dispatch_prescreen has the full story.
            screen0, scr_mode, cold, delta = self._dispatch_prescreen(
                staged, pre_fn, raw_args[0], raw_args[9], args[0],
                cache_hit, layout, screen_mode,
            )
            if self.profile_phases:
                jax.block_until_ready(screen0)
            _mark(
                "prescreen", slots=geom[7], mode=scr_mode, cold=cold,
                delta_rows=len(delta.rows) if delta is not None else -1,
                delta_cols=len(delta.cols) if delta is not None else -1,
            )
            self.last_prescreen_mode = scr_mode
            run_args = (args[0], screen0, *args[1:])
            # segmented scan dispatch (ISSUE 14): partition the item axis
            # into conflict-independent segments off the verdict tensor and
            # pack them as parallel vmapped lanes. Any failure — structural
            # ineligibility, a single conflict component, post-hoc
            # slot-budget overflow, or a device fault (chaos site
            # solver.segment) — degrades to the sequential dispatch below,
            # which is also the proven fixup path: correctness can degrade
            # TO the sequential kernel, never past it.
            self.last_segment_stats = None
            scan_mode = self.pack_scan or ops_compat.resolve_pack_scan()
            if scan_mode == "segmented":
                try:
                    chaos.maybe_fail(chaos.SOLVER_SEGMENT)
                    seg = self._try_segmented(
                        snap, staged, geom, args, screen0, raw_args,
                        layout, screen_mode, scr_mode, delta, _mark,
                    )
                except Exception as exc:  # noqa: BLE001 — degrade, never fail
                    self.last_segment_stats = {
                        "mode": "sequential-fallback",
                        "reason": f"error:{type(exc).__name__}",
                        "segments": 0, "max_segment": 0,
                        "fixup_fraction": 1.0,
                    }
                    seg = None
                if seg is not None:
                    return seg
        else:
            run_args = args

        t_dispatch = _time.perf_counter()
        # re-label the heartbeat for the long silent stretch ahead: a wedge
        # inside the XLA compile/execute block must name the device phase,
        # not the last completed host-side mark (upload/prescreen)
        supervise.touch_heartbeat("solver.phase.device")
        # opt-in device profiling around the Solve dispatch (obs.device_
        # profiler, KARPENTER_TPU_PROFILE) — the analog of the reference's
        # pprof-profiled benchmark capture (scheduling_benchmark_test.go:
        # 84-95); view with tensorboard or xprof. One trace per solve
        # while the env var is set. The barrier keeps the execution inside
        # the captured window.
        with device_profiler():
            log, ptr, state = fn(*run_args)
            if profile_dir():
                jax.block_until_ready(state)
        if layout is not None:
            # rehome the outputs to ONE device before the fetch path: its
            # eager ops (slicing, packbits, nonzero compaction) each
            # compile tiny executables, which must be SINGLE-device —
            # eager ops can't carry the cache_salt, and XLA:CPU reloads of
            # multi-device executables are nondeterministic
            # (specs.SpecLayout.cache_salt has the full story)
            log, ptr, state = jax.device_put(
                (log, ptr, state), jax.devices()[0]
            )

        # fetch ONLY what decode reads: log entries [:ptr], bulk rows
        # [:bulk_n], and state slot rows [:nopen] (the slot budget is mostly
        # unused headroom — at 50k pods this cuts the fetch ~10x). Slice
        # lengths round UP to buckets: each distinct slice shape compiles
        # its own tiny device program, so exact lengths would pay seconds of
        # mini-compiles on every new batch outcome.
        #
        # The tunnel charges per-ROUND-TRIP latency (~75-150ms at 50k pods
        # for <1MB of payload), so the steady-state path fetches the result
        # scalars AND the data slices in ONE device_get, slicing
        # SPECULATIVELY with the previous solve's bucket sizes; only when a
        # solve's actual sizes exceed the speculation (rare — buckets are
        # pow2 round-ups) does it pay the old second round trip.
        pods_idx = snap.resource_names.index("pods")
        pods_cap_max = max(
            float(snap.type_alloc[:, pods_idx].max()) if len(snap.type_alloc) else 0.0,
            float(snap.exist_cap[:, pods_idx].max())
            if snap.exist_cap is not None and snap.exist_cap.size
            else 0.0,
        )
        bulk_dtype = jnp.int16 if pods_cap_max < 32767 else jnp.int32

        # bulk_take fetches SPARSE: the [LB, BR] plane is ~99.9% zeros
        # (measured 0.12% nonzero at the headline config = 2.1 MB dense),
        # so the device compacts it to fixed-size (index, value) arrays
        # with jnp.nonzero(size=...) and the host scatters it back — ~10x
        # less payload on a link that runs tens of MB/s. The nonzero count
        # rides the scalar fetch so a compaction overflow is detected and
        # repaired by the same second-round-trip path as a bucket miss.
        BR = log["bulk_take"].shape[1]
        bulk_nnz = (
            (log["bulk_take"] != 0).sum().astype(jnp.int32)
            if BR
            else jnp.int32(0)
        )

        def _sliced(ptr_b, bulk_b, nopen_b, nnz_b):
            # bulk values ride as int16 when every pod capacity fits (counts
            # are bounded by a slot's 'pods' allocatable). Lazy planes
            # (tmask/allow/out/defined — read by SolvedMachine
            # .requirements()/instance_type_options() AFTER Solve returns)
            # pack+slice ON DEVICE (async dispatch) so only ~3MB of packed
            # bits stay pinned, and defer to a one-shot batched fetch on
            # first access.
            if BR and nnz_b:
                flat = log["bulk_take"][:bulk_b].reshape(-1)
                idx = jnp.nonzero(flat, size=nnz_b, fill_value=-1)[0].astype(
                    jnp.int32
                )
                vals = jnp.take(flat, jnp.clip(idx, 0), mode="clip").astype(
                    bulk_dtype
                )
                bulk_sparse = (idx, jnp.where(idx >= 0, vals, 0))
            else:
                bulk_sparse = (
                    jnp.zeros(0, jnp.int32),
                    jnp.zeros(0, bulk_dtype),
                )
            eager = (
                {k: log[k][:ptr_b] for k in ("item", "slot", "ns", "k", "k_last")},
                bulk_sparse,
                {f: getattr(state, f)[:nopen_b] for f in ("tmpl", "used", "pods")},
            )
            lazy = {
                f: jnp.packbits(getattr(state, f)[:nopen_b], axis=-1)
                for f in _SlotState._LAZY
            }
            return eager, lazy

        from karpenter_core_tpu.solver.encode import bucket_pow2

        def _buckets(ptr_i, nopen, bulk_n, nnz):
            flat_cap = log["bulk_take"].shape[0] * BR
            return (
                min(bucket_pow2(max(ptr_i, 1), 1024), log["item"].shape[0]),
                min(bucket_pow2(max(bulk_n, 1), 1024), log["bulk_take"].shape[0]),
                min(bucket_pow2(max(nopen, 1), 1024), state.tmpl.shape[0]),
                min(bucket_pow2(max(nnz, 1), 1024), max(flat_cap, 1)),
            )

        def _densify(bulk_b, idx, vals):
            dense = np.zeros((bulk_b, BR), dtype=vals.dtype if BR else np.int16)
            if BR and len(idx):
                ok = idx >= 0
                dense.reshape(-1)[idx[ok]] = vals[ok]
            return dense

        lazy_widths = {f: getattr(state, f).shape[1] for f in _SlotState._LAZY}
        with self._cache_lock:
            spec_bk = self._fetch_buckets.get(key)
        fused = spec_bk is not None
        if fused:
            sliced, lazy_packed = _sliced(*spec_bk)
            (ptr_i, nopen, bulk_n, nnz), (log_h, bulk_sp, state_d) = jax.device_get(
                ((ptr, state.nopen, log["bulk_n"], bulk_nnz), sliced)
            )
        else:
            ptr_i, nopen, bulk_n, nnz = jax.device_get(
                (ptr, state.nopen, log["bulk_n"], bulk_nnz)
            )
        # dispatch -> first readback ≈ device execution time for this solve
        # (observability; on the fused path this includes the eager-slice
        # transfer, which the single-RT design makes inseparable)
        self.last_device_ms = (_time.perf_counter() - t_dispatch) * 1e3
        _mark("device", compile_cache="hit" if cache_hit else "miss")
        proghealth.record_dispatch("solve", key, self.last_device_ms)
        if not cache_hit:
            # a miss's first dispatch pays jit trace + XLA compile (or the
            # persistent disk-cache load): attribute it to the compile
            # histogram so restart stalls are visible in /metrics
            record_compile_seconds(phases["device"] / 1e3)
            proghealth.record_compile(
                "solve", key, phases["device"] / 1e3, compiled=fn.aot
            )
        ptr_i, nopen, bulk_n, nnz = int(ptr_i), int(nopen), int(bulk_n), int(nnz)
        need_bk = _buckets(ptr_i, nopen, bulk_n, nnz)
        # keep the speculation MONOTONE (max with the previous buckets):
        # storing the exact need would ping-pong on workloads oscillating
        # across a pow2 boundary — every step-up solve would pay the wasted
        # fused transfer plus the old second round trip. Over-fetch is
        # bounded by one bucket step per axis.
        with self._cache_lock:
            self._fetch_buckets[key] = (
                tuple(max(n, s) for n, s in zip(need_bk, spec_bk))
                if spec_bk is not None
                else need_bk
            )
        if not fused or any(n > s for n, s in zip(need_bk, spec_bk)):
            # speculation miss (or first solve at this geometry): fetch the
            # correctly-sized slices in a second round trip
            sliced, lazy_packed = _sliced(*need_bk)
            log_h, bulk_sp, state_d = jax.device_get(sliced)
            spec_bk = need_bk
        log_h["bulk_take"] = _densify(spec_bk[1], *bulk_sp)
        log_h["bulk_n"] = bulk_n
        state_h = _SlotState(state_d, lazy_packed, lazy_widths)
        _mark("fetch")
        return log_h, ptr_i, state_h

class _MergedSlotState:
    """Host view of the merged per-slot state a segmented dispatch
    produces (TPUSolver._try_segmented): machine rows gathered from their
    owning lane's final state, renumbered into sequential open order.
    All fields are materialized numpy arrays — the segmented fetch already
    sliced them to the open-row buckets — so the lazy-plane machinery of
    _SlotState is unnecessary; release() is a no-op for decode symmetry."""

    def __init__(self, **fields):
        self.__dict__.update(fields)

    def release(self):
        pass


class _SlotState:
    """Host view of the final per-slot state. tmpl/used/pods are fetched
    eagerly (decode reads them for every machine); the launch-path planes
    (tmask, allow, out, defined) — read only by SolvedMachine.requirements()
    / instance_type_options(), i.e. after Solve() returns — defer to ONE
    batched device_get on first access. What stays pinned on device is only
    the bit-packed [:nopen_b] slices (~a few MB), not the full state pytree;
    the pack+slice ops are dispatched (async) before construction.

    Thread-safe: machine launches fan out over a thread pool
    (provisioner.py) and every machine's thunk shares this object."""

    _LAZY = ("tmask", "allow", "out", "defined")

    def __init__(self, eager: dict, packed_dev: dict, widths: dict):
        import threading

        self.__dict__.update(eager)
        self.__dict__["_packed_dev"] = packed_dev
        self.__dict__["_widths"] = widths
        self.__dict__["_lock"] = threading.Lock()

    def __getattr__(self, name):  # only called when not in __dict__
        if name in type(self)._LAZY:
            self._fetch_lazy()
            return self.__dict__[name]
        raise AttributeError(name)

    def _fetch_lazy(self):
        import jax

        with self.__dict__["_lock"]:
            if self._LAZY[0] in self.__dict__:  # another thread won the race
                return
            dev = self.__dict__.get("_packed_dev")
            if dev is None:
                raise RuntimeError(
                    "slot planes were released before first access"
                )
            packed = jax.device_get(dev)  # may raise transiently: retryable
            widths = self.__dict__["_widths"]
            for f in self._LAZY:
                self.__dict__[f] = (
                    np.unpackbits(packed[f], axis=-1)[:, : widths[f]].astype(bool)
                )
            del self.__dict__["_packed_dev"]  # drop refs only on success

    def release(self):
        """Drop the device references without fetching (discarded result);
        decode calls this when no machine will ever read the planes."""
        with self.__dict__["_lock"]:
            self.__dict__.pop("_packed_dev", None)


def expand_log(snap: EncodedSnapshot, log, ptr: int) -> np.ndarray:
    """Replay the kernel's commit log into a per-pod slot assignment [P]
    (-1 = unscheduled). Entry e places ns slots starting at slot, k replicas
    per slot (k_last on the final slot), consuming item e.item's member pods
    in order. (The GSPMD mesh program produces the same single log, so one
    replay serves both the single-device and multi-chip paths.)"""
    P = len(snap.pods)
    assigned = np.full(P, -1, dtype=np.int64)
    members = snap.item_members or [[i] for i in range(P)]
    cursor = [0] * len(members)
    cap = [len(m) for m in members]
    items = np.asarray(log["item"])
    slots = np.asarray(log["slot"])
    nss = np.asarray(log["ns"])
    ks = np.asarray(log["k"])
    k_lasts = np.asarray(log["k_last"])
    bulk_take = np.asarray(log.get("bulk_take", np.zeros((0, 0), np.int32)))
    for e in range(int(ptr)):
        item = int(items[e])
        if item < 0:
            continue
        mem = members[item]
        ns, k, k_last = int(nss[e]), int(ks[e]), int(k_lasts[e])
        if ns == -1:
            # bulk existing-fill marker: k is the bulk_take row; fill slots
            # in index order (the commit's own order), vectorized — at 50k
            # pods the per-member python loop would dominate decode
            row = bulk_take[k]
            nz = np.nonzero(row)[0]
            if len(nz) == 0:
                continue
            takes = row[nz].astype(np.int64)
            lo = cursor[item]
            avail = max(min(cap[item], len(mem)) - lo, 0)
            csum = np.cumsum(takes)
            tot = int(min(csum[-1], avail))
            act = np.clip(tot - (csum - takes), 0, takes)
            mem_arr = np.asarray(mem[lo : lo + tot], dtype=np.int64)
            assigned[mem_arr] = np.repeat(nz, act)
            cursor[item] = lo + tot
            continue
        # run commit: k replicas on each of ns slots from `slot` (k_last on
        # the final one), vectorized the same way — the nested per-slot/
        # per-member python loops were the decode profile's top eager cost
        # once everything else went lazy (one iteration per PLACED POD)
        if ns <= 0:
            continue
        lo = cursor[item]
        avail = max(min(cap[item], len(mem)) - lo, 0)
        if ns == 1:  # dominant case: one slot, take straight from k_last
            tot = min(k_last, avail)
            mem_arr = np.asarray(mem[lo : lo + tot], dtype=np.int64)
            assigned[mem_arr] = slots[e]
        else:
            takes = np.full(ns, k, dtype=np.int64)
            takes[-1] = k_last
            csum = np.cumsum(takes)
            tot = int(min(csum[-1], avail))
            act = np.clip(tot - (csum - takes), 0, takes)
            mem_arr = np.asarray(mem[lo : lo + tot], dtype=np.int64)
            assigned[mem_arr] = slots[e] + np.repeat(
                np.arange(ns, dtype=np.int64), act
            )
        cursor[item] = lo + tot
    return assigned


def decode_solve(snap: EncodedSnapshot, placements, state,
                 want_failed: bool = True) -> SolveResult:
    """Placements + final slot state -> SolveResult (shared by the in-process
    TPUSolver, the gRPC RemoteSolver client, and the native packer).
    `placements` is either a (commit log, ptr) pair from the device kernel or
    a per-pod assigned array [P] (native path)."""
    if isinstance(placements, tuple):
        log, ptr = placements
        # named sub-span: the commit-log replay is the bind phase's largest
        # host cost at bench geometries (it visits every placed pod), so it
        # gets its own attribution under solver.phase.bind
        with TRACER.span("solver.phase.expand", entries=int(ptr)):
            assigned = expand_log(snap, log, ptr)
    else:
        assigned = placements
    E = len(snap.state_nodes)
    # group pods by slot with one stable argsort instead of 50k dict
    # setdefault/appends; stable keeps FFD order within each slot
    assigned = np.asarray(assigned)
    all_pods = snap.pods
    ok_idx = np.nonzero(assigned >= 0)[0]
    failed: List[Pod] = (
        [all_pods[i] for i in np.nonzero(assigned < 0)[0]]
        if want_failed and len(ok_idx) < len(all_pods)
        else []
    )
    order = np.argsort(assigned[ok_idx], kind="stable")
    sorted_idx = ok_idx[order]
    sorted_slots = assigned[sorted_idx]
    cuts = np.nonzero(np.diff(sorted_slots))[0] + 1
    starts = np.concatenate([[0], cuts]).astype(np.int64)
    ends = np.concatenate([cuts, [len(sorted_idx)]]).astype(np.int64)
    slot_groups = [
        (int(sorted_slots[s]), [all_pods[i] for i in sorted_idx[s:e]])
        for s, e in zip(starts, ends)
        if e > s
    ]

    machines: List[SolvedMachine] = []
    existing: List[Tuple[object, List[Pod]]] = []
    for slot, pods in slot_groups:
        if slot < E:
            existing.append((snap.state_nodes[slot], pods))
            continue
        tmpl_id = int(state.tmpl[slot])
        template = snap.templates[tmpl_id]
        requests = dict(zip(snap.resource_names, np.asarray(state.used[slot]).tolist()))
        requests = {k: v for k, v in requests.items() if v}

        def options_thunk(slot=slot):
            tmask = np.asarray(state.tmask[slot])
            # the mask rides the padded type axis; pad columns can never be
            # feasible (no template offers them) — guard anyway
            return [
                snap.instance_types[t]
                for t in np.nonzero(tmask)[0]
                if t < len(snap.instance_types)
            ]

        machines.append(
            SolvedMachine(
                provisioner_name=template.provisioner_name,
                template=template,
                pods=pods,
                instance_type_options=options_thunk,
                requests=requests,
                requirements=partial(slot_requirements, snap, state, slot),
            )
        )
    if not machines and hasattr(state, "release"):
        state.release()  # no thunk will ever read the lazy planes
    return SolveResult(
        new_machines=machines, existing_assignments=existing, failed_pods=failed
    )


def slot_requirements(snap: EncodedSnapshot, state, slot) -> Requirements:
    """Reconstruct the machine's merged requirements from the slot masks —
    includes topology domain narrowing the kernel committed. (Integer
    Gt/Lt bounds on complement sets are already baked into the allow
    masks for dictionary values; the bound itself is not recoverable.)"""
    from karpenter_core_tpu.scheduling.requirement import Requirement

    dictionary = snap.dictionary
    allow = np.asarray(state.allow[slot])
    out = np.asarray(state.out[slot])
    defined = np.asarray(state.defined[slot])
    requirements = Requirements()
    for k, key in enumerate(dictionary.keys):
        if not defined[k]:
            continue
        lo, hi = dictionary.segment(key)
        vals = dictionary.values_of(key)
        if out[k]:
            excluded = [v for v, a in zip(vals, allow[lo:hi]) if not a]
            requirements.add(Requirement(key, "NotIn", excluded))
        else:
            allowed = [v for v, a in zip(vals, allow[lo:hi]) if a]
            requirements.add(Requirement(key, "In", allowed))
    return requirements


class GreedySolver:
    """Host fallback implementing the same Solver interface via the Python
    Scheduler (the reference-semantics path)."""

    def solve(
        self,
        pods: List[Pod],
        provisioners: List[Provisioner],
        instance_types: Dict[str, List[InstanceType]],
        daemonset_pods: Optional[List[Pod]] = None,
        state_nodes: Optional[List] = None,
        kube_client=None,
        cluster=None,
    ) -> SolveResult:
        from karpenter_core_tpu.controllers.provisioning.scheduling.scheduler import (
            SchedulerOptions,
            build_scheduler,
        )

        pods = [copy.deepcopy(p) for p in pods]
        scheduler = build_scheduler(
            kube_client,
            cluster,
            provisioners,
            instance_types,
            pods,
            state_nodes=state_nodes,
            daemonset_pods=daemonset_pods,
            opts=SchedulerOptions(simulation_mode=True),
        )
        res = scheduler.solve(pods)
        machines = [
            SolvedMachine(
                provisioner_name=m.provisioner_name,
                template=m.template,
                pods=m.pods,
                instance_type_options=m.instance_type_options,
                requests=m.requests,
                requirements=m.requirements,
            )
            for m in res.new_machines
            if m.pods
        ]
        existing = [(n.state_node, n.pods) for n in res.existing_nodes if n.pods]
        return SolveResult(
            new_machines=machines, existing_assignments=existing,
            failed_pods=res.failed_pods,
            # the scheduler's exact per-pod causes (topology, hostports,
            # limits included) ride along for the FailedScheduling events
            errors=dict(res.errors),
        )


# -- staged-program introspection (analysis/irlint) -------------------------


@dataclass(frozen=True)
class FamilyProgram:
    """One lowerable program from the compiled-program family, staged
    WITHOUT minting a live cache entry or a proghealth record: the jit
    object plus the exact abstract example args the live/prewarm paths
    would lower it with. `fn.lower(*example_args)` yields the jaxpr /
    StableHLO the irlint contracts walk; `.compile()` on that yields the
    post-SPMD HLO the collective budgets count."""

    name: str            # unique within one staging, e.g. "refresh[8x8]"
    family: str          # solve | prescreen | refresh | replan | segment
    fn: object           # the un-dispatched jax.jit object
    example_args: tuple  # ShapeDtypeStructs (bundle rides as concrete)
    donate_argnums: tuple = ()


def stage_family_programs(staged, solver, screen_mode, topo_meta=None,
                          families=None, segment_shape=(8, 16)):
    """Every program family the solver can mint for one staged call, as
    pure jit objects + lowering args — the irlint staging seam. Mirrors
    the live builders exactly (_build_entry / _build_refresh /
    _build_replan / _build_partition / _build_segment) but touches no
    LRU cache, no per-key lock, and no proghealth ledger: staging here
    is free of side effects on a live solver.

    `families` filters by family name ({"solve", "prescreen", "refresh",
    "replan", "segment"}; "segment" covers both the partition and lane
    programs). Prescreen-only satellites (prescreen, refresh, segment)
    are skipped under tiered mode, matching the live dispatch paths.
    `segment_shape` is the (lane bucket, segment bucket) the lane
    program stages at."""
    import jax

    from karpenter_core_tpu.solver.encode import REPLAN_K_BUCKETS

    want = None if families is None else frozenset(families)

    def _want(family):
        return want is None or family in want

    records = []
    fn, pre_fn = solver._build_entry(staged, screen_mode)
    bundle_sds = jax.ShapeDtypeStruct(staged.bundle.shape,
                                      staged.bundle.dtype)
    donated_sds = tuple(
        jax.ShapeDtypeStruct(s, d) for s, d in staged.donated_meta
    )
    n_donated = len(donated_sds)
    screen_sds = None
    if pre_fn is not None:
        screen_sds = jax.eval_shape(pre_fn.jit, bundle_sds)
        if _want("prescreen"):
            records.append(FamilyProgram(
                name="prescreen", family="prescreen", fn=pre_fn.jit,
                example_args=(bundle_sds,),
            ))
    if _want("solve"):
        if screen_sds is not None:
            solve_args = (bundle_sds, screen_sds, *donated_sds)
            donate = (
                tuple(range(2, 2 + n_donated)) if solver.donate else ()
            )
        else:
            solve_args = (bundle_sds, *donated_sds)
            donate = (
                tuple(range(1, 1 + n_donated)) if solver.donate else ()
            )
        records.append(FamilyProgram(
            name="solve", family="solve", fn=fn.jit,
            example_args=solve_args, donate_argnums=donate,
        ))
    if screen_sds is not None and _want("refresh"):
        # the (8, 8) budget the prewarm path AOT-compiles
        # (_prewarm_refresh): the steady-churn common case
        refresh_jit = solver._build_refresh(
            staged.geom, 8, 8, staged.rebuild, staged.donated_meta,
            spec_layout=staged.spec_layout,
        )
        idx = np.zeros(8, np.int32)
        records.append(FamilyProgram(
            name="refresh[8x8]", family="refresh", fn=refresh_jit,
            example_args=(bundle_sds, screen_sds, idx, 0, idx, 0),
            donate_argnums=(1,),
        ))
    if _want("replan"):
        # the smallest candidate-axis bucket, like _prewarm_replan; the
        # mesh path stages replan off its own single-device twin so a
        # spec_layout'd staged call skips it there, matching prewarm
        if staged.spec_layout is None:
            k = REPLAN_K_BUCKETS[0]
            P, E = staged.geom[0], staged.geom[3]
            replan_jit = solver._build_replan(
                staged, k, screen_mode, topo_meta
            )
            records.append(FamilyProgram(
                name="replan[k=%d]" % k, family="replan", fn=replan_jit,
                example_args=(
                    jax.ShapeDtypeStruct((k, P), np.int32),
                    jax.ShapeDtypeStruct((k, E), np.bool_),
                    jax.ShapeDtypeStruct((E,), np.bool_),
                    screen_sds, bundle_sds, *donated_sds,
                ),
            ))
    if screen_sds is not None and _want("segment"):
        E = staged.geom[3]
        part_jit = solver._build_partition(staged, screen_mode)
        records.append(FamilyProgram(
            name="segment-partition", family="segment", fn=part_jit,
            example_args=(bundle_sds, screen_sds),
        ))
        s_pad, m_pad = segment_shape
        seg_jit = solver._build_segment(
            staged, s_pad, m_pad, screen_mode, frozen=False
        )
        records.append(FamilyProgram(
            name="segment-lane[%dx%d]" % (s_pad, m_pad), family="segment",
            fn=seg_jit,
            example_args=(
                jax.ShapeDtypeStruct((s_pad, m_pad), np.int32),
                jax.ShapeDtypeStruct((s_pad, E), np.bool_),
                screen_sds, bundle_sds, *donated_sds,
            ),
        ))
    return records
