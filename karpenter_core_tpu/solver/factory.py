"""Solver factory — the ONE place the production stack decides which solve
path serves Solve().

The reference has a single in-process entry (`Solve` at
provisioner.go:297-301); this framework has three interchangeable backends
(host FFD, single-chip TPUSolver, multi-chip ShardedSolver) plus an
out-of-process gRPC boundary. Every production entrypoint — the operator
(`operator/__main__.py`), the solver service container
(`solver/service.py`), and the bench — builds its primary through
build_solver() so a v5e-4 pod automatically serves the sharded path instead
of solving on one chip.

Selection (KARPENTER_SOLVER_MODE, default "auto"):
  auto     >1 visible device -> ShardedSolver over a dp×tp Mesh;
           otherwise TPUSolver.
  single   TPUSolver regardless of device count.
  sharded  ShardedSolver; raises if only one device is visible.

Mesh shape: tp = KARPENTER_MESH_TP when set; else 2 when the device count
is a multiple of 2 and >= 4 (the dryrun-validated split — feasibility's
type-axis matmuls gather over 'tp' on ICI), else 1. dp takes the rest.

Multi-host: set KARPENTER_DIST_COORDINATOR (host:port of process 0) plus
KARPENTER_DIST_NUM_PROCESSES / KARPENTER_DIST_PROCESS_ID and the factory
calls jax.distributed.initialize before device detection — jax.devices()
then spans every host's chips and the Mesh covers the full slice, with
XLA routing the dp/tp collectives over ICI within a host and DCN across
hosts (the reference's NCCL/MPI multi-node analog). On TPU pods the
three variables can be omitted entirely (jax autodetects from the TPU
environment when KARPENTER_DIST_COORDINATOR=auto).
"""
from __future__ import annotations

from karpenter_core_tpu.obs import envflags
from typing import Optional

_dist_initialized = False


def ensure_distributed() -> bool:
    """Initialize jax.distributed from KARPENTER_DIST_* when configured.
    Idempotent; returns True when multi-host mode is active. Must run
    before the first jax.devices() call in the process."""
    global _dist_initialized
    coordinator = envflags.raw("KARPENTER_DIST_COORDINATOR")
    if not coordinator or _dist_initialized:
        return _dist_initialized
    import jax

    if coordinator == "auto":
        jax.distributed.initialize()  # TPU-pod autodetection
    else:
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=int(envflags.require("KARPENTER_DIST_NUM_PROCESSES")),
            process_id=int(envflags.require("KARPENTER_DIST_PROCESS_ID")),
        )
    _dist_initialized = True
    return True


def detect_mesh(devices=None, tp: Optional[int] = None):
    """Build the dp×tp Mesh over the visible devices; None when the process
    sees a single device (single-chip path)."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    if devices is None:
        ensure_distributed()  # multi-host: devices() spans the whole slice
        devices = jax.devices()
    n = len(devices)
    if n < 2:
        return None
    if tp is None:
        tp_env = envflags.raw("KARPENTER_MESH_TP")
        tp = int(tp_env) if tp_env else (2 if n % 2 == 0 and n >= 4 else 1)
    if tp < 1 or n % tp != 0:
        raise ValueError(f"tp={tp} does not divide device count {n}")
    return Mesh(np.array(devices).reshape(n // tp, tp), ("dp", "tp"))


def describe(solver) -> str:
    """One-line boot log / bench-artifact description of the chosen path."""
    name = type(solver).__name__
    mesh = getattr(solver, "mesh", None)
    if mesh is not None:
        return f"{name}(dp={mesh.shape['dp']}, tp={mesh.shape['tp']})"
    return name


def build_solver(max_nodes: int = 1024, mode: Optional[str] = None,
                 backend: Optional[str] = None,
                 screen_mode: Optional[str] = None):
    """Construct the primary in-process solver for this process's devices.

    max_nodes is the GLOBAL new-machine slot budget on both paths: the
    multi-chip ShardedSolver runs the same (byte-identical) solve as the
    single-device program, GSPMD-sharded over the mesh, so there is no
    per-shard budget split anymore (parallel/sharded.py).

    screen_mode pins the pack kernel's slot-screen strategy ('prescreen' =
    batched class×slot feasibility precompute + in-scan incremental
    refresh, 'tiered' = the per-step full screen); None defers to
    KCT_PACK_SCREEN via ops.compat.resolve_screen_mode (envflags-routed),
    which defaults to 'prescreen'."""
    mode = (mode or envflags.raw("KARPENTER_SOLVER_MODE", "auto")).lower()
    if mode not in ("auto", "single", "sharded"):
        raise ValueError(f"unknown KARPENTER_SOLVER_MODE {mode!r}")
    mesh = None
    if mode != "single":
        try:
            mesh = detect_mesh()
        except Exception:
            if mode == "sharded":
                raise
            mesh = None  # auto: a wedged backend degrades to the single path
    if mesh is None:
        if mode == "sharded":
            raise RuntimeError(
                "KARPENTER_SOLVER_MODE=sharded but only one device is visible"
            )
        from karpenter_core_tpu.solver.tpu_solver import TPUSolver

        return TPUSolver(max_nodes=max_nodes, backend=backend,
                         screen_mode=screen_mode)
    from karpenter_core_tpu.parallel.sharded import ShardedSolver

    return ShardedSolver(mesh, max_nodes=max_nodes, backend=backend,
                         screen_mode=screen_mode)


def host_mode_enabled(default: bool = False) -> bool:
    """KARPENTER_SOLVER_HOST: run the device dispatch in the supervised
    sidecar process (solver/host.py) instead of in-process. Default OFF
    here (unit tests, embedders, the host child itself); the operator
    entrypoint passes default=True — host mode is the production posture,
    ISSUE 12."""
    return envflags.get_bool("KARPENTER_SOLVER_HOST", default)


def build_primary(max_nodes: int = 1024, host_default: bool = False,
                  **host_kwargs):
    """The production primary: the hard-killable HostSolver when
    KARPENTER_SOLVER_HOST is on (a wedge means kill-and-respawn, not
    abandon-and-hope), the in-process build_solver() path otherwise."""
    if host_mode_enabled(host_default):
        from karpenter_core_tpu.solver.host import HostSolver

        return HostSolver(max_nodes=max_nodes, **host_kwargs)
    return build_solver(max_nodes=max_nodes)
