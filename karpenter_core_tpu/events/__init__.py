"""Dedup + rate-limited event publisher and typed event constructors.

Mirrors reference pkg/events: Recorder.Publish with a dedupe cache and a
per-event rate limiter (recorder.go), plus the typed constructors in
events.go (NominatePod, PodFailedToSchedule, EvictPod, ...).

Events are the user-facing explanation channel. They land in an in-memory
ring (inspectable in tests / exported by the operator runtime) AND — when
the recorder carries a kube client — as core/v1 Event objects in the
cluster, so `kubectl describe pod` shows scheduling decisions the way the
reference's client-go record.EventRecorder does (recorder.go:50-56).
"""
from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Deque, Dict, List, Optional


@dataclass(frozen=True)
class Event:
    involved_kind: str
    involved_name: str
    type: str  # Normal | Warning
    reason: str
    message: str
    dedupe_values: tuple = ()
    timestamp: float = 0.0
    # per-event override of the recorder's 2-minute window (events.go
    # Event.DedupeTimeout); None uses Recorder.DEDUPE_TTL
    dedupe_timeout: Optional[float] = None
    # (qps, burst) token bucket, shared per (kind, reason) across events —
    # the analog of events.go Event.RateLimiter: only events that carry a
    # limiter are rate-limited (recorder.go:75)
    rate_limit: Optional[tuple] = None

    def dedupe_key(self) -> tuple:
        return (
            self.involved_kind,
            self.involved_name,
            self.type,
            self.reason,
            self.dedupe_values or (self.message,),
        )


class Recorder:
    """recorder.go: 2-minute dedupe window per full event key, plus opt-in
    token-bucket rate limiting for events that carry one (recorder.go:75 —
    in the reference only pod nomination does, events.go:24-35)."""

    DEDUPE_TTL = 120.0  # defaultDedupeTimeout (recorder.go)
    # PodNominationRateLimiter (events.go:25) — shared across all nomination
    # events so the limit is cluster-wide, like the reference's pointer
    POD_NOMINATION_RATE_LIMIT = (5.0, 10)

    def __init__(self, clock=time.time, capacity: int = 4096, kube_client=None):
        self.clock = clock
        self.kube_client = kube_client  # optional cluster sink
        self._mu = threading.Lock()
        self._seen: Dict[tuple, float] = {}
        self._tokens: Dict[tuple, List[float]] = {}  # (kind, reason) -> [tokens, last]
        self._last_purge = 0.0
        self._posted = 0
        # cluster posts ride a bounded queue drained by one daemon worker
        # (client-go's recorder is buffered the same way): a slow or down
        # apiserver must never block the reconcile path that publishes
        self._post_q = None
        self._post_idle = threading.Event()
        self._post_idle.set()
        self.events: Deque[Event] = deque(maxlen=capacity)

    def publish(self, event: Event) -> bool:
        now = self.clock()
        key = event.dedupe_key()
        ttl = self.DEDUPE_TTL if event.dedupe_timeout is None else event.dedupe_timeout
        with self._mu:
            # periodic purge so the dedupe cache stays bounded; entries carry
            # their own expiry so a long per-event dedupe_timeout survives
            # the sweep (the reference's expiring cache is per-entry too)
            if now - self._last_purge > self.DEDUPE_TTL:
                self._seen = {k: exp for k, exp in self._seen.items() if now < exp}
                self._last_purge = now
            expiry = self._seen.get(key)
            if expiry is not None and now < expiry:
                return False
            self._seen[key] = now + ttl
            if event.rate_limit is not None:
                qps, burst = event.rate_limit
                type_key = (event.involved_kind, event.reason)
                tokens, last_t = self._tokens.get(type_key, [float(burst), now])
                tokens = min(float(burst), tokens + (now - last_t) * qps)
                if tokens < 1.0:
                    self._tokens[type_key] = [tokens, now]
                    return False
                self._tokens[type_key] = [tokens - 1.0, now]
            self.events.append(dataclasses.replace(event, timestamp=now))
            self._posted += 1
            seq = self._posted
        self._post_to_cluster(event, now, seq)
        return True

    def _post_to_cluster(self, event: Event, now: float, seq: int) -> None:
        """Enqueue the core/v1 Event object for the poster worker
        (recorder.go:50-56 — client-go's recorder posts through the events
        API, buffered). Dedupe/rate-limit already passed, so each surviving
        publish is one Event with count=1; name uniqueness follows the
        client-go `<name>.<hex>` convention. Posting is best-effort: a full
        queue drops the cluster copy (the in-memory ring keeps it) and an
        apiserver error never breaks the control loop the event narrates."""
        if self.kube_client is None:
            return
        try:
            from karpenter_core_tpu.kube.objects import Event as KubeEvent

            ns, _, name = event.involved_name.rpartition("/")
            obj = KubeEvent()
            obj.metadata.namespace = ns or "default"
            obj.metadata.name = f"{name}.{format(int(now * 1e6) + seq, 'x')}"
            obj.involved_object.kind = event.involved_kind
            obj.involved_object.namespace = ns
            obj.involved_object.name = name
            obj.reason = event.reason
            obj.message = event.message
            obj.type = event.type
            obj.first_timestamp = obj.last_timestamp = now
            self._poster().put_nowait(obj)
            self._post_idle.clear()
        except Exception:  # noqa: BLE001 — cluster sink is best-effort
            pass

    def _poster(self):
        import queue as _queue

        with self._mu:
            if self._post_q is None:
                # the worker takes the queue as an ARGUMENT: re-reading
                # self._post_q from the loop would be a lock-free read
                # racing this lazy-init write (racewatch, ISSUE 13)
                self._post_q = q = _queue.Queue(maxsize=1024)
                threading.Thread(
                    target=self._post_loop, args=(q,),
                    daemon=True, name="event-poster",
                ).start()
            return self._post_q

    def _post_loop(self, post_q) -> None:
        import queue as _queue

        posted = 0
        while True:
            try:
                obj = post_q.get(timeout=0.2)
            except _queue.Empty:
                self._post_idle.set()
                continue
            try:
                self.kube_client.create(obj)
            except Exception:  # noqa: BLE001 — best-effort
                pass
            posted += 1
            if posted % 256 == 0:
                self._prune_cluster_events()
            if post_q.empty():
                self._post_idle.set()

    def _prune_cluster_events(self) -> None:
        """The in-memory client has no apiserver event-TTL GC: bound the
        stored Events to the ring capacity so a long-lived single-process
        control plane doesn't grow without limit. A real apiserver TTLs
        events itself, so this only runs for the in-memory client."""
        from karpenter_core_tpu.kube.client import InMemoryKubeClient

        if not isinstance(self.kube_client, InMemoryKubeClient):
            return
        try:
            events = self.kube_client.list("Event")
            cap = self.events.maxlen or 4096
            if len(events) > cap:
                events.sort(key=lambda e: e.metadata.creation_timestamp or 0.0)
                for e in events[: len(events) - cap]:
                    self.kube_client.delete(
                        "Event", e.metadata.namespace, e.metadata.name
                    )
        except Exception:  # noqa: BLE001 — pruning is best-effort
            pass

    def flush(self, timeout: float = 5.0) -> bool:
        """Wait for queued cluster posts to drain (tests / shutdown)."""
        with self._mu:  # _post_q lazy-inits under _mu: read it there too
            started = self._post_q is not None
        if not started:
            return True
        return self._post_idle.wait(timeout)

    def export(self) -> List[dict]:
        """The ring as JSON-able dicts for the operator's /debug/events —
        dedupe/rate-limit metadata included, so an exported trail shows WHY
        an expected event is absent (deduped vs rate-limited vs never
        published)."""
        with self._mu:
            events = list(self.events)
        return [
            {
                "kind": e.involved_kind,
                "name": e.involved_name,
                "type": e.type,
                "reason": e.reason,
                "message": e.message,
                "timestamp": e.timestamp,
                "dedupe_values": list(e.dedupe_values),
                "dedupe_timeout": (
                    self.DEDUPE_TTL if e.dedupe_timeout is None
                    else e.dedupe_timeout
                ),
                "rate_limit": (
                    list(e.rate_limit) if e.rate_limit is not None else None
                ),
            }
            for e in events
        ]

    def for_object(self, kind: str, name: str) -> List[Event]:
        with self._mu:
            return [e for e in self.events if e.involved_kind == kind and e.involved_name == name]

    # -- typed constructors (events.go) ------------------------------------

    def nominate_pod(self, pod, node_name: str) -> None:
        self.publish(
            Event(
                "Pod",
                f"{pod.metadata.namespace}/{pod.metadata.name}",
                "Normal",
                "Nominated",
                f"Pod should schedule on {node_name}",
                rate_limit=self.POD_NOMINATION_RATE_LIMIT,
            )
        )

    def pod_failed_to_schedule(self, pod, err: str) -> None:
        self.publish(
            Event(
                "Pod",
                f"{pod.metadata.namespace}/{pod.metadata.name}",
                "Warning",
                "FailedScheduling",
                f"Failed to schedule pod, {err}",
            )
        )

    def evict_pod(self, pod) -> None:
        self.publish(
            Event(
                "Pod",
                f"{pod.metadata.namespace}/{pod.metadata.name}",
                "Normal",
                "Evicted",
                "Evicted pod",
            )
        )

    def node_failed_to_drain(self, node, err: str) -> None:
        self.publish(
            Event(
                "Node", node.metadata.name, "Warning", "FailedDraining", f"Failed to drain node, {err}"
            )
        )

    def node_inflight_check(self, node, message: str) -> None:
        self.publish(
            Event("Node", node.metadata.name, "Warning", "FailedInflightCheck", message)
        )

    def deprovisioning_blocked(self, kind: str, name: str, reason: str) -> None:
        self.publish(Event(kind, name, "Normal", "Unconsolidatable", reason))

    def deprovisioning_launching(self, machine_name: str, reason: str) -> None:
        self.publish(
            Event(
                "Machine",
                machine_name,
                "Normal",
                "DeprovisioningLaunching",
                f"Launching for {reason}",
            )
        )

    def deprovisioning_terminating(self, node_name: str, reason: str) -> None:
        self.publish(
            Event(
                "Node",
                node_name,
                "Normal",
                "DeprovisioningTerminating",
                f"Terminating for {reason}",
            )
        )
