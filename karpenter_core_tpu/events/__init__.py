"""Dedup + rate-limited event publisher and typed event constructors.

Mirrors reference pkg/events: Recorder.Publish with a dedupe cache and a
per-event rate limiter (recorder.go), plus the typed constructors in
events.go (NominatePod, PodFailedToSchedule, EvictPod, ...).

Events are the user-facing explanation channel; here they land in an
in-memory ring (inspectable in tests / exported by the operator runtime)
instead of the kube events API.
"""
from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Deque, Dict, List, Optional


@dataclass(frozen=True)
class Event:
    involved_kind: str
    involved_name: str
    type: str  # Normal | Warning
    reason: str
    message: str
    dedupe_values: tuple = ()
    timestamp: float = 0.0
    # per-event override of the recorder's 2-minute window (events.go
    # Event.DedupeTimeout); None uses Recorder.DEDUPE_TTL
    dedupe_timeout: Optional[float] = None
    # (qps, burst) token bucket, shared per (kind, reason) across events —
    # the analog of events.go Event.RateLimiter: only events that carry a
    # limiter are rate-limited (recorder.go:75)
    rate_limit: Optional[tuple] = None

    def dedupe_key(self) -> tuple:
        return (
            self.involved_kind,
            self.involved_name,
            self.type,
            self.reason,
            self.dedupe_values or (self.message,),
        )


class Recorder:
    """recorder.go: 2-minute dedupe window per full event key, plus opt-in
    token-bucket rate limiting for events that carry one (recorder.go:75 —
    in the reference only pod nomination does, events.go:24-35)."""

    DEDUPE_TTL = 120.0  # defaultDedupeTimeout (recorder.go)
    # PodNominationRateLimiter (events.go:25) — shared across all nomination
    # events so the limit is cluster-wide, like the reference's pointer
    POD_NOMINATION_RATE_LIMIT = (5.0, 10)

    def __init__(self, clock=time.time, capacity: int = 4096):
        self.clock = clock
        self._mu = threading.Lock()
        self._seen: Dict[tuple, float] = {}
        self._tokens: Dict[tuple, List[float]] = {}  # (kind, reason) -> [tokens, last]
        self._last_purge = 0.0
        self.events: Deque[Event] = deque(maxlen=capacity)

    def publish(self, event: Event) -> bool:
        now = self.clock()
        key = event.dedupe_key()
        ttl = self.DEDUPE_TTL if event.dedupe_timeout is None else event.dedupe_timeout
        with self._mu:
            # periodic purge so the dedupe cache stays bounded; entries carry
            # their own expiry so a long per-event dedupe_timeout survives
            # the sweep (the reference's expiring cache is per-entry too)
            if now - self._last_purge > self.DEDUPE_TTL:
                self._seen = {k: exp for k, exp in self._seen.items() if now < exp}
                self._last_purge = now
            expiry = self._seen.get(key)
            if expiry is not None and now < expiry:
                return False
            self._seen[key] = now + ttl
            if event.rate_limit is not None:
                qps, burst = event.rate_limit
                type_key = (event.involved_kind, event.reason)
                tokens, last_t = self._tokens.get(type_key, [float(burst), now])
                tokens = min(float(burst), tokens + (now - last_t) * qps)
                if tokens < 1.0:
                    self._tokens[type_key] = [tokens, now]
                    return False
                self._tokens[type_key] = [tokens - 1.0, now]
            self.events.append(dataclasses.replace(event, timestamp=now))
            return True

    def for_object(self, kind: str, name: str) -> List[Event]:
        with self._mu:
            return [e for e in self.events if e.involved_kind == kind and e.involved_name == name]

    # -- typed constructors (events.go) ------------------------------------

    def nominate_pod(self, pod, node_name: str) -> None:
        self.publish(
            Event(
                "Pod",
                f"{pod.metadata.namespace}/{pod.metadata.name}",
                "Normal",
                "Nominated",
                f"Pod should schedule on {node_name}",
                rate_limit=self.POD_NOMINATION_RATE_LIMIT,
            )
        )

    def pod_failed_to_schedule(self, pod, err: str) -> None:
        self.publish(
            Event(
                "Pod",
                f"{pod.metadata.namespace}/{pod.metadata.name}",
                "Warning",
                "FailedScheduling",
                f"Failed to schedule pod, {err}",
            )
        )

    def evict_pod(self, pod) -> None:
        self.publish(
            Event(
                "Pod",
                f"{pod.metadata.namespace}/{pod.metadata.name}",
                "Normal",
                "Evicted",
                "Evicted pod",
            )
        )

    def node_failed_to_drain(self, node, err: str) -> None:
        self.publish(
            Event(
                "Node", node.metadata.name, "Warning", "FailedDraining", f"Failed to drain node, {err}"
            )
        )

    def node_inflight_check(self, node, message: str) -> None:
        self.publish(
            Event("Node", node.metadata.name, "Warning", "FailedInflightCheck", message)
        )

    def deprovisioning_blocked(self, kind: str, name: str, reason: str) -> None:
        self.publish(Event(kind, name, "Normal", "Unconsolidatable", reason))

    def deprovisioning_launching(self, machine_name: str, reason: str) -> None:
        self.publish(
            Event(
                "Machine",
                machine_name,
                "Normal",
                "DeprovisioningLaunching",
                f"Launching for {reason}",
            )
        )

    def deprovisioning_terminating(self, node_name: str, reason: str) -> None:
        self.publish(
            Event(
                "Node",
                node_name,
                "Normal",
                "DeprovisioningTerminating",
                f"Terminating for {reason}",
            )
        )
