"""Canonical PartitionSpecs for every solver tensor family (SpecLayout).

The multi-chip solve is ONE jit-compiled GSPMD program over a named
``('dp', 'tp')`` mesh (parallel/sharded.py). This module is the single
source of truth for how each tensor family lays out on that mesh — the
SNIPPETS.md [2] pattern: a frozen SpecLayout whose methods name the spec
per family, so every consumer (the in-process solver, the gRPC service,
the prewarm path, tests) shards the same tensor the same way instead of
scattering ad-hoc PartitionSpecs through the code.

Axis semantics:

  'dp'  shards the SLOT axis — existing-node rows and the machine-slot
        region of every per-slot plane, i.e. where replicas land. The
        [N, C] prescreen verdict tensor and the bf16 screen contractions
        that produce it compute dp-sharded on their slot/existing rows.
  'tp'  shards the INSTANCE-TYPE / verdict-COLUMN axis — the type planes
        of the feasibility contraction and the class-column axis of the
        verdict tensor. Instance-type planes are replicated over 'dp',
        sharded over 'tp'.

Item (pod-equivalence-class) planes REPLICATE: the class-dedup gather
indices (scls/scls_first) must stay valid on every device, and the pack
scan reads item rows at traced indices every step.

The sequential pack scan itself runs REPLICATED: its carry is a chain of
small per-step updates whose cross-device reassembly would cost one
collective per scan step — the precompute phases (feasibility, prescreen)
are where the FLOPs are, so they shard, and one XLA-inserted all_gather
riding ICI reassembles the verdict rows/feasibility planes before the
scan consumes them. Program INPUTS and OUTPUTS are replicated for the
same reason (and because pjit I/O sharding demands divisible axes, which
geometry buckets don't guarantee for every (axis, mesh) pair): all
sharding enters through jax.lax.with_sharding_constraint seams inside
the program, so the compiled program is a pure function of (geometry,
mesh shape) with no per-batch sharding decisions.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional


@dataclass(frozen=True)
class SpecLayout:
    """Canonical PartitionSpecs for solver tensors on a ('dp', 'tp') mesh.

    Frozen + hashable: ``layout.key`` rides the compiled-program cache key
    so a mesh-shape change (or the single-device path, layout=None) mints
    its own programs.
    """

    mesh: object  # jax.sharding.Mesh with axes ('dp', 'tp')
    dp_axis: str = "dp"
    tp_axis: str = "tp"

    # -- identity ----------------------------------------------------------

    @property
    def ndp(self) -> int:
        return self.mesh.shape[self.dp_axis]

    @property
    def ntp(self) -> int:
        return self.mesh.shape[self.tp_axis]

    @property
    def key(self):
        """Compiled-program cache-key component (mesh shape, not devices:
        the same executable serves any device assignment of that shape)."""
        return ("gspmd", self.ndp, self.ntp)

    def __hash__(self):
        return hash(self.key)

    def __eq__(self, other):
        return isinstance(other, SpecLayout) and self.key == other.key

    # -- per-family PartitionSpecs ----------------------------------------

    def _ns(self, *spec):
        from jax.sharding import NamedSharding, PartitionSpec as P

        return NamedSharding(self.mesh, P(*spec))

    def replicated(self):
        """Item planes, template planes, scan carry, commit log, scalars —
        everything the sequential scan reads at traced indices."""
        return self._ns()

    def item_plane(self):
        """[I, ...] pod-equivalence-class rows: replicated (the scls dedup
        indices and per-step gathers must resolve on every device)."""
        return self._ns()

    def type_plane(self, rank: int = 2):
        """[T, ...] instance-type rows: replicated over dp, sharded over
        tp — the feasibility contraction's column family."""
        return self._ns(self.tp_axis, *([None] * (rank - 1)))

    def type_cols(self, rank: int = 2):
        """[..., T] planes whose LAST axis is the type axis
        (tmpl_type_mask [J, T])."""
        return self._ns(*([None] * (rank - 1)), self.tp_axis)

    def slot_plane(self, rank: int = 2):
        """[E, ...] / [N, ...] existing-node and slot rows: sharded over
        dp — the verdict tensor's row family. Also the dp-row family for
        the item rows feeding the feasibility contraction (the item axis
        plays the row role there; the REPLICATED item planes the scan
        gathers from are item_plane())."""
        return self._ns(self.dp_axis, *([None] * (rank - 1)))

    def segment_axis(self, rank: int = 2):
        """[S, ...] segmented pack-scan lane planes (ISSUE 14): the LANE
        axis shards over dp — under segmented mode the pack scan stops
        being the replicated part of the mesh program; each dp shard runs
        its own lanes' scans. The replication FENCE is unchanged WITHIN a
        lane: every shared scan input (item planes, templates, the frozen
        verdict tensor) stays pinned replicated by run_impl's gather seam,
        so the per-lane program is byte-identical to the single-device
        lane (docs/sharding.md "segmented lanes"). Same dp-leading spec as
        slot_plane — delegated so the lane fence can never drift from the
        slot-row family it mirrors."""
        return self.slot_plane(rank)

    def verdict(self):
        """The [N, C] prescreen verdict tensor: slot rows over dp, class
        columns over tp — both contraction outputs tile with zero
        communication; the reassembling all_gather happens where the
        scan (replicated) consumes it."""
        return self._ns(self.dp_axis, self.tp_axis)

    def feasibility(self):
        """[J, I, T] static feasibility: item rows over dp, type columns
        over tp (templates replicated)."""
        return self._ns(None, self.dp_axis, self.tp_axis)

    # -- constraint helpers (trace-time, inside jit) ----------------------

    def constrain(self, x, sharding):
        import jax

        return jax.lax.with_sharding_constraint(x, sharding)

    def shard_reqset(self, reqset: dict, sharding) -> dict:
        """Apply one spec to each plane of a ReqSet-style dict."""
        return {k: self.constrain(v, sharding) for k, v in reqset.items()}

    def gather(self, x):
        """Reassemble to replicated — the explicit all_gather seam between
        a sharded precompute and the replicated scan."""
        return self.constrain(x, self.replicated())

    def cache_salt(self, x):
        """Make a mesh program's persistent-cache key PROCESS-UNIQUE on
        the CPU backend by or-ing a constant-False term derived from a
        per-process salt into a bool tensor (semantically a no-op; the
        optimizer folds it away AFTER the cache key is computed from the
        unoptimized module).

        Why: XLA:CPU deserializes multi-device executables
        NONDETERMINISTICALLY (jax 0.4.x) — a GSPMD solve program reloaded
        from the persistent cache flips placements per dispatch, while
        the same program freshly compiled is byte-stable (isolated by the
        ISSUE 8 parity suite; see docs/sharding.md). The config toggles
        can't gate reads mid-process (jax memoizes is_cache_used), so the
        key itself must never match across processes. Single-device
        programs and real-TPU mesh programs keep full cache reuse — the
        deserialization path there is the battle-tested one."""
        import jax
        import jax.numpy as jnp

        if jax.default_backend() != "cpu":
            return x
        return x | (jnp.int32(_process_salt()) < jnp.int32(0))

    # -- pre-sharded upload (host -> device, outside jit) ------------------

    def put_replicated(self, tree):
        """device_put a pytree fully replicated over the mesh — the upload
        form for the bundled in-process path (the bundle is opaque bytes;
        per-family sharding happens at the in-program seams)."""
        import jax

        sharding = self.replicated()
        return jax.device_put(
            tree, jax.tree_util.tree_map(lambda _: sharding, tree)
        )

    def arg_sharding(self, name: str, arr):
        """The canonical NamedSharding for one device_args tensor (by its
        RUN_ARG_NAMES entry), used by the unbundled gRPC-service path so
        the upload lands pre-sharded. Falls back to replicated whenever
        the sharded axis does not divide the mesh axis (pjit I/O requires
        divisibility; the in-program constraints still engage)."""
        family = RUN_ARG_FAMILIES.get(name, "replicated")
        shape = getattr(arr, "shape", ())
        if family == "type_rows" and shape and shape[0] % self.ntp == 0:
            return self.type_plane(rank=max(len(shape), 1))
        if family == "type_cols" and shape and shape[-1] % self.ntp == 0:
            return self.type_cols(rank=max(len(shape), 1))
        if family == "slot_rows" and shape and shape[0] % self.ndp == 0:
            return self.slot_plane(rank=max(len(shape), 1))
        return self.replicated()

    def put_args(self, names, args):
        """device_put a device_args-style tuple with each tensor's
        canonical sharding (dict-valued args shard per leaf)."""
        import jax

        def put_one(name, arg):
            if isinstance(arg, dict):
                return {
                    k: jax.device_put(v, self.arg_sharding(name, v))
                    for k, v in arg.items()
                }
            return jax.device_put(arg, self.arg_sharding(name, arg))

        return tuple(put_one(n, a) for n, a in zip(names, args))


# device_args tensor name -> sharding family (names match
# tpu_solver.RUN_ARG_NAMES; anything absent replicates). The reqset dicts
# under 'types' share the type-row family leaf-wise; 'exist*' planes are
# slot rows. pod/item planes, templates, topology state, and the donated
# scan-carry seeds replicate — the scan reads them at traced indices.
RUN_ARG_FAMILIES = {
    "types": "type_rows",
    "type_alloc": "type_rows",
    "type_capacity": "type_rows",
    "type_offering_ok": "type_rows",
    "tmpl_type_mask": "type_cols",
    "exist": "slot_rows",
    "exist_used": "slot_rows",
    "exist_cap": "slot_rows",
    "exist_ports": "slot_rows",
    "exist_vols": "slot_rows",
    "exist_vol_limits": "slot_rows",
}


_PROCESS_SALT = None


def _process_salt() -> int:
    """Per-process 31-bit salt for SpecLayout.cache_salt (stable within
    the process so in-process program reuse is unaffected)."""
    global _PROCESS_SALT
    if _PROCESS_SALT is None:
        import uuid

        _PROCESS_SALT = int(uuid.uuid4().int & 0x7FFFFFFF) or 1
    return _PROCESS_SALT


def layout_for(mesh) -> Optional[SpecLayout]:
    """SpecLayout for a mesh (None passes through: single-device path)."""
    return None if mesh is None else SpecLayout(mesh)
