"""Multi-chip solve: ONE jit-compiled GSPMD program on a ('dp','tp') mesh.

Architecture (the ISSUE 8 rebuild — see docs/sharding.md for the
per-tensor PartitionSpec table and collective inventory):

The previous multi-chip path split the batch's replica counts across dp
shards and ran an independent pack scan per device under shard_map, with
host-side plan/split/merge orchestration around it. MULTICHIP_r05 proved
it correct (0.0% quality delta at 50k pods) and slow (35.3s wall vs the
sub-second goal): every shard still pays the full sequential scan, the
per-shard slot budgets force encode at shard-local geometry, and the
host-side shard orchestration (plan_shards / shard_args / per-shard log
merge) sat on the critical path of every solve.

The rebuild inverts the design: the multi-chip solve IS the single-device
program — the PR 5 prescreen + pack scan, the PR 6 incremental refresh,
the PR 7 bucket-ladder/AOT-prewarm machinery, all of it — jit-compiled
once with canonical NamedSharding constraints (parallel/specs.SpecLayout)
at the precompute seams:

  * the [N, C] prescreen verdict tensor and its bf16 screen contractions
    compute as (dp x tp) tiles — slot rows over 'dp', class columns over
    'tp' — with zero communication (no contraction axis is ever split);
  * the static-feasibility planes compute item-rows-over-'dp' x
    type-columns-over-'tp', instance-type planes replicated over 'dp'
    and sharded over 'tp';
  * ONE XLA-inserted all_gather per precompute rides ICI to reassemble
    the tensors for the sequential pack scan, which runs replicated
    (its carry is a chain of small per-step updates; resharding it would
    cost a collective per scan step).

Because sharding only tiles output axes, the compiled math is identical
and placements are BYTE-IDENTICAL (flightrec-canonical) to the
single-device program — asserted by tests/test_sharded.py across the
screen-parity geometry families. That identity is what lets ShardedSolver
be a TPUSolver subclass: the compiled-program LRU, GeometryTier cache
keys, startup AOT prewarm, and the incremental-refresh residency all
apply to mesh programs unchanged (keys carry the mesh shape so the two
program families never collide).

Small batches skip the mesh entirely: below MIN_SPLIT_REPLICAS_PER_SHARD
replicas per dp row the collective/mesh overhead outweighs any precompute
parallelism, so _layout_for routes the solve through the plain
single-device program on device 0 (same cache, different key namespace).
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from karpenter_core_tpu.parallel.specs import SpecLayout, layout_for
from karpenter_core_tpu.solver.tpu_solver import TPUSolver

__all__ = [
    "ShardedSolver",
    "MIN_SPLIT_REPLICAS_PER_SHARD",
    "route_to_mesh",
    "SpecLayout",
    "layout_for",
]


# below this many replicas per dp mesh row the mesh program's collective /
# multi-device dispatch overhead costs more than the sharded precompute
# buys: route the WHOLE batch through the plain single-device program.
# Production small batches route to the host FFD before reaching here
# (ResilientSolver); this guards direct ShardedSolver use and the gRPC
# service, whose clients send whatever the batcher accumulated.
MIN_SPLIT_REPLICAS_PER_SHARD = 32


def route_to_mesh(total_replicas: int, ndp: int) -> bool:
    """Mesh-vs-single routing for a batch's total replica count: the mesh
    program engages once the batch clears the per-dp-row work floor, with
    an absolute cap so a huge mesh (dp=64) doesn't demand thousands of
    replicas before parallelizing."""
    return total_replicas >= min(ndp * MIN_SPLIT_REPLICAS_PER_SHARD, 256)


def snapshot_replicas(snap) -> int:
    """Total replica count of an encoded snapshot (the routing signal)."""
    if snap.item_counts is not None:
        return int(np.asarray(snap.item_counts).sum())
    return len(snap.pods)


class ShardedSolver(TPUSolver):
    """The multi-chip Solver: TPUSolver whose programs build against a
    ('dp','tp') mesh SpecLayout. Drop-in for TPUSolver wherever a Mesh is
    available (solver/factory.py builds one when the process sees >1
    device); encode()/solve(encoded=)/prewarm_snapshot and the whole
    relaxation/incremental machinery are inherited — the ONLY difference
    is which program family _layout_for selects, so a multi-chip
    deployment gets bucket-ladder cache keys, startup AOT prewarm, and
    delta-refresh residency for its mesh programs for free."""

    def __init__(self, mesh, max_nodes: int = 1024,
                 max_relax_rounds: Optional[int] = None,
                 donate: bool = True, backend: Optional[str] = None,
                 profile_phases: bool = False,
                 screen_mode: Optional[str] = None,
                 incremental: Optional[str] = None):
        from karpenter_core_tpu.solver.tpu_solver import DEFAULT_MAX_RELAX_ROUNDS

        super().__init__(
            max_nodes=max_nodes,
            max_relax_rounds=(
                DEFAULT_MAX_RELAX_ROUNDS
                if max_relax_rounds is None
                else max_relax_rounds
            ),
            donate=donate, backend=backend, profile_phases=profile_phases,
            screen_mode=screen_mode, incremental=incremental,
        )
        self.mesh = mesh
        self._mesh_layout = SpecLayout(mesh)
        # which program family served the last dispatch ("mesh"/"single"):
        # observability + the small-batch routing tests/bench column
        self.last_path = None

    def _layout_for(self, snap):
        """Mesh layout for batches worth parallelizing; None (the plain
        single-device program, same compiled-program LRU under its own
        key namespace) for small batches — they stop paying collective
        and multi-device dispatch overhead entirely."""
        if route_to_mesh(snapshot_replicas(snap), self._mesh_layout.ndp):
            self.last_path = "mesh"
            return self._mesh_layout
        self.last_path = "single"
        return None
