"""Multi-chip sharded solve over a jax.sharding.Mesh.

Scaling design (the "DP/TP" of this framework — SURVEY.md section 2.7):
  - 'dp'  : the REPLICA COUNT axis is sharded across devices — every
            device sees the same item (pod-equivalence-class) rows but
            packs its 1/ndp share of each class's replicas into its own
            node-slot budget (independent greedy sub-solves; machines are
            disjoint by construction, so the merge is a concat). Splitting
            counts instead of item rows keeps per-device work balanced even
            when one deployment dominates the batch. This is how 50k-pod
            batches ride ICI.
  - 'tp'  : the INSTANCE-TYPE axis of the feasibility matmuls is sharded;
            each device computes F over its type columns, then an
            all_gather over 'tp' reassembles the [I, T] row an item
            needs for packing. The gather rides ICI (XLA collective), not
            host memory.

Provisioner limits are coordinated pessimistically: the remaining-resource
budget is pre-split evenly across 'dp' shards (a conservative under-
approximation of the reference's global subtract_max accounting,
scheduler.go:276-293).
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np


def make_sharded_solve(snap, provisioners, mesh, max_nodes_per_shard: int = 256):
    """Build (fn, args) where fn is a jit-compiled shard_map program over
    `mesh` (axes 'dp' and 'tp') and args are the host arrays.

    Pod-axis arrays must divide by mesh.shape['dp']; type-axis arrays by
    mesh.shape['tp'] (the caller pads — see pad_snapshot_for_mesh).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from karpenter_core_tpu.ops.feasibility import feasibility_static, openable_mask
    from karpenter_core_tpu.ops.pack import PackState, make_pack_kernel
    from karpenter_core_tpu.solver.tpu_solver import device_args, solve_geometry

    geom = solve_geometry(snap, max_nodes_per_shard)
    (_, J, T, E, R, K, V, _, segments_t, zone_seg, ct_seg, _topo_sig,
     log_len) = geom
    assert E == 0, "sharded solve packs new machines only (existing nodes are host-side)"
    assert snap.topo_meta is None, (
        "sharded solve requires a topology-free batch: domain counts are "
        "global state; cross-shard topology lands with the repair phase"
    )
    segments = list(segments_t)
    ndp = mesh.shape["dp"]
    ntp = mesh.shape["tp"]
    N = max_nodes_per_shard
    pack = make_pack_kernel(segments, zone_seg, ct_seg)

    def body(pod_arrays, count_split, tmpl, tmpl_daemon, tmpl_type_mask_l,
             types_l, type_offering_ok_l, types_full, type_alloc,
             type_capacity, type_offering_ok, pod_tol_all, well_known,
             remaining0):
        # ---- type-sharded feasibility + all_gather over 'tp' -------------
        f_local = feasibility_static(
            {k: pod_arrays[k] for k in ("allow", "out", "defined", "escape")},
            tmpl,
            types_l,
            pod_arrays["tol_tmpl"],
            tmpl_type_mask_l,
            type_offering_ok_l,
            zone_seg,
            ct_seg,
            segments,
            well_known,
        )  # [J, P_local, T_local]
        f_static = jax.lax.all_gather(f_local, "tp", axis=3, tiled=False)
        # [J, P_local, ntp, T_local] -> [J, P_local, T]
        f_static = jnp.moveaxis(f_static, 3, 2).reshape(
            f_local.shape[0], f_local.shape[1], -1
        )

        openable = openable_mask(
            f_static, pod_arrays["requests"], tmpl_daemon, type_alloc
        )
        state = PackState(
            used=jnp.zeros((N, R), jnp.float32),
            open=jnp.zeros(N, bool),
            is_existing=jnp.zeros(N, bool),
            tmpl=jnp.zeros(N, jnp.int32),
            tol_idx=jnp.zeros(N, jnp.int32),
            pods=jnp.zeros(N, jnp.int32),
            allow=jnp.ones((N, V), bool),
            out=jnp.ones((N, K), bool),
            defined=jnp.zeros((N, K), bool),
            tmask=jnp.zeros((N, T), bool),
            cap=jnp.zeros((N, R), jnp.float32),
            nopen=jnp.int32(0),
            # pessimistic even split of provisioner limits across dp shards
            remaining=remaining0 / ndp,
            tcounts=jnp.zeros((0, V), jnp.float32),
            thost=jnp.zeros((0, N), jnp.float32),
            tdoms=jnp.zeros((0, V), bool),
        )
        pod_arrays = dict(pod_arrays)
        pod_arrays["tol"] = pod_tol_all
        # this shard's share of each class's replicas
        pod_arrays["count"] = count_split[0]
        tmpl_type_mask = jax.lax.all_gather(tmpl_type_mask_l, "tp", axis=2, tiled=False)
        tmpl_type_mask = jnp.moveaxis(tmpl_type_mask, 2, 1).reshape(J, -1)
        state, log, ptr = pack(
            state,
            pod_arrays,
            f_static,
            openable,
            {k: tmpl[k] for k in ("allow", "out", "defined")},
            tmpl_daemon,
            tmpl_type_mask,
            types_full,
            type_alloc,
            type_capacity,
            type_offering_ok,
            log_len=log_len,
        )
        # global stats via psum over dp: pods scheduled (an ICI collective)
        scheduled = jax.lax.psum(state.pods.sum(), "dp")
        # rank-0 per-shard values need a singleton axis to concatenate over dp
        state = state._replace(nopen=state.nopen[None])
        return log, ptr[None], state, scheduled

    # item rows replicate; only the per-shard replica counts shard over dp
    pod_spec = {
        "allow": P(None, None),
        "out": P(None, None),
        "defined": P(None, None),
        "escape": P(None, None),
        "custom_deny": P(None, None),
        "requests": P(None, None),
        "tol_tmpl": P(None, None),
        "valid": P(None),
    }
    reqset_rep = {k: P(None, None) for k in ("allow", "out", "defined", "escape")}
    reqset_tp = {k: P("tp", None) for k in ("allow", "out", "defined", "escape")}
    in_specs = (
        pod_spec,  # pod_arrays
        P("dp", None),  # count_split [ndp, I]
        reqset_rep,  # tmpl
        P(None, None),  # tmpl_daemon
        P(None, "tp"),  # tmpl_type_mask_l
        reqset_tp,  # types_l
        P("tp", None, None),  # type_offering_ok_l
        reqset_rep,  # types_full (replicated for packing)
        P(None, None),  # type_alloc
        P(None, None),  # type_capacity
        P(None, None, None),  # type_offering_ok
        P(None, None),  # pod_tol_all
        P(None),  # well_known
        P(None, None),  # remaining0
    )
    out_specs = (
        {k: P("dp") for k in ("item", "slot", "ns", "k", "k_last")},  # commit log
        P("dp"),  # log ptr (singleton axis per shard)
        PackState(
            used=P("dp", None),
            open=P("dp"),
            is_existing=P("dp"),
            tmpl=P("dp"),
            tol_idx=P("dp"),
            pods=P("dp"),
            allow=P("dp", None),
            out=P("dp", None),
            defined=P("dp", None),
            tmask=P("dp", None),
            cap=P("dp", None),
            nopen=P("dp"),
            remaining=P("dp", None),
            tcounts=P("dp", None),
            thost=P("dp", None),
            tdoms=P("dp", None),
        ),
        P(),  # scheduled count (replicated)
    )

    sharded = jax.shard_map(body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                            check_vma=False)
    fn = jax.jit(sharded)

    base_args = device_args(snap, provisioners)
    (pod_arrays, tmpl, tmpl_daemon, tmpl_type_mask, types, type_alloc,
     type_capacity, type_offering_ok, pod_tol_all, _exist, _eu, _ec,
     well_known, remaining0, _tc, _th, _td, _tt) = base_args
    # split each class's replica count evenly across the dp shards (the
    # item rows themselves replicate); remainders go to the low shards
    counts = pod_arrays.pop("count").astype(np.int64)
    I = counts.shape[0]
    count_split = np.tile(counts // ndp, (ndp, 1)).astype(np.int32)
    for d in range(ndp):
        count_split[d] += (counts % ndp > d)
    args = (
        pod_arrays,
        count_split,
        tmpl,
        tmpl_daemon,
        tmpl_type_mask,
        types,
        type_offering_ok,
        types,
        type_alloc,
        type_capacity,
        type_offering_ok,
        pod_tol_all,
        well_known,
        remaining0,
    )
    return fn, args


def pad_pods(pods: List, multiple: int) -> List:
    """Pad the pod list to a multiple with filler pods marked invalid at
    encode time (they request an impossible amount, so they never schedule).
    Sharding requires equal-size shards; the valid mask excludes fillers."""
    from karpenter_core_tpu.testing import make_pod

    short = (-len(pods)) % multiple
    return pods + [make_pod(requests={"cpu": "1e18"}) for _ in range(short)]
