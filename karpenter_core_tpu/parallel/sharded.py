"""Multi-chip sharded solve over a jax.sharding.Mesh.

Scaling design (the "DP/TP" of this framework — SURVEY.md section 2.7):
  - 'dp'  : the REPLICA COUNT axis is sharded across devices — every
            device sees the same item (pod-equivalence-class) rows but
            packs its share of each class's replicas into its own
            node-slot budget (independent greedy sub-solves; machines are
            disjoint by construction, so the merge is a concat). Splitting
            counts instead of item rows keeps per-device work balanced even
            when one deployment dominates the batch. This is how 50k-pod
            batches ride ICI.
  - 'tp'  : the INSTANCE-TYPE axis of the feasibility matmuls is sharded;
            each device computes F over its type columns, then an
            all_gather over 'tp' reassembles the [I, T] row an item
            needs for packing. The gather rides ICI (XLA collective), not
            host memory.

Topology (round 2): domain counts are global mutable state, so
topology-entangled work cannot split freely. Items are partitioned into
COMPONENTS by union-find over the topology groups they own or select into
(two groups sharing a pod must co-locate); each component is routed whole
to one 'dp' shard (LPT on replica counts), so every group's counts evolve
on exactly one device and the per-shard solve follows the reference
semantics (topologygroup.go:155-243) with no cross-shard races.
Topology-free items still split evenly. Every shard carries the full
[G, V] count state; only its own groups' rows ever change. SLOT-LOCAL
hostname groups are the exception and split freely: hostname spread
(round 4 of the previous session) and hostname anti-affinity (round 4 —
separation across disjoint shard slots can only over-satisfy the
constraint; see plan_shards).

Existing nodes (round 2): each existing node is OWNED by one shard
(round-robin); all shards carry the slots [0, E) at the same indices but
non-owned slots stay closed, so capacity can never be double-booked. A
topology component whose pods could have landed on another shard's
existing node opens a new machine instead — a valid (possibly costlier)
packing, never a constraint violation.

Provisioner limits are coordinated pessimistically: the remaining-resource
budget is pre-split across 'dp' shards proportional to each shard's replica
load (a conservative under-approximation of the reference's global
subtract_max accounting, scheduler.go:276-293).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np


def plan_shards(snap, ndp: int) -> Tuple[np.ndarray, np.ndarray]:
    """Partition the batch across dp shards.

    Returns (count_split [ndp, I], exist_owner [ndp, E] bool).

    Topology-entangled items (owning or selected into any group) are routed
    whole: union-find joins groups sharing an item, components go to shards
    by longest-processing-time on replica count, and every item of a
    component lands on its shard. Free items split evenly with remainders
    to the low shards.
    """
    counts = (
        snap.item_counts
        if snap.item_counts is not None
        else np.ones(len(snap.pods), dtype=np.int32)
    )
    # the exist axis is bucket-padded at encode; sentinel rows [E_real, E_pad)
    # stay unowned, i.e. closed on every shard
    E_pad = snap.exist_used.shape[0] if snap.exist_used is not None else 0
    E = len(snap.state_nodes)
    touch = None
    if snap.topo_meta is not None and len(snap.topo_meta.groups) > 0:
        rep = snap.item_rep
        touch = (snap.topo_arrays.owner | snap.topo_arrays.sel)[:, rep]  # [G, I]
    return plan_shards_arrays(counts, E, E_pad, ndp, touch, snap.topo_meta)


# below this many replicas per dp shard the split costs more packing
# quality than it buys in parallelism (per-shard leftovers + components
# that can't share nodes across shards dominate): route the WHOLE batch to
# shard 0 with single-device semantics. Production small batches route to
# the host FFD before reaching here (ResilientSolver); this guards direct
# ShardedSolver use.
MIN_SPLIT_REPLICAS_PER_SHARD = 32


def plan_shards_arrays(counts, E_real: int, E_pad: int, ndp: int,
                       touch=None, topo_meta=None,
                       ) -> Tuple[np.ndarray, np.ndarray]:
    """Array-level core of plan_shards: counts [I] replica counts per item,
    touch [G, I] bool (item owns/selects into group g) or None. Shared by
    the snapshot path (plan_shards) and the gRPC service, which rebuilds
    `touch` from the wire tensors (pod_arrays/topo_own|topo_sel)."""
    counts = np.asarray(counts).astype(np.int64)
    I = len(counts)
    exist_owner = np.zeros((ndp, E_pad), dtype=bool)

    total = int(counts.sum())
    # single-shard threshold: the per-dp work floor, with an absolute cap
    # so a huge mesh (dp=64) never serializes thousands of replicas onto
    # one chip. A single-shard batch that exhausts shard 0's slot budget
    # retries with a TRANSIENT doubling (ShardedSolver._solve_once keeps
    # growth non-sticky when the plan didn't split), so no permanent
    # geometry cliff hides here.
    threshold = min(ndp * MIN_SPLIT_REPLICAS_PER_SHARD, 256)
    if total < threshold:
        # too small to split: shard 0 owns every replica AND every existing
        # node, making the result exactly the single-device packing
        count_split = np.zeros((ndp, I), dtype=np.int32)
        count_split[0] = counts
        exist_owner[0, :E_real] = True
        return count_split, exist_owner

    for e in range(E_real):
        exist_owner[e % ndp, e] = True

    # even base split; remainders ROUND-ROBIN by item index. Sending every
    # remainder to the low shards (pre-round-5) piled ALL the replicas of a
    # batch of one-replica items onto shard 0 — a 100-pod no-topology batch
    # ran entirely serial (the water-fill rebalance below only runs when
    # topology groups exist).
    count_split = np.tile(counts // ndp, (ndp, 1)).astype(np.int32)
    rem = (counts % ndp).astype(np.int64)
    d_idx = np.arange(ndp, dtype=np.int64)[:, None]
    i_idx = np.arange(I, dtype=np.int64)[None, :]
    count_split += (((d_idx - i_idx) % ndp) < rem[None, :]).astype(np.int32)

    if touch is not None and topo_meta is not None and len(topo_meta.groups) > 0:
        from karpenter_core_tpu.ops import topology as topo_mod
        # hostname SPREAD groups split freely: their counts live in the
        # per-SLOT thost lane and slots are disjoint across dp shards (fresh
        # slots open on one shard; existing slots are owned), so every
        # domain's count evolves on exactly one device and the global
        # min-count/skew rule reduces to the local one (fresh empty slots
        # pin min=0 on every shard, as globally). Routing them whole was
        # round 3's dominant packing-quality loss: the one shard holding
        # the hostname component monopolized the colocation headroom that
        # other shards' hostPort/generic pods needed.
        #
        # hostname ANTI groups (direct and inverse, no filter terms) split
        # freely too: the constraint is pairwise SEPARATION on the slot
        # axis, so placing its pods on different shards' disjoint slots can
        # only over-satisfy it — owners repel selector-matching pods, which
        # therefore could never have co-located with them anyway, and the
        # within-shard thost lane enforces the rule among same-shard
        # replicas exactly. Existing slots are owned by one shard, so the
        # identically-seeded existing columns never race. Value-key
        # affinity/anti stay routed (their assume/seed semantics span
        # shards through the shared domain counts).
        touch = touch.copy()
        for g, gm in enumerate(topo_meta.groups):
            if not gm.is_hostname:
                continue
            if gm.gtype == topo_mod.TOPO_SPREAD and not gm.is_inverse:
                # spread groups always carry the pod's node-filter term
                # row; the filter constrains WHICH nodes count, not the
                # cross-shard accounting, so it doesn't gate the split
                touch[g, :] = False
            elif (
                gm.gtype == topo_mod.TOPO_ANTI
                and len(gm.filter_term_rows) == 0
            ):
                # anti groups have no node filter in the reference;
                # guard anyway — a filtered variant would make per-slot
                # admission row-dependent
                touch[g, :] = False
        G = touch.shape[0]
        parent = list(range(G))

        def find(g):
            while parent[g] != g:
                parent[g] = parent[parent[g]]
                g = parent[g]
            return g

        for i in range(I):
            gs = np.nonzero(touch[:, i])[0]
            for g in gs[1:]:
                ra, rb = find(int(gs[0])), find(int(g))
                if ra != rb:
                    parent[rb] = ra
        comp_of_item = np.full(I, -1, dtype=np.int64)
        for i in range(I):
            gs = np.nonzero(touch[:, i])[0]
            if len(gs):
                comp_of_item[i] = find(int(gs[0]))
        comps = [c for c in np.unique(comp_of_item) if c >= 0]
        loads = {c: int(counts[comp_of_item == c].sum()) for c in comps}
        shard_load = np.zeros(ndp, dtype=np.int64)
        comp_shard: Dict[int, int] = {}
        for c in sorted(comps, key=lambda c: -loads[c]):
            d = int(np.argmin(shard_load))
            comp_shard[c] = d
            shard_load[d] += loads[c]
        for i in range(I):
            c = comp_of_item[i]
            if c >= 0:
                count_split[:, i] = 0
                count_split[comp_shard[int(c)], i] = counts[i]
        # rebalance FREE items against the component loads (water-fill):
        # an even free split on top of LPT-routed components leaves the
        # component shards overloaded; instead free replicas fill toward
        # the common target load
        free_items = np.nonzero(comp_of_item < 0)[0]
        if len(free_items):
            # largest items first; shard_load ACCUMULATES as items are
            # assigned, so count-1 classes spread instead of all landing on
            # the same largest-remainder shard
            for i in sorted(free_items, key=lambda i: -int(counts[i])):
                c = int(counts[i])
                level = (int(shard_load.sum()) + c) / ndp
                deficit = np.maximum(0.0, level - shard_load.astype(np.float64))
                if deficit.sum() <= 0:
                    deficit = np.ones(ndp)
                frac = deficit / deficit.sum()
                split = np.floor(frac * c).astype(np.int64)
                rem = c - int(split.sum())
                for _ in range(rem):  # leftovers one-by-one to least loaded
                    d = int(np.argmin(shard_load + split))
                    split[d] += 1
                count_split[:, i] = split
                shard_load += split
    return count_split, exist_owner


def make_sharded_run(segments, zone_seg, ct_seg, topo_meta, n_slots, mesh,
                     log_len: Optional[int] = None,
                     screen_v: Optional[int] = None):
    """Build the jit-compiled shard_map program over `mesh` (axes 'dp' and
    'tp') from GEOMETRY alone — the sharded analog of
    tpu_solver.make_device_run, shared by make_sharded_solve (snapshot path)
    and the gRPC SolverService (which reconstructs geometry from the wire).
    All other dims derive from argument shapes at trace time."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from karpenter_core_tpu.ops.feasibility import feasibility_static, openable_mask
    from karpenter_core_tpu.ops.pack import PackState, make_pack_kernel

    segments = list(segments)
    N = n_slots
    has_topo = topo_meta is not None and len(topo_meta.groups) > 0
    pack = make_pack_kernel(segments, zone_seg, ct_seg,
                            topo_meta=topo_meta,
                            screen_v=screen_v)

    def body(pod_arrays, count_split, tmpl, tmpl_daemon, tmpl_type_mask_l,
             types_l, type_offering_ok_l, types_full, type_alloc,
             type_capacity, type_offering_ok, pod_tol_all, exist, exist_used,
             exist_cap, exist_owner, well_known, remaining_split,
             topo_counts0, topo_hcounts0, topo_doms0, topo_terms,
             exist_ports, exist_vols, exist_vol_limits, vol_driver):
        E = exist_used.shape[0]
        R = exist_used.shape[1]
        J = tmpl_daemon.shape[0]
        T = type_alloc.shape[0]
        V = pod_arrays["allow"].shape[1]
        K = pod_arrays["out"].shape[1]
        # ---- type-sharded feasibility + all_gather over 'tp' -------------
        f_local = feasibility_static(
            {k: pod_arrays[k] for k in ("allow", "out", "defined", "escape")},
            tmpl,
            types_l,
            pod_arrays["tol_tmpl"],
            tmpl_type_mask_l,
            type_offering_ok_l,
            zone_seg,
            ct_seg,
            segments,
            well_known,
        )  # [J, I, T_local]
        f_static = jax.lax.all_gather(f_local, "tp", axis=3, tiled=False)
        f_static = jnp.moveaxis(f_static, 3, 2).reshape(
            f_local.shape[0], f_local.shape[1], -1
        )

        openable = openable_mask(
            f_static, pod_arrays["requests"], tmpl_daemon, type_alloc
        )
        mine = exist_owner[0]  # [E] this shard's existing slots
        slot_exist = jnp.arange(N) < E
        open0 = jnp.where(slot_exist, jnp.pad(mine, (0, N - E)), False)
        state = PackState(
            used=jnp.zeros((N, R), jnp.float32).at[:E].set(exist_used),
            open=open0,
            is_existing=open0,
            tmpl=jnp.zeros(N, jnp.int32),
            tol_idx=jnp.concatenate(
                [J + jnp.arange(E, dtype=jnp.int32), jnp.zeros(N - E, jnp.int32)]
            ),
            pods=jnp.zeros(N, jnp.int32),
            allow=jnp.ones((N, V), bool).at[:E].set(exist["allow"]),
            out=jnp.ones((N, K), bool).at[:E].set(exist["out"]),
            defined=jnp.zeros((N, K), bool).at[:E].set(exist["defined"]),
            tmask=jnp.zeros((N, T), bool),
            cap=jnp.zeros((N, R), jnp.float32).at[:E].set(exist_cap),
            nopen=jnp.int32(E),
            remaining=remaining_split[0],
            tcounts=topo_counts0,
            thost=topo_hcounts0,
            tdoms=topo_doms0,
            ports=jnp.zeros((N, exist_ports.shape[1]), bool).at[:E].set(
                exist_ports
            ),
            vols=exist_vols,
        )
        pod_arrays = dict(pod_arrays)
        pod_arrays["tol"] = pod_tol_all
        # this shard's share of each class's replicas
        pod_arrays["count"] = count_split[0]
        tmpl_type_mask = jax.lax.all_gather(tmpl_type_mask_l, "tp", axis=2, tiled=False)
        tmpl_type_mask = jnp.moveaxis(tmpl_type_mask, 2, 1).reshape(J, -1)
        state, log, ptr = pack(
            state,
            pod_arrays,
            f_static,
            openable,
            {k: tmpl[k] for k in ("allow", "out", "defined")},
            tmpl_daemon,
            tmpl_type_mask,
            types_full,
            type_alloc,
            type_capacity,
            type_offering_ok,
            well_known=well_known,
            topo_terms=topo_terms,
            log_len=log_len,
            n_exist=E,
            vol_limits=exist_vol_limits,
            vol_driver=vol_driver,
        )
        # global stats via psum over dp: pods scheduled (an ICI collective)
        scheduled = jax.lax.psum(state.pods.sum(), "dp")
        # rank-0 per-shard values need a singleton axis to concatenate over dp
        state = state._replace(nopen=state.nopen[None])
        log = {**log, "bulk_n": log["bulk_n"][None]}
        return log, ptr[None], state, scheduled

    # item rows replicate; only the per-shard replica counts shard over dp
    pod_spec = {
        "allow": P(None, None),
        "out": P(None, None),
        "defined": P(None, None),
        "escape": P(None, None),
        "custom_deny": P(None, None),
        "requests": P(None, None),
        "tol_tmpl": P(None, None),
        "ports": P(None, None),
        "port_conflict": P(None, None),
        "vols": P(None, None),
        "valid": P(None),
        # prescreen verdict-column maps: the item axis replicates, so the
        # class-dedup indices stay valid on every shard
        "scls": P(None),
        "scls_first": P(None),
    }
    if has_topo:
        pod_spec["topo_own"] = P(None, None)
        pod_spec["topo_sel"] = P(None, None)
    reqset_rep = {k: P(None, None) for k in ("allow", "out", "defined", "escape")}
    reqset_tp = {k: P("tp", None) for k in ("allow", "out", "defined", "escape")}
    in_specs = (
        pod_spec,  # pod_arrays
        P("dp", None),  # count_split [ndp, I]
        reqset_rep,  # tmpl
        P(None, None),  # tmpl_daemon
        P(None, "tp"),  # tmpl_type_mask_l
        reqset_tp,  # types_l
        P("tp", None, None),  # type_offering_ok_l
        reqset_rep,  # types_full (replicated for packing)
        P(None, None),  # type_alloc
        P(None, None),  # type_capacity
        P(None, None, None),  # type_offering_ok
        P(None, None),  # pod_tol_all
        reqset_rep,  # exist
        P(None, None),  # exist_used
        P(None, None),  # exist_cap
        P("dp", None),  # exist_owner [ndp, E]
        P(None),  # well_known
        P("dp", None, None),  # remaining_split [ndp, J, R]
        P(None, None),  # topo_counts0 [G, V]
        P(None, None),  # topo_hcounts0 [G, N]
        P(None, None),  # topo_doms0 [G, V]
        {k: P(None, None) for k in ("allow", "out", "defined", "escape")},  # topo_terms
        P(None, None),  # exist_ports [E, Q]
        P(None, None),  # exist_vols [E, W]
        P(None, None),  # exist_vol_limits [E, D]
        P(None, None),  # vol_driver [W, D]
    )
    out_specs = (
        {
            **{k: P("dp") for k in ("item", "slot", "ns", "k", "k_last", "bulk_n")},
            "bulk_take": P("dp", None),
        },  # commit log
        P("dp"),  # log ptr (singleton axis per shard)
        PackState(
            used=P("dp", None),
            open=P("dp"),
            is_existing=P("dp"),
            tmpl=P("dp"),
            tol_idx=P("dp"),
            pods=P("dp"),
            allow=P("dp", None),
            out=P("dp", None),
            defined=P("dp", None),
            tmask=P("dp", None),
            cap=P("dp", None),
            nopen=P("dp"),
            remaining=P("dp", None),
            tcounts=P("dp", None),
            thost=P("dp", None),
            tdoms=P("dp", None),
            ports=P("dp", None),
            vols=P("dp", None),
        ),
        P(),  # scheduled count (replicated)
    )

    # version compat: jax >= 0.6 exposes jax.shard_map (check_vma);
    # 0.4.x only has jax.experimental.shard_map (check_rep)
    if hasattr(jax, "shard_map"):
        sharded = jax.shard_map(
            body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    else:
        from jax.experimental.shard_map import shard_map as _shard_map

        sharded = _shard_map(
            body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=False,
        )
    fn = jax.jit(sharded)
    return fn


def shard_args(base_args, count_split: np.ndarray, exist_owner: np.ndarray):
    """Assemble the shard_map argument tuple from a device_args() tuple plus
    the plan_shards partition. The count axis is padded to the item bucket
    (device_args pads the item rows); the caller keeps the real-I count_split
    for decoding."""
    ndp = count_split.shape[0]
    (pod_arrays, tmpl, tmpl_daemon, tmpl_type_mask, types, type_alloc,
     type_capacity, type_offering_ok, pod_tol_all, exist, exist_used,
     exist_cap, well_known, remaining0, topo_counts0, topo_hcounts0,
     topo_doms0, topo_terms, exist_ports, exist_vols, exist_vol_limits,
     vol_driver) = base_args
    pod_arrays = dict(pod_arrays)
    pod_arrays.pop("count")
    E = exist_used.shape[0]
    I_pad = pod_arrays["valid"].shape[0]
    count_split_dev = np.zeros((ndp, I_pad), dtype=count_split.dtype)
    count_split_dev[:, : count_split.shape[1]] = count_split

    # limits split proportional to each shard's replica load (pessimistic:
    # the shares always sum to <= the global budget)
    total = max(int(count_split.sum()), 1)
    share = count_split.sum(axis=1).astype(np.float64) / total  # [ndp]
    finite = remaining0 < np.float32(1e29)
    remaining_split = np.where(
        finite[None], remaining0[None] * share[:, None, None], remaining0[None]
    ).astype(np.float32)

    # per-shard hostname-count state: existing columns seed identically on
    # every shard (only the owner shard's groups ever read/update them);
    # machine columns start at zero. [G, N] with N = E + max_nodes_per_shard
    th0 = np.zeros_like(topo_hcounts0)
    th0[:, :E] = topo_hcounts0[:, :E]

    return (
        pod_arrays,
        count_split_dev,
        tmpl,
        tmpl_daemon,
        tmpl_type_mask,
        types,
        type_offering_ok,
        types,
        type_alloc,
        type_capacity,
        type_offering_ok,
        pod_tol_all,
        exist,
        exist_used,
        exist_cap,
        exist_owner,
        well_known,
        remaining_split,
        topo_counts0,
        th0,
        topo_doms0,
        topo_terms,
        exist_ports,
        exist_vols,
        exist_vol_limits,
        vol_driver,
    )


def make_sharded_solve(snap, provisioners, mesh, max_nodes_per_shard: int = 256,
                       program_cache=None):
    """Build (fn, args, plan) where fn is a jit-compiled shard_map program
    over `mesh` (axes 'dp' and 'tp'), args are the host arrays, and plan is
    (count_split, exist_owner) for decoding.

    Type-axis arrays must divide by mesh.shape['tp'] (ShardedSolver routes
    non-dividing geometries through a dp-only mesh). Supports topology
    constraints and existing nodes via component routing / slot ownership
    (module docstring)."""
    from karpenter_core_tpu.solver.tpu_solver import device_args, solve_geometry

    geom = solve_geometry(snap, max_nodes_per_shard)
    (_, _J, _T, _E, _R, _K, _V, N, segments_t, zone_seg, ct_seg, _topo_sig,
     log_len, _Q, _W, _D, screen_v) = geom
    ndp = mesh.shape["dp"]
    ntp = mesh.shape["tp"]
    count_split, exist_owner = plan_shards(snap, ndp)

    # the shard_map program is pure in everything but the label geometry
    # (+ topo signature, baked into geom), the mesh shape, and the screen
    # mode resolved at trace time: cache on all three so steady-state
    # solves reuse one compiled program AND a KCT_PACK_SCREEN flip takes
    # effect instead of returning the other mode's cached program
    from karpenter_core_tpu.ops import compat as ops_compat

    cache_key = (geom, ndp, ntp, ops_compat.resolve_screen_mode())
    fn = None if program_cache is None else program_cache.get(cache_key)
    if fn is not None and hasattr(program_cache, "move_to_end"):
        program_cache.move_to_end(cache_key)  # LRU recency (ShardedSolver)
    if fn is None:
        fn = make_sharded_run(
            segments_t, zone_seg, ct_seg, snap.topo_meta, N, mesh,
            log_len=log_len, screen_v=screen_v,
        )
        if program_cache is not None:
            program_cache[cache_key] = fn

    args = shard_args(device_args(snap, provisioners), count_split, exist_owner)
    return fn, args, (count_split, exist_owner)


def decode_sharded(snap, log, ptr, state, count_split):
    """Merge per-shard commit logs into one SolveResult.

    log: dict of [ndp, L] arrays; ptr: [ndp]; state: PackState stacked on a
    leading dp axis. Shard d consumes members[off_d : off_d + split_d] of
    each item, where off_d is the cumulative split below d — the same
    partition plan_shards produced. Each shard's log replays through the
    single-device expand_log/decode_solve (bounded to the shard's member
    slice); merging is a concat because machines are shard-local and every
    existing slot is owned by exactly one shard."""
    from types import SimpleNamespace

    from karpenter_core_tpu.solver.tpu_solver import (
        SolveResult,
        decode_solve,
        expand_log,
    )

    ndp = count_split.shape[0]
    # shard_map concatenates per-shard outputs along the leading axis:
    # reshape [ndp*L] logs and [ndp*N, ...] state fields back to per-shard
    # (trailing dims preserved — bulk_take is [ndp*LB, BR]: the
    # existing prefix, or the full slot axis under mach_bulk geometries)
    log = {
        k: (lambda a: a.reshape((ndp, a.shape[0] // ndp) + a.shape[1:]))(
            np.asarray(v)
        )
        for k, v in log.items()
    }
    ptr = np.asarray(ptr).reshape(-1)
    P = len(snap.pods)
    offs = np.cumsum(count_split, axis=0) - count_split  # [ndp, I]

    N = np.asarray(state.tmpl).shape[0] // ndp
    fields = {
        name: np.asarray(getattr(state, name)).reshape((ndp, N) + np.asarray(
            getattr(state, name)
        ).shape[1:])
        for name in ("tmpl", "tmask", "used", "allow", "out", "defined")
    }

    machines: List = []
    existing: List[Tuple[object, List]] = []
    scheduled = np.zeros(P, dtype=bool)
    for d in range(ndp):
        assigned_d = expand_log(
            snap,
            {k: v[d] for k, v in log.items()},
            int(ptr[d]),
            member_lo=offs[d],
            member_hi=offs[d] + count_split[d],
        )
        shard_state = SimpleNamespace(**{k: v[d] for k, v in fields.items()})
        # failures are recomputed below from the cross-shard bitmask: a
        # shard's assigned is -1 for every OTHER shard's pods, so per-shard
        # failed lists would be O(ndp * P) garbage
        res_d = decode_solve(snap, assigned_d, shard_state, want_failed=False)
        machines.extend(res_d.new_machines)
        existing.extend(res_d.existing_assignments)
        scheduled |= assigned_d >= 0

    failed = [pod for i, pod in enumerate(snap.pods) if not scheduled[i]]
    return SolveResult(
        new_machines=machines, existing_assignments=existing, failed_pods=failed
    )


class ShardedSolver:
    """Solver-interface front end for the multi-chip path: encode once,
    run the shard_map program over `mesh`, merge shard logs. Drop-in for
    TPUSolver where a Mesh is available (solver/factory.py builds one when
    the process sees >1 device); relaxation shares solve_with_relaxation and
    the pipelined encode()/solve(encoded=) surface matches TPUSolver so the
    provisioning loop overlaps encode with the previous solve either way."""

    # the consolidation ladder's vmapped screen (solver/replan.py) is
    # independent of the provisioning solve path: it builds its own device
    # program and runs on ONE device (a 1k-node ladder fits a single chip),
    # so a multi-chip deployment keeps the batched-replan fast path —
    # provisioning fans out over the mesh, the screen rides chip 0
    supports_batched_replan = True
    backend = None  # default kernel lowering for the screen program

    def __init__(self, mesh, max_nodes_per_shard: int = 256,
                 max_relax_rounds: Optional[int] = None):
        from karpenter_core_tpu.solver.tpu_solver import DEFAULT_MAX_RELAX_ROUNDS

        self.mesh = mesh
        self.max_nodes_per_shard = max_nodes_per_shard
        self.max_relax_rounds = (
            DEFAULT_MAX_RELAX_ROUNDS if max_relax_rounds is None else max_relax_rounds
        )
        # LRU-bounded (same rationale as TPUSolver/SolverService: label
        # churn mints geometries; don't pin old executables forever)
        from collections import OrderedDict

        self.MAX_COMPILED = 32
        self._compiled = OrderedDict()
        from karpenter_core_tpu.solver.encode import EncodeReuse

        self._encode_reuse = EncodeReuse()

    @property
    def max_nodes(self) -> int:
        # the GLOBAL new-machine budget (consolidation sizes its ladder
        # screen off this); each shard owns max_nodes_per_shard of it
        return self.mesh.shape["dp"] * self.max_nodes_per_shard

    def encode(self, pods, provisioners, instance_types, daemonset_pods=None,
               state_nodes=None, kube_client=None, cluster=None):
        """Pre-encode a batch off the Solve critical path (same contract as
        TPUSolver.encode); the snapshot is sized to the PER-SHARD slot
        budget, which is what every per-device plane keys off."""
        from karpenter_core_tpu.solver.encode import encode_snapshot

        return encode_snapshot(
            pods, provisioners, instance_types, daemonset_pods, state_nodes,
            kube_client=kube_client, cluster=cluster,
            max_nodes=self.max_nodes_per_shard,
            reuse=self._encode_reuse,
        )

    def solve(self, pods, provisioners, instance_types, daemonset_pods=None,
              state_nodes=None, kube_client=None, cluster=None, encoded=None):
        from karpenter_core_tpu.solver.tpu_solver import solve_with_relaxation

        if encoded is not None:
            # must be OF this batch (see TPUSolver.solve for why identity)
            if len(encoded.pods) != len(pods) or (
                {id(p) for p in encoded.pods} != {id(p) for p in pods}
            ):
                raise ValueError(
                    "encoded snapshot was built from a different pod batch"
                )
        relax_ctx = {"encoded": encoded}
        return solve_with_relaxation(
            lambda p: self._solve_once(
                p, provisioners, instance_types, daemonset_pods, state_nodes,
                kube_client, cluster, relax_ctx,
            ),
            pods,
            provisioners,
            instance_types,
            self.max_relax_rounds,
        )

    # a shard that exhausts its per-shard slot budget doubles it and
    # re-solves (the grown program is compiled once and cached); cap the
    # growth so a pathological batch can't compile unbounded geometries
    MAX_NODES_PER_SHARD_CAP = 4096

    def _solve_once(self, pods, provisioners, instance_types, daemonset_pods,
                    state_nodes, kube_client, cluster, relax_ctx=None):
        import jax

        from karpenter_core_tpu.solver.encode import encode_snapshot

        snap = relax_ctx.pop("encoded", None) if relax_ctx else None
        per_shard = self.max_nodes_per_shard
        while True:
            if snap is None:
                snap = encode_snapshot(
                    pods, provisioners, instance_types, daemonset_pods,
                    state_nodes, kube_client=kube_client, cluster=cluster,
                    max_nodes=per_shard,
                    reuse=self._encode_reuse,
                )
            mesh = self.mesh
            # the PADDED type-axis width (ladder tiers are even, so padded
            # geometries stay tp-divisible; raw odd universes fall back)
            T_axis = (
                snap.type_alloc.shape[0]
                if snap.type_alloc is not None
                else len(snap.instance_types)
            )
            if T_axis % mesh.shape["tp"] != 0:
                # the tp all_gather needs the type axis to divide; rare odd
                # geometries route through a dp-only view of the same devices
                mesh = _dp_only_mesh(mesh)
            fn, args, (count_split, _exist_owner) = make_sharded_solve(
                snap, provisioners, mesh,
                max_nodes_per_shard=per_shard,
                program_cache=self._compiled,
            )
            while len(self._compiled) > self.MAX_COMPILED:
                self._compiled.popitem(last=False)
            # chaos hook: the multi-chip accelerator edge (same point as
            # TPUSolver._run_kernels — one name covers "the device path")
            from karpenter_core_tpu import chaos

            chaos.maybe_fail(chaos.SOLVER_DEVICE)
            with mesh:
                log, ptr, state, _scheduled = fn(*args)
                jax.block_until_ready(log)
            state = jax.tree_util.tree_map(np.asarray, state)
            result = decode_sharded(snap, log, ptr, state, count_split)
            if not result.failed_pods:
                return result
            # slot-budget exhaustion is NOT a constraint failure: the dp
            # split can concentrate more machines on one shard than the
            # per-shard budget admits even when the global budget fits
            # (scheduler.go has one global node list; shards have disjoint
            # budgets). Grow and retry. The growth PERSISTS only when the
            # plan actually split: a small-batch single-shard solve that
            # overflowed must not permanently double every future solve's
            # slot geometry (the compiled program for the transient size
            # stays cached, so repeats pay one extra dispatch, not a
            # recompile).
            exhausted = bool(
                np.any(np.asarray(state.nopen).reshape(-1) >= snap.n_slots)
            )
            if not exhausted or per_shard * 2 > self.MAX_NODES_PER_SHARD_CAP:
                return result
            per_shard *= 2
            if int((count_split.sum(axis=1) > 0).sum()) > 1:
                self.max_nodes_per_shard = per_shard
            snap = None  # re-encode at the grown slot budget


def _dp_only_mesh(mesh):
    """Reshape a dp×tp mesh's devices into dp×1 (all devices on 'dp')."""
    from jax.sharding import Mesh

    devices = np.asarray(mesh.devices).reshape(-1, 1)
    return Mesh(devices, ("dp", "tp"))


