"""Process-discipline pass (ISSUE 13).

The repo's supervision story (utils/supervise, solver/host) is built on
one invariant: every child process lives in its OWN process group, so a
wedge kill (`os.killpg` SIGKILL) takes the grandchildren with it. Three
rules keep that invariant from eroding as new spawn sites appear:

Rule `proc-group`: every `subprocess.Popen(...)` must pass an explicit
``start_new_session=`` — or live in one of the audited supervisor funnels
(config.popen_funnels). A Popen that shares the parent's process group
cannot be group-killed without killing the parent, and its own children
survive a plain kill(): exactly the zombie class ISSUE 12 buried.

Rule `proc-kill-group`: `os.kill(...)` on a child pid where `os.killpg`
is the repo convention. A lone os.kill reaps the child but leaks any
grandchild holding a pipe — the supervisor's `_kill_group` exists so
nothing outlives the kill. Audited exceptions (e.g. a signal-0 liveness
probe) go in config.os_kill_allowlist as `relpath::function`.

Rule `thread-join`: a `threading.Thread(...)` constructed with
``daemon=False`` (a child-waiter the process will wait on at exit) must
have a reachable ``.join(`` somewhere in the same file, or be flagged:
an unjoined non-daemon thread wedges interpreter shutdown — the exact
hang class the operator's watch pumps are daemonized to avoid. (The
`thread-discipline` rule already forces the daemon= decision to be
explicit; this rule polices the False branch.)
"""
from __future__ import annotations

import ast
from typing import List, Sequence

from karpenter_core_tpu.analysis.core import Pass, SourceFile, Violation


def _call_name(func: ast.expr) -> str:
    """Dotted tail of a call target: `subprocess.Popen` -> 'subprocess.Popen',
    `Popen` -> 'Popen'."""
    parts: List[str] = []
    node = func
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


class ProcessDisciplinePass(Pass):
    name = "procdiscipline"
    rules = ("proc-group", "proc-kill-group", "thread-join")

    def run(self, files: Sequence[SourceFile], config) -> List[Violation]:
        out: List[Violation] = []
        funnels = getattr(config, "popen_funnels", frozenset())
        kill_allowlist = getattr(config, "os_kill_allowlist", frozenset())
        for f in files:
            if f.tree is None:
                continue
            popen_names = self._popen_aliases(f.tree)
            thread_names = self._thread_aliases(f.tree)
            join_targets = self._joined_names(f.tree)
            for node in ast.walk(f.tree):
                if not isinstance(node, ast.Call):
                    continue
                name = _call_name(node.func)
                if name in popen_names and f.relpath not in funnels:
                    kwargs = {kw.arg for kw in node.keywords if kw.arg}
                    if "start_new_session" not in kwargs:
                        out.append(Violation(
                            relpath=f.relpath, line=node.lineno,
                            rule="proc-group",
                            message=(
                                "subprocess.Popen without explicit "
                                "start_new_session= — a child sharing the "
                                "parent's process group cannot be wedge-"
                                "killed (os.killpg) without killing the "
                                "parent; set start_new_session= or spawn "
                                "through utils/supervise or solver/host"
                            ),
                        ))
                elif name == "os.kill":
                    func_name = self._enclosing_function(f.tree, node)
                    if f"{f.relpath}::{func_name}" not in kill_allowlist:
                        out.append(Violation(
                            relpath=f.relpath, line=node.lineno,
                            rule="proc-kill-group",
                            message=(
                                "os.kill on a child pid — the repo "
                                "convention is os.killpg (grandchildren "
                                "holding pipes survive a lone kill); use "
                                "supervise._kill_group / killpg, or add "
                                "an audited os_kill_allowlist entry"
                            ),
                        ))
                elif name in thread_names or (
                    name == "threading.Thread"
                ):
                    for kw in node.keywords:
                        if (
                            kw.arg == "daemon"
                            and isinstance(kw.value, ast.Constant)
                            and kw.value.value is False
                        ):
                            target = self._assigned_name(f.tree, node)
                            if target is None or target not in join_targets:
                                out.append(Violation(
                                    relpath=f.relpath, line=node.lineno,
                                    rule="thread-join",
                                    message=(
                                        "non-daemon Thread with no "
                                        "reachable .join() in this file — "
                                        "an unjoined child-waiter thread "
                                        "wedges interpreter shutdown; join "
                                        "it (with a timeout) or daemonize "
                                        "and supervise it"
                                    ),
                                ))
        return out

    @staticmethod
    def _popen_aliases(tree: ast.AST) -> set:
        """Spellings Popen is reachable under in this module."""
        names = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name == "subprocess":
                        names.add(f"{alias.asname or 'subprocess'}.Popen")
            elif isinstance(node, ast.ImportFrom):
                if node.module == "subprocess" and not node.level:
                    for alias in node.names:
                        if alias.name == "Popen":
                            names.add(alias.asname or "Popen")
        return names

    @staticmethod
    def _thread_aliases(tree: ast.AST) -> set:
        names = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name == "threading":
                        names.add(f"{alias.asname or 'threading'}.Thread")
            elif isinstance(node, ast.ImportFrom):
                if node.module == "threading" and not node.level:
                    for alias in node.names:
                        if alias.name == "Thread":
                            names.add(alias.asname or "Thread")
        return names

    @staticmethod
    def _joined_names(tree: ast.AST) -> set:
        """Names (and self-attrs) that have a .join(...) call in the file."""
        joined = set()
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "join"
            ):
                base = node.func.value
                if isinstance(base, ast.Name):
                    joined.add(base.id)
                elif isinstance(base, ast.Attribute):
                    joined.add(base.attr)
        return joined

    @staticmethod
    def _assigned_name(tree: ast.AST, call: ast.Call):
        """The simple name or self-attr the Thread(...) result is bound to
        (None when constructed anonymously)."""
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and node.value is call:
                target = node.targets[0]
                if isinstance(target, ast.Name):
                    return target.id
                if isinstance(target, ast.Attribute):
                    return target.attr
        return None

    @staticmethod
    def _enclosing_function(tree: ast.AST, target: ast.AST) -> str:
        """Name of the innermost def containing `target` ('' at module
        scope) — matches the `relpath::function` allowlist convention."""
        best = ""
        best_span = None
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                end = getattr(node, "end_lineno", None)
                if (
                    end is not None
                    and node.lineno <= target.lineno <= end
                ):
                    span = end - node.lineno
                    if best_span is None or span < best_span:
                        best, best_span = node.name, span
        return best
