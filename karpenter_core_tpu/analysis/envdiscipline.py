"""Env-flag discipline pass (rule `env-flags`): every environment read in
the package funnels through obs/envflags.py.

The operator, solver service, chaos registry, compile cache, and the three
obs subsystems are all env-configured; when each module calls os.environ
directly the spellings drift (\"1\" vs \"true\" vs \"on\"), defaults fork, and
there is no single place to enumerate the knobs. obs/envflags.py owns the
truthy/falsy grammar and the accessors; everything else imports it.

Flags any use of `os.environ` / `os.getenv` (including aliased module
imports and `from os import environ`) outside the funnel module.
"""
from __future__ import annotations

import ast
from typing import List, Sequence, Set

from karpenter_core_tpu.analysis.core import Pass, SourceFile, Violation


class EnvDisciplinePass(Pass):
    name = "envdiscipline"
    rules = ("env-flags",)

    def run(self, files: Sequence[SourceFile], config) -> List[Violation]:
        out: List[Violation] = []
        for f in files:
            if f.tree is None or f.relpath == config.env_funnel:
                continue
            os_aliases: Set[str] = set()
            direct: Set[str] = set()  # names bound to os.environ / os.getenv
            for node in ast.walk(f.tree):
                if isinstance(node, ast.Import):
                    for alias in node.names:
                        if alias.name == "os":
                            os_aliases.add(alias.asname or "os")
                elif isinstance(node, ast.ImportFrom):
                    if node.module == "os" and not node.level:
                        for alias in node.names:
                            if alias.name in ("environ", "getenv", "putenv"):
                                direct.add(alias.asname or alias.name)
            if not os_aliases and not direct:
                continue
            for node in ast.walk(f.tree):
                if isinstance(node, ast.Attribute):
                    if (
                        isinstance(node.value, ast.Name)
                        and node.value.id in os_aliases
                        and node.attr in ("environ", "getenv", "putenv")
                    ):
                        out.append(self._violation(f, node))
                elif isinstance(node, ast.Name) and node.id in direct:
                    if isinstance(node.ctx, ast.Load):
                        out.append(self._violation(f, node))
        return out

    @staticmethod
    def _violation(f: SourceFile, node: ast.AST) -> Violation:
        return Violation(
            relpath=f.relpath,
            line=node.lineno,
            rule="env-flags",
            message=(
                "direct os.environ access — route through "
                "karpenter_core_tpu.obs.envflags (raw/require/get_bool/environ)"
            ),
        )
