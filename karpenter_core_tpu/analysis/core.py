"""Framework core: source loading, suppression parsing, pass protocol,
baseline handling, and the runner.

A pass sees the WHOLE file set at once (layering needs the global import
graph); single-file passes just loop. Violations carry (relpath, line,
rule, message); suppressions and the baseline subtract by key. Everything
here is stdlib-only so the analyzer can run in a bare interpreter and never
participates in the package's own layering constraints.
"""
from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

SUPPRESS_RE = re.compile(r"#\s*lint:\s*disable=([A-Za-z0-9_,\- ]+)")


@dataclass(frozen=True)
class Violation:
    relpath: str  # repo-relative, '/'-separated
    line: int
    rule: str
    message: str

    def key(self) -> str:
        return f"{self.relpath}:{self.line}:{self.rule}"

    def render(self) -> str:
        return f"{self.relpath}:{self.line}: [{self.rule}] {self.message}"


@dataclass
class SourceFile:
    """One parsed module plus its per-line suppressions."""

    path: str  # absolute
    relpath: str  # relative to the scan root, '/'-separated
    module: Optional[str]  # dotted module name when under the package root
    text: str = ""
    tree: Optional[ast.AST] = None
    parse_error: Optional[SyntaxError] = None
    # line -> set of suppressed rule names for that line
    suppressions: Dict[int, Set[str]] = field(default_factory=dict)

    def suppressed(self, line: int, rule: str) -> bool:
        rules = self.suppressions.get(line)
        return rules is not None and (rule in rules or "*" in rules)


def parse_suppressions(text: str) -> Dict[int, Set[str]]:
    out: Dict[int, Set[str]] = {}
    for i, line in enumerate(text.splitlines(), start=1):
        if "lint:" not in line:
            continue
        m = SUPPRESS_RE.search(line)
        if m:
            out[i] = {r.strip() for r in m.group(1).split(",") if r.strip()}
    return out


def load_tree(path: str, relpath: str, module: Optional[str] = None) -> SourceFile:
    with open(path, encoding="utf-8") as f:
        text = f.read()
    sf = SourceFile(path=path, relpath=relpath, module=module, text=text)
    sf.suppressions = parse_suppressions(text)
    try:
        sf.tree = ast.parse(text, filename=path)
    except SyntaxError as exc:
        sf.parse_error = exc
    return sf


def collect_sources(
    root: str, package_name: str, subdir: Optional[str] = None
) -> List[SourceFile]:
    """Walk `<root>/<package_name>` (or a subdir of it) into SourceFiles.

    `relpath` is relative to `root`; `module` is the dotted import name, so
    `<root>/<pkg>/solver/encode.py` -> `<pkg>.solver.encode`.
    """
    base = os.path.join(root, package_name)
    scan = os.path.join(base, subdir) if subdir else base
    files: List[SourceFile] = []
    for dirpath, dirnames, filenames in os.walk(scan):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for filename in sorted(filenames):
            if not filename.endswith(".py"):
                continue
            path = os.path.join(dirpath, filename)
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            module = rel[: -len(".py")].replace("/", ".")
            if module.endswith(".__init__"):
                module = module[: -len(".__init__")]
            files.append(load_tree(path, rel, module))
    return files


class Pass:
    """One analysis pass. Subclasses set `name` (the pass id) and `rules`
    (every rule id the pass can emit — used by --rule filtering and the
    docs catalog) and implement run().

    `scope` declares what run() needs to see: "file" (the default) means
    findings for a file depend only on that file, so the runner may invoke
    run() once per file — in parallel; "fileset" (layering: the global
    import graph) always gets the whole set in one call."""

    name: str = ""
    rules: Tuple[str, ...] = ()
    scope: str = "file"

    def run(self, files: Sequence[SourceFile], config) -> List[Violation]:
        raise NotImplementedError

    # -- helpers shared by AST passes -------------------------------------

    @staticmethod
    def syntax_violations(files: Sequence[SourceFile], rule: str) -> List[Violation]:
        return [
            Violation(
                relpath=f.relpath,
                line=f.parse_error.lineno or 0,
                rule=rule,
                message=f"file does not parse: {f.parse_error.msg}",
            )
            for f in files
            if f.parse_error is not None
        ]


def module_scope_imports(tree: ast.AST) -> List[ast.stmt]:
    """Import statements executed at module import time: top level, plus
    inside top-level if/try bodies (version shims) — but NOT inside
    `if TYPE_CHECKING:` blocks, which never run."""
    out: List[ast.stmt] = []

    def is_type_checking(test: ast.expr) -> bool:
        return (isinstance(test, ast.Name) and test.id == "TYPE_CHECKING") or (
            isinstance(test, ast.Attribute) and test.attr == "TYPE_CHECKING"
        )

    def scan(body: Iterable[ast.stmt]) -> None:
        for node in body:
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                out.append(node)
            elif isinstance(node, ast.If):
                if not is_type_checking(node.test):
                    scan(node.body)
                scan(node.orelse)
            elif isinstance(node, ast.Try):
                scan(node.body)
                for handler in node.handlers:
                    scan(handler.body)
                scan(node.orelse)
                scan(node.finalbody)
            elif isinstance(node, ast.With):
                scan(node.body)

    scan(getattr(tree, "body", []))
    return out


def resolve_import_targets(
    node: ast.stmt,
    current_module: str,
    known_modules: Set[str],
    package_name: str,
    is_package: bool = False,
) -> List[str]:
    """Dotted module names a single import statement binds, restricted to
    modules inside the package (`known_modules`). Handles absolute imports,
    `from pkg import submodule`, and explicit relative imports
    (`is_package`: current_module is an __init__, so `from .` is the module
    itself, not its parent)."""
    targets: List[str] = []
    if isinstance(node, ast.Import):
        for alias in node.names:
            name = alias.name
            if name == package_name or name.startswith(package_name + "."):
                targets.append(name)
    elif isinstance(node, ast.ImportFrom):
        if node.level:
            parts = current_module.split(".")
            # `from . import x` inside pkg/a/b.py: level 1 strips b; inside
            # pkg/a/__init__.py (module 'pkg.a') level 1 is pkg.a itself
            strip = node.level - 1 if is_package else node.level
            base_parts = parts[: len(parts) - strip] if strip else parts
            base = ".".join(base_parts)
            if node.module:
                base = f"{base}.{node.module}" if base else node.module
        else:
            base = node.module or ""
        if base == package_name or base.startswith(package_name + "."):
            for alias in node.names:
                candidate = f"{base}.{alias.name}"
                # `from pkg.x import y`: y may be a module or an object
                targets.append(candidate if candidate in known_modules else base)
    # de-dup while keeping order, and resolve to known modules only
    seen: Set[str] = set()
    resolved: List[str] = []
    for t in targets:
        mod = t if t in known_modules else _longest_known_prefix(t, known_modules)
        if mod and mod not in seen:
            seen.add(mod)
            resolved.append(mod)
    return resolved


def _longest_known_prefix(dotted: str, known: Set[str]) -> Optional[str]:
    parts = dotted.split(".")
    for end in range(len(parts), 0, -1):
        prefix = ".".join(parts[:end])
        if prefix in known:
            return prefix
    return None


def load_baseline(path: str) -> Set[str]:
    """Baseline entries: `relpath:line:rule` lines; '#' comments and blanks
    ignored. The checked-in baseline ships empty — this exists so a future
    emergency can land with a debt marker instead of a suppression spray."""
    if not os.path.exists(path):
        return set()
    entries: Set[str] = set()
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if line and not line.startswith("#"):
                entries.add(line)
    return entries


@dataclass
class RunResult:
    violations: List[Violation]
    suppressed: List[Violation]
    baselined: List[Violation]
    # warn-only: `# lint: disable=<rule>` comments whose line no longer
    # triggers the named rule — dead suppressions accumulate as silent
    # blind spots, so the driver surfaces them (they never affect the
    # exit code; deleting the comment clears the warning)
    unused_suppressions: List[Violation] = field(default_factory=list)


def run_passes(
    files: Sequence[SourceFile],
    config,
    passes: Optional[Sequence[Pass]] = None,
    rules: Optional[Set[str]] = None,
    baseline: Optional[Set[str]] = None,
    workers: int = 1,
) -> RunResult:
    """Run the passes; `workers` > 1 fans file-scope passes out over a
    thread pool, one (pass, file) task each — findings are identical to
    the sequential run because the result is canonically sorted below
    (tests/test_analysis_framework.py asserts the equality)."""
    if passes is None:
        from karpenter_core_tpu.analysis import all_passes

        passes = all_passes()
    baseline = baseline or set()
    selected = [
        p for p in passes if not rules or (rules & set(p.rules))
    ]
    raw: List[Violation] = []
    if workers > 1 and len(files) > 1:
        from concurrent.futures import ThreadPoolExecutor

        per_file = [p for p in selected if p.scope == "file"]
        whole_set = [p for p in selected if p.scope != "file"]
        with ThreadPoolExecutor(
            max_workers=workers, thread_name_prefix="lint"
        ) as pool:
            futures = [
                pool.submit(p.run, [f], config)
                for p in per_file for f in files
            ]
            for p in whole_set:
                raw.extend(p.run(files, config))
            for fut in futures:
                raw.extend(fut.result())
    else:
        for p in selected:
            raw.extend(p.run(files, config))
    return filter_findings(raw, files, rules=rules, baseline=baseline)


def _mp_run_file(payload):
    """Process-pool worker: re-load ONE source file in the child and run
    every registry file-scope pass over it. Module-level (picklable);
    takes/returns plain tuples so the only things crossing the pipe are
    primitives and the (dataclass, frozenset-valued) AnalysisConfig.
    Re-loading from disk in the child costs one read+parse but keeps
    SourceFile/ast trees out of pickle entirely."""
    path, relpath, module, config, rules = payload
    from karpenter_core_tpu.analysis import all_passes

    sf = load_tree(path, relpath, module)
    out = []
    for p in all_passes():
        if p.scope != "file":
            continue
        if rules is not None and not (set(p.rules) & rules):
            continue
        for v in p.run([sf], config):
            out.append((v.relpath, v.line, v.rule, v.message))
    return out


def run_passes_multiprocessing(
    files: Sequence[SourceFile],
    config,
    rules: Optional[Set[str]] = None,
    baseline: Optional[Set[str]] = None,
    jobs: int = 2,
) -> RunResult:
    """run_passes with the file-scope passes fanned out over a PROCESS
    pool (`hack/lint.py --jobs`): one (file) task per child call, registry
    passes only (children re-instantiate all_passes() — a custom `passes`
    list can't ship by reference, use run_passes for those). Fileset
    passes run in the parent. Findings are byte-identical to the
    sequential run: the shared filter_findings tail canonically sorts and
    splits (tests/test_analysis_framework.py asserts the equality).
    Workers spawn (not fork): the parent may have jax's thread pools live
    (pytest, --ir in the same process), and forking a multithreaded
    process can deadlock; the worker import surface is stdlib-only so a
    fresh interpreter costs ~30ms."""
    import multiprocessing
    from concurrent.futures import ProcessPoolExecutor

    from karpenter_core_tpu.analysis import all_passes

    raw: List[Violation] = []
    payloads = [
        (f.path, f.relpath, f.module, config, rules) for f in files
    ]
    with ProcessPoolExecutor(
        max_workers=max(1, jobs),
        mp_context=multiprocessing.get_context("spawn"),
    ) as pool:
        for chunk in pool.map(_mp_run_file, payloads, chunksize=8):
            raw.extend(Violation(*t) for t in chunk)
    for p in all_passes():
        if p.scope == "file":
            continue
        if rules is not None and not (set(p.rules) & rules):
            continue
        raw.extend(p.run(files, config))
    return filter_findings(raw, files, rules=rules, baseline=baseline)


def filter_findings(
    raw: Sequence[Violation],
    files: Sequence[SourceFile],
    rules: Optional[Set[str]] = None,
    baseline: Optional[Set[str]] = None,
) -> RunResult:
    """Canonical-sort raw findings and subtract suppressions and the
    baseline — the one spelling of the kept/suppressed/baselined split,
    shared by the sequential, thread-pool, and multiprocessing drivers
    (identical findings across all three is what the parallel tests
    assert). Also flags *unused* suppressions: a `# lint: disable=<rule>`
    whose line produced no finding for that rule. Skipped under a --rule
    filter (only some passes ran, so absence proves nothing) — same
    reason a partial run must not --update-baseline."""
    baseline = baseline or set()
    if rules:
        raw = [v for v in raw if v.rule in rules]
    by_rel: Dict[str, SourceFile] = {f.relpath: f for f in files}
    kept: List[Violation] = []
    suppressed: List[Violation] = []
    baselined: List[Violation] = []
    hit: Set[Tuple[str, int, str]] = set()
    for v in sorted(raw, key=lambda v: (v.relpath, v.line, v.rule, v.message)):
        sf = by_rel.get(v.relpath)
        if sf is not None and sf.suppressed(v.line, v.rule):
            suppressed.append(v)
            hit.add((v.relpath, v.line, v.rule))
            hit.add((v.relpath, v.line, "*"))
        elif v.key() in baseline:
            baselined.append(v)
        else:
            kept.append(v)
    unused: List[Violation] = []
    if not rules:
        for f in files:
            for line, names in sorted(f.suppressions.items()):
                for rule in sorted(names):
                    if (f.relpath, line, rule) not in hit:
                        unused.append(Violation(
                            relpath=f.relpath, line=line,
                            rule="unused-suppression",
                            message=(
                                f"suppression 'lint: disable={rule}' no "
                                "longer matches a finding on this line — "
                                "delete the comment"
                            ),
                        ))
    return RunResult(
        violations=kept, suppressed=suppressed, baselined=baselined,
        unused_suppressions=unused,
    )
