"""Monotonic-time pass (rule `monotonic-time`): time.time() is banned from
the package except at audited wall-clock sites.

Wall clocks jump: NTP slews, manual resets, leap smearing. Any duration,
deadline, or backoff computed from time.time() deltas can go negative or
explode — the reference's clock discipline (monotonic for durations, wall
for object timestamps) is enforced here. The allowlist in
AnalysisConfig.wallclock_allowlist names `relpath::function` sites whose
job IS producing a wall-clock timestamp (log record ts, k8s condition
lastTransitionTime, deletionTimestamp, flight-record stamps); everything
else must use time.monotonic() / time.perf_counter().

Instance-clock references (`clock=time.time` defaults on METHODS, stored on
the instance at construction) are not calls and are not flagged — those
clocks are compared against object wall-clock timestamps by design. But the
same spelling on a MODULE-LEVEL FUNCTION is flagged (rule
`monotonic-time-default`): a function default evaluates ONCE at import, so
the bound clock is a hidden global — a fake clock installed later (tests
monkeypatching time.time, a steppable clock threaded most of the way down)
silently never reaches the call site. Spell it `clock=None` and resolve at
call time instead (deprovisioning/core.lifetime_remaining is the audited
pattern; tests/analysis_fixtures/montime_default_{good,bad}.py pin it).
"""
from __future__ import annotations

import ast
from typing import List, Optional, Sequence, Set

from karpenter_core_tpu.analysis.core import Pass, SourceFile, Violation


class MonotonicTimePass(Pass):
    name = "montime"
    rules = ("monotonic-time", "monotonic-time-default")

    def run(self, files: Sequence[SourceFile], config) -> List[Violation]:
        out: List[Violation] = []
        for f in files:
            if f.tree is None:
                continue
            time_aliases: Set[str] = set()
            bare_time: Set[str] = set()  # names bound to the time.time function
            for node in ast.walk(f.tree):
                if isinstance(node, ast.Import):
                    for alias in node.names:
                        if alias.name == "time":
                            time_aliases.add(alias.asname or "time")
                elif isinstance(node, ast.ImportFrom):
                    if node.module == "time" and not node.level:
                        for alias in node.names:
                            if alias.name == "time":
                                bare_time.add(alias.asname or "time")
            if not time_aliases and not bare_time:
                continue

            def is_time_ref(expr) -> bool:
                return (
                    isinstance(expr, ast.Attribute)
                    and expr.attr == "time"
                    and isinstance(expr.value, ast.Name)
                    and expr.value.id in time_aliases
                ) or (isinstance(expr, ast.Name) and expr.id in bare_time)

            # module-level function defaults: `def f(..., clock=time.time)`
            # at module scope binds the clock AT IMPORT — flag it. Methods
            # (functions inside a ClassDef) are exempt: they stash the
            # injectable clock on the instance at construction, the
            # audited convention.
            for node in f.tree.body:
                if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                defaults = list(node.args.defaults) + [
                    d for d in node.args.kw_defaults if d is not None
                ]
                for default in defaults:
                    if is_time_ref(default):
                        out.append(Violation(
                            relpath=f.relpath,
                            line=default.lineno,
                            rule="monotonic-time-default",
                            message=(
                                "time.time bound as a module-level function "
                                "parameter default — evaluated once at "
                                "import, so later-installed clocks (fakes, "
                                "monkeypatches) never reach the call; use "
                                "`clock=None` and resolve at call time"
                            ),
                        ))
            # map each call to its enclosing function for allowlist checks
            parents = _FuncIndex(f.tree)
            for node in ast.walk(f.tree):
                if not isinstance(node, ast.Call):
                    continue
                func = node.func
                is_time_call = (
                    isinstance(func, ast.Attribute)
                    and func.attr == "time"
                    and isinstance(func.value, ast.Name)
                    and func.value.id in time_aliases
                ) or (isinstance(func, ast.Name) and func.id in bare_time)
                if not is_time_call:
                    continue
                site = f"{f.relpath}::{parents.enclosing(node.lineno) or '<module>'}"
                if site in config.wallclock_allowlist:
                    continue
                out.append(Violation(
                    relpath=f.relpath,
                    line=node.lineno,
                    rule="monotonic-time",
                    message=(
                        "time.time() outside the wall-clock allowlist — use "
                        "time.monotonic()/perf_counter() for durations and "
                        "deadlines, or add the audited site to "
                        "AnalysisConfig.wallclock_allowlist"
                    ),
                ))
        return out


class _FuncIndex:
    """Line -> innermost enclosing function name."""

    def __init__(self, tree: ast.AST) -> None:
        self.spans: List[tuple] = []
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                end = getattr(node, "end_lineno", node.lineno)
                self.spans.append((node.lineno, end, node.name))
        # innermost = narrowest span containing the line
        self.spans.sort(key=lambda s: (s[1] - s[0]))

    def enclosing(self, line: int) -> Optional[str]:
        for lo, hi, name in self.spans:
            if lo <= line <= hi:
                return name
        return None
