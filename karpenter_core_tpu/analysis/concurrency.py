"""Concurrency-discipline pass.

Rule `bare-except`: `except:` swallows KeyboardInterrupt/SystemExit and
masks the cancellation paths the operator's shutdown depends on — name the
exception (`except Exception:` at minimum).

Rule `thread-discipline`: every threading.Thread must be constructed with
an explicit `daemon=` AND `name=`. A non-daemon background thread wedges
process exit (the operator's watch pumps and probe threads must never
outlive main), and an unnamed one is invisible in stack dumps — py-spy on a
wedged operator showing eight `Thread-5`s is how concurrency bugs stay
unfixed.

Rule `guarded-by`: within a class that owns a threading lock, an attribute
written both inside `with self.<lock>:` blocks and outside them (in any
non-init method) has an inconsistent locking story — either the lock is
unnecessary or the unguarded write is a race. Inference is syntactic:
  - lock attributes: `self.X = threading.Lock()/RLock()` anywhere in the class
  - guarded write: an Assign/AugAssign to `self.attr` lexically inside a
    `with self.<lock>` block in the same method
  - `__init__`/`__post_init__`/`__new__` writes are construction, exempt
  - methods named `*_locked` (config.locked_suffix) are callee-guarded by
    convention: the caller holds the lock, so their writes count as guarded

Rule `guarded-by-v2` (ISSUE 13): the lockset upgrade of `guarded-by`.
Where v1 reduces "guarded" to a boolean (inside ANY `with self.<lock>:`),
v2 computes an intraprocedural lockset summary per method — which lock
attributes are held at each write, flowing through `with` nesting AND the
`self.X.acquire()` / `self.X.release()` statement pattern v1 cannot see.
Per attribute, the candidate lockset is the intersection of every
non-exempt write's held set (Eraser's discipline, statically): if some
write is guarded but the intersection is EMPTY, the writes missing the
protecting lock are flagged — catching an attribute written under
`self._lock_a` in one method and `self._lock_b` (or no lock) in another,
even when the second method never mentions the first lock. Findings v1
already reports (same line, same attribute) are not re-reported.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Sequence, Set, Tuple

from karpenter_core_tpu.analysis.core import Pass, SourceFile, Violation

INIT_METHODS = {"__init__", "__post_init__", "__new__", "__init_subclass__"}
LOCK_FACTORIES = {"Lock", "RLock"}


def _self_attr(node: ast.expr) -> str:
    """'attr' when node is `self.attr`, else ''."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return ""


def _is_lock_ctor(value: ast.expr) -> bool:
    if not isinstance(value, ast.Call):
        return False
    func = value.func
    name = func.attr if isinstance(func, ast.Attribute) else (
        func.id if isinstance(func, ast.Name) else ""
    )
    return name in LOCK_FACTORIES


@dataclass
class _Write:
    attr: str
    line: int
    method: str
    guarded: bool


@dataclass
class _LocksetWrite:
    attr: str
    line: int
    method: str
    lockset: frozenset


class ConcurrencyPass(Pass):
    name = "concurrency"
    rules = ("bare-except", "thread-discipline", "guarded-by", "guarded-by-v2")

    def run(self, files: Sequence[SourceFile], config) -> List[Violation]:
        out: List[Violation] = []
        for f in files:
            if f.tree is None:
                continue
            # names the threading module is bound to (`import threading as t`)
            # and names Thread itself is bound to (`from threading import Thread`)
            mod_aliases: set = set()
            thread_names: set = set()
            for node in ast.walk(f.tree):
                if isinstance(node, ast.Import):
                    for alias in node.names:
                        if alias.name == "threading":
                            mod_aliases.add(alias.asname or "threading")
                elif isinstance(node, ast.ImportFrom):
                    if node.module == "threading" and not node.level:
                        for alias in node.names:
                            if alias.name == "Thread":
                                thread_names.add(alias.asname or "Thread")
            for node in ast.walk(f.tree):
                if isinstance(node, ast.ExceptHandler) and node.type is None:
                    out.append(Violation(
                        relpath=f.relpath, line=node.lineno, rule="bare-except",
                        message=(
                            "bare `except:` catches KeyboardInterrupt/SystemExit"
                            " — catch Exception (or narrower) instead"
                        ),
                    ))
                elif isinstance(node, ast.Call) and self._is_thread_ctor(
                    node, mod_aliases, thread_names
                ):
                    kwargs = {kw.arg for kw in node.keywords if kw.arg}
                    missing = [k for k in ("daemon", "name") if k not in kwargs]
                    if missing:
                        out.append(Violation(
                            relpath=f.relpath, line=node.lineno,
                            rule="thread-discipline",
                            message=(
                                "threading.Thread without explicit "
                                + " and ".join(f"{k}=" for k in missing)
                                + " — background threads must be daemonized "
                                "and named for stack-dump triage"
                            ),
                        ))
                elif isinstance(node, ast.ClassDef):
                    out.extend(self._check_guarded_by(f, node, config))
        return out

    @staticmethod
    def _is_thread_ctor(node: ast.Call, mod_aliases: set, thread_names: set) -> bool:
        func = node.func
        if isinstance(func, ast.Attribute):
            return (
                func.attr == "Thread"
                and isinstance(func.value, ast.Name)
                and func.value.id in mod_aliases
            )
        return isinstance(func, ast.Name) and func.id in thread_names

    def _check_guarded_by(
        self, f: SourceFile, cls: ast.ClassDef, config
    ) -> List[Violation]:
        lock_attrs: Set[str] = set()
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign) and _is_lock_ctor(node.value):
                for target in node.targets:
                    attr = _self_attr(target)
                    if attr:
                        lock_attrs.add(attr)
        if not lock_attrs:
            return []

        writes: List[_Write] = []
        for method in cls.body:
            if not isinstance(method, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            callee_guarded = method.name in INIT_METHODS or method.name.endswith(
                config.locked_suffix
            )
            self._collect_writes(
                method, method.name, lock_attrs, in_lock=callee_guarded,
                init=method.name in INIT_METHODS, writes=writes,
            )

        by_attr: Dict[str, List[_Write]] = {}
        for w in writes:
            by_attr.setdefault(w.attr, []).append(w)

        out: List[Violation] = []
        v1_flagged: Set[Tuple[int, str]] = set()
        for attr, ws in sorted(by_attr.items()):
            if attr in lock_attrs:
                continue
            guarded = [w for w in ws if w.guarded]
            unguarded = [w for w in ws if not w.guarded]
            if guarded and unguarded:
                guard_lines = ", ".join(
                    f"{w.method}:{w.line}" for w in guarded[:3]
                )
                for w in unguarded:
                    v1_flagged.add((w.line, attr))
                    out.append(Violation(
                        relpath=f.relpath, line=w.line, rule="guarded-by",
                        message=(
                            f"{cls.name}.{attr} written without the lock in "
                            f"{w.method}() but under it at {guard_lines} — "
                            "hold the lock at every write or rename the "
                            f"method with the '{config.locked_suffix}' suffix "
                            "if the caller holds it"
                        ),
                    ))
        out.extend(
            self._check_guarded_by_v2(f, cls, config, lock_attrs, v1_flagged)
        )
        return out

    # -- guarded-by-v2: intraprocedural lockset summaries --------------------

    def _check_guarded_by_v2(
        self, f: SourceFile, cls: ast.ClassDef, config,
        lock_attrs: Set[str], v1_flagged: Set[Tuple[int, str]],
    ) -> List[Violation]:
        writes: List[_LocksetWrite] = []
        for method in cls.body:
            if not isinstance(method, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if method.name in INIT_METHODS or method.name.endswith(
                config.locked_suffix
            ):
                continue  # construction / callee-guarded: exempt, and they
                # must not poison the intersection either
            self._lockset_flow(
                method.body, frozenset(), method.name, lock_attrs, writes
            )

        by_attr: Dict[str, List[_LocksetWrite]] = {}
        for w in writes:
            by_attr.setdefault(w.attr, []).append(w)

        out: List[Violation] = []
        for attr, ws in sorted(by_attr.items()):
            if attr in lock_attrs:
                continue
            if not any(w.lockset for w in ws):
                continue  # never written under a lock: v2 has no evidence
            common = frozenset.intersection(*[w.lockset for w in ws])
            if common:
                continue  # a consistent protecting lock exists
            # the protecting candidate: the lock most writes hold
            counts: Dict[str, int] = {}
            for w in ws:
                for lock in w.lockset:
                    counts[lock] = counts.get(lock, 0) + 1
            protect = max(sorted(counts), key=lambda k: counts[k])
            held_lines = ", ".join(
                f"{w.method}:{w.line}" for w in ws if protect in w.lockset
            )
            for w in ws:
                if protect in w.lockset:
                    continue
                if (w.line, attr) in v1_flagged:
                    continue  # v1 already reports this exact write
                under = ", ".join(sorted(w.lockset)) or "no lock"
                out.append(Violation(
                    relpath=f.relpath, line=w.line, rule="guarded-by-v2",
                    message=(
                        f"{cls.name}.{attr} written under [{under}] in "
                        f"{w.method}() but under {protect} at {held_lines} "
                        "— the write locksets share no common lock; hold "
                        f"{protect} at every write (or rename the method "
                        f"with the '{config.locked_suffix}' suffix if the "
                        "caller holds it)"
                    ),
                ))
        return out

    def _lockset_flow(
        self,
        body: List[ast.stmt],
        held: frozenset,
        method: str,
        lock_attrs: Set[str],
        writes: List[_LocksetWrite],
    ) -> frozenset:
        """Statement-ordered lockset flow through one body: `with self.X:`
        scopes X over its block; `self.X.acquire(...)` holds X from that
        statement on (conditional acquires count — the common pattern is
        `if not self.X.acquire(False): return`); `self.X.release()` drops
        it. Compound statements recurse per sub-body, so a `with` nested
        in an `if`/`try` still scopes correctly; acquires/releases found
        anywhere in a compound statement propagate to its siblings."""
        current = held
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # nested defs are their own analysis context
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                block = set(current)
                for item in stmt.items:
                    expr = item.context_expr
                    if isinstance(expr, ast.Call):
                        expr = expr.func
                    attr = _self_attr(expr)
                    if not attr and isinstance(expr, ast.Attribute):
                        attr = _self_attr(expr.value)
                    if attr in lock_attrs:
                        block.add(attr)
                self._lockset_flow(
                    stmt.body, frozenset(block), method, lock_attrs, writes
                )
                # acquire()/release() inside the block outlive it
                current = self._apply_lock_calls(stmt, current, lock_attrs)
                continue
            sub_bodies = self._sub_bodies(stmt)
            if sub_bodies:
                # header acquires (`if not self.X.acquire(): return`) are
                # held inside the bodies; each body starts from there
                entry = self._apply_lock_calls(
                    stmt, current, lock_attrs, headers_only=True
                )
                for sub in sub_bodies:
                    self._lockset_flow(sub, entry, method, lock_attrs, writes)
                current = self._apply_lock_calls(stmt, current, lock_attrs)
                continue
            # simple statement: record writes at the CURRENT lockset
            for node in ast.walk(stmt):
                if isinstance(node, (ast.Assign, ast.AugAssign)):
                    targets = (
                        node.targets if isinstance(node, ast.Assign)
                        else [node.target]
                    )
                    for target in targets:
                        attr = _self_attr(target)
                        if attr:
                            writes.append(_LocksetWrite(
                                attr=attr, line=node.lineno, method=method,
                                lockset=current,
                            ))
            current = self._apply_lock_calls(stmt, current, lock_attrs)
        return current

    @staticmethod
    def _sub_bodies(stmt: ast.stmt) -> List[List[ast.stmt]]:
        if isinstance(stmt, ast.If):
            return [stmt.body, stmt.orelse]
        if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
            return [stmt.body, stmt.orelse]
        if isinstance(stmt, ast.Try):
            return (
                [stmt.body]
                + [h.body for h in stmt.handlers]
                + [stmt.orelse, stmt.finalbody]
            )
        return []

    @staticmethod
    def _apply_lock_calls(
        stmt: ast.stmt, current: frozenset, lock_attrs: Set[str],
        headers_only: bool = False,
    ) -> frozenset:
        """`current` after the acquire()/release() calls in `stmt` (or in
        its header expressions only: the If test / For iter / While test),
        nested defs excluded."""
        roots: List[ast.AST]
        if headers_only:
            roots = [
                n for n in (
                    getattr(stmt, "test", None), getattr(stmt, "iter", None)
                ) if n is not None
            ]
        else:
            roots = [stmt]
        acquired, released = set(), set()
        stack: List[ast.AST] = list(roots)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("acquire", "release")
            ):
                attr = _self_attr(node.func.value)
                if attr in lock_attrs:
                    (acquired if node.func.attr == "acquire"
                     else released).add(attr)
            stack.extend(ast.iter_child_nodes(node))
        if not (acquired or released):
            return current
        return frozenset((set(current) | acquired) - released)

    def _collect_writes(
        self,
        node: ast.AST,
        method: str,
        lock_attrs: Set[str],
        in_lock: bool,
        init: bool,
        writes: List[_Write],
    ) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue  # nested defs get their own analysis context: skip
            child_in_lock = in_lock
            if isinstance(child, ast.With):
                for item in child.items:
                    expr = item.context_expr
                    if isinstance(expr, ast.Call):
                        expr = expr.func
                    attr = _self_attr(expr)
                    if not attr and isinstance(expr, ast.Attribute):
                        # with self._lock.acquire_timeout(...): the lock is
                        # the attribute's VALUE, one level down
                        attr = _self_attr(expr.value)
                    if attr in lock_attrs:
                        child_in_lock = True
            if isinstance(child, (ast.Assign, ast.AugAssign)):
                targets = (
                    child.targets if isinstance(child, ast.Assign) else [child.target]
                )
                for target in targets:
                    attr = _self_attr(target)
                    if attr and not init:
                        writes.append(_Write(
                            attr=attr, line=child.lineno, method=method,
                            guarded=in_lock,
                        ))
            self._collect_writes(
                child, method, lock_attrs, child_in_lock, init, writes
            )
        return None
