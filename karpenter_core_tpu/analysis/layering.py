"""Layering pass: subpackage dependency DAG + module-scope import cycles.

Rule `layering`: a module in subpackage A importing subpackage B at module
scope when B is not in A's allowed set (config.DEFAULT_LAYERING). The
canonical violation this exists to prevent: solver/ importing controllers/
— the solver is a backend the controllers call, never the reverse.

Rule `import-cycle`: strongly-connected components (size > 1) in the
module-scope import graph. Python tolerates some cycles depending on import
order; none of them are intentional here, and the ones that "work" break
the moment an entrypoint imports the other module first.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Sequence, Set, Tuple

from karpenter_core_tpu.analysis.core import (
    Pass,
    SourceFile,
    Violation,
    module_scope_imports,
    resolve_import_targets,
)


class LayeringPass(Pass):
    name = "layering"
    rules = ("layering", "import-cycle")
    scope = "fileset"  # needs the global import graph: never per-file

    def run(self, files: Sequence[SourceFile], config) -> List[Violation]:
        out: List[Violation] = []
        known = {f.module for f in files if f.module}
        by_module = {f.module: f for f in files if f.module}
        # module -> [(target_module, line)]
        graph: Dict[str, List[Tuple[str, int]]] = {}
        for f in files:
            if f.tree is None or f.module is None:
                continue
            edges: List[Tuple[str, int]] = []
            for node in module_scope_imports(f.tree):
                for target in resolve_import_targets(
                    node, f.module, known, config.package_name,
                    is_package=f.relpath.endswith("__init__.py"),
                ):
                    if target != f.module:
                        edges.append((target, node.lineno))
            graph[f.module] = edges

        # -- DAG check ----------------------------------------------------
        for module, edges in sorted(graph.items()):
            src_sub = config.subpackage_of(module)
            allowed = config.layering.get(src_sub)
            for target, line in edges:
                dst_sub = config.subpackage_of(target)
                if not dst_sub or dst_sub == src_sub:
                    continue
                if not src_sub:
                    continue  # root-level modules are unconstrained
                if allowed is None:
                    if config.layering_strict:
                        out.append(Violation(
                            relpath=by_module[module].relpath,
                            line=line,
                            rule="layering",
                            message=(
                                f"subpackage '{src_sub}' has no declared layer"
                                " — add it to the dependency DAG"
                                " (analysis/config.py DEFAULT_LAYERING)"
                            ),
                        ))
                    continue
                if dst_sub not in allowed:
                    out.append(Violation(
                        relpath=by_module[module].relpath,
                        line=line,
                        rule="layering",
                        message=(
                            f"module-scope import of '{target}':"
                            f" '{src_sub}' may not depend on '{dst_sub}'"
                            f" (allowed: {', '.join(sorted(allowed)) or 'none'})"
                        ),
                    ))

        # -- cycle check --------------------------------------------------
        for scc in _tarjan({m: [t for t, _ in e] for m, e in graph.items()}):
            if len(scc) < 2:
                continue
            cycle = sorted(scc)
            for module in cycle:
                line = next(
                    (ln for t, ln in graph[module] if t in scc), 1
                )
                out.append(Violation(
                    relpath=by_module[module].relpath,
                    line=line,
                    rule="import-cycle",
                    message=(
                        "module-scope import cycle: "
                        + " <-> ".join(cycle)
                    ),
                ))
        return out


def _tarjan(graph: Dict[str, List[str]]) -> List[Set[str]]:
    """Iterative Tarjan SCC (the module graph is deep enough that the
    recursive form can hit the default recursion limit)."""
    index: Dict[str, int] = {}
    lowlink: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    sccs: List[Set[str]] = []
    counter = [0]

    for root in graph:
        if root in index:
            continue
        work: List[Tuple[str, int]] = [(root, 0)]
        while work:
            node, ei = work[-1]
            if ei == 0:
                index[node] = lowlink[node] = counter[0]
                counter[0] += 1
                stack.append(node)
                on_stack.add(node)
            advanced = False
            targets = [t for t in graph.get(node, []) if t in graph]
            while ei < len(targets):
                target = targets[ei]
                ei += 1
                if target not in index:
                    work[-1] = (node, ei)
                    work.append((target, 0))
                    advanced = True
                    break
                if target in on_stack:
                    lowlink[node] = min(lowlink[node], index[target])
            if advanced:
                continue
            work.pop()
            if lowlink[node] == index[node]:
                scc: Set[str] = set()
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.add(w)
                    if w == node:
                        break
                sccs.append(scc)
            if work:
                parent, _ = work[-1]
                lowlink[parent] = min(lowlink[parent], lowlink[node])
    return sccs
