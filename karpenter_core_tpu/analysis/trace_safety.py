"""Trace-safety pass (rule `trace-safety`): host-side Python on traced
values inside jit/pjit/shard_map-wrapped functions.

Inside a traced body, a Python `if`/`while` on a traced array raises a
ConcretizationTypeError at best; at worst the branch silently becomes a
compile-time constant keyed into the trace, and every new value RECOMPILES
the program — which blows the <1s p99 Solve() target the whole solver is
built around. `.item()` / `bool()` / `float()` / `int()` coercions and host
`np.` calls on traced arguments force a device sync or bake a constant the
same way.

Detection is name-taint based and deliberately conservative:

  1. Find traced functions: `@jax.jit`-style decorators, and functions whose
     NAME is passed to jit/pjit/shard_map/vmap in the same module (assignment
     chains like `sharded = shard_map(body, ...); jax.jit(sharded)` are
     followed one level). NamedSharding-jit mesh-program bodies (ISSUE 8:
     `jax.jit(body, in_shardings=..., donate_argnums=...)` and bodies that
     apply with_sharding_constraint via a SpecLayout) are the same `jit`
     spelling, so they are covered by the same name-based detection.
  2. Taint the function's parameters, then propagate through simple
     assignments whose RHS mentions a tainted name.
  3. Flag `if`/`while` tests, coercion calls, `np.*` calls, and explicit
     host transfers (`jax.device_get` / `device_get`) that touch a
     tainted name — inside a mesh program a host transfer is a
     cross-device sync of EVERY shard, not just one chip's stall.
     (`device_put` inside a jitted body is deliberately NOT flagged: it
     is on-device placement, not a host round-trip — see the jaxpr
     tripwire in tests/test_sharded.py.)

Functions produced by factories (`jax.jit(make_device_run(...))`) are out of
static reach — the kernels those factories close over are covered by their
own fixture-style unit tests and by the runtime differential suites.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set

from karpenter_core_tpu.analysis.core import Pass, SourceFile, Violation

COERCIONS = {"bool", "float", "int"}
NUMPY_ALIASES = {"np", "numpy"}
# explicit host-transfer calls: flagged on tainted values inside any traced
# body — jit, shard_map, or a NamedSharding-jit mesh-program body, where
# the sync stalls every device on the mesh. device_put is NOT here: inside
# a jitted body it lowers to on-device placement, not a host round-trip.
HOST_TRANSFERS = {"device_get"}


def _called_name(func: ast.expr) -> Optional[str]:
    """`jax.jit` -> 'jit', `pjit` -> 'pjit', `jax.experimental.shard_map.shard_map`
    -> 'shard_map'."""
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


class _NameCollector(ast.NodeVisitor):
    def __init__(self) -> None:
        self.names: Set[str] = set()

    def visit_Name(self, node: ast.Name) -> None:
        self.names.add(node.id)


def _names_in(node: ast.AST) -> Set[str]:
    c = _NameCollector()
    c.visit(node)
    return c.names


class TraceSafetyPass(Pass):
    name = "trace_safety"
    rules = ("trace-safety",)

    def run(self, files: Sequence[SourceFile], config) -> List[Violation]:
        out: List[Violation] = []
        wrappers = set(config.trace_wrappers)
        for f in files:
            if f.tree is None:
                continue
            out.extend(self._check_module(f, wrappers))
        return out

    def _check_module(self, f: SourceFile, wrappers: Set[str]) -> List[Violation]:
        # index every function definition in the module by name (innermost
        # definition wins — good enough for the closure-factory idiom)
        defs: Dict[str, ast.FunctionDef] = {}
        # name -> name it aliases via `x = wrapper(y, ...)`
        aliases: Dict[str, str] = {}
        for node in ast.walk(f.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs[node.name] = node
            elif isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                callee = _called_name(node.value.func)
                if callee in wrappers or callee == "vmap":
                    arg0 = node.value.args[0] if node.value.args else None
                    if isinstance(arg0, ast.Name) and len(node.targets) == 1:
                        target = node.targets[0]
                        if isinstance(target, ast.Name):
                            aliases[target.id] = arg0.id

        traced: Set[str] = set()
        for node in ast.walk(f.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    dec_name = _called_name(
                        dec.func if isinstance(dec, ast.Call) else dec
                    )
                    if dec_name in wrappers:
                        traced.add(node.name)
                    elif dec_name == "partial" and isinstance(dec, ast.Call):
                        if dec.args and _called_name(dec.args[0]) in wrappers:
                            traced.add(node.name)
            elif isinstance(node, ast.Call):
                callee = _called_name(node.func)
                if callee in wrappers:
                    for arg in node.args[:1]:
                        if isinstance(arg, ast.Name):
                            name = arg.id
                            # follow one alias hop: jit(sharded) where
                            # sharded = shard_map(body, ...)
                            name = aliases.get(name, name)
                            traced.add(name)

        out: List[Violation] = []
        for name in sorted(traced):
            fn = defs.get(name)
            if fn is not None:
                out.extend(self._check_function(f, fn))
        return out

    def _check_function(self, f: SourceFile, fn: ast.FunctionDef) -> List[Violation]:
        tainted: Set[str] = set()
        a = fn.args
        for arg in (
            list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)
            + ([a.vararg] if a.vararg else [])
            + ([a.kwarg] if a.kwarg else [])
        ):
            tainted.add(arg.arg)

        # forward taint propagation through simple assignments; iterate to a
        # fixpoint so `a = x; b = a` taints b regardless of nesting order
        assigns: List[ast.Assign] = [
            n for n in ast.walk(fn) if isinstance(n, (ast.Assign, ast.AugAssign))
        ]
        changed = True
        while changed:
            changed = False
            for node in assigns:
                value = node.value
                if not (_names_in(value) & tainted):
                    continue
                targets = node.targets if isinstance(node, ast.Assign) else [node.target]
                for target in targets:
                    for t in ast.walk(target):
                        if isinstance(t, ast.Name) and t.id not in tainted:
                            tainted.add(t.id)
                            changed = True

        out: List[Violation] = []

        def flag(node: ast.AST, message: str) -> None:
            out.append(Violation(
                relpath=f.relpath, line=node.lineno, rule="trace-safety",
                message=f"in traced function '{fn.name}': {message}",
            ))

        for node in ast.walk(fn):
            if isinstance(node, (ast.If, ast.While)):
                hit = _names_in(node.test) & tainted
                if hit:
                    kind = "if" if isinstance(node, ast.If) else "while"
                    flag(node, (
                        f"Python `{kind}` on traced value(s) "
                        f"{', '.join(sorted(hit))} — use jnp.where/lax.cond, "
                        "or hoist the branch out of the traced body"
                    ))
            elif isinstance(node, ast.Call):
                callee = node.func
                transfer = None
                if isinstance(callee, ast.Name) and callee.id in HOST_TRANSFERS:
                    transfer = callee.id
                elif (
                    isinstance(callee, ast.Attribute)
                    and callee.attr in HOST_TRANSFERS
                ):
                    transfer = callee.attr
                if transfer is not None:
                    hit = set()
                    for arg in list(node.args) + [kw.value for kw in node.keywords]:
                        hit |= _names_in(arg) & tainted
                    if hit:
                        flag(node, (
                            f"`{transfer}` host transfer on traced value(s) "
                            f"{', '.join(sorted(hit))} — inside a mesh "
                            "program this syncs every device; fetch after "
                            "the program returns"
                        ))
                    continue
                if isinstance(callee, ast.Name) and callee.id in COERCIONS:
                    hit = set()
                    for arg in node.args:
                        hit |= _names_in(arg) & tainted
                    if hit:
                        flag(node, (
                            f"`{callee.id}()` coerces traced value(s) "
                            f"{', '.join(sorted(hit))} to a host scalar "
                            "(forces a device sync / constant-folds the trace)"
                        ))
                elif isinstance(callee, ast.Attribute):
                    if callee.attr == "item":
                        base = callee.value
                        hit = _names_in(base) & tainted
                        if hit:
                            flag(node, (
                                f"`.item()` on traced value(s) "
                                f"{', '.join(sorted(hit))} — host sync inside "
                                "the traced body"
                            ))
                    elif (
                        isinstance(callee.value, ast.Name)
                        and callee.value.id in NUMPY_ALIASES
                    ):
                        hit = set()
                        for arg in list(node.args) + [kw.value for kw in node.keywords]:
                            hit |= _names_in(arg) & tainted
                        if hit:
                            flag(node, (
                                f"host-side `{callee.value.id}.{callee.attr}` on "
                                f"traced value(s) {', '.join(sorted(hit))} — "
                                "use jax.numpy inside the traced body"
                            ))
        return out
