"""Pluggable AST-based static analysis for karpenter_core_tpu.

The Go reference leans on `go vet` and the race detector in presubmit; this
package is the Python/JAX analog, grown out of the one-off no-print guard
from the observability PR. Each pass is a `Pass` subclass registered in
`all_passes()`; `hack/lint.py` is the CLI driver (`make lint`, fatal in
`make verify`). Per-line suppression: `# lint: disable=<rule>[,<rule>...]`.

Passes (rule ids in parentheses):
  trace_safety  (trace-safety)    — host-side Python control flow/coercions
                                    inside jit/pjit/shard_map-traced bodies
  layering      (layering,        — subpackage dependency DAG + module-scope
                 import-cycle)      import-cycle detection
  envdiscipline (env-flags)       — all os.environ access funnels through
                                    obs/envflags.py
  montime       (monotonic-time)  — time.time() banned outside the audited
                                    wall-clock allowlist
  concurrency   (bare-except,     — exception/thread/lock discipline with
                 thread-discipline,  guarded-by inference for self._lock;
                 guarded-by,         v2 adds intraprocedural lockset
                 guarded-by-v2)      summaries incl. acquire()/release()
  procdiscipline (proc-group,     — process-group spawn discipline,
                 proc-kill-group,    killpg convention, joined non-daemon
                 thread-join)        child-waiter threads
  atomicwrite   (atomic-write)    — artifact writes must be atomic
                                    (write-temp-fsync-rename) for the
                                    resume/health/replay readers
  noprint       (no-print)        — bare print() in production code
  metriclabels  (metric-label-keys, — instrument label key sets must be
                 metric-tenant-guard) static literals/tracked dicts;
                                    "tenant" values route through the
                                    cardinality guard (obs/reqctx)
  recompileguard (recompile-guard) — runtime collection sizes (len of
                                    pods/nodes/types) must pass through
                                    the bucket ladder before reaching a
                                    jit/pjit boundary or kernel-factory
                                    static argument

A second backend, analysis/irlint (rule ids ir-*), checks the LOWERED
jaxpr/HLO of every compiled program the solver can mint against per-family
contracts — it needs jax + staged programs, so it runs via
`hack/lint.py --ir` (`make irlint`), not in all_passes().
"""
from karpenter_core_tpu.analysis.core import (  # noqa: F401
    Pass,
    SourceFile,
    Violation,
    load_baseline,
    load_tree,
    run_passes,
)
from karpenter_core_tpu.analysis.config import AnalysisConfig, default_config  # noqa: F401


def all_passes():
    """Instantiate every registered pass, import-cycle-free at module load."""
    from karpenter_core_tpu.analysis.atomicwrite import AtomicWritePass
    from karpenter_core_tpu.analysis.concurrency import ConcurrencyPass
    from karpenter_core_tpu.analysis.envdiscipline import EnvDisciplinePass
    from karpenter_core_tpu.analysis.layering import LayeringPass
    from karpenter_core_tpu.analysis.metriclabels import MetricLabelsPass
    from karpenter_core_tpu.analysis.montime import MonotonicTimePass
    from karpenter_core_tpu.analysis.noprint import NoPrintPass
    from karpenter_core_tpu.analysis.procdiscipline import ProcessDisciplinePass
    from karpenter_core_tpu.analysis.recompileguard import RecompileGuardPass
    from karpenter_core_tpu.analysis.trace_safety import TraceSafetyPass

    return [
        TraceSafetyPass(),
        LayeringPass(),
        EnvDisciplinePass(),
        MonotonicTimePass(),
        ConcurrencyPass(),
        ProcessDisciplinePass(),
        AtomicWritePass(),
        NoPrintPass(),
        MetricLabelsPass(),
        RecompileGuardPass(),
    ]
