"""irlint — kernel contracts: static analysis over the LOWERED IR.

The AST passes (analysis/*.py, rule ids without a prefix) read source
text; this package is the second backend. It stages every compiled
program the solver can mint — solve x {S,M,L,XL} x screen modes,
prescreen, refresh, replan, segmented partition/lane, the GSPMD mesh
variant — through the PURE builder seams (tpu_solver.stage_family_programs,
no cache entries, no proghealth mints), then checks each program's
jaxpr (and, for the mesh family, post-SPMD compiled HLO) against the
declarative per-family contracts in contracts.py (rule ids `ir-*`).

Violations are ordinary `Violation`s anchored at the contract's
declaration line in contracts.py, so the whole kept/suppressed/baselined
pipeline — per-line disable comments naming an ir-* rule, the baseline
file, --rule filtering, SARIF output — applies unchanged. Entry points:

  * `hack/lint.py --ir` (`make irlint`) — the CLI sweep; needs jax, runs
    on CPU with a forced 8-device host platform for the mesh family;
  * `IRContractsPass` — the Pass-shaped wrapper the driver invokes; NOT
    registered in analysis.all_passes() (plain `make lint` must not pay
    a jax startup);
  * engine walkers (scan_dot_output_dims, collective_counts, ...) —
    imported directly by tests/test_perf_floor.py and friends, so test
    tripwires and CI contracts share one spelling of every predicate.

Layering note: this subpackage imports jax and the solver at FUNCTION
scope only (families.py / engine.ProgramIR), which the layering pass
exempts — `analysis` stays a module-scope leaf.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

from karpenter_core_tpu.analysis.core import Pass, SourceFile, Violation


class IRContractsPass(Pass):
    """Pass-shaped wrapper over the staged-program contract sweep. scope
    is "fileset": one run stages the whole family and evaluates every
    contract (per-file parallelism is meaningless here — the unit of
    work is a staged program, not a source file)."""

    name = "irlint"
    scope = "fileset"

    def __init__(self, tiers: Optional[Sequence[str]] = None,
                 families: Optional[Sequence[str]] = None,
                 compile_level: bool = True):
        self.tiers = tuple(tiers) if tiers is not None else None
        self.families = tuple(families) if families is not None else None
        self.compile_level = compile_level

    @property
    def rules(self):  # type: ignore[override]
        from karpenter_core_tpu.analysis.irlint import contracts

        return contracts.rule_ids()

    def run(self, files: Sequence[SourceFile], config) -> List[Violation]:
        # `files` is the AST corpus — unused: the inputs here are staged
        # programs. The signature stays Pass-shaped so the driver's
        # filter_findings tail (suppressions, baseline, sorting) applies.
        del files, config
        from karpenter_core_tpu.analysis.irlint import engine, families

        programs, extra_ctx = families.stage_all(
            tiers=self.tiers, families=self.families,
            compile_level=self.compile_level,
        )
        return engine.evaluate(programs, extra_ctx=extra_ctx)
