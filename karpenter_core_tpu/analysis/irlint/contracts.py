"""The per-family IR contract catalog — contracts are DATA.

Each entry binds a rule id to a predicate over one staged program's
jaxpr/HLO (engine.ProgramIR) plus the applicability filter (families,
screen modes, mesh-ness, compile level). Violations anchor at the
`@contract` declaration line in THIS file, so the standard
`relpath:line:rule` suppression and baseline grammar covers IR findings
without any new machinery — a per-line disable comment naming the ir-*
rule beside a contract mutes it exactly like an AST rule.

Budgets live here, once: the structural tripwires in
tests/test_perf_floor.py assert through the same predicates
(engine.check_family_counts / off_ladder_axes / scan_dot_output_dims), so
a budget can only change by editing this catalog. docs/static-analysis.md
carries the human-readable table; `how to add a contract` is documented
there — in short: declare it here with `@contract`, give it a rule id
starting with `ir-`, and the driver, suppression grammar, docs registry
test, and `--rule` filtering all pick it up from CONTRACTS.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, List, Optional, Tuple

from karpenter_core_tpu.analysis.irlint import engine

RELPATH = "karpenter_core_tpu/analysis/irlint/contracts.py"

# -- budget tables (the one spelling) ---------------------------------------

# mesh solve FLOAT-collective inventory (docs/sharding.md: one all_gather
# seam per precompute reassembly; the scan runs replicated; no contraction
# axis is ever split, so no float reduction may cross the mesh — float
# re-association is exactly what would break the byte-identity guarantee
# tests/test_sharded.py asserts). The budget counts collectives whose
# result dtype is floating (engine.FLOAT_DTYPES): the SPMD partitioner
# also mints small pred/u8 bookkeeping collectives that are
# backend-dependent noise, bitwise-safe, and NOT budgeted.
MESH_COLLECTIVE_BUDGET = {
    "all-gather": 2,
    "all-reduce": 0,
    "reduce-scatter": 0,
}

# compiled programs one staging may mint per (tier, screen-mode) —
# the same ceilings the live-cache tripwires enforce: a solve entry is
# the (solve, prescreen) pair, refresh warms one (8,8) budget, replan one
# K bucket, segment the partitioner + one lane program.
PER_TIER_PROGRAM_BUDGET = {
    "solve": 1,
    "prescreen": 1,
    "refresh": 1,
    "replan": 1,
    "segment": 2,
}


@dataclass(frozen=True)
class Contract:
    rule: str
    doc: str
    check: Callable
    line: int
    families: Optional[frozenset] = None   # None = every family
    modes: Optional[frozenset] = None      # None = every screen mode
    mesh: Optional[bool] = None            # None = mesh and single alike
    compile_level: bool = False            # needs compiled HLO (tier-S only)
    whole_family: bool = False             # check(all_programs, extra) form

    def applies(self, prog: "engine.ProgramIR") -> bool:
        if self.families is not None and prog.family not in self.families:
            return False
        if self.modes is not None and prog.ctx.screen_mode not in self.modes:
            return False
        if self.mesh is not None and prog.ctx.mesh != self.mesh:
            return False
        if self.compile_level and not prog.ctx.compile_level:
            return False
        return True


CONTRACTS: List[Contract] = []


def contract(rule: str, doc: str, families=None, modes=None, mesh=None,
             compile_level: bool = False, whole_family: bool = False):
    """Register a contract; the decorated predicate's source line is the
    violation anchor (suppressions/baseline key on it)."""

    def register(fn: Callable) -> Callable:
        CONTRACTS.append(Contract(
            rule=rule, doc=doc, check=fn,
            line=fn.__code__.co_firstlineno,
            families=frozenset(families) if families else None,
            modes=frozenset(modes) if modes else None,
            mesh=mesh, compile_level=compile_level,
            whole_family=whole_family,
        ))
        return fn

    return register


def rule_ids() -> Tuple[str, ...]:
    """Every ir-* rule id, sorted — the docs/registry cross-check and the
    --rule filter read the catalog through this."""
    return tuple(sorted({c.rule for c in CONTRACTS}))


# -- the catalog ------------------------------------------------------------


@contract(
    "ir-host-callback",
    "no host round-trips (pure/io/debug callbacks) in any traced body",
)
def no_host_callbacks(prog, ctx) -> List[str]:
    hits = engine.host_callback_prims(prog.jaxpr())
    if hits:
        return [f"host round-trip primitives in traced body: {sorted(hits)}"]
    return []


@contract(
    "ir-scan-dot",
    "prescreen scan body has no dot_general producing an N-sized axis "
    "(the slot screen must stay OUT of the sequential loop); tiered is "
    "the positive control proving the predicate still detects it",
    families=("solve",),
)
def scan_dot_budget(prog, ctx) -> List[str]:
    if not ctx.n_unique:
        # N collides with another static dim: 'an N-sized output axis'
        # would be ambiguous, so the predicate proves nothing — skip
        # (families.py stages a dedicated N-unique geometry for this)
        return []
    if ctx.backend != "mxu":
        # the CPU-default 'sliced' screen is a per-key loop with no
        # dot_general — the predicate would be vacuous either way
        return []
    N = prog.ctx.geom[7]
    dims = engine.scan_dot_output_dims(prog.jaxpr())
    if ctx.screen_mode == "prescreen":
        if N in dims:
            return [
                f"scan body contains dot_general producing an N={N}-sized "
                f"axis — the full-width slot screen re-grew into the "
                f"sequential loop (dot output dims inside the scan: "
                f"{sorted(dims)})"
            ]
    else:
        if N not in dims:
            return [
                f"positive control lost: the tiered scan body shows no "
                f"N={N}-wide contraction, so the prescreen predicate can "
                f"no longer detect a regression"
            ]
    return []


@contract(
    "ir-collectives",
    "mesh solve float-collective inventory: <=2 float all-gathers (one "
    "precompute reassembly seam each), 0 float all-reduces / "
    "reduce-scatters (no contraction axis is split — float "
    "re-association would break mesh byte-identity)",
    families=("solve", "prescreen"),
    mesh=True,
    compile_level=True,
)
def collective_budget(prog, ctx) -> List[str]:
    text = prog.compiled_text()
    float_counts = engine.collective_counts(text, dtypes=engine.FLOAT_DTYPES)
    out = []
    for op, cap in sorted(MESH_COLLECTIVE_BUDGET.items()):
        n = float_counts.get(op, 0)
        if n > cap:
            out.append(
                f"compiled HLO contains {n} float-dtype {op} ops > budget "
                f"{cap} (float inventory: {float_counts}; all dtypes: "
                f"{engine.collective_counts(text)})"
            )
    return out


@contract(
    "ir-mesh-fence",
    "mesh programs carry their SpecLayout replication fence "
    "(sharding_constraint present) — without it the program compiles as "
    "a plain single-device trace and the mesh buys nothing",
    families=("solve", "prescreen", "segment"),
    mesh=True,
)
def mesh_fence(prog, ctx) -> List[str]:
    prims = engine.primitive_names(prog.jaxpr())
    if "sharding_constraint" not in prims:
        return [
            "no sharding_constraint in the traced body — the SpecLayout "
            "fence is gone"
        ]
    return []


@contract(
    "ir-single-clean",
    "single-device programs carry NO sharding constraints — layout "
    "plumbing must not leak mesh ops into the plain program family",
    families=("solve",),
    mesh=False,
)
def single_device_clean(prog, ctx) -> List[str]:
    prims = engine.primitive_names(prog.jaxpr())
    if "sharding_constraint" in prims:
        return [
            "sharding_constraint in a single-device program — layout "
            "plumbing leaked into the plain family"
        ]
    return []


@contract(
    "ir-donation",
    "every declared donated buffer matches an output aval (shape+dtype) "
    "— a donation no output can alias is a silent copy",
)
def donation_honored(prog, ctx) -> List[str]:
    nums = tuple(getattr(prog.record, "donate_argnums", ()) or ())
    if not nums or not ctx.donate:
        return []
    return engine.donation_holes(prog.jaxpr(), nums)


@contract(
    "ir-ladder",
    "every staged geometry's solve-shaping axes are LISTED bucket-ladder "
    "tier values — an off-ladder axis means unbounded program minting",
)
def ladder_axes(prog, ctx) -> List[str]:
    if not ctx.ladder or ctx.geom is None:
        return []
    if ctx.tier == "tripwire":
        return []  # the N-unique geometry is deliberately off-ladder
    return engine.off_ladder_axes(ctx.geom, ctx.ladder)


@contract(
    "ir-segment-scan",
    "the segmented lane program's pack scan runs over the SEGMENT bucket "
    "M, never the full item axis P — the partition's whole point",
    families=("segment",),
)
def segment_scan_length(prog, ctx) -> List[str]:
    if "lane" not in prog.name:
        return []  # the partitioner has no pack scan
    P = ctx.geom[0]
    _s, m_pad = ctx.segment_shape
    if m_pad == P:
        return []  # ambiguous staging; families.py picks M != P
    lengths = [n for n in engine.scan_lengths(prog.jaxpr()) if n is not None]
    if not lengths:
        return ["segmented lane program lost its pack scan"]
    out = []
    if m_pad not in lengths:
        out.append(
            f"pack scan lengths {sorted(set(lengths))} do not include the "
            f"segment bucket {m_pad}"
        )
    if P in lengths:
        out.append(
            f"a scan still runs over the full item axis {P} — the "
            f"sequential wall did not shrink to the segment bucket"
        )
    return out


@contract(
    "ir-program-count",
    "per-family compiled-program count ceilings: one staging mints at "
    "most the budget table's programs per (tier, screen-mode) — more "
    "means a builder re-minting behind the cache's back",
    whole_family=True,
)
def program_count_ceilings(programs, extra) -> List[str]:
    stagings = {}
    for prog in programs:
        key = (prog.ctx.tier, prog.ctx.screen_mode, prog.ctx.mesh)
        fam = stagings.setdefault(key, {})
        fam[prog.family] = fam.get(prog.family, 0) + 1
    out: List[str] = []
    for key, counts in sorted(stagings.items()):
        for msg in engine.check_family_counts(
            counts, PER_TIER_PROGRAM_BUDGET
        ):
            tier, mode, mesh = key
            where = f"tier={tier},mode={mode}" + (",mesh" if mesh else "")
            out.append(f"[{where}] {msg}")
    minted = (extra or {}).get("minted_during_staging")
    if minted:
        # cross-check against the PR 18 ProgramLedger: families.stage_all
        # snapshots the process ledger's family totals before and after
        # staging and passes the mint DELTA here. Staging goes through the
        # pure _build_* seams, so ANY mint recorded while staging means an
        # introspection path leaked into the live cache.
        for fam, n in sorted(minted.items()):
            if n > 0:
                out.append(
                    f"ProgramLedger recorded {n} '{fam}' mint(s) DURING "
                    f"staging — the introspection seam created live cache "
                    f"entries (the _build_* builders must stay pure)"
                )
    return out
