"""jaxpr/HLO walkers + the contract evaluator.

One spelling of every IR predicate: the walkers here serve BOTH the
`hack/lint.py --ir` contract sweep and the structural tripwires in
tests/test_perf_floor.py / tests/test_sharded.py — a budget asserted in a
test and the same budget checked in CI lint can never drift apart, because
they are the same function.

The walkers take already-traced jaxprs (or HLO text) and use only
duck-typed attributes (`eqn.primitive.name`, `eqn.params`, `var.aval`), so
this module imports neither jax nor the solver — `hack/lint.py` can import
the catalog without paying the jax startup, and only `--ir` (which stages
real programs via families.py) needs a device runtime.
"""
from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set, Tuple

from karpenter_core_tpu.analysis.core import Violation

# host round-trips a jitted program can express. device_put eqns are NOT
# in this set — inside a jitted body they are on-device constant
# placement (how jnp.asarray of closure constants lowers), not a host
# transfer (tests/test_sharded.py documented this first).
HOST_CALLBACK_PRIMS = frozenset({
    "pure_callback", "io_callback", "debug_callback", "callback",
    "host_callback", "outside_call",
})

# post-SPMD-partitioning collective INSTRUCTION DEFINITIONS in compiled
# HLO text: `%name = dtype[shape]... op(...)`. Matching the definition
# (result dtype + op + open paren) rather than any textual mention keeps
# computation names, metadata strings, and the async -done halves out of
# the count (-start forms match; their -done partners end in `-done(` so
# the trailing `\(` rejects them).
_HLO_COLLECTIVE_RE = re.compile(
    r"=\s*\(?\s*([a-z][a-z0-9]*)\[[^\]]*\][^=\n]*?"
    r"\b(all-gather|all-reduce|all-to-all|collective-permute|"
    r"reduce-scatter)(?:-start)?\("
)

# dtypes where cross-replica reduction/reassembly re-associates floating
# point — the byte-identity hazard the mesh collective budget guards
FLOAT_DTYPES = frozenset({"f8", "f16", "bf16", "f32", "f64", "c64", "c128"})


def _as_jaxpr(jx):
    """Accept a ClosedJaxpr, a Jaxpr, or anything with `.jaxpr`."""
    return getattr(jx, "jaxpr", jx)


def subjaxprs(eqn) -> Iterator:
    """Sub-jaxprs an equation closes over (scan/while/cond bodies, pjit
    calls), in params order."""
    for v in eqn.params.values():
        if hasattr(v, "jaxpr"):  # ClosedJaxpr
            yield v.jaxpr
        elif isinstance(v, (list, tuple)):
            for item in v:
                if hasattr(item, "jaxpr"):
                    yield item.jaxpr


def walk_eqns(jx) -> Iterator:
    """Every equation in the jaxpr, recursively — tracing a jit object
    yields an outer jaxpr whose single pjit eqn wraps the body, so any
    non-recursive walk would see nothing."""
    jx = _as_jaxpr(jx)
    for eqn in jx.eqns:
        yield eqn
        for sub in subjaxprs(eqn):
            yield from walk_eqns(sub)


def primitive_names(jx) -> Set[str]:
    return {eqn.primitive.name for eqn in walk_eqns(jx)}


def host_callback_prims(jx) -> Set[str]:
    return primitive_names(jx) & HOST_CALLBACK_PRIMS


def scan_eqns(jx) -> Iterator:
    for eqn in walk_eqns(jx):
        if eqn.primitive.name == "scan":
            yield eqn


def scan_lengths(jx) -> List[Optional[int]]:
    """`length` param of every scan in the program, outermost first."""
    return [eqn.params.get("length") for eqn in scan_eqns(jx)]


def scan_dot_output_dims(jx) -> Set[int]:
    """Output dims of every dot_general anywhere INSIDE a scan body
    (incl. nested while/cond branches) — the predicate behind the
    prescreen tripwire: an N-sized dim here means the full-width slot
    screen re-grew into the sequential loop."""
    dims: Set[int] = set()
    for eqn in scan_eqns(jx):
        for sub in subjaxprs(eqn):
            for inner in walk_eqns(sub):
                if inner.primitive.name == "dot_general":
                    for var in inner.outvars:
                        dims.update(var.aval.shape)
    return dims


def collective_counts(hlo_text: str,
                      dtypes: Optional[frozenset] = None) -> Dict[str, int]:
    """Collective-op inventory of compiled (post-SPMD) HLO text: counts
    instruction definitions (async -start forms count once; -done halves
    never). `dtypes` restricts to instructions whose result dtype (first
    tuple element for async pairs) is in the set — pass FLOAT_DTYPES for
    the re-association-hazard subset the mesh budget caps. The SPMD
    partitioner freely mints small pred/u8 bookkeeping collectives, so an
    unrestricted count is backend noise; the float subset is the
    program's real collective surface."""
    counts: Dict[str, int] = {}
    for m in _HLO_COLLECTIVE_RE.finditer(hlo_text):
        dtype, op = m.group(1), m.group(2)
        if dtypes is not None and dtype not in dtypes:
            continue
        counts[op] = counts.get(op, 0) + 1
    return counts


def donation_holes(jx, donate_argnums: Sequence[int]) -> List[str]:
    """Donated inputs that no output can possibly reuse — aval (shape,
    dtype) of each donated invar must match some outvar's, or XLA cannot
    alias it and the donation silently copies. Necessary-condition check
    at the jaxpr level (the positive signal, `tf.aliasing_output` in the
    lowered module, is backend-dependent); assumes each top-level arg is
    a single leaf, which holds for every program in the solver family
    (the bundle is one packed array, donated planes are arrays)."""
    jx = _as_jaxpr(jx)
    out_avals = [(tuple(v.aval.shape), str(v.aval.dtype)) for v in jx.outvars]
    holes: List[str] = []
    for pos in donate_argnums:
        if pos >= len(jx.invars):
            holes.append(f"donate_argnums position {pos} out of range")
            continue
        aval = jx.invars[pos].aval
        sig = (tuple(aval.shape), str(aval.dtype))
        if sig not in out_avals:
            holes.append(
                f"donated arg {pos} {sig[1]}{list(sig[0])} matches no "
                "output buffer — the donation is a silent copy"
            )
    return holes


def off_ladder_axes(geom, ladder) -> List[str]:
    """Solve-shaping axes of a geometry that are NOT listed tier values —
    the same membership test test_perf_floor.py's churn tripwire applies
    to live cache keys (geom[0]=items, geom[2]=types, geom[3]=existing;
    a zero existing axis is the no-nodes case, always legal)."""
    item_values = {t.items for t in ladder}
    type_values = {t.instance_types for t in ladder}
    exist_values = {t.existing_nodes for t in ladder} | {0}
    bad: List[str] = []
    if geom[0] not in item_values:
        bad.append(f"item axis {geom[0]} off-ladder (allowed {sorted(item_values)})")
    if geom[2] not in type_values:
        bad.append(f"type axis {geom[2]} off-ladder (allowed {sorted(type_values)})")
    if geom[3] not in exist_values:
        bad.append(
            f"existing axis {geom[3]} off-ladder (allowed {sorted(exist_values)})"
        )
    return bad


def check_family_counts(counts: Dict[str, int],
                        budgets: Dict[str, int]) -> List[str]:
    """Per-family program-count ceilings: `counts` (family -> programs
    minted) against `budgets` (family -> ceiling). One spelling for the
    live-cache tripwires AND the staged-ledger cross-check."""
    over: List[str] = []
    for family, n in sorted(counts.items()):
        cap = budgets.get(family)
        if cap is not None and n > cap:
            over.append(
                f"family '{family}' minted {n} programs > ceiling {cap}"
            )
    return over


# -- staged-program handle --------------------------------------------------


@dataclass
class ProgramIR:
    """One staged program + lazily-computed IR views. Wraps a
    tpu_solver.FamilyProgram (`record`) with the staging context the
    contracts key on; jaxpr/lowering/compile happen at most once each."""

    record: object              # tpu_solver.FamilyProgram
    ctx: "StagingContext"
    _jaxpr: object = None
    _lowered: object = None
    _compiled: object = None

    @property
    def name(self) -> str:
        return self.record.name

    @property
    def family(self) -> str:
        return self.record.family

    def jaxpr(self):
        if self._jaxpr is None:
            import jax

            self._jaxpr = jax.make_jaxpr(self.record.fn)(
                *self.record.example_args
            ).jaxpr
        return self._jaxpr

    def lowered(self):
        if self._lowered is None:
            self._lowered = self.record.fn.lower(*self.record.example_args)
        return self._lowered

    def compiled_text(self) -> str:
        """Post-SPMD compiled HLO text — pays the XLA compile (persistent
        cache applies); only the compile-level contracts (collectives)
        call this, and families.py stages them at tier S only."""
        if self._compiled is None:
            self._compiled = self.lowered().compile()
        return self._compiled.as_text()


@dataclass
class StagingContext:
    """What one staging pass knew when it built a program — the
    per-family contract predicates key on these."""

    tier: str                   # "S" | "M" | "L" | "XL" | "tripwire"
    screen_mode: str            # "prescreen" | "tiered"
    mesh: bool = False
    backend: Optional[str] = None
    geom: Optional[tuple] = None
    ladder: tuple = ()
    n_unique: bool = False      # N (geom[7]) unique among int geometry dims
    segment_shape: Tuple[int, int] = (8, 16)
    compile_level: bool = False  # compile-level contracts may run here
    donate: bool = True

    def label(self) -> str:
        bits = [f"tier={self.tier}", f"mode={self.screen_mode}"]
        if self.mesh:
            bits.append("mesh")
        if self.backend:
            bits.append(self.backend)
        return ",".join(bits)


def evaluate(programs: Iterable[ProgramIR], contracts=None,
             extra_ctx: Optional[dict] = None) -> List[Violation]:
    """Run every applicable contract over every staged program.
    Violations anchor at the contract's declaration line in contracts.py
    so the standard `relpath:line:rule` suppression/baseline grammar
    applies to IR findings unchanged."""
    from karpenter_core_tpu.analysis.irlint import contracts as contracts_mod

    if contracts is None:
        contracts = contracts_mod.CONTRACTS
    out: List[Violation] = []
    programs = list(programs)
    for c in contracts:
        if c.whole_family:
            msgs = c.check(programs, extra_ctx or {})
            out.extend(
                Violation(
                    relpath=contracts_mod.RELPATH, line=c.line,
                    rule=c.rule, message=m,
                )
                for m in msgs
            )
            continue
        for prog in programs:
            if not c.applies(prog):
                continue
            for m in c.check(prog, prog.ctx):
                out.append(Violation(
                    relpath=contracts_mod.RELPATH, line=c.line,
                    rule=c.rule,
                    message=f"{prog.name}[{prog.ctx.label()}]: {m}",
                ))
    return out
