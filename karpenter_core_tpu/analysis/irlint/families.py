"""Stage the whole compiled-program family for the contract sweep.

This is the only irlint module that imports jax and the solver (function
scope — the AST driver imports the catalog without paying for either).
It mirrors the prewarm path end to end: a vocabulary-neutral synthetic
workload per bucket-ladder tier (solver/prewarm.synthetic_workload),
encoded against a fake instance-type universe, bundled through the live
`_bundle_args` seam, then staged through the PURE builders
(tpu_solver.stage_family_programs) — no LRU entry, no per-key lock, no
proghealth mint. The ir-program-count contract cross-checks exactly that:
stage_all snapshots the process ProgramLedger's family mint totals before
and after staging and hands the delta to the contracts.

Coverage (bounded so `make irlint` stays ~2 minutes warm):

  * every ladder tier (S/M/L/XL) stages its full single-device family in
    prescreen mode — jaxpr-level contracts only (tracing is cheap even at
    XL; nothing compiles);
  * tier S additionally stages: tiered mode (the prescreen-only
    satellites drop, matching live dispatch); the GSPMD mesh variant on a
    4x2 host-device mesh with compile-level contracts armed (the
    collective budgets need post-SPMD HLO, and only tier S pays an XLA
    compile — the shared persistent cache absorbs repeat runs);
  * one off-ladder "tripwire" staging at backend="mxu" whose slot count
    N is UNIQUE among array dims (the ir-scan-dot contract needs an
    unambiguous N, and the CPU-default 'sliced' screen has no
    dot_general) — staged in BOTH screen modes so the tiered program
    doubles as the positive control.

The mesh variant silently drops when fewer than 8 devices are visible
(the driver and tests force XLA_FLAGS=--xla_force_host_platform_device_count=8
before importing jax; a bare interpreter session may not).
"""
from __future__ import annotations

from typing import Dict, Iterable, List, Optional

from karpenter_core_tpu.analysis.irlint.engine import ProgramIR, StagingContext

MESH_SHAPE = (4, 2)  # (dp, tp) — the test suite's canonical host mesh

# families staged per variant; "segment" yields the partition + one lane
DEFAULT_FAMILIES = ("prescreen", "solve", "refresh", "replan", "segment")

# the lane/segment buckets the segmented lane program stages at — M=16
# differs from every small-tier item bucket so the ir-segment-scan
# membership test is unambiguous
SEGMENT_SHAPE = (8, 16)


def _mint_totals() -> Dict[str, int]:
    from karpenter_core_tpu.obs import proghealth

    snap = proghealth.LEDGER.snapshot() or {}
    return {
        fam: int(t.get("minted", 0))
        for fam, t in (snap.get("totals") or {}).items()
    }


def _tier_workload(tier, max_nodes: int):
    """(snap, geom-source) for one ladder tier: the prewarm synthetic
    workload, sized at tier.items pods so encode time stays bounded while
    the item/type/existing axes still land on the tier's rungs."""
    from karpenter_core_tpu.cloudprovider import fake
    from karpenter_core_tpu.solver.encode import encode_snapshot, resolve_ladder
    from karpenter_core_tpu.solver.prewarm import synthetic_workload
    from karpenter_core_tpu.testing import make_provisioner

    ladder = resolve_ladder(None)
    provisioners = [make_provisioner(name="default")]
    its = {"default": fake.instance_types(tier.instance_types)}
    pods, nodes = synthetic_workload(
        tier, provisioners, its, pods_count=tier.items
    )
    snap = encode_snapshot(
        list(pods), provisioners, its, state_nodes=nodes,
        max_nodes=max_nodes, ladder=ladder,
    )
    return snap, provisioners, ladder


def _tripwire_workload(max_nodes: int = 48):
    """The N-unique geometry (20 distinct pods, 5 types, 3 nodes,
    max_nodes 48 -> N=56 colliding with no other int dim) — the same
    geometry tests/test_perf_floor.py asserts the scan-dot tripwire on."""
    from karpenter_core_tpu.cloudprovider import fake
    from karpenter_core_tpu.solver.encode import encode_snapshot
    from karpenter_core_tpu.state.node import StateNode
    from karpenter_core_tpu.testing import make_node, make_pod, make_provisioner

    universe = fake.instance_types(5)
    pods = [
        make_pod(labels={"app": f"t{i}"}, requests={"cpu": str(0.1 * (i + 1))})
        for i in range(20)
    ]
    provisioners = [make_provisioner(name="default")]
    its = {"default": universe}
    nodes = []
    for e in range(3):
        it = universe[e % len(universe)]
        nodes.append(StateNode(node=make_node(
            name=f"irlint-trip-{e}",
            labels={
                "karpenter.sh/provisioner-name": "default",
                "karpenter.sh/initialized": "true",
                "node.kubernetes.io/instance-type": it.name,
                "karpenter.sh/capacity-type": "on-demand",
                "topology.kubernetes.io/zone": "test-zone-1",
            },
            capacity={k: str(v) for k, v in it.capacity.items()},
        )))
    snap = encode_snapshot(
        pods, provisioners, its, None, nodes, max_nodes=max_nodes
    )
    return snap, provisioners


def _stage_variant(snap, provisioners, *, tier: str, screen_mode: str,
                   ladder=(), backend: Optional[str] = None,
                   spec_layout=None, n_unique: bool = False,
                   compile_level: bool = False,
                   families: Optional[Iterable[str]] = None,
                   max_nodes: int = 1024) -> List[ProgramIR]:
    """Stage one (workload, screen-mode, layout, backend) variant through
    the pure seams and wrap each program with its StagingContext."""
    from karpenter_core_tpu.solver.tpu_solver import (
        TPUSolver,
        _bundle_args,
        build_device_solve,
        device_args,
        stage_family_programs,
    )

    solver = TPUSolver(max_nodes=max_nodes, backend=backend,
                       screen_mode=screen_mode)
    geom, run = build_device_solve(
        snap, max_nodes, backend=backend, screen_mode=screen_mode,
        external_prescreen=True, spec_layout=spec_layout,
    )
    args = device_args(snap, provisioners)
    staged = _bundle_args(
        args, geom, run, backend, screen_mode, spec_layout=spec_layout
    )
    records = stage_family_programs(
        staged, solver, screen_mode, families=families,
        segment_shape=SEGMENT_SHAPE,
    )
    ctx = StagingContext(
        tier=tier, screen_mode=screen_mode, mesh=spec_layout is not None,
        backend=backend, geom=geom, ladder=tuple(ladder),
        n_unique=n_unique, segment_shape=SEGMENT_SHAPE,
        compile_level=compile_level, donate=solver.donate,
    )
    return [ProgramIR(record=r, ctx=ctx) for r in records]


def _mesh_layout():
    """SpecLayout over the canonical 4x2 host mesh, or None when the
    interpreter wasn't started with 8 visible devices."""
    import jax
    import numpy as np

    if len(jax.devices()) < MESH_SHAPE[0] * MESH_SHAPE[1]:
        return None
    from jax.sharding import Mesh

    from karpenter_core_tpu.parallel.specs import SpecLayout

    mesh = Mesh(
        np.array(jax.devices()[: MESH_SHAPE[0] * MESH_SHAPE[1]]).reshape(
            *MESH_SHAPE
        ),
        ("dp", "tp"),
    )
    return SpecLayout(mesh)


def stage_all(tiers: Optional[Iterable[str]] = None,
              families: Optional[Iterable[str]] = None,
              compile_level: bool = True,
              max_nodes: int = 1024):
    """Stage the full program family. Returns (programs, extra_ctx) ready
    for engine.evaluate. `tiers` filters ladder tiers by name (the
    'tripwire' and mesh variants ride with tier S); `families` filters
    program families; compile_level=False skips the post-SPMD compile
    contracts (jaxpr-only sweep, fastest)."""
    from karpenter_core_tpu.solver.encode import resolve_ladder

    want_tiers = None if tiers is None else frozenset(tiers)
    mints_before = _mint_totals()
    ladder = resolve_ladder(None)
    programs: List[ProgramIR] = []
    for tier in ladder:
        if want_tiers is not None and tier.name not in want_tiers:
            continue
        snap, provisioners, lad = _tier_workload(tier, max_nodes)
        programs.extend(_stage_variant(
            snap, provisioners, tier=tier.name, screen_mode="prescreen",
            ladder=lad, families=families, max_nodes=max_nodes,
        ))
        if tier.name != "S":
            continue
        # tier S carries the variant axes: tiered mode (prescreen-only
        # satellites drop), the GSPMD mesh family (compile-level), and
        # the N-unique mxu tripwire in both screen modes
        programs.extend(_stage_variant(
            snap, provisioners, tier=tier.name, screen_mode="tiered",
            ladder=lad, families=families, max_nodes=max_nodes,
        ))
        layout = _mesh_layout()
        if layout is not None:
            programs.extend(_stage_variant(
                snap, provisioners, tier=tier.name,
                screen_mode="prescreen", ladder=lad, spec_layout=layout,
                compile_level=compile_level, families=families,
                max_nodes=max_nodes,
            ))
        trip_snap, trip_prov = _tripwire_workload()
        for mode in ("prescreen", "tiered"):
            programs.extend(_stage_variant(
                trip_snap, trip_prov, tier="tripwire", screen_mode=mode,
                backend="mxu", n_unique=True,
                families=("solve",) if families is None else families,
                max_nodes=48,
            ))
    mints_after = _mint_totals()
    delta = {
        fam: n - mints_before.get(fam, 0)
        for fam, n in mints_after.items()
        if n - mints_before.get(fam, 0) > 0
    }
    extra_ctx = {"minted_during_staging": delta}
    return programs, extra_ctx
