"""Analysis configuration: the dependency DAG, the wall-clock allowlist,
and the structural knobs every pass reads. One default instance describes
THIS repo; fixture tests build their own to lint synthetic trees.
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, Set, Tuple

# Subpackage dependency DAG: subpackage -> subpackages it may import at
# MODULE scope. Function-scope imports are exempt (they express a runtime
# collaboration, not a load-order dependency — e.g. solver/encode.py builds
# host Topology inside encode_snapshot). The map is the architecture:
# adding an edge here is a design decision, reviewed like one.
#
# Layers, roughly bottom-up:
#   metrics                                      (leaf)
#   obs, analysis                                (obs -> metrics only)
#   chaos                                        (registry + env arming)
#   utils, kube                                  (kube <-> utils is two
#                                                 module-level acyclic edges:
#                                                 utils/podutils -> kube/objects,
#                                                 kube/apiserver -> utils/backoff)
#   api, events, scheduling                      (domain objects)
#   cloudprovider, state                         (cluster model)
#   ops, native, parallel                        (device kernels)
#   solver                                       (MUST NOT see controllers)
#   controllers                                  (may orchestrate solver)
#   operator, webhooks, testing                  (process wiring)
#   loadgen                                      (churn driver: may see
#                                                 everything, seen by nobody)
DEFAULT_LAYERING: Dict[str, FrozenSet[str]] = {
    "metrics": frozenset(),
    "analysis": frozenset(),
    "obs": frozenset({"metrics"}),
    "chaos": frozenset({"metrics", "obs"}),
    "utils": frozenset({"kube", "metrics", "obs"}),
    "kube": frozenset({"chaos", "metrics", "obs", "utils"}),
    "events": frozenset({"kube", "metrics", "obs", "utils"}),
    "api": frozenset({"kube", "utils"}),
    "scheduling": frozenset({"api", "kube", "utils"}),
    "cloudprovider": frozenset({"api", "kube", "metrics", "obs", "scheduling", "utils"}),
    "state": frozenset({"api", "chaos", "kube", "obs", "scheduling", "utils"}),
    "ops": frozenset({"metrics", "obs", "utils"}),
    "native": frozenset({"metrics", "obs", "utils"}),
    # ISSUE 8 re-layering: parallel sits ABOVE solver now — ShardedSolver
    # is a TPUSolver subclass that swaps in the GSPMD mesh program family
    # (parallel/sharded.py), so parallel may see solver and solver may NOT
    # see parallel at module scope (factory/service reach it lazily,
    # function-scope, which the pass exempts)
    "parallel": frozenset({"chaos", "metrics", "obs", "ops", "solver", "utils"}),
    "solver": frozenset({
        "api", "chaos", "cloudprovider", "events", "kube", "metrics", "native",
        "obs", "ops", "scheduling", "state", "utils",
    }),
    "controllers": frozenset({
        "api", "chaos", "cloudprovider", "events", "kube", "metrics", "native",
        "obs", "ops", "parallel", "scheduling", "solver", "state", "utils",
    }),
    "operator": frozenset({
        "api", "chaos", "cloudprovider", "controllers", "events", "kube",
        "metrics", "obs", "scheduling", "solver", "state", "utils", "webhooks",
    }),
    "webhooks": frozenset({"api", "kube", "obs", "utils"}),
    "testing": frozenset({
        "api", "chaos", "cloudprovider", "controllers", "events", "kube",
        "metrics", "obs", "operator", "scheduling", "solver", "state", "utils",
    }),
    # churn/soak load generation: drives the REAL operator loop (batcher ->
    # provisioner -> solver -> bind), so it sits above everything — and is
    # a leaf the other way: NOTHING may depend on loadgen (no other layer
    # lists it), so load generation can never leak into the control plane
    "loadgen": frozenset({
        "api", "chaos", "cloudprovider", "controllers", "events", "kube",
        "metrics", "obs", "operator", "scheduling", "solver", "state",
        "testing", "utils",
    }),
}

# monotonic-time allowlist: `relpath::function` sites whose time.time() IS
# the point — they produce wall-clock timestamps that are serialized,
# compared against k8s object timestamps, or rendered for humans. Audited
# in PR 4 (docs/static-analysis.md has the per-site rationale); everything
# else in the package measures durations and must use time.monotonic()
# or time.perf_counter().
DEFAULT_WALLCLOCK_ALLOWLIST: FrozenSet[str] = frozenset({
    # structured log records carry an epoch ts field (logfmt/JSON output)
    "karpenter_core_tpu/obs/log.py::_emit",
    # k8s condition lastTransitionTime is wall-clock API surface
    "karpenter_core_tpu/api/machine.py::set_condition",
    # deletionTimestamp mirrors metav1.Time — wall-clock like
    # creation_timestamp (kube/objects.py ObjectMeta default)
    "karpenter_core_tpu/kube/client.py::delete",
    # flight records are stamped with the wall-clock solve time; the dump
    # filename renders it via time.gmtime
    "karpenter_core_tpu/obs/flightrec.py::__init__",
    "karpenter_core_tpu/obs/flightrec.py::dump",
    # consolidation decision records carry the same wall-clock stamp
    "karpenter_core_tpu/obs/flightrec.py::record_consolidation",
    # supervisor heartbeat files and TTL'd health verdicts are CROSS-PROCESS
    # liveness signals: the only clock a worker and its supervisor share is
    # the filesystem's wall clock (mtime / serialized ts), so these sites
    # compare against it by design (ISSUE 11; docs/bench-rounds.md)
    "karpenter_core_tpu/utils/supervise.py::age",
    "karpenter_core_tpu/utils/supervise.py::write_verdict",
    "karpenter_core_tpu/utils/supervise.py::read_verdict",
    # clock=time.time *references* as INSTANCE-clock defaults (methods
    # store the injectable clock at construction) are not calls and are
    # not flagged; module-level FUNCTION parameter defaults ARE flagged —
    # they bind the clock at import, so a later-installed fake/monkeypatch
    # silently never reaches the call (montime.py, ISSUE 10 satellite).
})


@dataclass
class AnalysisConfig:
    repo_root: str
    package_name: str = "karpenter_core_tpu"
    layering: Dict[str, FrozenSet[str]] = field(
        default_factory=lambda: dict(DEFAULT_LAYERING)
    )
    # subpackages whose absence from `layering` is an error (catches a new
    # top-level subpackage landing without a declared layer)
    layering_strict: bool = True
    wallclock_allowlist: FrozenSet[str] = DEFAULT_WALLCLOCK_ALLOWLIST
    # the single module allowed to touch os.environ
    env_funnel: str = "karpenter_core_tpu/obs/envflags.py"
    # callables that trace the function they wrap (trace-safety pass)
    trace_wrappers: FrozenSet[str] = frozenset({"jit", "pjit", "shard_map"})
    # method-name suffix conventionally meaning "caller holds the lock" —
    # writes there are treated as guarded (guarded-by pass)
    locked_suffix: str = "_locked"
    # modules allowed to Popen without an inline start_new_session= (the
    # audited supervisor funnels — both DO set it today; the funnel list
    # exists so refactors inside them don't fight the lint)
    popen_funnels: FrozenSet[str] = frozenset({
        "karpenter_core_tpu/utils/supervise.py",
        "karpenter_core_tpu/solver/host.py",
    })
    # `relpath::function` sites where a bare os.kill IS the point (none
    # today: the convention is os.killpg / supervise._kill_group)
    os_kill_allowlist: FrozenSet[str] = frozenset()
    # modules exempt from atomic-write wholesale: supervise IMPLEMENTS the
    # write-temp-fsync-rename idiom and owns the supervised workers'
    # stdout/stderr stream files, whose tail readers (tail_bytes_of)
    # tolerate partial lines by design
    atomic_write_funnels: FrozenSet[str] = frozenset({
        "karpenter_core_tpu/utils/supervise.py",
    })
    # `relpath::function` sites audited for a bare open-for-write (docs/
    # static-analysis.md has the per-site rationale): the solver host's
    # child stderr file is a LIVE STREAM handed to Popen — there is no
    # final artifact to rename into place, and its reader (tail_bytes_of
    # in _stderr_tail) tolerates a partial tail by design
    plain_write_allowlist: FrozenSet[str] = frozenset({
        "karpenter_core_tpu/solver/host.py::_spawn_locked",
    })
    # bucketing funnels that absorb a runtime-size taint (recompile-guard
    # pass): a len()-derived value routed through one of these lands on
    # the geometry bucket ladder, so downstream static shapes are bounded
    recompile_sanitizers: FrozenSet[str] = frozenset({
        "ladder_pad",
        "bucket_pow2",
        "replan_k_pad",
        "replan_chunks",
        "segment_lane_pad",
        "segment_item_pad",
        "solve_geometry",
    })
    # compile boundaries whose static arguments shape a program
    # (recompile-guard pass): the ops/pack kernel factories
    # (pack.kernel_factories), shape-struct constructors, and jit/pjit
    # themselves — a raw runtime size arriving here mints one program per
    # distinct value
    recompile_sinks: FrozenSet[str] = frozenset({
        "jit",
        "pjit",
        "ShapeDtypeStruct",
        "make_device_run",
        "make_prescreen_kernel",
        "make_screen_refresh_kernel",
        "make_batched_replan_kernel",
        "make_replan_verdict_kernel",
        "make_segment_partition_kernel",
        "make_pack_kernel",
        "make_screen_ops",
    })

    def subpackage_of(self, module: str) -> str:
        """`pkg.solver.encode` -> `solver`; root-level modules -> ''."""
        prefix = self.package_name + "."
        if not module.startswith(prefix):
            return ""
        rest = module[len(prefix):]
        return rest.split(".")[0] if "." in rest else (
            rest if self._is_subpackage(rest) else ""
        )

    def _is_subpackage(self, name: str) -> bool:
        return os.path.isdir(
            os.path.join(self.repo_root, self.package_name, name)
        )


def default_config(repo_root: str | None = None) -> AnalysisConfig:
    if repo_root is None:
        # analysis/config.py lives two levels under the repo root
        repo_root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
    return AnalysisConfig(repo_root=repo_root)
