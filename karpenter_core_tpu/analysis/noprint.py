"""No-print pass (rule `no-print`): bare print() in production code.

The package logs through the structured logger (obs/log) — prints bypass
the level gate, the /debug/logs ring, and trace-id correlation. AST-based,
not grep: a `print(` inside a string literal (the subprocess probe source
in solver/fallback.py) is not a violation, and a real call can't hide
behind formatting. This is the PR 3 `hack/check_no_print.py` guard folded
into the framework; unparseable files are flagged too so a syntax error
can't smuggle one through.
"""
from __future__ import annotations

import ast
from typing import List, Sequence

from karpenter_core_tpu.analysis.core import Pass, SourceFile, Violation


class NoPrintPass(Pass):
    name = "noprint"
    rules = ("no-print",)

    def run(self, files: Sequence[SourceFile], config) -> List[Violation]:
        out: List[Violation] = self.syntax_violations(files, "no-print")
        for f in files:
            if f.tree is None:
                continue
            for node in ast.walk(f.tree):
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "print"
                ):
                    out.append(Violation(
                        relpath=f.relpath,
                        line=node.lineno,
                        rule="no-print",
                        message=(
                            "bare print() — log through "
                            "karpenter_core_tpu.obs.log instead"
                        ),
                    ))
        return out
