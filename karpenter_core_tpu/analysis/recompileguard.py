"""Recompile-guard pass (rule `recompile-guard`): raw runtime sizes at
compile boundaries.

Every static shape a compiled program is traced with must come off the
geometry bucket ladder (solver/encode.py `ladder_pad` / `bucket_pow2` and
friends): a value derived from a live collection size (`len(pods)`,
`len(state_nodes)`, ...) that reaches a jit/pjit boundary or a kernel
factory's static argument mints one program per distinct size — unbounded
compile churn that the runtime counter `karpenter_bucket_overflow_total`
only notices after the fact. This pass is that counter's static twin: it
catches the unbucketed route at review time.

Mechanics: per-function taint tracking in statement order. `len(...)` is
the taint source; assignments propagate taint through arithmetic and
ordinary calls; calls to the configured sanitizers
(`config.recompile_sanitizers` — the bucketing funnels) clean it. A
tainted expression arriving as an argument to a configured sink
(`config.recompile_sinks` — the kernel factories and shape-struct
constructors, plus jit/pjit boundaries and immediate `jit(f)(...)`
dispatches) is a violation. Flow analysis is intraprocedural and
name-based — a taint laundered through an attribute or a container is out
of reach (same known-limits posture as trace-safety).
"""
from __future__ import annotations

import ast
from typing import List, Optional, Sequence, Set

from karpenter_core_tpu.analysis.core import Pass, SourceFile, Violation

_JIT_NAMES = frozenset({"jit", "pjit"})


def _call_name(node: ast.Call) -> Optional[str]:
    """The terminal name a call dispatches through: `jax.jit(...)` ->
    'jit', `ladder_pad(...)` -> 'ladder_pad'."""
    func = node.func
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


class _FunctionTaint(ast.NodeVisitor):
    """Statement-order taint walk over ONE function body (nested defs get
    their own walker: their bodies run later, with their own locals)."""

    def __init__(self, pass_, relpath: str, config) -> None:
        self.pass_ = pass_
        self.relpath = relpath
        self.config = config
        self.sanitizers: Set[str] = set(config.recompile_sanitizers)
        self.sinks: Set[str] = set(config.recompile_sinks)
        self.tainted: Set[str] = set()
        self.out: List[Violation] = []

    # -- expression taint --------------------------------------------------

    def is_tainted(self, node: ast.expr) -> bool:
        if isinstance(node, ast.Call):
            name = _call_name(node)
            if name == "len":
                return True
            if name in self.sanitizers:
                return False  # bucketed: the funnel absorbs the taint
            return any(self.is_tainted(a) for a in node.args) or any(
                self.is_tainted(k.value) for k in node.keywords
            )
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.BinOp):
            return self.is_tainted(node.left) or self.is_tainted(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.is_tainted(node.operand)
        if isinstance(node, ast.IfExp):
            return self.is_tainted(node.body) or self.is_tainted(node.orelse)
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self.is_tainted(e) for e in node.elts)
        if isinstance(node, ast.Starred):
            return self.is_tainted(node.value)
        return False

    # -- statements --------------------------------------------------------

    def _bind(self, target: ast.expr, tainted: bool) -> None:
        if isinstance(target, ast.Name):
            if tainted:
                self.tainted.add(target.id)
            else:
                self.tainted.discard(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            # tuple unpack: conservative — every bound name inherits the
            # RHS verdict (a mixed tuple is rare at the sizes this tracks)
            for elt in target.elts:
                self._bind(elt, tainted)

    def visit_Assign(self, node: ast.Assign) -> None:
        self._check_expr(node.value)
        tainted = self.is_tainted(node.value)
        for target in node.targets:
            self._bind(target, tainted)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._check_expr(node.value)
            self._bind(node.target, self.is_tainted(node.value))

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._check_expr(node.value)
        if isinstance(node.target, ast.Name):
            if self.is_tainted(node.value):
                self.tainted.add(node.target.id)

    def visit_For(self, node: ast.For) -> None:
        self._check_expr(node.iter)
        self._bind(node.target, self.is_tainted(node.iter))
        for stmt in node.body:
            self.visit(stmt)
        for stmt in node.orelse:
            self.visit(stmt)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self.pass_.check_function(node, self.relpath, self.out, self.config)

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    def generic_visit(self, node: ast.AST) -> None:
        # sink checks on every expression statement / call we walk past
        if isinstance(node, ast.expr):
            self._check_expr(node)
            return  # _check_expr recurses into calls itself
        super().generic_visit(node)

    # -- sinks -------------------------------------------------------------

    def _check_expr(self, node: ast.expr) -> None:
        for call in ast.walk(node):
            if not isinstance(call, ast.Call):
                continue
            name = _call_name(call)
            if name in self.sinks:
                self._check_sink(call, name)
            elif (
                isinstance(call.func, ast.Call)
                and _call_name(call.func) in _JIT_NAMES
            ):
                # immediate dispatch of a fresh jit: jit(f)(args...) — the
                # arguments ARE the traced shapes
                self._check_sink(call, "jit(...)")

    def _check_sink(self, call: ast.Call, name: str) -> None:
        exprs = list(call.args) + [k.value for k in call.keywords]
        if name in _JIT_NAMES:
            # jax.jit(fn, donate_argnums=..., static_argnums=...): the
            # keywords are argument POSITIONS (commonly counted off a
            # fixed-size donation tuple), not shapes — only positional
            # values trace
            exprs = list(call.args)
        for arg in exprs:
            if self.is_tainted(arg):
                self.out.append(Violation(
                    relpath=self.relpath,
                    line=arg.lineno,
                    rule="recompile-guard",
                    message=(
                        f"runtime collection size reaches {name} without "
                        "bucketing — pad through ladder_pad/bucket_pow2 "
                        "(solver/encode.py) or one program per distinct "
                        "size gets minted"
                    ),
                ))


class RecompileGuardPass(Pass):
    name = "recompileguard"
    rules = ("recompile-guard",)

    def run(self, files: Sequence[SourceFile], config) -> List[Violation]:
        out: List[Violation] = self.syntax_violations(
            files, "recompile-guard"
        )
        for f in files:
            if f.tree is None:
                continue
            for node in ast.iter_child_nodes(f.tree):
                self._walk_defs(node, f.relpath, out, config)
        return out

    def _walk_defs(self, node: ast.AST, relpath: str, out, config) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self.check_function(node, relpath, out, config)
        else:
            for child in ast.iter_child_nodes(node):
                self._walk_defs(child, relpath, out, config)

    def check_function(self, node, relpath: str, out, config) -> None:
        walker = _FunctionTaint(self, relpath, config)
        for stmt in node.body:
            walker.visit(stmt)
        out.extend(walker.out)
