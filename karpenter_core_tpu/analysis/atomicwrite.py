"""Atomic-write pass (ISSUE 13).

The resume/health machinery reads other processes' files while they are
being written: bench `--resume` re-reads stage artifacts, the health
daemon's verdict file is polled by every consumer, replay reads flight-
recorder dumps. A bare ``open(path, "w")`` on any of those paths is a
torn-read hazard — a reader can observe a truncated file between the
truncate and the final flush. The repo idiom is write-temp-fsync-rename
(`utils/supervise.atomic_write_json`): `os.replace` is atomic on POSIX,
so a reader sees the old version or the new one, never a prefix.

Rule `atomic-write`: every ``open(..., "w"/"wb"/"x"...)`` call in the
package must either

  * live in a supervisor funnel module (config.atomic_write_funnels —
    the module that IMPLEMENTS the idiom, plus stream files whose
    readers tolerate partial tails by design), or
  * sit in a function that also calls ``os.replace``/``os.rename`` (the
    inline idiom: the open targets a temp path renamed into place), or
  * carry an audited `relpath::function` entry in
    config.plain_write_allowlist (rationale documented in
    docs/static-analysis.md).

Append mode is exempt: appends don't truncate (heartbeat touches, log
tails), so a torn read shows a short tail, not a half-written artifact.
"""
from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Sequence, Tuple

from karpenter_core_tpu.analysis.core import Pass, SourceFile, Violation

WRITE_MODES = {"w", "wb", "wt", "x", "xb", "xt", "w+", "wb+", "w+b"}


def _open_mode(node: ast.Call) -> Optional[str]:
    """The literal mode string of an open() call: '' when absent (read),
    None when non-literal (out of static reach, skipped)."""
    if len(node.args) >= 2:
        mode = node.args[1]
    else:
        mode = next(
            (kw.value for kw in node.keywords if kw.arg == "mode"), None
        )
    if mode is None:
        return ""
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
        return mode.value
    return None


def _scope_nodes(scope: ast.AST) -> Iterator[ast.AST]:
    """Every node lexically in `scope`, NOT descending into nested
    function definitions (each def is judged as its own scope)."""
    stack = list(getattr(scope, "body", []))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue  # nested scope: judged on its own
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _scopes(tree: ast.AST) -> List[Tuple[str, ast.AST]]:
    """(function name, scope node) for every def plus ('', module)."""
    out: List[Tuple[str, ast.AST]] = [("", tree)]
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.append((node.name, node))
    return out


class AtomicWritePass(Pass):
    name = "atomicwrite"
    rules = ("atomic-write",)

    def run(self, files: Sequence[SourceFile], config) -> List[Violation]:
        out: List[Violation] = []
        funnels = getattr(config, "atomic_write_funnels", frozenset())
        allowlist = getattr(config, "plain_write_allowlist", frozenset())
        for f in files:
            if f.tree is None or f.relpath in funnels:
                continue
            for scope_name, scope in _scopes(f.tree):
                nodes = list(_scope_nodes(scope))
                has_rename = any(
                    isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr in ("replace", "rename")
                    and isinstance(n.func.value, ast.Name)
                    and n.func.value.id == "os"
                    for n in nodes
                )
                if has_rename:
                    continue  # inline write-temp + atomic-rename idiom
                if f"{f.relpath}::{scope_name}" in allowlist:
                    continue
                for n in nodes:
                    if not (
                        isinstance(n, ast.Call)
                        and isinstance(n.func, ast.Name)
                        and n.func.id == "open"
                    ):
                        continue
                    mode = _open_mode(n)
                    if mode is None or mode not in WRITE_MODES:
                        continue
                    out.append(Violation(
                        relpath=f.relpath, line=n.lineno,
                        rule="atomic-write",
                        message=(
                            f"bare open(..., {mode!r}) — a concurrent "
                            "reader (resume/health/replay) can see a "
                            "truncated file; use supervise."
                            "atomic_write_json / ArtifactStore or the "
                            "write-temp-fsync-os.replace idiom, or add "
                            "an audited plain_write_allowlist entry"
                        ),
                    ))
        return out
