"""Metric-labels pass (rules `metric-label-keys`, `metric-tenant-guard`):
label discipline for the attribution plane (ISSUE 16).

Prometheus label KEYS define a family's schema and label VALUES define its
cardinality. Both invariants are load-bearing here: the merge plane
(ProcessSeriesMerger) and the SLO engine pattern-match on static key sets,
and tenant values are request-derived strings — unbounded unless every one
routes through the cardinality guard (obs/reqctx.TenantGuard, which caps
the slot count and folds overflow into "other").

So, for every call on an instrument constant (UPPER_CASE receiver —
`SOLVER_SHED_TOTAL.inc`, `reqctx-style module.CACHE_HITS.inc`, including
the `(A if hit else B).inc` conditional form) the labels argument must be
one of:

  * absent / None,
  * a dict literal with constant-string keys and no `**` unpacking,
  * a call to the guard helpers `tenant_labels(...)` (static kwargs only)
    or `TENANTS.admit(...)` — the only functions allowed to mint label
    dicts from request state,
  * a local name whose every assignment in the enclosing scope is one of
    the above (the tracer's build-then-observe idiom: `labels = {...};
    labels["tenant"] = TENANTS.admit(t)`).

and any "tenant" KEY — in a literal or a tracked local — must carry a
guard-call VALUE (`TENANTS.admit(...)`), never a raw request string.
Everything else (bare names from parameters, comprehensions, `dict(...)`
with dynamic keys) is a violation: either the schema is no longer static
(`metric-label-keys`) or a request string reached a label unguarded
(`metric-tenant-guard`).
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Tuple

from karpenter_core_tpu.analysis.core import Pass, SourceFile, Violation

# instrument methods whose signature carries a labels dict, and the
# positional index the labels argument occupies
_METHODS = {
    "inc": 0,        # Counter.inc(labels)
    "observe": 1,    # Histogram.observe(value, labels, exemplar)
    "set": 1,        # Gauge.set(value, labels)
    "delete": 0,     # Gauge.delete(labels)
}

# the cardinality-guard helpers: the only calls allowed to mint label
# dicts (tenant_labels) or tenant label values (TENANTS.admit) from
# request-derived state
_GUARD_FUNCS = ("tenant_labels",)
_GUARD_METHOD = "admit"
_GUARD_RECEIVER = "TENANTS"


def _is_upper(name: str) -> bool:
    return name.isupper() and any(c.isalpha() for c in name)


def _is_instrument(node: ast.expr) -> bool:
    """Receiver looks like a module-level instrument constant."""
    if isinstance(node, ast.Name):
        return _is_upper(node.id)
    if isinstance(node, ast.Attribute):
        return _is_upper(node.attr)
    if isinstance(node, ast.IfExp):  # (CACHE_HITS if hit else CACHE_MISSES)
        return _is_instrument(node.body) and _is_instrument(node.orelse)
    return False


def _terminal_name(func: ast.expr) -> Optional[str]:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _is_guard_call(node: ast.expr) -> bool:
    """tenant_labels(...) / reqctx.tenant_labels(...) /
    TENANTS.admit(...) / reqctx.TENANTS.admit(...)."""
    if not isinstance(node, ast.Call):
        return False
    func = node.func
    name = _terminal_name(func)
    if name in _GUARD_FUNCS:
        # static kwargs only: tenant_labels(**dynamic) would smuggle keys
        return all(kw.arg is not None for kw in node.keywords)
    if name == _GUARD_METHOD and isinstance(func, ast.Attribute):
        recv = func.value
        recv_name = _terminal_name(recv) if isinstance(
            recv, (ast.Name, ast.Attribute)
        ) else None
        return recv_name == _GUARD_RECEIVER
    return False


def _dict_literal_problems(node: ast.Dict) -> List[str]:
    problems: List[str] = []
    for key, value in zip(node.keys, node.values):
        if key is None:
            problems.append("label dict uses `**` unpacking — keys are not static")
            continue
        if not (isinstance(key, ast.Constant) and isinstance(key.value, str)):
            problems.append("label key is not a constant string")
            continue
        if key.value == "tenant" and not _is_guard_call(value):
            problems.append(
                'label "tenant" value must come from the cardinality guard '
                "(TENANTS.admit(...)/tenant_labels(...)), not a raw request string"
            )
    return problems


class _ScopeFacts:
    """Per-scope dataflow for the build-then-observe idiom: which local
    names hold label dicts assembled ONLY from compliant pieces."""

    def __init__(self) -> None:
        # name -> list of problems accumulated across all assignments;
        # None entry means the name was assigned something untrackable
        self.names: Dict[str, Optional[List[str]]] = {}

    def assign(self, name: str, value: ast.expr) -> None:
        if isinstance(value, ast.Dict):
            probs = _dict_literal_problems(value)
        elif _is_guard_call(value) or (
            isinstance(value, ast.Constant) and value.value is None
        ):
            probs = []
        else:
            self.names[name] = None
            return
        if name not in self.names:
            self.names[name] = probs
        elif self.names[name] is not None:
            self.names[name] = self.names[name] + probs  # type: ignore[operator]

    def subscript_assign(self, name: str, key: ast.expr, value: ast.expr) -> None:
        prior = self.names.get(name)
        if name not in self.names or prior is None:
            return  # base dict untracked: already a violation at use sites
        if not (isinstance(key, ast.Constant) and isinstance(key.value, str)):
            prior.append("label key is not a constant string")
        elif key.value == "tenant" and not _is_guard_call(value):
            prior.append(
                'label "tenant" value must come from the cardinality guard '
                "(TENANTS.admit(...)/tenant_labels(...)), not a raw request string"
            )

    def problems_for(self, name: str) -> Optional[List[str]]:
        """None = untracked (violation); [] = clean; else the problems."""
        return self.names.get(name)


def _collect_scope_facts(scope_body: Sequence[ast.stmt]) -> _ScopeFacts:
    facts = _ScopeFacts()

    def scan(stmts: Sequence[ast.stmt]) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # nested scopes track their own facts
            if isinstance(stmt, ast.Assign):
                for target in stmt.targets:
                    if isinstance(target, ast.Name):
                        facts.assign(target.id, stmt.value)
                    elif (isinstance(target, ast.Subscript)
                          and isinstance(target.value, ast.Name)):
                        facts.subscript_assign(
                            target.value.id, target.slice, stmt.value
                        )
            elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                target = stmt.target
                if isinstance(target, ast.Name):
                    if isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                        facts.assign(target.id, stmt.value)
                    elif isinstance(stmt, ast.AugAssign):
                        facts.names[target.id] = None
            # recurse into compound statement bodies (if/for/while/with/try)
            for field in ("body", "orelse", "finalbody"):
                inner = getattr(stmt, field, None)
                if inner:
                    scan(inner)
            for handler in getattr(stmt, "handlers", []) or []:
                scan(handler.body)

    scan(scope_body)
    return facts


def _labels_arg(call: ast.Call, method: str) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg == "labels":
            return kw.value
    idx = _METHODS[method]
    if len(call.args) > idx:
        return call.args[idx]
    return None


class MetricLabelsPass(Pass):
    name = "metriclabels"
    rules = ("metric-label-keys", "metric-tenant-guard")

    def run(self, files: Sequence[SourceFile], config) -> List[Violation]:
        out: List[Violation] = self.syntax_violations(files, "metric-label-keys")
        for f in files:
            if f.tree is None:
                continue
            for scope_node, scope_body in _scopes(f.tree):
                facts = _collect_scope_facts(scope_body)
                for call in _metric_calls(scope_body):
                    method = call.func.attr  # type: ignore[union-attr]
                    labels = _labels_arg(call, method)
                    out.extend(
                        _check_labels(f, call, labels, facts)
                    )
        return out


def _scopes(tree: ast.AST) -> List[Tuple[ast.AST, Sequence[ast.stmt]]]:
    """(scope node, body) for the module and every function."""
    scopes: List[Tuple[ast.AST, Sequence[ast.stmt]]] = [
        (tree, getattr(tree, "body", []))
    ]
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scopes.append((node, node.body))
    return scopes


def _metric_calls(scope_body: Sequence[ast.stmt]) -> List[ast.Call]:
    """Instrument calls whose receiver is in THIS scope (nested function
    bodies are their own scope and are skipped here)."""
    calls: List[ast.Call] = []

    def scan(nodes) -> None:
        for node in nodes:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _METHODS
                    and _is_instrument(node.func.value)):
                calls.append(node)
            scan(ast.iter_child_nodes(node))

    scan(scope_body)
    return calls


def _check_labels(
    f: SourceFile, call: ast.Call, labels: Optional[ast.expr], facts: _ScopeFacts
) -> List[Violation]:
    def v(rule: str, message: str) -> Violation:
        return Violation(
            relpath=f.relpath, line=call.lineno, rule=rule, message=message
        )

    if labels is None or (
        isinstance(labels, ast.Constant) and labels.value is None
    ):
        return []
    if isinstance(labels, ast.Dict):
        return [
            v(
                "metric-tenant-guard" if "tenant" in p else "metric-label-keys",
                p,
            )
            for p in _dict_literal_problems(labels)
        ]
    if _is_guard_call(labels):
        return []
    if isinstance(labels, ast.Name):
        problems = facts.problems_for(labels.id)
        if problems is None:
            return [v(
                "metric-label-keys",
                f"labels `{labels.id}` is not a tracked static dict — build it "
                "as a dict literal (or tenant_labels(...)) in this scope",
            )]
        return [
            v(
                "metric-tenant-guard" if "tenant" in p else "metric-label-keys",
                p,
            )
            for p in problems
        ]
    return [v(
        "metric-label-keys",
        "labels argument must be a dict literal with constant keys, "
        "tenant_labels(...), or a tracked local dict",
    )]
