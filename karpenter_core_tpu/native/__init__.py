"""Native (C++) greedy packer — ctypes bindings with build-on-first-use.

NativeSolver implements the Solver interface for the NO-TOPOLOGY fallback
path: the encoder computes the pod x type static feasibility mask (all
requirement/taint/offering semantics), fast_pack.cpp runs the greedy FFD
packing at C++ speed. Used by the solver service when no TPU is attached and
as the in-process emergency fallback.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Dict, List, Optional

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "fast_pack.cpp")
_LIB = os.path.join(_HERE, "libfastpack.so")

_lib = None
_load_mu = threading.Lock()


def _load():
    global _lib
    with _load_mu:
        if _lib is not None:
            return _lib
        if not os.path.exists(_LIB) or os.path.getmtime(_LIB) < os.path.getmtime(_SRC):
            # compile to a temp path + atomic rename so a concurrent process
            # never dlopens a half-written .so
            tmp = f"{_LIB}.{os.getpid()}.tmp"
            try:
                subprocess.run(
                    ["g++", "-O3", "-shared", "-fPIC", _SRC, "-o", tmp], check=True
                )
            except (OSError, subprocess.CalledProcessError) as e:
                raise RuntimeError(
                    "native packer unavailable: building libfastpack.so failed "
                    f"({e}); ship a prebuilt .so next to fast_pack.cpp or use "
                    "the TPU/Greedy solver"
                ) from e
            os.replace(tmp, _LIB)
        lib = ctypes.CDLL(_LIB)
        lib.fast_pack.restype = ctypes.c_int
        lib.fast_pack.argtypes = [
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
            np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
        ]
        _lib = lib
        return lib


def fast_pack(pod_requests, f_static, type_alloc, daemon, max_nodes: int):
    """Run the native packer. Returns (assigned[P], slot_tmask[N,T],
    slot_used[N,R], slot_pods[N], nopen)."""
    lib = _load()
    P, R = pod_requests.shape
    T = type_alloc.shape[0]
    N = max_nodes
    pod_requests = np.ascontiguousarray(pod_requests, dtype=np.float32)
    f_static = np.ascontiguousarray(f_static, dtype=np.uint8)
    type_alloc = np.ascontiguousarray(type_alloc, dtype=np.float32)
    daemon = np.ascontiguousarray(daemon, dtype=np.float32)
    assigned = np.full(P, -1, dtype=np.int32)
    slot_tmask = np.zeros((N, T), dtype=np.uint8)
    slot_used = np.zeros((N, R), dtype=np.float32)
    slot_pods = np.zeros(N, dtype=np.int32)
    nopen = np.zeros(1, dtype=np.int32)
    lib.fast_pack(
        P, T, R, N, pod_requests, f_static, type_alloc, daemon,
        assigned, slot_tmask, slot_used, slot_pods, nopen,
    )
    return assigned, slot_tmask, slot_used, slot_pods, int(nopen[0])


class NativeSolver:
    """Solver interface over the C++ packer (single-template, no-topology
    path; richer batches raise so the caller falls back to GreedySolver)."""

    def __init__(self, max_nodes: int = 1024):
        self.max_nodes = max_nodes

    def solve(
        self,
        pods,
        provisioners,
        instance_types,
        daemonset_pods=None,
        state_nodes=None,
        kube_client=None,
        cluster=None,
    ):
        from karpenter_core_tpu.solver.tpu_solver import (
            DEFAULT_MAX_RELAX_ROUNDS,
            solve_with_relaxation,
        )

        return solve_with_relaxation(
            lambda p: self._solve_once(
                p, provisioners, instance_types, daemonset_pods, state_nodes,
                kube_client, cluster,
            ),
            pods,
            provisioners,
            instance_types,
            max_relax_rounds=DEFAULT_MAX_RELAX_ROUNDS,
        )

    def _solve_once(self, pods, provisioners, instance_types, daemonset_pods,
                    state_nodes, kube_client=None, cluster=None):
        from karpenter_core_tpu.ops.feasibility import feasibility_static
        from karpenter_core_tpu.solver.encode import encode_snapshot
        from karpenter_core_tpu.solver.tpu_solver import (
            _reqset_to_dict,
            decode_solve,
        )

        snap = encode_snapshot(
            pods, provisioners, instance_types, daemonset_pods, state_nodes,
            kube_client=kube_client, cluster=cluster, max_nodes=self.max_nodes,
        )
        if snap.topo_meta is not None:
            raise NotImplementedError("native packer handles topology-free batches")
        if len(snap.templates) != 1 or snap.state_nodes:
            raise NotImplementedError("native packer handles single-template fresh packs")
        if any(p.spec.limits is not None for p in provisioners):
            # the device kernel enforces limits via state.remaining
            # (scheduler.go:276-293); the native path has no equivalent yet
            raise NotImplementedError("native packer does not enforce provisioner limits")

        segments = [snap.dictionary.segment(k) for k in snap.dictionary.keys]
        f = feasibility_static(
            _reqset_to_dict(snap.pod_reqs),
            _reqset_to_dict(snap.tmpl_reqs),
            _reqset_to_dict(snap.type_reqs),
            snap.pod_tol,
            snap.tmpl_type_mask,
            snap.type_offering_ok,
            snap.zone_seg,
            snap.ct_seg,
            segments,
            snap.well_known,
        )
        f_static = np.asarray(f[0])  # [P, T]
        assigned, slot_tmask, slot_used, slot_pods, nopen = fast_pack(
            snap.pod_requests, f_static, snap.type_alloc, snap.tmpl_daemon[0],
            min(self.max_nodes, max(len(pods), 1)),
        )

        class _State:
            pass

        state = _State()
        state.tmpl = np.zeros(slot_tmask.shape[0], dtype=np.int32)
        state.tmask = slot_tmask.astype(bool)
        state.used = slot_used
        # merged requirement masks: template ∩ assigned pods (host recompute)
        N, V = slot_tmask.shape[0], snap.dictionary.V
        allow = np.ones((N, V), dtype=bool)
        out = np.ones((N, snap.dictionary.K), dtype=bool)
        defined = np.zeros((N, snap.dictionary.K), dtype=bool)
        allow[:] = snap.tmpl_reqs.allow[0]
        out[:] = snap.tmpl_reqs.out[0]
        defined[:] = snap.tmpl_reqs.defined[0]
        for i, slot in enumerate(assigned):
            if slot >= 0:
                allow[slot] &= snap.pod_reqs.allow[i]
                out[slot] &= snap.pod_reqs.out[i]
                defined[slot] |= snap.pod_reqs.defined[i]
        state.allow = allow
        state.out = out
        state.defined = defined
        return decode_solve(snap, assigned, state)
