// fast_pack — native greedy FFD packing over dense arrays.
//
// The host-side fallback for the solver service when no TPU is attached:
// the same screen/verify greedy the device kernel (ops/pack.py) runs, over
// the pre-computed pod x type static feasibility mask, restricted to the
// no-topology constraint path (resources + selectors + taints are all baked
// into f_static by the encoder). Replaces the reference's per-pod Go loop
// (scheduler.go:96-133) for the fallback path at C++ speed.
//
// Build: g++ -O3 -march=native -shared -fPIC fast_pack.cpp -o libfastpack.so
// ABI: plain C, consumed via ctypes (karpenter_core_tpu/native/__init__.py).

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <vector>

extern "C" {

// Returns the number of pods assigned. Arrays are C-order.
//   pod_requests [P,R]  pod resource vectors (FFD-sorted by caller)
//   f_static     [P,T]  pod x type feasibility (compat+offering+taints)
//   type_alloc   [T,R]  allocatable per type
//   daemon       [R]    daemon overhead for fresh machines
//   assigned     [P]    out: slot id or -1
//   slot_tmask   [N,T]  out: remaining types per slot
//   slot_used    [N,R]  out: accumulated requests per slot
//   slot_pods    [N]    out: pod count per slot
//   nopen_out    [1]    out: number of opened slots
int fast_pack(int32_t P, int32_t T, int32_t R, int32_t N,
              const float* pod_requests, const uint8_t* f_static,
              const float* type_alloc, const float* daemon,
              int32_t* assigned, uint8_t* slot_tmask, float* slot_used,
              int32_t* slot_pods, int32_t* nopen_out) {
  std::memset(slot_tmask, 0, (size_t)N * T);
  std::memset(slot_used, 0, (size_t)N * R * sizeof(float));
  std::memset(slot_pods, 0, (size_t)N * sizeof(int32_t));
  int32_t nopen = 0;
  int assigned_count = 0;

  // per-slot optimistic max-allocatable cache for the cheap screen
  std::vector<float> slot_cap((size_t)N * R, 0.0f);

  auto recompute_cap = [&](int32_t n) {
    float* cap = &slot_cap[(size_t)n * R];
    for (int r = 0; r < R; r++) cap[r] = -1.0f;
    const uint8_t* tm = &slot_tmask[(size_t)n * T];
    for (int32_t t = 0; t < T; t++) {
      if (!tm[t]) continue;
      const float* alloc = &type_alloc[(size_t)t * R];
      for (int r = 0; r < R; r++)
        if (alloc[r] > cap[(size_t)r]) cap[r] = alloc[r];
    }
  };

  for (int32_t p = 0; p < P; p++) {
    const float* req = &pod_requests[(size_t)p * R];
    const uint8_t* fs = &f_static[(size_t)p * T];
    assigned[p] = -1;

    // try open slots, fewest pods first (scheduler.go:186-193)
    int32_t best = -1;
    {
      std::vector<int32_t> idx;
      idx.reserve(nopen);
      for (int32_t n = 0; n < nopen; n++) idx.push_back(n);
      std::stable_sort(idx.begin(), idx.end(), [&](int32_t a, int32_t b) {
        return slot_pods[a] < slot_pods[b];
      });
      for (int32_t n : idx) {
        const float* used = &slot_used[(size_t)n * R];
        const float* cap = &slot_cap[(size_t)n * R];
        bool screen = true;
        for (int r = 0; r < R; r++) {
          if (used[r] + req[r] > cap[r]) { screen = false; break; }
        }
        if (!screen) continue;
        // exact: any remaining type that is pod-feasible and fits
        const uint8_t* tm = &slot_tmask[(size_t)n * T];
        bool any = false;
        for (int32_t t = 0; t < T && !any; t++) {
          if (!tm[t] || !fs[t]) continue;
          const float* alloc = &type_alloc[(size_t)t * R];
          bool fit = true;
          for (int r = 0; r < R; r++) {
            if (used[r] + req[r] > alloc[r] || alloc[r] < 0.0f) { fit = false; break; }
          }
          if (fit) any = true;
        }
        if (any) { best = n; break; }
      }
    }

    if (best >= 0) {
      // commit: narrow types, accumulate usage
      float* used = &slot_used[(size_t)best * R];
      uint8_t* tm = &slot_tmask[(size_t)best * T];
      for (int r = 0; r < R; r++) used[r] += req[r];
      for (int32_t t = 0; t < T; t++) {
        if (!tm[t]) continue;
        if (!fs[t]) { tm[t] = 0; continue; }
        const float* alloc = &type_alloc[(size_t)t * R];
        for (int r = 0; r < R; r++) {
          if (used[r] > alloc[r] || alloc[r] < 0.0f) { tm[t] = 0; break; }
        }
      }
      recompute_cap(best);
      slot_pods[best]++;
      assigned[p] = best;
      assigned_count++;
      continue;
    }

    // open a new slot
    if (nopen >= N) continue;
    int32_t n = nopen;
    uint8_t* tm = &slot_tmask[(size_t)n * T];
    float* used = &slot_used[(size_t)n * R];
    bool any = false;
    for (int32_t t = 0; t < T; t++) {
      if (!fs[t]) continue;
      const float* alloc = &type_alloc[(size_t)t * R];
      bool fit = true;
      for (int r = 0; r < R; r++) {
        if (daemon[r] + req[r] > alloc[r] || alloc[r] < 0.0f) { fit = false; break; }
      }
      if (fit) { tm[t] = 1; any = true; }
    }
    if (!any) continue;
    for (int r = 0; r < R; r++) used[r] = daemon[r] + req[r];
    recompute_cap(n);
    slot_pods[n] = 1;
    assigned[p] = n;
    assigned_count++;
    nopen++;
  }
  *nopen_out = nopen;
  return assigned_count;
}

}  // extern "C"
